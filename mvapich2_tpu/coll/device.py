"""The ICI device-collective channel — XLA collectives behind the MPI seam.

This is the analog of the mrail channel installing tuned collectives
per-communicator in init_MV2_collops (reference:
src/mpid/ch3/channels/mrail/src/rdma/ch3i_comm.c:27-100): a mesh-bound
``Comm`` gets its ``coll_fns`` entries overwritten with wrappers that
dispatch to the XLA-native ops (ops/collectives.py) when the tuning layer
selects the device transport, and fall back to the host algorithm zoo
otherwise.

Execution model (TPU-first): MPI ranks are bound 1:1 to the devices of a
1-D ``jax.sharding.Mesh``. A collective call is executed *once* as a jitted
``shard_map`` program over the mesh — each rank deposits its local shard at
a rendezvous, the lowest rank runs the XLA op (which lowers to ICI
ring/tree collectives in one fused program), and every rank picks up its
output shard. This is exactly how a single-controller JAX job drives a TPU
pod slice; on a multi-controller (multi-host) job the same ops run under
``jax.distributed`` with each host contributing its local shards.

The rendezvous requires all bound ranks to share one process (rank threads
— the virtual-pod harness, ``mpirun --vpod``) or one jax.distributed
runtime; process-mode ranks without either keep the host path (the install
is a no-op, logged).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.config import cvar, get_config
from ..utils.mlog import get_logger

log = get_logger("device_coll")

cvar("USE_DEVICE_COLL", True, bool, "coll",
     "Enable the ICI device-collective channel on mesh-bound comms "
     "(analog of MV2_USE_RDMA_COLL-style channel toggles).")
cvar("DEVICE_COLL_MIN_BYTES", 16384, int, "coll",
     "Host->device transport crossover: host-buffer collectives below "
     "this size keep the host path (device dispatch has fixed "
     "rendezvous+dispatch overhead). Device-resident buffers always take "
     "the device path. Measured profiles override this.")
cvar("DEVICE_NBC_SEG_BYTES", 1 << 20, int, "coll",
     "Segment size (bytes per shard) of device NONBLOCKING collectives: "
     "elementwise-safe ops (iallreduce/ibcast) split into independent "
     "program segments, each an async dispatch the NBC DAG's poll "
     "vertices pump to completion — compute overlaps the still-flying "
     "segments. 0 = one segment (no split).")
cvar("DEVICE_NBC_MAX_SEGS", 8, int, "coll",
     "Upper bound on device nonblocking-collective segments per call "
     "(each segment is one cached program signature; unbounded "
     "splitting would thrash the program/executable caches).")

from ..utils import is_device_array  # noqa: E402 — shared predicate

# -- MV2T_JAX_PROFILE: hardware-profiler bracket ------------------------
# When the cvar names a directory, the FIRST device collective starts a
# jax.profiler trace there and an atexit hook stops it — one xplane
# trace covering the whole device-collective region of the run, the
# input the TPU-hardware tuning pass (ROADMAP item 1: ici_chunk_bytes /
# ICI_PIPELINE_DEPTH at the 64 MiB point) reads in TensorBoard/XProf.
# Declared in mpit.py (cvar JAX_PROFILE) so MPI_T enumerates it early.
_jax_profile_started = False
_jax_profile_lock = threading.Lock()


def _maybe_start_jax_profile() -> None:
    global _jax_profile_started
    if _jax_profile_started:          # one attr check once started
        return
    out_dir = str(get_config().get("JAX_PROFILE", "") or "")
    if not out_dir:
        return    # cheap re-check per call: device dispatch is ms-scale
    with _jax_profile_lock:
        if _jax_profile_started:
            return
        _jax_profile_started = True
        try:
            import atexit

            import jax
            jax.profiler.start_trace(out_dir)
            atexit.register(_stop_jax_profile)
            log.info("jax.profiler trace started -> %s "
                     "(MV2T_JAX_PROFILE)", out_dir)
        except Exception as e:   # profiling must never kill a collective
            log.warn("MV2T_JAX_PROFILE start failed: %r", e)


def _stop_jax_profile() -> None:
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:
        pass


def _op_name(op) -> Optional[str]:
    """Map a core.op builtin to an XLA reduction name (None = no analog)."""
    from ..core import op as opmod
    table = {id(opmod.SUM): "sum", id(opmod.MAX): "max",
             id(opmod.MIN): "min", id(opmod.PROD): "prod"}
    return table.get(id(op))


def _dtype_lowers(dtype: np.dtype) -> bool:
    """True when the dtype round-trips through the device unchanged.
    With jax x64 disabled, 64-bit types would be silently downcast —
    wrong answers, so they stay on the host path."""
    import jax
    if dtype.itemsize == 8 and not jax.config.jax_enable_x64:
        return False
    return dtype.kind in "fiu"


# -- daemon device-executable cache (ISSUE 14) --------------------------
# The PiP attach-not-construct model applied to compiled programs: with
# MV2T_DAEMON + MV2T_DAEMON_EXEC_CACHE on, a program build first asks
# the node daemon's exec-cache for a serialized executable under the
# (kernel, shape, mesh, jax/profile fingerprint) key and deserializes
# it — skipping jax tracing + Mosaic compile, the dominant cold-start
# cost of a device job. A miss builds as before and exports the traced
# program after its first successful call (the only point the concrete
# input layout exists). Every failure path degrades to the plain build:
# the cache can be absent, stale-epoch, or unexportable (pre-export
# jax, interpreter callbacks) without ever breaking a collective.

class _ExportingProgram:
    """Built program that serializes itself into the daemon exec-cache
    after its first successful call."""

    __slots__ = ("fn", "key", "_stored")

    def __init__(self, fn, key: str):
        self.fn = fn
        self.key = key
        self._stored = False

    def __call__(self, x):
        out = self.fn(x)
        if not self._stored:
            self._stored = True    # one export attempt per process
            from ..ops import _compat
            from ..runtime import daemon
            blob = _compat.serialize_executable(self.fn, x)
            if blob is not None:
                daemon.exec_cache_put(self.key, blob)
        return out


class _ImportedProgram:
    """Deserialized cached executable; a failure on the FIRST call
    (corrupt entry, incompatible artifact that slipped the fingerprint)
    rebuilds from source instead of failing the collective."""

    __slots__ = ("fn", "rebuild", "_proven")

    def __init__(self, fn, rebuild):
        self.fn = fn
        self.rebuild = rebuild
        self._proven = False

    def __call__(self, x):
        if self._proven:
            return self.fn(x)
        try:
            out = self.fn(x)
        except Exception as e:   # noqa: BLE001 — cache must not break calls
            log.warn("cached executable failed on first call (%r); "
                     "rebuilding from source", e)
            self.fn = self.rebuild()
            out = self.fn(x)
        self._proven = True
        return out


class _Rendezvous:
    """Per-bound-comm meeting point: slots for each rank's shard, two
    barrier phases per collective (deposit -> leader compute -> pickup).
    MPI already requires every rank to issue collectives on a comm in the
    same order, so one in-flight collective per comm is the contract."""

    def __init__(self, size: int):
        self.size = size
        self.barrier = threading.Barrier(size)
        self.slots: List = [None] * size
        self.result: List = [None] * size
        self.error: Optional[BaseException] = None
        # nonblocking rendezvous: no barrier to block in — ranks deposit
        # under nb_lock into per-sequence call records and the NBC DAG's
        # poll vertices observe arrival/launch/completion state instead
        self.nb_lock = threading.Lock()
        self.nb_calls: Dict[int, dict] = {}
        self.nb_failed = False

    def abort(self) -> None:
        """Break the barrier so peers blocked in a device collective see
        a failure instead of deadlocking (called when a rank dies).
        In-flight NONBLOCKING device collectives have no barrier to
        break: the sticky nb_failed flag makes every later poll raise
        MPIX_ERR_PROC_FAILED so survivor DAGs unwind."""
        self.nb_failed = True
        self.barrier.abort()


class _VDeposit:
    """One rank's alltoallv contribution at the rendezvous: the densely
    packed send payload (canonical packed order — peer 0's elements
    first) plus this rank's scounts row, from which the leader assembles
    the full static counts matrix."""

    __slots__ = ("data", "scounts")

    def __init__(self, data, scounts):
        self.data = data
        self.scounts = tuple(int(c) for c in scounts)


class DeviceCollChannel:
    """One rank's handle on the mesh-bound collective engine."""

    # hierarchy levels one call on this channel exercises — the
    # coll_level_* pvars bumped per call in _run (three-level contract:
    # chip = HBM slot fold, ici = mesh ring phases, net = node leaders)
    LEVELS: Tuple[str, ...] = ("ici",)
    # collectives this channel routes to the device tier; the rest keep
    # their host entries at install time
    SUPPORTED: Tuple[str, ...] = ("allreduce", "reduce", "bcast",
                                  "allgather", "alltoall",
                                  "reduce_scatter_block", "alltoallv")

    def __init__(self, mesh, axis, rendezvous: _Rendezvous, rank: int):
        self.mesh = mesh
        # ``axis``: one mesh axis name, or an ordered tuple of names —
        # then ranks span the product extent row-major and the programs
        # lower through the multi-axis torus decomposition
        # (ops/pallas_ici.ici_*_mesh, ISSUE 20)
        if isinstance(axis, (tuple, list)):
            self.axes: Tuple[str, ...] = tuple(str(a) for a in axis)
        else:
            self.axes = (str(axis),)
        self.axis = self.axes[0]
        self.rv = rendezvous
        self.rank = rank
        devices = list(np.asarray(mesh.devices).reshape(-1))
        self.device = devices[rank]
        self.devices = devices
        self.size = len(devices)
        # per-instance program cache (a class-level lru_cache would pin
        # freed channels + their compiled executables for process life)
        self._programs: Dict = {}
        self._nb_seq = 0     # per-rank nonblocking-collective sequence

    @property
    def multi_axis(self) -> bool:
        return len(self.axes) > 1

    def _axis_sizes(self) -> Tuple[Tuple[str, int], ...]:
        return tuple((a, self.mesh.shape[a]) for a in self.axes)

    def _pspec0(self):
        """The leading PartitionSpec entry covering this channel's
        ranks: the bare axis name (1-D, the classic binding) or the
        ordered axes tuple (row-major flattened rank order)."""
        return self.axes if self.multi_axis else self.axis

    def _mesh_extent(self) -> int:
        """Participant count of the mesh program: the comm size on the
        1:1 binding, the chip count on the fold channel (where each
        mesh shard carries a whole chip's folded contribution)."""
        return self.size

    def abort(self) -> None:
        self.rv.abort()

    # -- jitted program cache (per mesh, keyed by op signature) ----------
    def _program(self, name: str, n: int, dtype_str: str, op: str,
                 root: int, extra=None):
        key = (name, n, dtype_str, op, root, extra)
        got = self._programs.get(key)
        if got is None:
            got = self._programs[key] = self._cached_build(
                name, n, dtype_str, op, root, extra)
        return got

    def _chan_desc(self) -> str:
        """The mesh half of the executable-cache key: channel flavor,
        extent and platform (two geometries must never share an
        artifact). Multi-axis channels key on every (axis, extent)
        pair — a 2x4 and a 4x2 mesh must never share an artifact
        either."""
        if self.multi_axis:
            shape = "x".join(f"{a}{s}" for a, s in self._axis_sizes())
            return (f"mesh{self.size}x{self.device.platform}"
                    f"@{shape}")
        return (f"mesh{self.size}x{self.device.platform}"
                f"@{self.axis}")

    def _cached_build(self, name: str, n: int, dtype_str: str, op: str,
                      root: int, extra=None):
        """The exec-cache seam around ``_build``: deserialize on hit,
        build + export-on-first-call on miss, plain build whenever the
        cache is off or this jax cannot export. ``extra`` is the
        per-signature static payload (the alltoallv counts matrix) —
        part of both cache keys."""
        from ..runtime import daemon
        if not daemon.exec_cache_enabled():
            return self._build(name, n, op, root, extra)
        from ..ops import _compat
        ck = "|".join(("mv2t-exec-v1", self._chan_desc(), name,
                       f"n{n}", dtype_str, f"op:{op}", f"root:{root}",
                       f"x:{extra!r}", _compat.exec_fingerprint()))
        blob = daemon.exec_cache_get(ck)
        if blob is not None:
            fn = _compat.deserialize_executable(blob)
            if fn is not None:
                return _ImportedProgram(
                    fn, lambda: self._build(name, n, op, root, extra))
        return _ExportingProgram(self._build(name, n, op, root, extra), ck)

    def _build(self, name: str, n: int, op: str, root: int, extra=None):
        if self.multi_axis:
            return self._build_mesh(name, n, op, root, extra)
        import jax
        from jax.sharding import PartitionSpec as P

        from .. import ops
        from ..parallel.mesh import shard_map
        axis, p = self.axis, self._mesh_extent()

        if name in ("allreduce", "reduce"):
            def f(x):                       # block [1, n]
                # tier dispatch: VMEM flat ring / HBM-streaming chunked
                # ring / XLA, by shard bytes (coll/tuning.device_tier)
                from ..ops import pallas_ici
                return pallas_ici.ici_all_reduce(
                    x.reshape(-1), axis, p, op=op).reshape(1, -1)
            out_specs = P(None, None)       # replicated [1, n]
        elif name == "bcast":
            def f(x):
                return ops.bcast(x, axis, root)
            out_specs = P(None, None)
        elif name == "allgather":
            def f(x):
                from ..ops import pallas_ici
                return pallas_ici.ici_all_gather(
                    x.reshape(-1), axis, p).reshape(p, -1)
            out_specs = P(None, None)       # replicated [p, n]
        elif name == "alltoall":
            c = n // p

            def f(x):                       # block [1, n] -> [p, c]
                # tier dispatch: chunked HBM remote-DMA pairwise streamer
                # or the XLA lowering (ops/pallas_alltoall)
                from ..ops import pallas_alltoall
                return pallas_alltoall.ici_all_to_all(
                    x.reshape(-1), axis, p).reshape(p, c)
            out_specs = P(axis, None)       # global [p*p, c]
        elif name == "alltoallv":
            counts = extra                  # static p x p matrix

            def f(x):                       # block [1, in_len] -> [1, out]
                from ..ops import pallas_alltoall
                return pallas_alltoall.ici_all_to_allv(
                    x.reshape(-1), axis, p, counts).reshape(1, -1)
            out_specs = P(axis, None)       # global [p, out_len]
        elif name == "reduce_scatter_block":
            c = n // p
            if op == "sum":
                def f(x):
                    y = ops.reduce_scatter(x.reshape(n), axis,
                                           scatter_dimension=0, tiled=True)
                    return y.reshape(1, c)
            else:
                # non-sum ops: full allreduce then keep this shard's block
                # (psum_scatter lowers natively only for sum)
                from jax import lax

                def f(x):
                    y = ops.allreduce(x.reshape(n), axis, op)
                    i = lax.axis_index(axis)
                    return lax.dynamic_slice(y, (i * c,), (c,)).reshape(1, c)
            out_specs = P(axis, None)       # global [p, c]
        else:  # pragma: no cover
            raise KeyError(name)

        sm = shard_map(f, mesh=self.mesh, in_specs=(P(axis, None),),
                       out_specs=out_specs, check_vma=False)
        return jax.jit(sm)

    def _flat_rank(self):
        """Traced flattened rank over this channel's axes (row-major) —
        the SPMD analog of ``self.rank`` inside a mesh program."""
        from jax import lax
        idx = lax.axis_index(self.axes[0])
        for a in self.axes[1:]:
            idx = idx * self.mesh.shape[a] + lax.axis_index(a)
        return idx

    def _build_mesh(self, name: str, n: int, op: str, root: int,
                    extra=None):
        """Multi-axis programs: reductions ride the per-axis RS/AG torus
        decomposition (ici_*_mesh), bcast composes per-axis phases from
        the root's coordinates innermost-first, and the structural
        collectives (alltoall(v)) lower through XLA over the flattened
        axes tuple — the per-axis pairwise streamer is 1-D-addressed
        (the kernel half is future hardware work, ROADMAP item 2)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from .. import ops
        from ..parallel.mesh import shard_map
        axes, p = self.axes, self._mesh_extent()
        sizes = self._axis_sizes()
        spec0 = self._pspec0()

        if name in ("allreduce", "reduce"):
            def f(x):                       # block [1, n]
                from ..ops import pallas_ici
                return pallas_ici.ici_all_reduce_mesh(
                    x.reshape(-1), sizes, op=op).reshape(1, -1)
            out_specs = P(None, None)       # replicated [1, n]
        elif name == "bcast":
            # root's per-axis coordinates, innermost phase first: after
            # axis k's bcast the root's whole k-line carries the payload
            coords, r = [], root
            for a in reversed(axes):
                coords.append(r % self.mesh.shape[a])
                r //= self.mesh.shape[a]
            coords.reverse()

            def f(x):
                for a, c in reversed(tuple(zip(axes, coords))):
                    x = ops.bcast(x, a, c)
                return x
            out_specs = P(None, None)
        elif name == "allgather":
            def f(x):
                from ..ops import pallas_ici
                return pallas_ici.ici_all_gather_mesh(
                    x.reshape(-1), sizes).reshape(p, -1)
            out_specs = P(None, None)       # replicated [p, n]
        elif name == "alltoall":
            c = n // p

            def f(x):                       # block [1, n] -> [p, c]
                y = lax.all_to_all(x.reshape(p, c), axes, split_axis=0,
                                   concat_axis=0, tiled=False)
                return y.reshape(p, c)
            out_specs = P(spec0, None)      # global [p*p, c]
        elif name == "alltoallv":
            counts = extra                  # static p x p matrix
            from ..ops.pallas_alltoall import packed_displs
            sdisp, rdisp, in_len, out_len = packed_displs(counts)

            def f(x):                       # block [1, in_len] -> [1, out]
                # gather every rank's packed payload, then assemble ALL
                # receive rows statically (counts are static) and keep
                # this rank's — O(p) memory, but structurally correct on
                # any torus shape
                g = x.reshape(1, in_len)
                for a in reversed(axes):
                    g = lax.all_gather(g, a, tiled=True, axis=0)
                rows = []
                for dst in range(p):
                    parts = [lax.slice_in_dim(
                                g[src], sdisp[src][dst],
                                sdisp[src][dst] + counts[src][dst])
                             for src in range(p) if counts[src][dst]]
                    row = (jnp.concatenate(parts) if parts
                           else g[0][:0])
                    pad = out_len - row.shape[0]
                    if pad > 0:
                        row = jnp.pad(row, (0, pad))
                    rows.append(row)
                me = self._flat_rank()
                return lax.dynamic_index_in_dim(
                    jnp.stack(rows), me, axis=0,
                    keepdims=True).reshape(1, -1)
            out_specs = P(spec0, None)      # global [p, out_len]
        elif name == "reduce_scatter_block":
            c = n // p

            def f(x):
                from ..ops import pallas_ici
                y = pallas_ici.ici_reduce_scatter_mesh(
                    x.reshape(n), sizes, op=op)
                return y.reshape(1, c)
            out_specs = P(spec0, None)      # global [p, c]
        else:  # pragma: no cover
            raise KeyError(name)

        sm = shard_map(f, mesh=self.mesh, in_specs=(P(spec0, None),),
                       out_specs=out_specs, check_vma=False)
        return jax.jit(sm)

    # -- the rendezvous execution ----------------------------------------
    @staticmethod
    def _slot_extent(slot):
        """(n, dtype) of a deposited slot without pulling device arrays
        back to the host."""
        if isinstance(slot, _VDeposit):
            slot = slot.data
        if is_device_array(slot):
            return int(np.prod(slot.shape)), np.dtype(str(slot.dtype))
        arr = np.asarray(slot)
        return int(arr.size), arr.dtype

    def _execute(self, name: str, local: np.ndarray, op: str = "sum",
                 root: int = 0):
        """Run one device collective; ``local`` is this rank's shard
        ([n] host numpy or device array). Deposit at the rendezvous,
        rank 0 runs the channel's ``_leader`` hook, everyone picks up
        its result. Returns whatever the leader deposited for this rank
        (device array)."""
        rv = self.rv
        rv.slots[self.rank] = local
        try:
            rv.barrier.wait()
        except threading.BrokenBarrierError:
            raise RuntimeError(
                "device collective aborted: a peer rank failed") from None
        if self.rank == 0:
            try:
                rv.result = self._leader(name, op, root)
                rv.error = None
            except BaseException as e:   # noqa: BLE001 — must release peers
                rv.error = e
                rv.result = [None] * self.size
        try:
            rv.barrier.wait()
        except threading.BrokenBarrierError:
            rv.slots[self.rank] = None
            raise RuntimeError(
                "device collective aborted: a peer rank failed") from None
        # release this rank's references promptly — retained slots/results
        # would pin device memory for the life of an idle comm
        res, rv.result[self.rank] = rv.result[self.rank], None
        rv.slots[self.rank] = None
        if rv.error is not None:
            raise RuntimeError(
                f"device collective {name} failed on the leader"
            ) from rv.error
        return res

    def _leader(self, name: str, op: str, root: int) -> List:
        """Leader compute: assemble the mesh-sharded global array, run
        the jitted shard_map program, scatter output shards per rank."""
        import jax

        rv = self.rv
        if name == "alltoallv":
            return self._leader_v()
        n, dtype = self._slot_extent(rv.slots[0])
        shards = []
        for r in range(self.size):
            s = rv.slots[r]
            if is_device_array(s) and \
                    s.devices() == {self.devices[r]}:
                shards.append(s.reshape(1, n))
            else:
                shards.append(jax.device_put(
                    np.asarray(s).reshape(1, n), self.devices[r]))
        from jax.sharding import NamedSharding, PartitionSpec as P
        global_arr = jax.make_array_from_single_device_arrays(
            (self.size, n),
            NamedSharding(self.mesh, P(self._pspec0(), None)), shards)
        out = self._program(name, n, str(dtype), op, root)(global_arr)
        per_dev: Dict = {}
        for s in out.addressable_shards:
            per_dev[s.device] = s.data
        return [per_dev[self.devices[r]] for r in range(self.size)]

    def _v_shards(self, slots, in_len: int, dtype) -> List:
        """Per-rank device shards for an alltoallv call: each rank's
        dense packed payload padded to the mesh-wide ``in_len`` (the
        shard_map shapes must be uniform)."""
        import jax
        shards = []
        for r in range(self.size):
            d = slots[r].data
            if is_device_array(d) and d.devices() == {self.devices[r]}:
                import jax.numpy as jnp
                v = d.reshape(-1)
                if int(v.size) < in_len:
                    v = jnp.pad(v, (0, in_len - int(v.size)))
                shards.append(v.reshape(1, in_len))
            else:
                buf = np.zeros((1, in_len), dtype)
                a = np.asarray(d).reshape(-1)
                buf[0, :a.size] = a
                shards.append(jax.device_put(buf, self.devices[r]))
        return shards

    def _leader_v(self) -> List:
        """Leader compute for alltoallv: assemble the static counts
        matrix from every rank's deposited scounts row, stage the padded
        packed payloads, run the counts-keyed program (the matrix is
        part of the program/executable cache key)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.pallas_alltoall import packed_displs
        rv = self.rv
        counts = tuple(tuple(s.scounts) for s in rv.slots)
        _, _, in_len, _ = packed_displs(counts)
        _, dtype = self._slot_extent(rv.slots[0])
        shards = self._v_shards(rv.slots, in_len, dtype)
        global_arr = jax.make_array_from_single_device_arrays(
            (self.size, in_len),
            NamedSharding(self.mesh, P(self._pspec0(), None)), shards)
        out = self._program("alltoallv", in_len, str(dtype), "none", 0,
                            counts)(global_arr)
        per_dev: Dict = {}
        for s in out.addressable_shards:
            per_dev[s.device] = s.data
        return [per_dev[self.devices[r]] for r in range(self.size)]

    # -- per-call tier accounting (the observable-fallback contract) -----
    def _note_tier(self, comm, name: str, local, op: Optional[str]) -> str:
        """Count which device tier THIS call runs (pvars
        dev_coll_tier_{vmem,hbm} / dev_coll_fallback_*) and drop a trace
        instant when the XLA lowering is taken — the once-invisible
        VMEM-cap cliff. Per call, unlike the per-traced-shape counting
        at the kernel wrappers (programs are cached per signature).
        Returns the tier label the call will run on ('vmem'/'hbm'/
        'quant'/'xla', 'slot' on the single-device channel) — the
        dispatch span and the dev_effbw watermark key off it."""
        if self.mesh is None:
            return "slot"   # single-device slot channel: no ICI tiers
        from .. import mpit
        from ..ops import pallas_ici
        n, dtype = self._slot_extent(local)
        nbytes = n * dtype.itemsize * (self.size if name == "allgather"
                                       else 1)
        if name in ("alltoall", "alltoallv"):
            from ..ops import pallas_alltoall
            tier, reason = pallas_alltoall.planned_a2a_tier(
                max(1, nbytes), dtype)
            if reason is None:
                mpit.pvar(f"dev_coll_tier_{tier}").inc()
                return tier
            mpit.pvar(f"dev_coll_fallback_{reason}").inc()
            tr = getattr(comm.u.engine, "tracer", None)
            if tr is not None:
                tr.record("channel", "dev_coll_fallback", "i", coll=name,
                          nbytes=int(nbytes), reason=reason)
            return "xla"
        if name not in ("allreduce", "reduce", "allgather"):
            return "xla"    # ops without a ring-kernel lowering
        tier, reason = pallas_ici.planned_tier(name, nbytes, dtype, op,
                                               num_devices=self._mesh_extent())
        if reason is None:
            mpit.pvar(f"dev_coll_tier_{tier}").inc()
            if tier == "quant":
                # the measurable half of the quant claim: bytes kept
                # off the ICI wire by this call, per rank
                from ..ops import pallas_quant
                exact_b, wire_b = pallas_quant.wire_stats(
                    n, dtype, self._mesh_extent())
                mpit.pvar("dev_coll_quant_bytes_saved").inc(
                    max(0, exact_b - wire_b))
            return tier
        mpit.pvar(f"dev_coll_fallback_{reason}").inc()
        tr = getattr(comm.u.engine, "tracer", None)
        if tr is not None:
            tr.record("channel", "dev_coll_fallback", "i", coll=name,
                      nbytes=int(nbytes), reason=reason)
        return "xla"

    def _run(self, comm, name: str, local, op: str = "sum",
             root: int = 0):
        """Traced dispatch: one B/E span in the 'device' lane carrying
        tier/op/bytes/duration around the whole rendezvous+execute, the
        per-tier dev_effbw watermark (end-to-end GB/s), and the
        MV2T_JAX_PROFILE bracket for hardware runs. The span is what
        makes the device path visible on the same Perfetto axis as the
        host layers — the r5/r6 rounds tuned it blind."""
        import time as _time

        tier = self._note_tier(comm, name, local,
                               op if name != "bcast" else None)
        from .. import mpit
        for lv in self.LEVELS:   # which hierarchy levels this call rides
            mpit.pvar(f"coll_level_{lv}").inc()
        n, dtype = self._slot_extent(local)
        nbytes = int(n * dtype.itemsize)
        tr = getattr(comm.u.engine, "tracer", None)
        if tr is not None:
            tr.record("device", f"dev_{name}", "B", tier=tier, op=op,
                      bytes=nbytes)
        _maybe_start_jax_profile()
        t0 = _time.perf_counter()
        try:
            out = self._execute(name, local, op=op, root=root)
        finally:
            dt = _time.perf_counter() - t0
            if tr is not None:
                tr.record("device", f"dev_{name}", "E", tier=tier,
                          us=round(dt * 1e6, 3))
        if dt > 0 and nbytes > 0:
            from .. import mpit
            mpit.pvar(f"dev_effbw_{tier}").mark(nbytes / dt / 1e9)
        from .. import metrics as _metrics
        mx = _metrics.LIVE
        if mx is not None:
            # per-tier latency distribution (the watermark above keeps
            # only the peak; quantiles need the whole shape)
            mx.rec_us(f"lat_dev_{tier}", dt * 1e6)
        return out

    # -- MPI-shaped entry points (match coll_fns signatures) -------------
    def allreduce(self, comm, sendbuf, recvbuf, count, datatype, op):
        local = _as_local(sendbuf, recvbuf, count)
        out = self._run(comm, "allreduce", local, op=_op_name(op))
        return _deliver(out, recvbuf)

    def reduce(self, comm, sendbuf, recvbuf, count, datatype, op, root):
        local = _as_local(sendbuf, recvbuf, count)
        out = self._run(comm, "reduce", local, op=_op_name(op))
        if comm.rank != root:
            return None
        return _deliver(out, recvbuf)

    def bcast(self, comm, buf, count, datatype, root):
        out = self._run(comm, "bcast", _as_local(buf, buf, count),
                        root=root)
        return _deliver(out, buf)

    def allgather(self, comm, sendbuf, recvbuf, count, datatype):
        local = _as_local(sendbuf, recvbuf, count,
                          in_place_start=comm.rank * count)
        out = self._run(comm, "allgather", local, op=None)
        return _deliver(out, recvbuf)

    def alltoall(self, comm, sendbuf, recvbuf, count, datatype):
        local = _as_local(sendbuf, recvbuf, count * comm.size)
        out = self._run(comm, "alltoall", local)
        return _deliver(out, recvbuf)

    def alltoallv(self, comm, sendbuf, scounts, sdispls, recvbuf,
                  rcounts, rdispls, datatype):
        """MoE-shaped variable-count alltoall: each rank packs its sends
        densely, deposits payload + scounts row, the leader assembles
        the static counts matrix and runs the counts-keyed kernel; the
        canonical packed result is rearranged to the caller's rdispls
        on the way out."""
        dep = _VDeposit(_pack_v(sendbuf, scounts, sdispls), scounts)
        out = self._run(comm, "alltoallv", dep, op=None)
        return self._deliver_v(out, recvbuf, rcounts, rdispls)

    def _deliver_v(self, out, recvbuf, rcounts, rdispls):
        """Scatter the canonical packed device result (dense sender
        order — rank knows its own rcounts column, so no matrix needed)
        into the caller's layout."""
        rtotal = int(sum(rcounts))
        dense = _dense_displs(rcounts)
        if recvbuf is None or is_device_array(recvbuf) \
                or type(recvbuf).__name__ == "_InPlace":
            flat = out.reshape(-1)
            if list(rdispls) == dense:
                return flat[:rtotal]
            # non-dense user layout: assemble on the host, push back
            import jax
            host = np.asarray(flat)
            ext = max((rdispls[j] + rcounts[j]
                       for j in range(len(rcounts))), default=0)
            dst = np.zeros(ext, host.dtype)
            off = 0
            for j, cnt in enumerate(rcounts):
                dst[rdispls[j]:rdispls[j] + cnt] = host[off:off + cnt]
                off += cnt
            return jax.device_put(dst, self.device)
        host = np.asarray(out).reshape(-1)
        dst = np.asarray(recvbuf).reshape(-1)
        off = 0
        for j, cnt in enumerate(rcounts):
            dst[rdispls[j]:rdispls[j] + cnt] = host[off:off + cnt]
            off += cnt
        return None

    def reduce_scatter_block(self, comm, sendbuf, recvbuf, count, datatype,
                             op):
        local = _as_local(sendbuf, recvbuf, count * comm.size)
        out = self._run(comm, "reduce_scatter_block", local,
                        op=_op_name(op))
        return _deliver(out, recvbuf)

    # -- nonblocking device collectives on the NBC DAG (ISSUE 18) --------
    # The blocking path rendezvouses on a threading.Barrier; that cannot
    # ride a schedule vertex (DAG issue must never block). Instead the
    # i-collective becomes a small DAG: one CALL deposits this rank's
    # shard into a per-sequence call record, per-segment POLL vertices
    # launch the async jitted dispatch (first poller past full arrival)
    # and then re-read its completion state on every engine progress
    # pass, and a final CALL lands this rank's output shards. drain_all
    # pumps the parked polls exactly like shm work — communication
    # overlaps whatever compute the rank does between Icoll and Wait.

    def _nb_segments(self, name: str, n: int, dtype) -> List[tuple]:
        """[(off, len)] program segments. Elementwise-safe collectives
        (allreduce/bcast) stream segment-wise — early segments complete
        while later ones are still flying; structural ones (allgather,
        alltoall(v)) run as one dispatch."""
        if name not in ("allreduce", "bcast") or n <= 1:
            return [(0, n)]
        cfg = get_config()
        seg_bytes = int(cfg["DEVICE_NBC_SEG_BYTES"])
        if seg_bytes <= 0:
            return [(0, n)]
        seg = max(1, seg_bytes // max(1, dtype.itemsize))
        nseg = min(int(cfg["DEVICE_NBC_MAX_SEGS"]),
                   (n + seg - 1) // seg)
        if nseg <= 1:
            return [(0, n)]
        per = (n + nseg - 1) // nseg
        return [(o, min(per, n - o)) for o in range(0, n, per)]

    def nonblocking(self, comm, name: str, *a, plan: bool = False):
        """Build the device-tier request for one i-collective; None when
        this call cannot route (caller counts dev_coll_fallback_nbc).
        ``plan=True`` is the MPI_*_init pre-warm: run the same routing
        gates, then build the program signatures through the exec-cache
        seam instead of launching (returns True/False)."""
        if self.mesh is None:
            return None      # slot channel keeps the host schedule
        opn, op_sel, root = None, None, 0
        rcounts = rdispls = None
        if name == "allreduce":
            sendbuf, recvbuf, count, datatype, op_sel = a
            opn = _op_name(op_sel)
            if opn is None:
                return None
            send_eff, n = sendbuf, count
            wire = count * datatype.size
        elif name == "bcast":
            buf, count, datatype, root = a
            sendbuf = recvbuf = send_eff = buf
            n = count
            wire = count * datatype.size
        elif name == "allgather":
            sendbuf, recvbuf, count, datatype = a
            send_eff, n = sendbuf, count
            wire = count * datatype.size * self.size
        elif name == "alltoall":
            sendbuf, recvbuf, count, datatype = a
            send_eff, n = sendbuf, count * self.size
            wire = count * datatype.size * self.size
        elif name == "alltoallv":
            (sendbuf, scounts, sdispls, recvbuf, rcounts, rdispls,
             datatype) = a
            if sdispls is None:
                sdispls = _dense_displs(scounts)
            if rdispls is None:
                rdispls = _dense_displs(rcounts)
            send_eff, n = sendbuf, int(sum(scounts))
            wire = n * datatype.size
        else:
            return None
        if type(sendbuf).__name__ == "_InPlace" \
                or type(recvbuf).__name__ == "_InPlace":
            return None
        if recvbuf is None or is_device_array(recvbuf):
            # jax arrays are immutable: the completion CALL needs a host
            # recv it can write through at wait() time
            return None
        if not _dtype_ok(send_eff) or not _dtype_ok(recvbuf):
            return None
        if _select_transport(comm, name, wire, op_sel,
                             send_eff) != "device":
            return None
        if plan:
            if name == "alltoallv":
                # the counts MATRIX is cross-rank state: the first
                # start() assembles it and builds (the build then sticks
                # in the program + exec caches for every later start)
                return False
            return self.prewarm(name, n, np.dtype(send_eff.dtype),
                                opn or "sum", root)
        if name == "alltoallv":
            local = _VDeposit(_pack_v(sendbuf, scounts, sdispls), scounts)
        else:
            local = _as_local(sendbuf, recvbuf, n)
        return self._build_nonblocking(comm, name, local, opn or "sum",
                                       root, recvbuf, rcounts, rdispls)

    def _build_nonblocking(self, comm, name: str, local, op: str,
                           root: int, recvbuf, rcounts=None,
                           rdispls=None):
        """The i-collective as an NBC DAG (deposit CALL -> per-segment
        POLLs -> completion CALL); returns the schedule's Request."""
        from ..core.errors import MPIException, MPIX_ERR_PROC_FAILED
        from .nbc import engine as nbc_engine
        from .nbc.dag import SchedDAG
        rv = self.rv
        rank = self.rank
        seq = self._nb_seq
        self._nb_seq += 1
        n, dtype = self._slot_extent(local)
        segs = self._nb_segments(name, n, dtype)
        dag = SchedDAG()

        def deposit():
            with rv.nb_lock:
                if rv.nb_failed:
                    raise MPIException(
                        MPIX_ERR_PROC_FAILED,
                        f"device nonblocking {name}: a peer rank failed")
                rec = rv.nb_calls.get(seq)
                if rec is None:
                    rec = rv.nb_calls[seq] = {
                        "slots": [None] * self.size, "arrived": 0,
                        "shards": None, "counts": None,
                        "outs": [None] * len(segs),
                        "t0": [None] * len(segs),
                        "landed": [False] * len(segs),
                        "picked": 0}
                rec["slots"][rank] = local
                rec["arrived"] += 1
        dep = dag.call(deposit)
        polls = []
        for si, (off, ln) in enumerate(segs):
            polls.append(dag.poll(
                lambda si=si, off=off, ln=ln: self._nb_poll(
                    comm, name, seq, si, off, ln, dtype, op, root,
                    len(segs)),
                after=(dep,)))
        dag.call(lambda: self._nb_finish(name, seq, recvbuf, rcounts,
                                         rdispls),
                 after=tuple(polls))
        req = nbc_engine.start(comm, dag, f"dev-i{name}")
        req.device_nbc = True
        return req

    def _nb_poll(self, comm, name: str, seq: int, si: int, off: int,
                 ln: int, dtype, op: str, root: int, nseg: int) -> bool:
        """One engine pump of a parked device segment. False while peers
        are still arriving or the dispatch is in flight; the launch
        itself happens here, on the first poll past full arrival."""
        import time as _time

        from .. import mpit
        from ..core.errors import MPIException, MPIX_ERR_PROC_FAILED
        rv = self.rv
        if rv.nb_failed:
            raise MPIException(
                MPIX_ERR_PROC_FAILED,
                f"device nonblocking {name}: a peer rank failed")
        with rv.nb_lock:
            rec = rv.nb_calls.get(seq)
            if rec is None or rec["arrived"] < self.size:
                return False
            out = rec["outs"][si]
            if out is None:
                out = rec["outs"][si] = self._nb_launch(
                    rec, name, si, off, ln, dtype, op, root)
                rec["t0"][si] = _time.perf_counter()
                mpit.pvar("dev_nbc_segments").inc()
                tr = getattr(comm.u.engine, "tracer", None)
                if tr is not None:
                    tr.record("device", "nbc_dev_issue", "i", coll=name,
                              seg=si, of=nseg, n=int(ln))
        ready = True
        if hasattr(out, "is_ready"):
            try:
                ready = bool(out.is_ready())
            except Exception:   # dispatch already resolved: treat as done
                ready = True
        if not ready:
            return False
        with rv.nb_lock:
            rec = rv.nb_calls.get(seq)
            if rec is not None and not rec["landed"][si]:
                rec["landed"][si] = True
                dt = _time.perf_counter() - (rec["t0"][si] or 0.0)
                tr = getattr(comm.u.engine, "tracer", None)
                if tr is not None:
                    tr.record("device", "nbc_dev_complete", "i",
                              coll=name, seg=si, us=round(dt * 1e6, 3))
                from .. import metrics as _metrics
                mx = _metrics.LIVE
                if mx is not None:
                    mx.rec_us("lat_dev_nbc", dt * 1e6)
        return True

    def _nb_launch(self, rec: dict, name: str, si: int, off: int,
                   ln: int, dtype, op: str, root: int):
        """Dispatch one program segment (under nb_lock, by whichever
        rank's poll got there first). Staging happens once per call;
        segment launches are plain async jit dispatches."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        if name == "alltoallv":
            from ..ops.pallas_alltoall import packed_displs
            counts = tuple(tuple(s.scounts) for s in rec["slots"])
            _, _, in_len, _ = packed_displs(counts)
            rec["counts"] = counts
            shards = self._v_shards(rec["slots"], in_len, dtype)
            global_arr = jax.make_array_from_single_device_arrays(
                (self.size, in_len),
                NamedSharding(self.mesh, P(self._pspec0(), None)), shards)
            return self._program("alltoallv", in_len, str(dtype), "none",
                                 0, counts)(global_arr)
        if rec["shards"] is None:
            shards = []
            for r in range(self.size):
                s = rec["slots"][r]
                if is_device_array(s) and \
                        s.devices() == {self.devices[r]}:
                    shards.append(s.reshape(1, -1))
                else:
                    shards.append(jax.device_put(
                        np.asarray(s).reshape(1, -1), self.devices[r]))
            rec["shards"] = shards
        shards = rec["shards"]
        n = int(shards[0].shape[1])
        seg = shards if (off, ln) == (0, n) else \
            [s[:, off:off + ln] for s in shards]
        global_arr = jax.make_array_from_single_device_arrays(
            (self.size, ln),
            NamedSharding(self.mesh, P(self._pspec0(), None)), seg)
        return self._program(name, ln, str(dtype), op, root)(global_arr)

    def _nb_finish(self, name: str, seq: int, recvbuf, rcounts,
                   rdispls) -> None:
        """Completion CALL: every segment polled ready — land this
        rank's output shards in recvbuf, retire the call record once the
        last rank picked up."""
        rv = self.rv
        with rv.nb_lock:
            rec = rv.nb_calls[seq]
            outs = list(rec["outs"])
        parts = []
        for out in outs:
            mine = None
            for s in out.addressable_shards:
                if s.device == self.device:
                    mine = s.data
                    break
            parts.append(np.asarray(mine).reshape(-1))
        res = parts[0] if len(parts) == 1 else np.concatenate(parts)
        if name == "alltoallv":
            self._deliver_v(res, recvbuf, rcounts, rdispls)
        else:
            _deliver(res, recvbuf)
        with rv.nb_lock:
            rec["picked"] += 1
            if rec["picked"] >= self.size:
                rv.nb_calls.pop(seq, None)

    def prewarm(self, name: str, n: int, dtype, op: str = "sum",
                root: int = 0, extra=None) -> bool:
        """Persistent-init hook: build (or exec-cache fetch) every
        program signature a start() of this call will dispatch, so the
        per-start cost is rendezvous + dispatch only. Returns False when
        a build fails (start falls back to building lazily)."""
        try:
            dt = np.dtype(dtype)
            for _, ln in self._nb_segments(name, n, dt):
                self._program(name, ln, str(dt), op, root, extra)
            return True
        except Exception:   # noqa: BLE001 — warm-up must never fail init
            return False


class HBMSlotChannel(DeviceCollChannel):
    """All bound ranks share ONE device: collectives run through an HBM
    slot segment — the device-side analog of the reference's slotted
    shared-memory collective segment (ch3_shmem_coll.c:527-528; see
    ops/pallas_hbm.py). Every rank deposits at the rendezvous, the
    leader stages one planar ``(R, n)`` slot array and runs one program:

      * allreduce/reduce: one fused slot-reduce pass writing the result
        ONCE; the broadcast is zero-copy (every rank's result is a view
        of the shared slot) — ``R*m`` read + ``m`` written instead of
        the materialized ``2*R*m``.
      * allgather: the slot array *is* the result (no device compute).
      * alltoall: one transpose of the slot array.
      * reduce_scatter_block: slot-reduce, then per-rank slice views.
      * bcast: stage the root slot only; all ranks share it.

    Used when more ranks than devices are bound (the mpirun-on-one-chip
    model); the 1:1 mesh binding uses DeviceCollChannel above.
    """

    LEVELS = ("chip",)
    SUPPORTED = ("allreduce", "reduce", "bcast", "allgather", "alltoall",
                 "reduce_scatter_block")

    def __init__(self, device, rendezvous: _Rendezvous, rank: int,
                 size: int):
        self.mesh = None
        self.axis = None
        self.rv = rendezvous
        self.rank = rank
        self.device = device
        self.devices = [device] * size
        self.size = size
        self._programs: Dict = {}
        self._nb_seq = 0
        # flipped (shared via the rendezvous, since each rank holds its
        # own channel object) when Mosaic rejects the fused kernel on
        # this TPU generation: reductions fall back to the XLA path
        self.rv.no_pallas = getattr(self.rv, "no_pallas", False)

    def _use_pallas(self, op: str) -> bool:
        from ..ops import pallas_hbm as ph
        return op == "sum" and ph.HAVE_PALLAS and not self.rv.no_pallas

    def _chan_desc(self) -> str:
        return f"slot{self.size}x{self.device.platform}"

    def _build(self, name: str, n: int, op: str, root: int, extra=None):
        import jax
        import jax.numpy as jnp

        from ..ops import pallas_hbm as ph
        R = self.size
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
               "prod": jnp.prod}[op or "sum"]

        if name in ("allreduce", "reduce"):
            if self._use_pallas(op):
                def f(x):
                    return ph.hbm_slot_allreduce(x)
            else:
                def f(x):
                    return red(x, axis=0)
        elif name == "bcast":
            def f(x):                       # staged root slot [n]
                return x
        elif name == "allgather":
            def f(x):                       # [R, n] -> [R*n], zero compute
                return x.reshape(R * n)
        elif name == "alltoall":
            c = n // R

            def f(x):                       # [R, n] -> [R, R, c] transpose
                return jnp.transpose(x.reshape(R, R, c), (1, 0, 2))
        elif name == "reduce_scatter_block":
            if self._use_pallas(op):
                def f(x):
                    return ph.hbm_slot_allreduce(x)
            else:
                def f(x):
                    return red(x, axis=0)
        else:  # pragma: no cover
            raise KeyError(name)
        return jax.jit(f)

    def _leader(self, name: str, op: str, root: int) -> List:
        """Leader compute: stage the planar slot array on the one
        device, run the program, share/scatter the result."""
        import jax

        rv = self.rv
        R = self.size
        n, dtype = self._slot_extent(rv.slots[root])
        if name == "bcast":
            x = rv.slots[root]
            x = (x.reshape(n) if is_device_array(x)
                 else jax.device_put(
                     np.asarray(x).reshape(n), self.device))
        elif all(is_device_array(s) and s.devices() == {self.device}
                 for s in rv.slots):
            import jax.numpy as jnp
            x = jnp.stack([s.reshape(n) for s in rv.slots])
        else:
            # host slots, or device arrays committed elsewhere on a
            # multi-device host: stage everything onto the slot device
            x = jax.device_put(
                np.stack([np.asarray(s).reshape(n)
                          for s in rv.slots]), self.device)
        prog = self._program(name, n, str(dtype), op, root)
        try:
            out = jax.block_until_ready(prog(x))
        except Exception:
            if not self._use_pallas(op):
                raise
            # Mosaic rejected the fused kernel on this TPU generation
            # (bench/autotune catch the same failure mode): fall back to
            # the XLA reduction for the life of this binding
            log.warn("pallas slot kernel failed for %s; falling back to "
                     "the XLA reduction path", name)
            self.rv.no_pallas = True
            self._programs.clear()
            prog = self._program(name, n, str(dtype), op, root)
            out = jax.block_until_ready(prog(x))
        if name == "alltoall":
            return [out[r] for r in range(R)]
        if name == "reduce_scatter_block":
            c = n // R
            return [out[r * c:(r + 1) * c] for r in range(R)]
        # the zero-copy share: every rank gets the same array
        return [out] * R


class DeviceFoldChannel(DeviceCollChannel):
    """Leaders-per-chip fold: more ranks than devices, but more than one
    device — the middle binding between the 1:1 mesh channel and the
    single-device slot channel (the two-level shmem/leader split of
    create_2level_comm.c, with the chip standing in for the node).

    ``n`` ranks over ``ndev`` devices, ``k = n // ndev`` ranks per chip,
    rank ``r`` on chip ``r // k`` (blocked, so a chip's ranks own
    contiguous result blocks). Each collective runs in two levels:

      * **chip fold** — every chip's ``k`` deposited slots are staged as
        one planar ``(k, n)`` array on that chip and folded in HBM (the
        fused slot-reduce kernel for sum, the XLA reduction otherwise;
        concatenation for allgather), exactly the slot channel's move
        applied per chip;
      * **ICI phase** — the ``ndev`` folded shards form one mesh-sharded
        global array and ride the ordinary mesh program (ring RS/AG
        tiers, per-axis torus phases when the mesh is multi-axis), built
        over the CHIP count (``_mesh_extent``).

    Results fan back zero-copy per chip: every rank on a chip shares its
    chip's output shard (slices of it for reduce_scatter_block).
    alltoall(v) has no fold composition (per-peer payloads cross chips
    pairwise) and keeps the host path; nonblocking calls take the host
    schedule (counted dev_coll_fallback_nbc).
    """

    LEVELS = ("chip", "ici")
    SUPPORTED = ("allreduce", "reduce", "bcast", "allgather",
                 "reduce_scatter_block")

    def __init__(self, mesh, axis, rendezvous: _Rendezvous, rank: int,
                 nranks: int):
        self.mesh = mesh
        if isinstance(axis, (tuple, list)):
            self.axes: Tuple[str, ...] = tuple(str(a) for a in axis)
        else:
            self.axes = (str(axis),)
        self.axis = self.axes[0]
        self.rv = rendezvous
        self.rank = rank
        mesh_devs = list(np.asarray(mesh.devices).reshape(-1))
        self.ndev = len(mesh_devs)
        self.k = nranks // self.ndev
        self.size = nranks
        self.chip = rank // self.k
        self.device = mesh_devs[self.chip]
        # rank -> its chip's device (the _leader/_deliver contract)
        self.devices = [mesh_devs[r // self.k] for r in range(nranks)]
        self._mesh_devices = mesh_devs
        self._programs: Dict = {}
        self._nb_seq = 0
        # shared via the rendezvous, like the slot channel: Mosaic
        # rejecting the fused fold kernel demotes every chip's fold to
        # the XLA reduction for the life of the binding
        self.rv.no_pallas = getattr(self.rv, "no_pallas", False)

    def _mesh_extent(self) -> int:
        return self.ndev

    def _chan_desc(self) -> str:
        return f"fold{self.size}r{self.ndev}d_{super()._chan_desc()}"

    def nonblocking(self, comm, name: str, *a, plan: bool = False):
        return None     # host NBC schedule (fold has no DAG segments yet)

    def _use_pallas(self, op: str) -> bool:
        from ..ops import pallas_hbm as ph
        return op == "sum" and ph.HAVE_PALLAS and not self.rv.no_pallas

    def _fold_prog(self, op: str):
        """Per-chip fold program: the HBM fused slot-reduce when it
        lowers, the XLA reduction otherwise (cached like any program)."""
        key = ("chipfold", 0, "", op, 0, None)
        got = self._programs.get(key)
        if got is None:
            import jax
            import jax.numpy as jnp

            from ..ops import pallas_hbm as ph
            red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
                   "prod": jnp.prod}[op or "sum"]
            if self._use_pallas(op):
                def f(x):
                    return ph.hbm_slot_allreduce(x)
            else:
                def f(x):
                    return red(x, axis=0)
            got = self._programs[key] = jax.jit(f)
        return got

    def _chip_stack(self, j: int, n: int, dtype):
        """Chip ``j``'s k deposited slots as one planar (k, n) array on
        its device (device-resident slots stack in place)."""
        import jax
        import jax.numpy as jnp
        sl = self.rv.slots[j * self.k:(j + 1) * self.k]
        dev = self._mesh_devices[j]
        if all(is_device_array(s) and s.devices() == {dev} for s in sl):
            return jnp.stack([s.reshape(n) for s in sl])
        return jax.device_put(
            np.stack([np.asarray(s).reshape(n) for s in sl]), dev)

    def _fold_chip(self, j: int, n: int, dtype, op: str):
        """Fold chip ``j``'s slots to one [n] contribution (level 1)."""
        import jax
        if self.k == 1:
            s = self.rv.slots[j]
            if is_device_array(s) and \
                    s.devices() == {self._mesh_devices[j]}:
                return s.reshape(n)
            return jax.device_put(np.asarray(s).reshape(n),
                                  self._mesh_devices[j])
        x = self._chip_stack(j, n, dtype)
        try:
            return self._fold_prog(op)(x)
        except Exception:
            if not self._use_pallas(op):
                raise
            log.warn("pallas chip-fold kernel failed; falling back to "
                     "the XLA reduction path")
            self.rv.no_pallas = True
            self._programs.pop(("chipfold", 0, "", op, 0, None), None)
            return self._fold_prog(op)(x)

    def _leader(self, name: str, op: str, root: int) -> List:
        """Leader compute: fold per chip, run the mesh program over the
        folded shards, fan the chip outputs back to their ranks."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rv = self.rv
        nd, k = self.ndev, self.k
        n, dtype = self._slot_extent(rv.slots[0])
        shards, prog_root, prog_n = [], 0, n
        if name == "bcast":
            # only the root chip's shard matters: stage the root rank's
            # payload there, zero-fill the rest (the mesh bcast program
            # overwrites them)
            prog_root = root // k
            for j in range(nd):
                if j == prog_root:
                    s = rv.slots[root]
                    s = (s.reshape(1, n) if is_device_array(s)
                         and s.devices() == {self._mesh_devices[j]}
                         else jax.device_put(
                             np.asarray(s).reshape(1, n),
                             self._mesh_devices[j]))
                else:
                    s = jax.device_put(np.zeros((1, n), dtype),
                                       self._mesh_devices[j])
                shards.append(s)
        elif name == "allgather":
            # chip fold is CONCATENATION: blocked rank->chip mapping
            # makes the stacked chip payload already rank-ordered
            prog_n = k * n
            for j in range(nd):
                shards.append(self._chip_stack(j, n, dtype)
                              .reshape(1, prog_n))
        else:   # allreduce / reduce / reduce_scatter_block
            for j in range(nd):
                shards.append(self._fold_chip(j, n, dtype, op)
                              .reshape(1, n))
        global_arr = jax.make_array_from_single_device_arrays(
            (nd, prog_n),
            NamedSharding(self.mesh, P(self._pspec0(), None)), shards)
        out = self._program(name, prog_n, str(dtype), op, prog_root)(
            global_arr)
        per_dev: Dict = {}
        for s in out.addressable_shards:
            per_dev[s.device] = s.data
        if name == "reduce_scatter_block":
            # chip shard = its k ranks' contiguous blocks: slice per rank
            c = (n // nd) // k
            res = []
            for r in range(self.size):
                blk = per_dev[self.devices[r]].reshape(-1)
                s = r % k
                res.append(blk[s * c:(s + 1) * c])
            return res
        # zero-copy share per chip: every rank gets its chip's shard
        return [per_dev[self.devices[r]] for r in range(self.size)]


def _dense_displs(counts) -> List[int]:
    """Dense prefix displacements (the canonical packed layout)."""
    out, off = [], 0
    for c in counts:
        out.append(off)
        off += int(c)
    return out


def _pack_v(sendbuf, scounts, sdispls):
    """This rank's alltoallv sends packed densely in peer order (the
    canonical layout the device kernel's displacement tables assume).
    Device arrays stay on device; dense user layouts are zero-copy."""
    if is_device_array(sendbuf):
        flat = sendbuf.reshape(-1)
        if list(sdispls) == _dense_displs(scounts):
            return flat[:int(sum(scounts))]
        import jax.numpy as jnp
        parts = [flat[sdispls[j]:sdispls[j] + scounts[j]]
                 for j in range(len(scounts)) if scounts[j]]
        return jnp.concatenate(parts) if parts else flat[:0]
    arr = np.asarray(sendbuf).reshape(-1)
    if list(sdispls) == _dense_displs(scounts):
        return np.ascontiguousarray(arr[:int(sum(scounts))])
    parts = [arr[sdispls[j]:sdispls[j] + scounts[j]]
             for j in range(len(scounts)) if scounts[j]]
    return (np.ascontiguousarray(np.concatenate(parts)) if parts
            else arr[:0].copy())


def _as_local(sendbuf, recvbuf, count: int, in_place_start: int = 0):
    """This rank's contribution as a flat [count] array (device or host).
    MPI_IN_PLACE reads from recvbuf; ``in_place_start`` selects the
    rank's chunk (allgather-style in-place semantics)."""
    buf = sendbuf
    start = 0
    if type(sendbuf).__name__ == "_InPlace":
        buf = recvbuf
        start = in_place_start
    if is_device_array(buf):
        return buf.reshape(-1)[start:start + count]
    return np.ascontiguousarray(
        np.asarray(buf).reshape(-1)[start:start + count])


def _deliver(out, recvbuf):
    """Write the device result into a host recvbuf (host-staged mode) or
    hand the flat device array back (device-resident mode — the comm
    methods return it to the caller)."""
    if recvbuf is None or is_device_array(recvbuf) \
            or type(recvbuf).__name__ == "_InPlace":
        return out.reshape(-1)
    host = np.asarray(out).reshape(-1)
    dst = np.asarray(recvbuf)
    if dst.size == host.size:
        # copyto writes through views, including non-contiguous ones
        # (a flat reshape of a strided view would silently copy)
        np.copyto(dst, host.reshape(dst.shape))
    else:
        if not dst.flags.c_contiguous:
            raise ValueError(
                "device collective: non-contiguous recvbuf larger than "
                "the result is not supported")
        dst.reshape(-1)[:host.size] = host
    return None


# ---------------------------------------------------------------------------
# per-comm install (the init_MV2_collops moment)
# ---------------------------------------------------------------------------

# wrapper name -> cvar prefix (reduce_scatter_block shares the
# REDUCE_SCATTER override and alltoallv the ALLTOALL one, matching the
# MPI-level collective family)
_CVAR_OF = {"allreduce": "ALLREDUCE", "bcast": "BCAST",
            "allgather": "ALLGATHER", "alltoall": "ALLTOALL",
            "alltoallv": "ALLTOALL",
            "reduce": "REDUCE", "reduce_scatter_block": "REDUCE_SCATTER"}


def _select_transport(comm, name: str, nbytes: int, op, buf) -> str:
    """'device' or 'host' for this call — step 2 of the tuning order
    (coll/tuning.py docstring). Note: the decision must be identical on
    every rank of the call; all inputs (msg size, op, dtype, env) are
    required-uniform by MPI except buffer residency, which therefore must
    also be uniform across ranks (device arrays everywhere or nowhere)."""
    cfg = get_config()
    forced = cfg.get(f"{_CVAR_OF[name]}_ALGO", "")
    lowers = ((op is None or _op_name(op) is not None)
              and _dtype_ok(buf))
    if forced == "device":
        if not lowers:
            log.warn("%s forced to device but op/dtype does not lower; "
                     "using host path", name)
            return "host"
        return "device"
    if forced:
        return "host"          # a named host algorithm wins
    if not cfg["USE_DEVICE_COLL"] or not lowers:
        return "host"
    if is_device_array(buf):
        return "device"        # already resident: never stage through host
    if name == "alltoallv":
        # the one size input that is NOT required-uniform: each rank
        # keys on its own sum(scounts), and a zero-count row is legal —
        # a size-gated decision could diverge (one rank host, peers
        # device) and deadlock the rendezvous, so the v-variant always
        # takes the device path once the uniform gates pass
        return "device"
    # host buffer: crossover (autotuner-overridable)
    from .tuning import device_crossover
    return "device" if nbytes >= device_crossover(name, comm) else "host"


def _dtype_ok(buf) -> bool:
    if not hasattr(buf, "dtype"):
        return False
    return _dtype_lowers(np.dtype(buf.dtype))


def install_device_coll(comm, channel: DeviceCollChannel) -> None:
    """Overwrite the device-capable entries of ``comm.coll_fns`` with
    transport-selecting wrappers — the channel's init_MV2_collops moment
    (ch3i_comm.c:27-100). The host entries installed by install_coll_ops
    remain the fallback."""
    from .tuning import install_coll_ops
    if not comm.coll_fns:
        install_coll_ops(comm)
    host = dict(comm.coll_fns)
    comm.device_channel = channel
    sz = comm.size

    # per-coll (bytes-on-the-wire, op-position, recv-count) metadata; the
    # args tuple `a` excludes the leading comm (core/comm.py signatures)
    meta = {
        "allreduce": (lambda a: a[2] * a[3].size, 4, lambda a: a[2]),
        "reduce": (lambda a: a[2] * a[3].size, 4, lambda a: a[2]),
        "bcast": (lambda a: a[1] * a[2].size, None, lambda a: a[1]),
        "allgather": (lambda a: a[2] * a[3].size * sz, None,
                      lambda a: a[2] * sz),
        "alltoall": (lambda a: a[2] * a[3].size * sz, None,
                     lambda a: a[2] * sz),
        "reduce_scatter_block": (lambda a: a[2] * a[3].size * sz, 4,
                                 lambda a: a[2]),
    }

    def wrap(name):
        hostfn = host[name]
        devfn = getattr(channel, name)
        nbytes_of, op_pos, out_count_of = meta[name]

        def entry(comm_, *a):
            buf = a[0]
            if type(buf).__name__ == "_InPlace" and len(a) > 1:
                buf = a[1]   # selection looks at the effective buffer
            op = a[op_pos] if op_pos is not None else None
            if _select_transport(comm_, name, nbytes_of(a), op,
                                 buf) == "device":
                return devfn(comm_, *a)
            # host path selected (forced algo / op or dtype doesn't lower):
            # device-array buffers are staged through the host and the
            # result pushed back to this rank's device
            if name == "bcast":
                if not is_device_array(a[0]):
                    return hostfn(comm_, *a)
                import jax
                h = np.asarray(a[0])
                hostfn(comm_, h, *a[1:])
                return jax.device_put(h, channel.device)
            send, recv = a[0], a[1]
            if not (is_device_array(send) or is_device_array(recv)):
                return hostfn(comm_, *a)
            if type(send).__name__ == "_InPlace" and is_device_array(recv):
                raise ValueError("MPI_IN_PLACE with a device recvbuf is "
                                 "not supported on the host transport")
            import jax
            send_h = np.asarray(send) if is_device_array(send) else send
            recv_h = recv
            if recv_h is None or is_device_array(recv_h):
                if name == "reduce" and comm_.rank != a[5]:
                    recv_h = None
                else:
                    recv_h = np.empty((out_count_of(a),),
                                      dtype=np.asarray(send_h).dtype)
            hostfn(comm_, send_h, recv_h, *a[2:])
            if recv_h is None:
                return None
            return jax.device_put(recv_h, channel.device)
        return entry

    for name in meta:
        if name not in channel.SUPPORTED:
            continue    # e.g. alltoall on the fold channel: host path
        comm.coll_fns[name] = wrap(name)

    # alltoallv: its own wrapper — the signature puts recvbuf at a[3]
    # (not a[1]) and the transport decision keys on this rank's send
    # total. Device tier needs the mesh channel (the slot channel keeps
    # its host path: per-peer variable counts have no slot-transpose).
    host_a2av = host.get("alltoallv")
    if host_a2av is not None and channel.mesh is not None \
            and "alltoallv" in channel.SUPPORTED:
        def a2av_entry(comm_, sendbuf, scounts, sdispls, recvbuf,
                       rcounts, rdispls, datatype):
            buf = sendbuf
            if type(buf).__name__ == "_InPlace":
                buf = recvbuf
            nbytes = int(sum(scounts)) * datatype.size
            if type(sendbuf).__name__ != "_InPlace" and \
                    _select_transport(comm_, "alltoallv", nbytes, None,
                                      buf) == "device":
                return channel.alltoallv(
                    comm_, sendbuf, list(scounts),
                    list(sdispls) if sdispls is not None
                    else _dense_displs(scounts),
                    recvbuf, list(rcounts),
                    list(rdispls) if rdispls is not None
                    else _dense_displs(rcounts), datatype)
            if is_device_array(sendbuf) or is_device_array(recvbuf):
                raise ValueError(
                    "alltoallv: device-array buffers need the device "
                    "transport (host algorithm was forced)")
            return host_a2av(comm_, sendbuf, scounts, sdispls, recvbuf,
                             rcounts, rdispls, datatype)
        comm.coll_fns["alltoallv"] = a2av_entry


def build_nonblocking_request(comm, name: str, *a):
    """Satellite routing hook for coll/nonblocking.py: i-collectives on
    a device-capable comm ride the device NBC tier; calls the channel
    cannot route (op/dtype/residency/size, or the slot channel) count
    dev_coll_fallback_nbc and take the host schedule. Returns the
    schedule Request or None."""
    channel = getattr(comm, "device_channel", None)
    if channel is None or getattr(comm, "is_inter", False):
        return None
    try:
        req = channel.nonblocking(comm, name, *a)
    except Exception as e:   # noqa: BLE001 — routing must not kill the call
        log.warn("device nonblocking %s routing failed (%r); host "
                 "schedule", name, e)
        req = None
    if req is None:
        from .. import mpit
        mpit.pvar("dev_coll_fallback_nbc").inc()
    return req


def prewarm_persistent(comm, name: str, *a) -> bool:
    """MPI_*_init hook (core/comm.py _coll_init): when a start() of this
    persistent collective would route to the device tier, build its
    program signatures NOW through the exec-cache seam
    (runtime/daemon.py) — a warm daemon cache turns the init into a
    deserialize and every start() into rendezvous + dispatch only."""
    channel = getattr(comm, "device_channel", None)
    if channel is None:
        return False
    try:
        return bool(channel.nonblocking(comm, name, *a, plan=True))
    except Exception as e:   # noqa: BLE001 — warm-up must never fail init
        log.warn("persistent %s pre-warm failed (%r)", name, e)
        return False


# ---------------------------------------------------------------------------
# binding helpers (harness / launcher entry points)
# ---------------------------------------------------------------------------

def bind_universes(universes, mesh=None, axis=None) -> bool:
    """Bind each thread-rank universe's COMM_WORLD to the device mesh —
    called by the in-process harness (run_ranks(device_mesh=...)) and the
    --vpod launcher before rank threads start. Returns False (no-op) when
    the mesh cannot cover the ranks.

    ``axis`` defaults to the mesh's axis names (ALL of them — a
    multi-axis mesh binds the multi-axis torus channel with ranks
    row-major over the flattened device order); pass one name or an
    ordered tuple to span a subset. Geometry selects the channel:

      * ``#devices == n``  -> DeviceCollChannel (1:1, single- or
        multi-axis mesh programs)
      * ``1 < #devices < n`` with ``n % #devices == 0``
                           -> DeviceFoldChannel (leaders-per-chip
        HBM fold, then the mesh program over chips)
      * one device         -> HBMSlotChannel (slot segment)
    """
    import jax

    n = len(universes)
    slot_device = None
    fold = False
    if mesh is None:
        from ..parallel.mesh import make_mesh
        devs = jax.devices()
        if len(devs) >= n:
            if isinstance(axis, (tuple, list)) and len(axis) > 1:
                # multi-axis request: near-square factorization of the
                # n ranks over the named axes (mesh_shape_for)
                mesh = make_mesh(None, tuple(axis), devs[:n])
            else:
                one = axis[0] if isinstance(axis, (tuple, list)) else axis
                mesh = make_mesh((n,), (one or "x",), devs[:n])
        elif len(devs) > 1 and n % len(devs) == 0:
            # more ranks than devices, evenly: the two-level fold —
            # ranks co-resident on a chip fold in HBM, chips ride ICI
            fold = True
            mesh = make_mesh((len(devs),), ("x",), devs)
            log.info("%d ranks over %d devices; binding the "
                     "leaders-per-chip fold channel (%d ranks/chip)",
                     n, len(devs), n // len(devs))
        else:
            # indivisible co-residence: the HBM slot-segment channel on
            # the first device (mpirun on one chip; the shm analog)
            slot_device = devs[0]
            log.info("%d ranks > %d devices; binding the HBM "
                     "slot-segment channel on %s", n, len(devs),
                     slot_device)
    if mesh is not None and slot_device is None:
        if axis is None:
            names = tuple(mesh.axis_names)
            axis = names[0] if len(names) == 1 else names
        msize = int(np.prod(list(mesh.shape.values())))
        if msize == 1 and n > 1:
            slot_device = list(np.asarray(mesh.devices).reshape(-1))[0]
        elif not fold and msize != n:
            if 1 < msize < n and n % msize == 0:
                fold = True
            else:
                log.warn("mesh shape %s does not match %d ranks; host "
                         "path only", dict(mesh.shape), n)
                return False
    rv = _Rendezvous(n)
    for r, u in enumerate(universes):
        if slot_device is not None:
            ch = HBMSlotChannel(slot_device, rv, r, n)
        elif fold:
            ch = DeviceFoldChannel(mesh, axis, rv, r, n)
        else:
            ch = DeviceCollChannel(mesh, axis, rv, r)
        install_device_coll(u.comm_world, ch)
    # arch is known here (jax initialized): pull in the measured tuning
    # profile for this mesh, if one is committed/pointed-to
    from ..autotune import load_default_profile
    load_default_profile()
    return True

"""net2: the node-leader networking tier past the np=64 flat2 ceiling.

Three-level hierarchy's outermost ring (create_2level_comm.c's
leader_comm, scaled out): ranks are folded round-robin into
``ceil(size/64)`` groups, each group small enough to ride the
single-node machinery (flat2 waves through the plane when the group is
plane-owned and the payload fits; the scheduled binomial/recursive-
doubling shapes otherwise), and the per-group leaders bridge the
KVS/TCP lanes with one small inter-leader exchange. np 64 -> 256 (and
up to NET2_MAX_RANKS) without widening any single wave.

Group color is ``rank % ngroups`` — round-robin, not blocked — so a
group's members sit at distinct node-local indices and the flat2 lane
(MIN local index of the group) stays inside the 8-lane window even
when several groups share a node. Leaders are then exactly global
ranks ``0..ngroups-1`` (the minimum-rank member of each group under a
rank-keyed split), which keeps the leader subcomm's membership
deterministic for the KVS rendezvous.

Subcomms are built lazily with ``comm.split`` *inside* the algorithm
(a collective, but every rank of the comm reaches the same algorithm
for the same call — the tuning verdict is uniform by construction) and
cached on the comm for its lifetime. When the split cannot produce the
two-level shape (degenerate group count, failed rendezvous), the
algorithms degrade internally to the scheduled single-level shapes so
the dispatch verdict stays uniform across ranks: no rank ever takes a
different *table* row than its peers, only a different interior.

Each phase mirrors api.py's plane branch: try the flat tiers first,
fall to the scheduled algorithm — that composition (node-local flat2
wave + tiny leader exchange) is what buys the latency win over running
one 128-wide recursive doubling across the TCP lanes.
"""

from __future__ import annotations

import math
import time as _time
from typing import Optional

import numpy as np

from .. import metrics as _metrics
from ..utils.config import get_config
from ..utils.mlog import get_logger
from . import algorithms as alg

log = get_logger("netcoll")

_STATE_ATTR = "_net2_state"


def _trace_net2(name: str, comm, **args) -> None:
    """Drop a 'cplane'-lane instant at a net2 phase boundary. Python-
    side (unlike the flat/flat2 instants, which the C ring emits) —
    the leader bridge runs above the plane, so the ring never sees
    it."""
    try:
        tr = getattr(comm.u.engine, "tracer", None)
        if tr is not None:
            tr.record("cplane", f"net2_{name}", "i", **args)
    except Exception:   # tracing must never kill a collective
        pass


def _bump(name: str) -> None:
    try:
        from .. import mpit
        mpit.pvar(name).inc()
    except Exception:
        pass


class _Net2State:
    """Cached two-level split of one comm: intra group + leader ring."""

    __slots__ = ("ngroups", "intra", "leaders", "is_leader")

    def __init__(self, ngroups, intra, leaders, is_leader):
        self.ngroups = ngroups
        self.intra = intra
        self.leaders = leaders
        self.is_leader = is_leader


def net2_enabled() -> bool:
    try:
        return bool(get_config()["NET2"])
    except Exception:
        return True


def net2_applicable(comm) -> bool:
    """Gate shared by every net2 algorithm AND api.py's plane branch:
    uniform across ranks (size + launcher-uniform cvars only)."""
    from .tuning import net2_max_ranks
    if not net2_enabled():
        return False
    if getattr(comm, "is_inter", False):
        return False
    return 64 < comm.size <= net2_max_ranks()


def _state(comm) -> Optional[_Net2State]:
    """The comm's cached two-level split; built on first use (all ranks
    reach here together — split is collective but safe). None when the
    shape cannot be built, and the miss is cached too (a failed split
    must not be retried asymmetrically)."""
    st = getattr(comm, _STATE_ATTR, "__unset__")
    if st != "__unset__":
        return st
    st = None
    try:
        ngroups = math.ceil(comm.size / 64)
        if 1 < ngroups < comm.size:
            color = comm.rank % ngroups
            intra = comm.split(color, key=comm.rank)
            is_leader = intra is not None and intra.rank == 0
            leaders = comm.split(0 if is_leader else None, key=comm.rank)
            if intra is not None and (not is_leader or leaders is not None):
                st = _Net2State(ngroups, intra, leaders, is_leader)
    except Exception as e:   # degrade, never desync: every rank that
        log.warn("net2 split failed (%s): scheduled fallback", e)
        st = None            # got here falls to the same sched shape
    try:
        setattr(comm, _STATE_ATTR, st)
    except Exception:
        pass
    if st is not None:
        log.dbg(1, "net2: %d ranks -> %d groups (leader=%s)",
                  comm.size, st.ngroups, st.is_leader)
    return st


# ---------------------------------------------------------------------------
# per-phase sub-collectives: flat tier first, sched second — the same
# gate order as api.py's plane branch, applied to the SUBcomm
# ---------------------------------------------------------------------------

def _sub_plane(sub):
    from .api import _plane_engine
    return _plane_engine(sub)


def _sub_allreduce(sub, arr: np.ndarray, op, tag: int) -> np.ndarray:
    pch = _sub_plane(sub)
    if pch is not None and sub.size > 1:
        from .api import _plane_coll_max, _plane_red_ok
        if arr.nbytes <= _plane_coll_max(pch, sub) \
                and _plane_red_ok(op, arr):
            from . import flatcoll
            got = flatcoll.try_allreduce(pch, sub, np.ascontiguousarray(arr),
                                         op)
            if got is not None:
                return got
    return alg.allreduce_recursive_doubling(sub, arr, op, tag)


def _sub_reduce(sub, arr: np.ndarray, op, tag: int) -> Optional[np.ndarray]:
    """Reduce to sub rank 0; the folded array there, None elsewhere."""
    pch = _sub_plane(sub)
    if pch is not None and sub.size > 1:
        from .api import _plane_coll_max, _plane_red_ok
        if arr.nbytes <= _plane_coll_max(pch, sub) \
                and _plane_red_ok(op, arr):
            from . import flatcoll
            taken, got = flatcoll.try_reduce(pch, sub,
                                             np.ascontiguousarray(arr),
                                             op, 0)
            if taken:
                return got
    return alg.reduce_binomial(sub, arr, op, 0, tag)


def _sub_bcast(sub, data: np.ndarray, root: int, tag: int) -> None:
    pch = _sub_plane(sub)
    if pch is not None and sub.size > 1:
        from .api import _plane_coll_max
        if data.nbytes <= _plane_coll_max(pch, sub):
            from . import flatcoll
            if flatcoll.try_bcast(pch, sub, data, root):
                return
    alg.bcast_binomial(sub, data, root, tag)


def _sub_barrier(sub, tag: int) -> None:
    pch = _sub_plane(sub)
    if pch is not None and sub.size > 1:
        from . import flatcoll
        if flatcoll.try_barrier(pch, sub):
            return
    alg.barrier_dissemination(sub, tag)


# ---------------------------------------------------------------------------
# ALGOS entries (tuning-table signatures)
# ---------------------------------------------------------------------------

def allreduce_net2(comm, arr: np.ndarray, op, tag: int) -> np.ndarray:
    """fold-in-group -> leader allreduce -> fan-out-in-group. The
    fan-in-first property holds per level: no leader publishes on the
    bridge before its whole group folded (reduce completes on the
    leader), and no member reads a result its leader has not
    republished — the PR 11 wave ordering, one level up."""
    st = _state(comm) if net2_applicable(comm) else None
    if st is None:
        return alg.allreduce_reduce_scatter_allgather(comm, arr, op, tag)
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    _bump("coll_level_net")
    _trace_net2("fold", comm, groups=st.ngroups, bytes=int(arr.nbytes))
    folded = _sub_reduce(st.intra, arr, op, st.intra.next_coll_tag())
    if st.is_leader:
        _trace_net2("bridge", comm, groups=st.ngroups,
                    bytes=int(arr.nbytes))
        folded = _sub_allreduce(st.leaders, folded, op,
                                st.leaders.next_coll_tag())
    else:
        folded = np.empty_like(arr)
    _trace_net2("fanout", comm, groups=st.ngroups, bytes=int(arr.nbytes))
    out = np.ascontiguousarray(folded)
    _sub_bcast(st.intra, out, 0, st.intra.next_coll_tag())
    if mx is not None:
        mx.rec_since("lat_coll_net2", t0)
    return out


def bcast_net2(comm, data: np.ndarray, root: int, tag: int) -> None:
    """root -> its leader (when distinct) -> leader bridge -> groups.
    With round-robin colors the root's group leader is global rank
    ``root % ngroups``; the root forwards to it inside the group, so
    the bridge always radiates from a leader."""
    st = _state(comm) if net2_applicable(comm) else None
    if st is None:
        alg.bcast_binomial(comm, data, root, tag)
        return
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    _bump("coll_level_net")
    in_root_group = (comm.rank % st.ngroups) == (root % st.ngroups)
    if in_root_group:
        # root's group: in-group bcast from the ROOT's sub-rank first,
        # which lands the payload on the group leader (sub rank 0)...
        root_sub = root // st.ngroups
        _trace_net2("fold", comm, groups=st.ngroups,
                    bytes=int(data.nbytes))
        _sub_bcast(st.intra, data, root_sub, st.intra.next_coll_tag())
    if st.is_leader:
        # ...then the bridge radiates from that leader...
        _trace_net2("bridge", comm, groups=st.ngroups,
                    bytes=int(data.nbytes))
        _sub_bcast(st.leaders, data, root % st.ngroups,
                   st.leaders.next_coll_tag())
    if not in_root_group:
        # ...and every other group fans out from ITS leader.
        _trace_net2("fanout", comm, groups=st.ngroups,
                    bytes=int(data.nbytes))
        _sub_bcast(st.intra, data, 0, st.intra.next_coll_tag())
    if mx is not None:
        mx.rec_since("lat_coll_net2", t0)


def barrier_net2(comm, tag: int) -> None:
    """group barrier (arrival) -> leader barrier -> group release
    bcast. The release is a bcast, not a second barrier: members may
    not leave until their leader has crossed the bridge (first-wave
    sync per level)."""
    st = _state(comm) if net2_applicable(comm) else None
    if st is None:
        alg.barrier_dissemination(comm, tag)
        return
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    _bump("coll_level_net")
    _trace_net2("fold", comm, groups=st.ngroups, bytes=0)
    _sub_barrier(st.intra, st.intra.next_coll_tag())
    if st.is_leader:
        _trace_net2("bridge", comm, groups=st.ngroups, bytes=0)
        _sub_barrier(st.leaders, st.leaders.next_coll_tag())
    _trace_net2("fanout", comm, groups=st.ngroups, bytes=0)
    release = np.zeros(1, dtype=np.uint8)
    _sub_bcast(st.intra, release, 0, st.intra.next_coll_tag())
    if mx is not None:
        mx.rec_since("lat_coll_net2", t0)

"""Python entry to the flat-slot shared-memory collective tiers.

The small-message fast phase, two tiers sharing one dispatch surface:

  * flat (cplane.cpp cp_flat_*, size <= 8): one cache-line-padded
    seqlock'd slot per comm rank in a per-(context, lane) region of the
    node's flat segment — fan-in to the leader (who reduces in place),
    seq-stamped broadcast out.
  * hierarchical flat2 (cp_flat2_*, 8 < size <= cp_flat2_max_ranks):
    leaders-of-k two-level waves — members fold intra-group into their
    group leader, leaders exchange partials in a leaders-only
    sub-region, seq-stamped fan-out back through the group blocks —
    plus a single-writer MULTICAST bcast (the root writes the payload
    once into the region's mcast block; N readers consume under the
    seqlock wave discipline).

Python ranks and C-ABI ranks (via native/mpi/fastpath.c) call the SAME
cp_flat_*/cp_flat2_* engines, so the schedule is identical across the
two ABIs by construction; this module only implements the dispatch
gate and per-comm call numbering.

Dispatch DETERMINISM is the load-bearing property: every member of a
comm — python-API or C-ABI — must reach the same flat-or-not verdict
for each collective, from the call signature and static comm state
alone. The gates here mirror fastpath.c's fpc_flat_next: plane-owned
intra comm, size <= cp_flat_nslots, payload <= cp_flat_payload_max,
(op, dtype) in the shared cp_flat_op_ok kernel table, region mappable
for (ctx_coll, lane).

Call numbering: seq = region base (broadcast seq at the comm's first
flat collective) + number of flat collectives issued on the comm. In a
C-ABI process both this module and the C dispatch can issue flat calls
on one comm, so the counter is unified through libmpi.so's
mv2t_fp_flat_next (reached via the process-global symbol table); pure
python ranks keep the counter on the comm object.
"""

from __future__ import annotations

import ctypes
import time as _time
from typing import Optional

import numpy as np

from .. import metrics as _metrics
from ..core import op as opmod
from ..core.errors import (MPIException, MPI_ERR_INTERN, MPI_ERR_TRUNCATE,
                           MPIX_ERR_PROC_FAILED)

# numpy dtype -> cplane kernel dtype code (the fl_reduce switch). Keyed
# by (kind, itemsize) so aliases (AINT/LONG/LONG_LONG...) collapse to
# one kernel the way the C table does.
_DT_CODES = {
    ("u", 1): 0, ("i", 1): 1, ("i", 4): 2, ("f", 4): 3, ("f", 8): 4,
    ("i", 8): 5, ("u", 8): 6, ("i", 2): 7, ("u", 4): 10, ("u", 2): 11,
    ("f", 16): 12, ("b", 1): 13,
}

_OP_CODES = {
    opmod.SUM: 0, opmod.PROD: 1, opmod.MAX: 2, opmod.MIN: 3,
    opmod.LAND: 4, opmod.LOR: 5, opmod.BAND: 6, opmod.BOR: 7,
    opmod.BXOR: 8, opmod.LXOR: 9,
}

_c_next = None          # (mv2t_fp_flat_next, mv2t_fp_flat_poison) or False


def _libmpi_hooks():
    """The embedded C ABI's flat-counter hooks, when this process IS a
    C MPI program (libmpi.so in the global symbol table)."""
    global _c_next
    if _c_next is None:
        try:
            dl = ctypes.CDLL(None)
            nxt = dl.mv2t_fp_flat_next
            nxt.restype = ctypes.c_longlong
            nxt.argtypes = [ctypes.c_int, ctypes.c_long]
            poi = dl.mv2t_fp_flat_poison
            poi.argtypes = [ctypes.c_int]
            _c_next = (nxt, poi)
        except (OSError, AttributeError):
            _c_next = False
    return _c_next


def _dt_code(dtype: np.dtype) -> int:
    return _DT_CODES.get((dtype.kind, dtype.itemsize), -1)


class _FlatComm:
    """Per-comm flat-tier state (cached on the comm object).

    ``tier`` is 1 for the flat-slot tier (size <= cp_flat_nslots) and 2
    for the hierarchical leaders-of-k tier + multicast bcast
    (cp_flat2_*, nslots < size <= cp_flat2_max_ranks). One comm is
    served by exactly one tier — the split is on static comm size, so
    every member (and the C-ABI dispatch, fpc_flat_next/fpc_flat2_next)
    reaches the same verdict."""

    __slots__ = ("lib", "plane", "ctx", "lane", "rank", "size", "base",
                 "k", "cabi", "max_nb", "tier")

    def __init__(self, lib, plane, ctx, lane, rank, size, base, cabi,
                 max_nb, tier=1):
        self.lib = lib
        self.plane = plane
        self.ctx = ctx
        self.lane = lane
        self.rank = rank
        self.size = size
        self.base = base
        self.k = 0
        self.cabi = cabi        # C comm handle when libmpi owns numbering
        self.max_nb = max_nb
        self.tier = tier

    def next_seq(self, nb: int) -> int:
        if self.cabi is not None:
            hooks = _libmpi_hooks()
            if hooks:
                return int(hooks[0](self.cabi, nb))
        self.k += 1
        return self.base + self.k

    def poison(self, comm) -> None:
        comm._flat_state = False
        if self.cabi is not None:
            hooks = _libmpi_hooks()
            if hooks:
                hooks[1](self.cabi)


def _state(comm, pch) -> Optional[_FlatComm]:
    """The comm's flat-tier state, or None when the tier is off for it
    (cached; the verdict is deterministic in static comm state)."""
    st = comm.__dict__.get("_flat_state")
    if st is not None:
        return st if st is not False else None
    st = _build_state(comm, pch)
    comm._flat_state = st if st is not None else False
    return st


def _build_state(comm, pch) -> Optional[_FlatComm]:
    lib = pch._ring.lib
    if lib is None or not pch.plane:
        return None
    if lib.cp_any_failed(pch.plane):
        # post-failure degradation: new comms never key flat regions
        # (every in-flight wave aborts on g_any_failed anyway); the
        # sched/python tiers own collectives until the process quiesces
        return None
    if comm.size < 2:
        return None
    tier = 1
    if comm.size > lib.cp_flat_nslots():
        # hierarchical leaders-of-k tier (cp_flat2_*) past the flat
        # ceiling; the gates mirror fastpath.c's fpc_flat2_next
        if comm.size > lib.cp_flat2_max_ranks():
            return None
        if not lib.cp_flat2_ok(pch.plane):
            return None
        tier = 2
    elif not lib.cp_flat_ok(pch.plane):
        return None
    lane = None
    for r in range(comm.size):
        i = pch.local_index.get(comm.group.world_of_rank(r))
        if i is None:
            return None
        lane = i if lane is None or i < lane else lane
    lanes = lib.cp_flat2_lanes() if tier == 2 else lib.cp_flat_lanes()
    if lane >= lanes:
        return None
    if tier == 2:
        base = int(lib.cp_flat2_base(pch.plane, comm.ctx_coll, lane))
        max_nb = int(lib.cp_flat2_payload_max())
    else:
        base = int(lib.cp_flat_base(pch.plane, comm.ctx_coll, lane))
        max_nb = int(lib.cp_flat_payload_max())
    if base < 0:
        return None
    cabi = getattr(comm, "_cabi_handle", None)
    if cabi is not None and not _libmpi_hooks():
        cabi = None
    return _FlatComm(lib, pch.plane, comm.ctx_coll, lane, comm.rank,
                     comm.size, base, cabi, max_nb, tier)


def _raise_rc(st, comm, rc) -> bool:
    """Handle a failed flat wave (rc -2 peer failure / -3 stall). The
    region is already sticky-poisoned by the C side (flat_fail) and the
    comm's tier is closed here in all cases.

    Outcome depends on WHOSE failure tore the wave. g_any_failed is
    process-global, so a death anywhere aborts every in-flight wave —
    including waves of comms the dead rank was never a member of. The
    wave verdict is consistent across members (the leader decides
    before stamping the broadcast block: either every member completes
    or every member fails with its send data intact), so:

      * a failed MEMBER -> raise (typed PeerDeadError when the lease is
        readable, else plain MPIX_ERR_PROC_FAILED) — ULFM semantics;
      * an UNRELATED failure (rc -2, no member failed) -> return False:
        the caller falls through to the scheduled tier and the
        collective completes there. Without this, one SIGKILL made
        every OTHER comm's next flat collective error — which broke
        the recovery path itself (shrink -> spawn -> merge runs
        collectives on healthy comms).

    Returns False for "degrade and retry"; raises otherwise."""
    st.poison(comm)
    pch = getattr(comm.u, "plane_channel", None)
    if pch is not None and pch.plane:
        try:
            # the C lease scan may have been the detector: reconcile its
            # marks into universe.failed_ranks before deciding
            pch._reconcile_plane_failures()
        except Exception:
            pass
    from ..ft.ulfm import ft_members
    dead = next((w for w in ft_members(comm)
                 if w in comm.u.failed_ranks), None)
    if dead is not None:
        if pch is not None and dead in pch.local_index:
            from ..core.errors import PeerDeadError
            age = pch.lease_age(dead)
            raise PeerDeadError(dead, age if age is not None else 0.0,
                                "flat collective")
        raise MPIException(
            MPIX_ERR_PROC_FAILED,
            f"peer failure during flat collective (world rank {dead})")
    if rc == -2:
        return False        # collateral abort: sched tier retries
    raise MPIException(MPI_ERR_INTERN,
                       f"flat collective failed (rc {rc})")


def _ptr(a: np.ndarray) -> int:
    return a.ctypes.data if a.size else 0


def try_allreduce(pch, comm, arr: np.ndarray, op) -> Optional[np.ndarray]:
    """Run ``arr``'s allreduce on the flat tier; the reduced array, or
    None when the tier does not carry this call (caller falls through
    to the scheduled algorithms)."""
    opc = _OP_CODES.get(op)
    dtc = _dt_code(arr.dtype)
    if opc is None or dtc < 0:
        return None
    st = _state(comm, pch)
    if st is None or arr.nbytes > st.max_nb:
        return None
    if not st.lib.cp_flat_op_ok(opc, dtc):
        return None
    seq = st.next_seq(arr.nbytes)
    if seq <= 0:
        comm._flat_state = False    # C side closed the tier: stay off
        return None
    out = np.empty_like(arr)
    fn = st.lib.cp_flat2_allreduce if st.tier == 2 \
        else st.lib.cp_flat_allreduce
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    rc = fn(
        st.plane, st.ctx, st.lane, st.rank, st.size,
        ctypes.c_longlong(seq), opc, dtc, _ptr(arr), _ptr(out),
        arr.size, arr.itemsize)
    if rc != 0:
        _raise_rc(st, comm, rc)
        return None     # collateral abort: fall through to sched tier
    if mx is not None:
        mx.rec_since("lat_coll_flat2" if st.tier == 2
                     else "lat_coll_flat", t0)
    return out


def try_reduce(pch, comm, arr: np.ndarray, op,
               root: int) -> "tuple[bool, Optional[np.ndarray]]":
    """(taken, result-at-root) — result is None on non-root ranks."""
    opc = _OP_CODES.get(op)
    dtc = _dt_code(arr.dtype)
    if opc is None or dtc < 0:
        return False, None
    st = _state(comm, pch)
    if st is None or arr.nbytes > st.max_nb:
        return False, None
    if not st.lib.cp_flat_op_ok(opc, dtc):
        return False, None
    seq = st.next_seq(arr.nbytes)
    if seq <= 0:
        comm._flat_state = False
        return False, None
    out = np.empty_like(arr) if comm.rank == root else None
    fn = st.lib.cp_flat2_reduce if st.tier == 2 else st.lib.cp_flat_reduce
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    rc = fn(
        st.plane, st.ctx, st.lane, st.rank, st.size,
        ctypes.c_longlong(seq), opc, dtc, root, _ptr(arr),
        _ptr(out) if out is not None else 0, arr.size, arr.itemsize)
    if rc != 0:
        _raise_rc(st, comm, rc)
        return False, None   # collateral abort: sched tier retries
    if mx is not None:
        mx.rec_since("lat_coll_flat2" if st.tier == 2
                     else "lat_coll_flat", t0)
    return True, out


def try_bcast(pch, comm, data: np.ndarray, root: int) -> bool:
    """Broadcast ``data`` (packed bytes, filled in place on non-roots)
    on the flat tier; False when the tier does not carry this call."""
    st = _state(comm, pch)
    if st is None or data.nbytes > st.max_nb:
        return False
    seq = st.next_seq(data.nbytes)
    if seq <= 0:
        comm._flat_state = False
        return False
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    if st.tier == 2:
        # sync=1 on the comm's first flat2 wave (seq == base + 1): the
        # mcast root runs a full arrival wave so no member's lazy base
        # read can count an in-flight wave; later waves pipeline
        rc = st.lib.cp_flat2_bcast(
            st.plane, st.ctx, st.lane, st.rank, st.size,
            ctypes.c_longlong(seq), root, _ptr(data), data.nbytes,
            1 if seq == st.base + 1 else 0)
    else:
        rc = st.lib.cp_flat_bcast(
            st.plane, st.ctx, st.lane, st.rank, st.size,
            ctypes.c_longlong(seq), root, _ptr(data), data.nbytes)
    if rc == -4:
        # root sent a different byte count — the wave completed, the
        # mismatch is reported (errors/coll/bcastlength.c), the tier
        # stays healthy
        raise MPIException(MPI_ERR_TRUNCATE,
                           "bcast length mismatch across ranks")
    if rc != 0:
        _raise_rc(st, comm, rc)
        return False        # collateral abort: sched tier retries
    if mx is not None:
        mx.rec_since("lat_coll_flat2" if st.tier == 2
                     else "lat_coll_flat", t0)
    return True


def try_barrier(pch, comm) -> bool:
    st = _state(comm, pch)
    if st is None:
        return False
    seq = st.next_seq(0)
    if seq <= 0:
        comm._flat_state = False
        return False
    fn = st.lib.cp_flat2_barrier if st.tier == 2 \
        else st.lib.cp_flat_barrier
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    rc = fn(st.plane, st.ctx, st.lane, st.rank,
            st.size, ctypes.c_longlong(seq))
    if rc != 0:
        _raise_rc(st, comm, rc)
        return False        # collateral abort: sched tier retries
    if mx is not None:
        mx.rec_since("lat_coll_flat2" if st.tier == 2
                     else "lat_coll_flat", t0)
    return True

"""Collective entry points (the MPIR_<Coll>_impl analog).

Handles datatype pack/unpack + MPI_IN_PLACE, then dispatches through the
tuning layer's per-comm function table (comm.coll_fns — the
``comm_ptr->coll_fns`` seam of /root/reference/src/mpi/coll/allreduce.c:
766-771). Algorithms operate on contiguous numpy arrays (see algorithms.py).
"""

from __future__ import annotations

import time as _time
from typing import List, Optional, Sequence


import numpy as np

from .. import metrics as _metrics
from ..core.datatype import Datatype, as_bytes_view, from_numpy_dtype
from ..core.errors import MPIException, MPI_ERR_OP, MPI_ERR_ROOT, mpi_assert
from ..core.op import Op
from . import algorithms as alg


class _InPlace:
    def __repr__(self):
        return "MPI_IN_PLACE"


IN_PLACE = _InPlace()


def _packed(buf, count: int, datatype: Optional[Datatype]) -> np.ndarray:
    """Pack into the basic dtype (reductions) or bytes (movement)."""
    if datatype is None:
        datatype = from_numpy_dtype(np.asarray(buf).dtype)
    if datatype.basic is not None:
        return datatype.to_numpy(buf, count)
    return datatype.pack(buf, count)


def _packed_ro(buf, count: int, datatype: Datatype) -> np.ndarray:
    """Read-only packed VIEW for reduction sources: a contiguous basic
    dtype needs no staging copy — every reduction algorithm copies
    before it mutates, and the blocking call keeps the user buffer
    stable. On an oversubscribed host the skipped 1 MiB memcpy is paid
    by every co-located rank in turn, so it is pure serial latency."""
    if datatype.basic is not None and datatype.is_contiguous \
            and datatype.basic.itemsize == datatype.size:
        try:
            mv = as_bytes_view(buf)
            n = datatype.size * count
            if len(mv) >= n:
                return np.frombuffer(mv, dtype=np.uint8,
                                     count=n).view(datatype.basic)
        except (ValueError, TypeError):
            pass
    return _packed(buf, count, datatype)


def _unpack(arr: np.ndarray, buf, count: int,
            datatype: Optional[Datatype]) -> None:
    if datatype is None:
        datatype = from_numpy_dtype(np.asarray(buf).dtype)
    datatype.unpack(np.ascontiguousarray(arr).view(np.uint8), buf, count)


def _dt(buf, datatype):
    return datatype if datatype is not None \
        else from_numpy_dtype(np.asarray(buf).dtype)


# ---------------------------------------------------------------------------
# native-engine delegation (the C plane's collective schedules)
#
# Small host collectives on plane-owned comms run the SAME schedules and
# tags as the C fast path (native/mpi/fastpath.c fp_try_* — recursive
# doubling / binomial / dissemination with tags from cp_coll_tag's
# shared per-context counter), so python-API ranks and C-ABI ranks
# interoperate on the same wire. Checked BEFORE next_coll_tag so
# delegated collectives never perturb the legacy tag sequence.
# ---------------------------------------------------------------------------

def _plane_engine(comm):
    pch = getattr(comm.u, "plane_channel", None)
    if pch is None or not pch.plane or comm.is_inter \
            or not getattr(comm, "_plane_owned", False):
        return None
    if not pch._wired and comm.size > 1:
        # lazy-wiring gate: tier choice (flat wave vs schedule vs
        # arena) consults the unanimous node agreement, and EVERY
        # member must reach the same verdict or the collective
        # deadlocks across tiers. A collective is the safe place to
        # block: all members are known to arrive.
        pch.ensure_wired()
    # graceful tier degradation (failure containment): once this comm is
    # revoked or has a failed member, the python tier owns the operation
    # — its ULFM semantics raise MPIX_ERR_PROC_FAILED/REVOKED uniformly
    # instead of entering a flat wave or C schedule some members will
    # never join. A member that races ahead of the detection still
    # unwinds: the dead peer's lease expires inside its flat wait /
    # wait quantum (-2) and the C gather checks per-member failure.
    if comm.revoked:
        return None
    if comm.u.failed_ranks:
        from ..ft.ulfm import ft_members
        if any(w in comm.u.failed_ranks for w in ft_members(comm)):
            return None
    return pch


def _plane_thr(pch) -> int:
    from ..utils.config import get_config
    thr = get_config()["SMP_EAGERSIZE"]
    cap = pch.plane_eager_max()
    return min(thr, cap) if cap else thr


def _plane_coll_max(pch, comm) -> int:
    """Largest payload the plane collective tier carries for ``comm``.

    A comm with any C-ABI member MUST use the C fast path's fpc_enter
    cap (FP_COLL_MAX, CMA-conditioned) on every member — a mixed
    C/python job deadlocks if two members pick different algorithm
    tiers for one collective, and a C-ABI process always dispatches
    through fastpath.c first. A pure python comm keeps the eager size:
    above it the tuning tier (arena/slotted) beats the interpreter-hop
    schedules. Deterministic in static membership, so every member —
    including the C processes' own python-side fallback dispatch —
    reaches the same verdict."""
    from ..utils.config import get_config
    thr = _plane_thr(pch)
    if not pch.cma_ok:
        return thr              # rendezvous hops need the CMA agreement
    mixed = comm.__dict__.get("_plane_mixed")
    if mixed is None:
        cabi = pch.cabi_ranks
        mixed = bool(cabi) and any(
            comm.group.world_of_rank(r) in cabi
            for r in range(comm.size))
        comm._plane_mixed = mixed
    if not mixed:
        return thr
    cap = int(get_config()["FP_COLL_MAX"])
    return cap if cap > thr else thr


def _plane_coll_tag(pch, comm) -> int:
    return pch._ring.lib.cp_coll_tag(pch.plane, comm.ctx_coll)


def _plane_red_ok(op: Op, arr: np.ndarray) -> bool:
    """Same (op x element-kind) set the C kernels carry (fpc_reduce)."""
    from ..core import op as opmod
    if op in (opmod.BAND, opmod.BOR, opmod.BXOR):
        return arr.dtype.kind in "iub"
    return op in (opmod.SUM, opmod.PROD, opmod.MAX, opmod.MIN,
                  opmod.LAND, opmod.LOR, opmod.LXOR)


def _displs_from_counts(counts: Sequence[int]) -> List[int]:
    displs = [0] * len(counts)
    for i in range(1, len(counts)):
        displs[i] = displs[i - 1] + counts[i - 1]
    return displs


# ---------------------------------------------------------------------------
# blocking collectives — each takes the algorithm fn from the tuning table
# ---------------------------------------------------------------------------

def barrier(comm) -> None:
    pch = _plane_engine(comm)
    if pch is not None:
        if comm.size > 1:
            from . import flatcoll
            if flatcoll.try_barrier(pch, comm):
                return
            from . import netcoll
            if netcoll.net2_applicable(comm):
                # past the flat2 rank ceiling: the node-leader bridge
                # (group barrier -> leader barrier -> release bcast)
                netcoll.barrier_net2(comm, _plane_coll_tag(pch, comm))
                return
            alg.barrier_dissemination(comm, _plane_coll_tag(pch, comm))
        return
    tag = comm.next_coll_tag()
    fn = _select(comm, "barrier", 0)
    fn(comm, tag)


def bcast(comm, buf, count: int, datatype: Optional[Datatype],
          root: int) -> None:
    mpi_assert(0 <= root < comm.size, MPI_ERR_ROOT, f"bad root {root}")
    datatype = _dt(buf, datatype)
    nbytes = datatype.size * count
    if comm.size == 1:
        return
    pch = _plane_engine(comm)
    data = datatype.pack(buf, count) if comm.rank == root \
        else np.empty(nbytes, dtype=np.uint8)
    data = np.ascontiguousarray(data)
    if pch is not None and nbytes <= _plane_coll_max(pch, comm):
        # bcast mixes signature-equivalent datatypes legally, so the
        # delegation gate is the SIGNATURE bytes only — identical on
        # every rank, identical to the C fast path's gate. Flat-slot
        # tier first (same gate order as fp_try_bcast).
        from . import flatcoll
        if flatcoll.try_bcast(pch, comm, data, root):
            if comm.rank != root or not datatype.is_contiguous:
                datatype.unpack(data, buf, count)
            return
        from . import netcoll
        fn = netcoll.bcast_net2 if netcoll.net2_applicable(comm) \
            else alg.bcast_binomial
        tag = _plane_coll_tag(pch, comm)
    else:
        tag = comm.next_coll_tag()
        fn = _select(comm, "bcast", nbytes)
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    fn(comm, data, root, tag)
    if mx is not None:
        mx.rec_since("lat_coll_sched", t0)
    if comm.rank != root or not datatype.is_contiguous:
        datatype.unpack(data, buf, count)


def reduce(comm, sendbuf, recvbuf, count: int, datatype: Optional[Datatype],
           op: Op, root: int) -> None:
    datatype = _dt(recvbuf if sendbuf is IN_PLACE else sendbuf, datatype)
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed(src, count, datatype)
    pch = _plane_engine(comm)
    if pch is not None and datatype.basic is not None \
            and arr.nbytes <= _plane_coll_max(pch, comm) and _plane_red_ok(op, arr):
        arr = np.ascontiguousarray(arr)
        if comm.size > 1:
            from . import flatcoll
            taken, got = flatcoll.try_reduce(pch, comm, arr, op, root)
            if taken:
                if comm.rank == root:
                    _unpack(got, recvbuf, count, datatype)
                return
        fn, tag = alg.reduce_binomial, _plane_coll_tag(pch, comm)
    else:
        tag = comm.next_coll_tag()
        fn = _select(comm, "reduce", arr.nbytes, op=op)
    out = fn(comm, arr, op, root, tag)
    if comm.rank == root:
        _unpack(out, recvbuf, count, datatype)


def allreduce(comm, sendbuf, recvbuf, count: int,
              datatype: Optional[Datatype], op: Op) -> None:
    datatype = _dt(recvbuf if sendbuf is IN_PLACE else sendbuf, datatype)
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed_ro(src, count, datatype)
    pch = _plane_engine(comm)
    if pch is not None and datatype.basic is not None \
            and arr.nbytes <= _plane_coll_max(pch, comm) and _plane_red_ok(op, arr):
        arr = np.ascontiguousarray(arr)
        if comm.size > 1:
            from . import flatcoll
            got = flatcoll.try_allreduce(pch, comm, arr, op)
            if got is not None:
                _unpack(got, recvbuf, count, datatype)
                return
        from . import netcoll
        fn = netcoll.allreduce_net2 if netcoll.net2_applicable(comm) \
            else alg.allreduce_recursive_doubling
        tag = _plane_coll_tag(pch, comm)
    else:
        tag = comm.next_coll_tag()
        fn = _select(comm, "allreduce", arr.nbytes, op=op)
    dest = None
    if sendbuf is not IN_PLACE and getattr(fn, "supports_out", False) \
            and datatype.basic is not None and datatype.is_contiguous \
            and datatype.basic.itemsize == datatype.size:
        # hand the algorithm a writable view of recvbuf so the result
        # lands in place (no staging copy; forbidden for IN_PLACE — the
        # source stays exposed to peers until the exchange's barrier)
        try:
            mv = as_bytes_view(recvbuf, writable=True)
            n = datatype.size * count
            if len(mv) >= n:
                dest = np.frombuffer(mv, dtype=np.uint8,
                                     count=n).view(datatype.basic)
        except (ValueError, TypeError):
            dest = None
    mx = _metrics.LIVE
    t0 = _time.perf_counter() if mx is not None else 0.0
    if dest is not None:
        out = fn(comm, arr, op, tag, out=dest)
        if mx is not None:
            mx.rec_since("lat_coll_sched", t0)
        if out is dest:
            return
    else:
        out = fn(comm, arr, op, tag)
        if mx is not None:
            mx.rec_since("lat_coll_sched", t0)
    _unpack(out, recvbuf, count, datatype)


def allgather(comm, sendbuf, recvbuf, count: int,
              datatype: Optional[Datatype]) -> None:
    datatype = _dt(recvbuf, datatype)
    tag = comm.next_coll_tag()
    nbytes = datatype.size * count
    if sendbuf is IN_PLACE:
        rb = datatype.pack(recvbuf, count * comm.size)
        mine = rb[comm.rank * nbytes:(comm.rank + 1) * nbytes].copy()
    else:
        mine = datatype.pack(sendbuf, count)
        rb = np.empty(comm.size * nbytes, dtype=np.uint8)
    fn = _select(comm, "allgather", nbytes)
    fn(comm, np.ascontiguousarray(mine), rb, tag)
    datatype.unpack(rb, recvbuf, count * comm.size)


def allgatherv(comm, sendbuf, recvbuf, counts: Sequence[int],
               displs: Optional[Sequence[int]],
               datatype: Optional[Datatype]) -> None:
    datatype = _dt(recvbuf, datatype)
    esz = datatype.size
    if displs is None:
        displs = _displs_from_counts(counts)
    total = max(displs[i] + counts[i] for i in range(comm.size))
    tag = comm.next_coll_tag()
    rb = datatype.pack(recvbuf, total) if sendbuf is IN_PLACE else \
        np.empty(total * esz, dtype=np.uint8)
    if sendbuf is IN_PLACE:
        mine = rb[displs[comm.rank] * esz:
                  (displs[comm.rank] + counts[comm.rank]) * esz].copy()
    else:
        mine = datatype.pack(sendbuf, counts[comm.rank])
    bcounts = [c * esz for c in counts]
    bdispls = [d * esz for d in displs]
    alg.allgatherv_ring(comm, np.ascontiguousarray(mine), rb, bcounts,
                        bdispls, tag)
    datatype.unpack(rb, recvbuf, total)


def gather(comm, sendbuf, recvbuf, count: int, datatype: Optional[Datatype],
           root: int) -> None:
    datatype = _dt(sendbuf if sendbuf is not IN_PLACE else recvbuf, datatype)
    tag = comm.next_coll_tag()
    nbytes = datatype.size * count
    if sendbuf is IN_PLACE and comm.rank == root:
        full = datatype.pack(recvbuf, count * comm.size)
        mine = full[comm.rank * nbytes:(comm.rank + 1) * nbytes].copy()
    else:
        mine = datatype.pack(sendbuf, count)
    out = None
    if comm.rank == root:
        out = np.empty(comm.size * nbytes, dtype=np.uint8)
    alg.gather_binomial(comm, np.ascontiguousarray(mine), out, root, tag)
    if comm.rank == root:
        datatype.unpack(out, recvbuf, count * comm.size)


def gatherv(comm, sendbuf, recvbuf, counts, displs, datatype, root) -> None:
    datatype = _dt(sendbuf if sendbuf is not IN_PLACE else recvbuf, datatype)
    esz = datatype.size
    tag = comm.next_coll_tag()
    if displs is None:
        displs = _displs_from_counts(counts)
    # linear gatherv (the reference's default for v-collectives)
    if comm.rank == root:
        total = max(displs[i] + counts[i] for i in range(comm.size))
        rb = np.asarray(datatype.pack(recvbuf, total))
        reqs = []
        for r in range(comm.size):
            if r == root:
                if sendbuf is not IN_PLACE:
                    seg = datatype.pack(sendbuf, counts[r])
                    rb[displs[r] * esz:(displs[r] + counts[r]) * esz] = seg
                continue
            seg = rb[displs[r] * esz:(displs[r] + counts[r]) * esz]
            reqs.append(alg.crecv(comm, seg, r, tag))
        from ..core.request import waitall
        waitall(reqs)
        datatype.unpack(rb, recvbuf, total)
    else:
        mine = datatype.pack(sendbuf, counts[comm.rank])
        alg.csend(comm, np.ascontiguousarray(mine), root, tag).wait()


def scatter(comm, sendbuf, recvbuf, count: int, datatype: Optional[Datatype],
            root: int) -> None:
    datatype = _dt(recvbuf if recvbuf is not IN_PLACE else sendbuf, datatype)
    tag = comm.next_coll_tag()
    nbytes = datatype.size * count
    full = None
    if comm.rank == root:
        full = np.asarray(datatype.pack(sendbuf, count * comm.size))
    mine = np.empty(nbytes, dtype=np.uint8)
    alg.scatter_binomial(comm, full, mine, root, tag)
    if recvbuf is IN_PLACE:
        return
    datatype.unpack(mine, recvbuf, count)


def scatterv(comm, sendbuf, counts, displs, recvbuf, datatype, root) -> None:
    datatype = _dt(recvbuf, datatype)
    esz = datatype.size
    tag = comm.next_coll_tag()
    from ..core.request import waitall
    if comm.rank == root:
        if displs is None:
            displs = _displs_from_counts(counts)
        total = max(displs[i] + counts[i] for i in range(comm.size))
        sb = np.asarray(datatype.pack(sendbuf, total))
        reqs = []
        for r in range(comm.size):
            seg = sb[displs[r] * esz:(displs[r] + counts[r]) * esz]
            if r == root:
                if recvbuf is not IN_PLACE:   # root's slice stays put
                    datatype.unpack(seg, recvbuf, counts[r])
                continue
            reqs.append(alg.csend(comm, seg.copy(), r, tag))
        waitall(reqs)
    else:
        n = counts[comm.rank] if counts is not None else \
            np.asarray(recvbuf).size
        mine = np.empty(n * esz, dtype=np.uint8)
        alg.crecv(comm, mine, root, tag).wait()
        datatype.unpack(mine, recvbuf, n)


def alltoall(comm, sendbuf, recvbuf, count: int,
             datatype: Optional[Datatype]) -> None:
    datatype = _dt(recvbuf, datatype)
    tag = comm.next_coll_tag()
    nbytes = datatype.size * count
    if sendbuf is IN_PLACE:
        sb = datatype.pack(recvbuf, count * comm.size)
    else:
        sb = datatype.pack(sendbuf, count * comm.size)
    rb = np.empty(comm.size * nbytes, dtype=np.uint8)
    fn = _select(comm, "alltoall", nbytes)
    fn(comm, np.ascontiguousarray(sb), rb, tag)
    datatype.unpack(rb, recvbuf, count * comm.size)


def alltoallv(comm, sendbuf, scounts, sdispls, recvbuf, rcounts, rdispls,
              datatype: Optional[Datatype]) -> None:
    datatype = _dt(recvbuf, datatype)
    esz = datatype.size
    tag = comm.next_coll_tag()
    stotal = max(sdispls[i] + scounts[i] for i in range(comm.size))
    rtotal = max(rdispls[i] + rcounts[i] for i in range(comm.size))
    sb = np.asarray(datatype.pack(sendbuf, stotal))
    rb = np.empty(rtotal * esz, dtype=np.uint8)
    alg.alltoallv_scattered(comm, sb, [c * esz for c in scounts],
                            [d * esz for d in sdispls], rb,
                            [c * esz for c in rcounts],
                            [d * esz for d in rdispls], tag)
    datatype.unpack(rb, recvbuf, rtotal)


def reduce_scatter_block(comm, sendbuf, recvbuf, count: int,
                         datatype: Optional[Datatype], op: Op) -> None:
    datatype = _dt(recvbuf, datatype)
    tag = comm.next_coll_tag()
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed(src, count * comm.size, datatype)
    nelem = count * (datatype.size // datatype.basic_size)
    out = np.empty(nelem, dtype=arr.dtype)
    if op.commutative:
        alg.reduce_scatter_ring(comm, arr, out, op, tag)
    else:
        # order-preserving fallback: ordered reduce at 0, scatter blocks
        red = alg.reduce_gather_local(comm, arr, op, 0, tag)
        alg.scatter_binomial(comm, red, out, 0, tag)
    _unpack(out, recvbuf, count, datatype)


def reduce_scatter(comm, sendbuf, recvbuf, counts: Sequence[int],
                   datatype: Optional[Datatype], op: Op) -> None:
    """General reduce_scatter: reduce + scatterv (reference fallback algo)."""
    datatype = _dt(recvbuf, datatype)
    total = sum(counts)
    tag = comm.next_coll_tag()
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed(src, total, datatype)
    reduce_fn = _select(comm, "reduce", arr.nbytes, op=op)
    out = reduce_fn(comm, arr, op, 0, tag)
    displs = _displs_from_counts(counts)
    scatterv(comm, out if comm.rank == 0 else None, counts, displs, recvbuf,
             datatype, 0)


def scan(comm, sendbuf, recvbuf, count: int, datatype: Optional[Datatype],
         op: Op) -> None:
    datatype = _dt(recvbuf, datatype)
    tag = comm.next_coll_tag()
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed(src, count, datatype)
    out = alg.scan_linear(comm, arr, op, tag, exclusive=False)
    _unpack(out, recvbuf, count, datatype)


def exscan(comm, sendbuf, recvbuf, count: int, datatype: Optional[Datatype],
           op: Op) -> None:
    datatype = _dt(recvbuf, datatype)
    tag = comm.next_coll_tag()
    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    arr = _packed(src, count, datatype)
    out = alg.scan_linear(comm, arr, op, tag, exclusive=True)
    if comm.rank > 0:
        _unpack(out, recvbuf, count, datatype)


def _select(comm, name: str, nbytes: int, op: Optional[Op] = None):
    """Dispatch through the per-comm table (installed by tuning layer).
    Wraps the chosen algorithm with an MPI_T timer+counter pvar pair —
    the MPIR_T_PVAR_DOUBLE_TIMER analog of allreduce_osu.c:35-50."""
    if not comm.coll_fns:
        from .tuning import install_coll_ops
        install_coll_ops(comm)
    fn = comm.coll_fns["_select"](name, nbytes, op)
    cached = _timed_cache.get((name, fn))
    if cached is None:
        from .. import mpit
        algo = getattr(fn, "__name__", "unknown")
        timer = mpit.pvar(f"coll_{name}_{algo}_time", mpit.PVAR_CLASS_TIMER,
                          "coll", f"cumulative seconds in {name}/{algo}")
        counter = mpit.pvar(f"coll_{name}_{algo}_calls",
                            mpit.PVAR_CLASS_COUNTER, "coll",
                            f"invocations of {name}/{algo}")
        def cached(*a, _fn=fn, _t=timer, _c=counter, **kw):
            _c.inc()
            with _t.timing():
                return _fn(*a, **kw)

        cached.__name__ = algo
        cached.supports_out = getattr(fn, "supports_out", False)
        _timed_cache[(name, fn)] = cached
    return cached


# (coll name, algorithm fn) -> timed wrapper; bounded by the algorithm zoo
_timed_cache: dict = {}

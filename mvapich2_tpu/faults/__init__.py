"""Deterministic fault-injection engine (the test/mpi/ft die.c analog,
grown into a first-class subsystem).

The reference proves its failure stack with launcher-driven kill tests;
this module makes peer death (and the messier failure modes around it)
a *reproducible input*: named injection sites in the datapath consult a
seeded spec parsed from ``MV2T_FAULTS`` and fire deterministically on
the nth eligible event.

Grammar (comma-separated specs)::

    MV2T_FAULTS=<site>[@<world-rank>]:<kind>[:<seed>[:<nth>[+]]]

    site  shm_send | shm_recv | arena_alloc | rndv_chunk | kvs | wire
          | claim (warm-attach daemon claim cycle, fired between the
          grant transaction and the claimer's attach)
          | flat_fold  (handled natively in cplane.cpp so the C-ABI
          hot path injects without an interpreter round-trip)
          | trace_stamp  (the Recorder.record stamp site — tracer
          corruption for conformance-checker tests, never datapath)
    kind  drop | delay | duplicate | truncate | crash
          | skip_stamp | reorder  (trace_stamp only: silently drop the
          stamp / swap it behind its predecessor — seeded trace
          mutations that bin/mv2tconform must catch by name)
    seed  seeds the per-spec RNG (delay durations); default 0
    nth   fire on the nth eligible event at the site (1-based,
          default 1); a trailing ``+`` keeps firing from the nth on

``@rank`` scopes the spec to one world rank (default: every rank —
rarely what a chaos test wants for ``crash``).

Kind semantics are site-interpreted: ``crash`` is applied here
(``os._exit(17)`` — SIGKILL-equivalent from the peers' point of view:
no Finalize, no departed-lease stamp), ``delay`` sleeps a seeded 1-20 ms
inline, and ``drop``/``duplicate``/``truncate`` are returned to the
call site, which applies the transport-specific meaning (a dropped
arena_alloc is a simulated exhaustion; a dropped shm_send is a lost
packet). ``drop``/``truncate`` on transport sites model *unrecoverable*
corruption — there is no retransmission layer — so the automated chaos
matrix (tests/test_faults.py) sticks to the terminating kinds and
leaves those two for interactive hunting.

Zero cost when off: every site calls ``fire(site)``, which returns
immediately while no spec is configured.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from .. import mpit
from ..utils.config import cvar, get_config
from ..utils.mlog import get_logger

log = get_logger("faults")

cvar("FAULTS", "", str, "ft",
     "Deterministic fault-injection spec(s): "
     "site[@rank]:kind[:seed[:nth[+]]], comma-separated. Sites: "
     "shm_send shm_recv arena_alloc rndv_chunk kvs wire claim "
     "flat_fold trace_stamp; kinds: drop delay duplicate truncate "
     "crash skip_stamp reorder. Empty = engine off (zero hot-path "
     "cost).")
cvar("FAULT_DELAY_MS", 0.0, float, "ft",
     "Fixed delay in ms for the 'delay' kind (0 = seeded 1-20 ms).")

SITES = ("shm_send", "shm_recv", "arena_alloc", "rndv_chunk", "kvs",
         "wire", "claim", "flat_fold", "trace_stamp")
KINDS = ("drop", "delay", "duplicate", "truncate", "crash",
         "skip_stamp", "reorder")

# containment observability (predeclared in mpit.py so tools enumerate
# them before any datapath import; fetched-by-name here)
pv_injected = mpit.pvar("faults_injected", mpit.PVAR_CLASS_COUNTER, "ft",
                        "faults fired by the MV2T_FAULTS engine "
                        "(python-side sites)")
pv_dead_peer = mpit.pvar("dead_peer_detections", mpit.PVAR_CLASS_COUNTER,
                         "ft", "peers declared dead by liveness-lease "
                         "expiry (python probe + C-plane scans)")
pv_deadline = mpit.pvar("wait_deadline_trips", mpit.PVAR_CLASS_COUNTER,
                        "ft", "blocking waits unwound by a lease "
                        "deadline instead of completing")


class FaultSpec:
    __slots__ = ("site", "rank", "kind", "seed", "nth", "repeat",
                 "count", "rng")

    def __init__(self, site: str, rank: Optional[int], kind: str,
                 seed: int, nth: int, repeat: bool):
        self.site = site
        self.rank = rank        # None = every rank
        self.kind = kind
        self.seed = seed
        self.nth = nth
        self.repeat = repeat
        self.count = 0          # eligible events seen (guarded-by: _lock)
        self.rng = random.Random(seed)

    def __repr__(self):
        at = f"@{self.rank}" if self.rank is not None else ""
        plus = "+" if self.repeat else ""
        return (f"FaultSpec({self.site}{at}:{self.kind}:{self.seed}"
                f":{self.nth}{plus})")


def parse(text: str) -> List[FaultSpec]:
    """Parse a MV2T_FAULTS string; raises ValueError on bad specs."""
    specs: List[FaultSpec] = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(f"bad fault spec {raw!r}: need site:kind")
        site, rank = parts[0], None
        if "@" in site:
            site, r = site.split("@", 1)
            rank = int(r)
        if site not in SITES:
            raise ValueError(f"bad fault site {site!r} (know {SITES})")
        kind = parts[1]
        if kind not in KINDS:
            raise ValueError(f"bad fault kind {kind!r} (know {KINDS})")
        seed = int(parts[2]) if len(parts) > 2 and parts[2] else 0
        nth_s = parts[3] if len(parts) > 3 and parts[3] else "1"
        repeat = nth_s.endswith("+")
        nth = int(nth_s.rstrip("+") or 1)
        if nth < 1:
            raise ValueError(f"bad fault nth {nth_s!r} (1-based)")
        specs.append(FaultSpec(site, rank, kind, seed, nth, repeat))
    return specs


# site -> specs scoped to this rank; None while unconfigured/off —
# fire() is a single attribute test in that state
_active: Optional[Dict[str, List[FaultSpec]]] = None
_lock = threading.Lock()


def configure(world_rank: int) -> int:
    """(Re)build the active spec table for this rank from the FAULTS
    cvar — called from Universe.initialize after the config reload.
    Returns how many specs are armed here. ``flat_fold`` specs are
    listed for visibility but fire natively (cplane.cpp parses the
    same env var), so they are never armed on the python side."""
    global _active
    text = str(get_config().get("FAULTS", "") or "")
    if not text:
        _active = None
        return 0
    table: Dict[str, List[FaultSpec]] = {}
    for spec in parse(text):
        if spec.rank is not None and spec.rank != world_rank:
            continue
        if spec.site == "flat_fold":
            continue            # native site (cplane.cpp flat_fault)
        table.setdefault(spec.site, []).append(spec)
    _active = table if table else None
    if _active:
        log.info("fault engine armed on rank %d: %s", world_rank,
                 [s for ss in table.values() for s in ss])
    return sum(len(v) for v in table.values())


def deconfigure() -> None:
    global _active
    _active = None


def fire(site: str) -> Optional[str]:
    """Count one eligible event at ``site``; returns the fault kind when
    a spec fires (after applying crash/delay inline), else None."""
    table = _active
    if table is None:
        return None
    specs = table.get(site)
    if not specs:
        return None
    for spec in specs:
        with _lock:
            spec.count += 1
            hit = spec.count == spec.nth or \
                (spec.repeat and spec.count > spec.nth)
            delay_s = 0.0
            if hit and spec.kind == "delay":
                fixed = float(get_config().get("FAULT_DELAY_MS", 0.0))
                delay_s = (fixed / 1e3) if fixed > 0 \
                    else (0.001 + spec.rng.random() * 0.019)
        if not hit:
            continue
        pv_injected.inc()
        if spec.kind == "crash":
            log.warn("fault engine: crash-self at %s (event %d)",
                     site, spec.count)
            os._exit(17)
        if spec.kind == "delay":
            time.sleep(delay_s)
        return spec.kind
    return None

"""Debugger interface: message-queue dumping.

Analog of the reference's TotalView/MPIR debugger DLL
(src/mpi/debugger/dll_mpich.c + dbginit.c): a debugger attaches and walks
the posted-receive, unexpected-message, and pending-send queues of each
rank. Here the same three queues are snapshotted from the live matcher /
engine state — usable from a REPL, a failure handler, or test assertions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class QueueEntry:
    kind: str                 # "posted-recv" | "unexpected" | "send"
    ctx: int = -1
    source: int = -1          # rank-in-comm (or sender for unexpected)
    tag: int = -1
    nbytes: int = -1
    comm_name: str = ""


@dataclass
class MessageQueues:
    rank: int
    posted: List[QueueEntry] = field(default_factory=list)
    unexpected: List[QueueEntry] = field(default_factory=list)
    sends: List[QueueEntry] = field(default_factory=list)

    def format(self) -> str:
        lines = [f"# message queues, world rank {self.rank}"]
        for title, q in (("posted receives", self.posted),
                         ("unexpected messages", self.unexpected),
                         ("pending sends", self.sends)):
            lines.append(f"## {title} ({len(q)})")
            for e in q:
                lines.append(
                    f"  ctx={e.ctx} {'comm=' + e.comm_name + ' ' if e.comm_name else ''}"
                    f"src={e.source} tag={e.tag} bytes={e.nbytes}")
        return "\n".join(lines)


def dump_message_queues(u=None) -> MessageQueues:
    """Snapshot this rank's matching/engine state (dll_mpich.c's
    mqs_setup_operation_iterator analog)."""
    from .runtime.universe import current_universe
    u = u or current_universe()
    if u is None or u.protocol is None:
        raise RuntimeError("MPI not initialized on this rank")
    m = u.protocol.matcher
    out = MessageQueues(rank=u.world_rank)

    def comm_of(ctx: int) -> str:
        c = u.comms_by_ctx.get(ctx & ~1)
        return getattr(c, "name", "") if c is not None else ""

    with u.engine.mutex:
        for req in m.posted:
            ctx, src, tag = req.match
            out.posted.append(QueueEntry("posted-recv", ctx, src, tag,
                                         req.capacity, comm_of(ctx)))
        for pkt in m.unexpected:
            out.unexpected.append(QueueEntry("unexpected", pkt.ctx,
                                             pkt.comm_src, pkt.tag,
                                             pkt.nbytes, comm_of(pkt.ctx)))
        for req in u.engine.outstanding.values():
            if getattr(req, "kind", "") == "send":
                out.sends.append(QueueEntry(
                    "send", -1, getattr(req, "dest_world", -1), -1,
                    len(req.packed) if getattr(req, "packed", None)
                    is not None else -1))
    return out

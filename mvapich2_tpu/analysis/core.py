"""mv2tlint framework: source model, pass protocol, baseline ratchet.

The checker is deliberately whole-package and syntactic: every pass gets
the full list of parsed modules (cross-module invariants like
tag-namespace disjointness and pvar registration need the global view)
plus per-line comment maps so annotations ride ordinary ``#`` comments
and survive formatting:

    # guarded-by: _lock            attribute may only be touched with
                                   the named lock held (| separates
                                   accepted aliases, e.g. a Condition
                                   wrapping the lock)
    # holds: _lock                 on a def line: the whole function runs
                                   with the lock held (caller contract)
    # tag-span: 32768              width of a *_TAG_BASE namespace
    # mv2tlint: handler            on a def line: treat as a progress
                                   callback / packet-handler context
    # mv2tlint: ignore[locks]      suppress named passes on this line
    # mv2tlint: ignore             suppress every pass on this line

Baseline discipline (the ratchet): findings are keyed by
(pass, path, message) — NOT line numbers, so unrelated edits don't churn
the file — and matched against analysis/baseline.json. A finding with a
baseline entry is demoted to "suppressed"; in ``--strict`` mode a
baseline entry that matches nothing is itself an error (stale
suppression), so the committed invariant set only ratchets down.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_IGNORE_RE = re.compile(r"mv2tlint:\s*ignore(?:\[([a-z, -]+)\])?")


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``key`` (pass:path:msg) is the baseline unit —
    stable across line drift, specific enough not to mask new breakage
    of the same kind at another site (the message names the symbol)."""

    pass_id: str
    path: str          # repo-relative
    line: int
    msg: str

    @property
    def key(self) -> str:
        return f"{self.pass_id}:{self.path}:{self.msg}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.msg}"


class SourceModule:
    """One parsed file: AST + per-line comments + per-line suppressions."""

    def __init__(self, path: str, text: str):
        self.path = os.path.abspath(path)
        self.relpath = os.path.relpath(self.path, REPO_ROOT)
        if self.relpath.startswith(".."):
            self.relpath = os.path.basename(self.path)
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        # line -> set of suppressed pass ids ({"*"} = all)
        self.ignores: Dict[int, set] = {}
        for line, c in self.comments.items():
            m = _IGNORE_RE.search(c)
            if m:
                which = m.group(1)
                self.ignores[line] = ({"*"} if which is None else
                                      {p.strip() for p in which.split(",")})

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def annotation(self, line: int, key: str) -> Optional[str]:
        """Value of ``# <key>: <value>`` on ``line`` (or None)."""
        m = re.search(rf"#\s*{re.escape(key)}:\s*([^#]+)", self.comment(line))
        return m.group(1).strip() if m else None

    def suppressed(self, line: int, pass_id: str) -> bool:
        ign = self.ignores.get(line)
        return bool(ign) and ("*" in ign or pass_id in ign)


class LintPass:
    """Pass protocol: subclasses set ``id``/``doc`` and implement run()."""

    id = "base"
    doc = ""

    def run(self, modules: List[SourceModule]) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: SourceModule, line: int, msg: str) -> Optional[Finding]:
        if mod.suppressed(line, self.id):
            return None
        return Finding(self.id, mod.relpath, line, msg)


# ---------------------------------------------------------------------------
# scanning
# ---------------------------------------------------------------------------

def scan_paths(paths: Sequence[str]) -> Tuple[List[SourceModule], List[Finding]]:
    """Parse every .py file under ``paths`` (files or directories).
    Unparseable files become findings of the pseudo-pass ``parse`` so a
    syntax error can never silently shrink coverage."""
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    modules, errors = [], []
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                modules.append(SourceModule(f, fh.read()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            rel = os.path.relpath(f, REPO_ROOT)
            errors.append(Finding("parse", rel, getattr(e, "lineno", 0) or 0,
                                  f"unparseable: {e!s:.120}"))
    return modules, errors


def all_passes(native_sources: Optional[Sequence[str]] = None,
               native_layout: bool = True,
               doc_sources: Optional[Sequence[str]] = None,
               profile_files: Optional[Sequence[str]] = None,
               device_profiles: Optional[Sequence[str]] = None) -> List[LintPass]:
    """The full pass set. ``native_sources`` overrides the C file set of
    the native pass (fixture tests); None = the committed native tree.
    ``native_layout`` gates the cross-language layout check (only
    meaningful against the real repo). ``doc_sources`` overrides the
    non-python surfaces of the env-drift doctor (native getenv / bin /
    README; [] disables it for fixture runs); ``profile_files`` /
    ``device_profiles`` override the tuning-profile JSON set of the
    profile doctor and the device pass's VMEM-budget estimator."""
    from . import (blocking, device, events, locks, native, profilecheck,
                   proto, registry, tags, traceguard)
    return [locks.LockDisciplinePass(), tags.TagNamespacePass(),
            events.EventCoveragePass(),
            registry.RegistryPass(
                doc_sources=list(doc_sources)
                if doc_sources is not None else None),
            blocking.BlockingCallPass(),
            traceguard.TraceGuardPass(
                list(native_sources) if native_sources is not None
                else None),
            native.NativeSourcePass(
                list(native_sources) if native_sources is not None else None,
                layout=native_layout),
            device.DevicePass(
                profiles=list(device_profiles)
                if device_profiles is not None else None),
            profilecheck.ProfileDoctorPass(
                profile_files=list(profile_files)
                if profile_files is not None else None),
            proto.ProtoPass()]


def run_passes(modules: List[SourceModule],
               passes: Optional[List[LintPass]] = None) -> List[Finding]:
    out: List[Finding] = []
    for p in passes or all_passes():
        out.extend(p.run(modules))
    out.sort(key=lambda f: (f.path, f.line, f.pass_id, f.msg))
    return out


# ---------------------------------------------------------------------------
# baseline (the ratchet)
# ---------------------------------------------------------------------------

@dataclass
class Baseline:
    path: str
    entries: List[dict] = field(default_factory=list)

    def keys(self) -> Dict[str, dict]:
        return {f"{e['pass']}:{e['path']}:{e['msg']}": e
                for e in self.entries}

    def split(self, findings: List[Finding]):
        """(new, suppressed, stale_entries)."""
        keys = self.keys()
        new = [f for f in findings if f.key not in keys]
        supp = [f for f in findings if f.key in keys]
        live = {f.key for f in findings}
        stale = [e for k, e in keys.items() if k not in live]
        return new, supp, stale


def load_baseline(path: Optional[str] = None) -> Baseline:
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return Baseline(path, [])
    with open(path) as f:
        data = json.load(f)
    return Baseline(path, list(data.get("suppressions", [])))


def write_baseline(path: str, findings: List[Finding],
                   reason: str = "seed baseline") -> None:
    data = {
        "comment": "mv2tlint suppressions — the invariant ratchet. Every "
                   "entry needs a justification; --strict fails on stale "
                   "entries so this file only shrinks.",
        "suppressions": [{"pass": f.pass_id, "path": f.path, "msg": f.msg,
                          "reason": reason} for f in findings],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------------
# shared AST helpers used by several passes
# ---------------------------------------------------------------------------

def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains ('self.engine.mutex'),
    None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last component of a Name/Attribute chain ('mutex' for
    self.engine.mutex) — lock identity for the discipline passes."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def const_int(node: ast.AST) -> Optional[int]:
    """Evaluate a compile-time integer expression (literals, + - * <<
    | and hex), the shapes *_TAG_BASE constants are written in."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.BinOp):
        lhs, rhs = const_int(node.left), const_int(node.right)
        if lhs is None or rhs is None:
            return None
        op = node.op
        if isinstance(op, ast.Add):
            return lhs + rhs
        if isinstance(op, ast.Sub):
            return lhs - rhs
        if isinstance(op, ast.Mult):
            return lhs * rhs
        if isinstance(op, ast.LShift):
            return lhs << rhs
        if isinstance(op, ast.BitOr):
            return lhs | rhs
        return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = const_int(node.operand)
        return None if v is None else -v
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents

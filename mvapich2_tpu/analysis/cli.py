"""mv2tlint command-line driver (bin/mv2tlint).

    mv2tlint                         lint the package against the
                                     committed baseline
    mv2tlint --strict                CI mode: new findings OR stale
                                     baseline entries fail (the ratchet)
    mv2tlint --baseline FILE         alternate suppressions file
    mv2tlint --write-baseline        snapshot current findings as the
                                     baseline (each entry then needs a
                                     hand-written justification)
    mv2tlint --select locks,tags     run a subset of passes
    mv2tlint path/to/file.py ...     lint specific files/dirs (fixture
                                     tests use this)

Exit codes: 0 clean (all findings suppressed; strict also requires no
stale suppressions), 1 violations, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import (DEFAULT_BASELINE, PKG_ROOT, REPO_ROOT, all_passes,
                   load_baseline, run_passes, scan_paths, write_baseline)


def _resolve_baseline(path: Optional[str]) -> str:
    if path is None:
        return DEFAULT_BASELINE
    if os.path.exists(path):
        return path
    # allow the repo-root spelling `--baseline analysis/baseline.json`
    alt = os.path.join(PKG_ROOT, path)
    if os.path.exists(alt):
        return alt
    return path


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mv2tlint",
        description="protocol/concurrency invariant checker "
                    "(mvapich2_tpu.analysis)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "installed mvapich2_tpu package)")
    ap.add_argument("--baseline", default=None,
                    help="suppressions file (default: the committed "
                         "analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline entirely (fixture tests)")
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries too — the "
                         "invariant set only ratchets down")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline "
                         "file and exit 0")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass ids to run "
                         "(default: all)")
    ap.add_argument("--list-passes", action="store_true")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable findings on stdout")
    opts = ap.parse_args(argv)

    # explicit paths: C/C++ files route to the native pass, .json files
    # to the profile doctor, .py files to the AST passes (runtime/ and
    # transport/ control-plane paths thereby reach the proto pass'
    # key-flow/deadline/wire-state doctors); with no paths the native
    # pass lints the committed native tree (+ the cross-language layout
    # check) and the profile doctor the committed profiles/ directory
    c_exts = (".c", ".cpp", ".cc", ".h", ".hpp")
    c_paths = [p for p in (opts.paths or []) if p.endswith(c_exts)]
    json_paths = [p for p in (opts.paths or []) if p.endswith(".json")]
    py_paths = [p for p in (opts.paths or [])
                if not p.endswith(c_exts + (".json",))]
    if opts.paths:
        # fixture mode: the committed doc / profile surfaces stay out of
        # the finding set so counts only reflect the given paths
        passes = all_passes(native_sources=c_paths, native_layout=False,
                            doc_sources=[], profile_files=json_paths,
                            device_profiles=[])
    else:
        passes = all_passes()
    if opts.list_passes:
        for p in passes:
            print(f"{p.id:<12} {p.doc}")
        return 0
    if opts.select:
        want = {s.strip() for s in opts.select.split(",") if s.strip()}
        unknown = want - {p.id for p in passes}
        if unknown:
            print(f"mv2tlint: unknown pass(es): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        passes = [p for p in passes if p.id in want]

    paths = py_paths if opts.paths else [PKG_ROOT]
    modules, parse_errors = scan_paths(paths)
    findings = parse_errors + run_passes(modules, passes)

    bl_path = _resolve_baseline(opts.baseline)
    if opts.write_baseline:
        write_baseline(bl_path, findings)
        print(f"# mv2tlint: wrote {len(findings)} suppression(s) to "
              f"{os.path.relpath(bl_path, REPO_ROOT)}")
        return 0

    baseline = load_baseline(None if opts.no_baseline else bl_path)
    if opts.no_baseline:
        baseline.entries = []
    new, suppressed, stale = baseline.split(findings)

    if opts.as_json:
        print(json.dumps({
            "findings": [{"pass": f.pass_id, "path": f.path, "line": f.line,
                          "msg": f.msg} for f in new],
            "suppressed": len(suppressed),
            "stale": [e for e in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"stale baseline entry: [{e['pass']}] {e['path']}: "
                  f"{e['msg']} (fixed? delete it)")
        print(f"# mv2tlint: {len(new)} finding(s), {len(suppressed)} "
              f"suppressed, {len(stale)} stale baseline entr(ies) — "
              f"{len(modules)} file(s), {len(passes)} pass(es)")

    if new:
        return 1
    if opts.strict and stale:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

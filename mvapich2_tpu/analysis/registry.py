"""Pass ``pvars`` — pvar/cvar registry consistency.

The MPI_T surface (mpit.py) is only as trustworthy as the declarations
feeding it. Three invariants, all checkable syntactically because the
registry idiom is declarative (utils/config.cvar, mpit.pvar):

  * every pvar FETCHED anywhere (a 1/2-argument ``pvar("name")`` call —
    the bump-side idiom) is DECLARED somewhere in the scanned set (a
    call carrying class/group/desc), so a typo'd counter name can never
    silently mint an undeclared, undocumented pvar;
  * every ``MV2T_*`` environment read resolves to a declared cvar —
    knobs must go through the config registry so ``mpiname -a`` /
    MPI_T enumeration stays complete. Launcher<->child wire-protocol
    plumbing (rank/size/KVS coordinates, not knobs) is exempted via
    INTERNAL_ENV; config-registry reads (``get_config()[...]``) must
    name a declared cvar too;
  * names follow convention: pvars lower_snake, cvars UPPER_SNAKE.

Dynamic keys (f-strings like ``MV2T_DEBUG_<subsys>``) are out of static
reach; the exempt prefixes below cover the two families in use.

The env-drift doctor extends the same invariant to the NON-python
surfaces: every ``getenv("MV2T_*")`` in the native C sources and every
``MV2T_*`` token in bin/ scripts and the README must resolve to a
declared cvar (or the internal-plumbing exemptions) — a documented knob
with no registration, or a native env read the registry never heard of,
is exactly the doc/env drift that makes ``mpiname -a`` lie.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintPass, REPO_ROOT, SourceModule, attr_chain

# launcher<->child wire plumbing: process coordinates, not tunables
INTERNAL_ENV: Set[str] = {
    "MV2T_RANK", "MV2T_SIZE", "MV2T_KVS", "MV2T_FAKE_NODE", "MV2T_FT",
    "MV2T_WORLD_BASE", "MV2T_SPAWN_CTX", "MV2T_APPNUM",
    "MV2T_PARENT_RANKS", "MV2T_RANK_PLATFORM", "MV2T_PLATFORM_EXPLICIT",
    "MV2T_VPOD_CHILD", "MV2T_VPOD_REAL", "MV2T_TEST_ON_TPU",
    "MV2T_TEST_FULL", "MV2T_FT_WATCHER",
    # sanitizer-lane plumbing (bin/runtests --tsan): points every ring
    # consumer in the job at one instrumented variant .so — a build
    # coordinate, not a tunable
    "MV2T_SHMRING_SO",
    # toolchain coordinates of the compiler wrappers (bin/mpicc and
    # friends): which cc/f90 to exec, not runtime knobs
    "MV2T_CC", "MV2T_CXX", "MV2T_FC",
}
# MV2T_MET_*: the metrics-segment layout #define namespace
# (native/shm_layout.h, doc-referenced) — cross-language constants
# pinned by the layout doctor, not env tunables
INTERNAL_PREFIXES = ("MV2T_DEBUG_", "MV2T_STASH_", "MV2T_MET_")

# env-drift doctor: the committed non-python surfaces scanned by
# default (native getenv reads; MV2T_* tokens in bin/ and the README)
_DOC_NATIVE_DIR = os.path.join(REPO_ROOT, "native")
_DOC_BIN_DIR = os.path.join(REPO_ROOT, "bin")
_DOC_README = os.path.join(REPO_ROOT, "README.md")
_GETENV_RE = re.compile(r'getenv\(\s*"(MV2T_[A-Z0-9_]*)"')
_TOKEN_RE = re.compile(r"\bMV2T_[A-Z0-9_]*")

_PVAR_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_CVAR_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_DECL_KWARGS = {"klass", "group", "desc", "source"}
_CFG_RECEIVERS = {"cfg", "config", "_config"}


def _str_arg0(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _is_config_receiver(node: ast.AST) -> bool:
    """get_config() / get_config().cvars-free receiver / cfg / config."""
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        return chain is not None and chain.endswith("get_config")
    if isinstance(node, ast.Name):
        return node.id in _CFG_RECEIVERS
    return False


def _is_environ(node: ast.AST) -> bool:
    chain = attr_chain(node)
    return chain is not None and chain.split(".")[-1] == "environ"


def _default_doc_sources() -> List[str]:
    out: List[str] = []
    for d, exts in ((_DOC_NATIVE_DIR, (".c", ".cpp", ".cc", ".h")),
                    (_DOC_BIN_DIR, None)):
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for f in names:
            p = os.path.join(d, f)
            if not os.path.isfile(p):
                continue
            if exts is None or f.endswith(exts):
                out.append(p)
    if os.path.exists(_DOC_README):
        out.append(_DOC_README)
    return out


class RegistryPass(LintPass):
    id = "pvars"
    doc = ("pvars fetched anywhere must be declared; MV2T_* env reads "
           "(python, native getenv, bin/ scripts, README) must have a "
           "declared cvar; names follow convention")

    def __init__(self, doc_sources: Optional[List[str]] = None):
        # doc_sources: non-python surfaces for the env-drift doctor;
        # None = the committed native/bin/README set, [] disables
        self.doc_sources = doc_sources

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        declared_pvars: Set[str] = set()
        declared_cvars: Set[str] = set()
        dynamic_cvar_pats: List[re.Pattern] = []
        pvar_uses: List[Tuple[SourceModule, int, str]] = []
        env_reads: List[Tuple[SourceModule, int, str]] = []
        cfg_reads: List[Tuple[SourceModule, int, str]] = []
        decl_sites: Dict[str, Tuple[SourceModule, int]] = {}

        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.Call, ast.Subscript)):
                    continue
                if isinstance(node, ast.Subscript):
                    if not isinstance(node.ctx, ast.Load):
                        continue
                    key = node.slice
                    if not (isinstance(key, ast.Constant)
                            and isinstance(key.value, str)):
                        continue
                    if _is_environ(node.value) \
                            and key.value.startswith("MV2T_"):
                        env_reads.append((mod, node.lineno, key.value))
                    elif _is_config_receiver(node.value):
                        cfg_reads.append((mod, node.lineno, key.value))
                    continue
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else \
                    (fn.id if isinstance(fn, ast.Name) else None)
                if name == "pvar":
                    pname = _str_arg0(node)
                    if pname is None:
                        continue
                    is_decl = len(node.args) >= 3 or \
                        any(kw.arg in _DECL_KWARGS for kw in node.keywords)
                    if is_decl:
                        declared_pvars.add(pname)
                        decl_sites.setdefault(f"p:{pname}",
                                              (mod, node.lineno))
                    else:
                        pvar_uses.append((mod, node.lineno, pname))
                elif name == "cvar" or (name == "declare"
                                        and isinstance(fn, ast.Attribute)):
                    cname = _str_arg0(node)
                    if cname is None:
                        # a loop-generated family (cvar(f"{_c}_ALGO")):
                        # the constant parts become a match pattern so
                        # doc mentions of family members still resolve
                        if node.args and isinstance(node.args[0],
                                                    ast.JoinedStr):
                            parts = [re.escape(v.value)
                                     if isinstance(v, ast.Constant)
                                     else "[A-Z0-9_]+"
                                     for v in node.args[0].values]
                            dynamic_cvar_pats.append(
                                re.compile("^" + "".join(parts) + "$"))
                        continue
                    declared_cvars.add(cname)
                    decl_sites.setdefault(f"c:{cname}", (mod, node.lineno))
                elif name == "get" and isinstance(fn, ast.Attribute):
                    key = _str_arg0(node)
                    if key is None:
                        continue
                    if _is_environ(fn.value) and key.startswith("MV2T_"):
                        env_reads.append((mod, node.lineno, key))
                    elif _is_config_receiver(fn.value):
                        cfg_reads.append((mod, node.lineno, key))

        def emit(mod: SourceModule, line: int, msg: str) -> None:
            f = self.finding(mod, line, msg)
            if f is not None:
                out.append(f)

        for pname in sorted(declared_pvars):
            if not _PVAR_RE.match(pname):
                mod, line = decl_sites[f"p:{pname}"]
                emit(mod, line, f"pvar '{pname}' violates the lower_snake "
                     "naming convention")
        for cname in sorted(declared_cvars):
            if not _CVAR_RE.match(cname):
                mod, line = decl_sites[f"c:{cname}"]
                emit(mod, line, f"cvar '{cname}' violates the UPPER_SNAKE "
                     "naming convention")
        seen: Set[str] = set()
        for mod, line, pname in pvar_uses:
            if pname not in declared_pvars and pname not in seen:
                seen.add(pname)
                emit(mod, line, f"pvar '{pname}' is fetched but never "
                     "declared (no klass/group/desc registration in the "
                     "scanned set)")
        for mod, line, env in env_reads:
            if env in INTERNAL_ENV or env.startswith(INTERNAL_PREFIXES):
                continue
            if env[len("MV2T_"):] not in declared_cvars:
                emit(mod, line, f"env read '{env}' has no declared cvar "
                     "(declare it with utils.config.cvar or add it to "
                     "INTERNAL_ENV)")
        for mod, line, key in cfg_reads:
            if key not in declared_cvars:
                emit(mod, line, f"config read '{key}' names no declared "
                     "cvar")

        # -- env-drift doctor over the non-python surfaces --------------
        def known(env: str) -> bool:
            if env in INTERNAL_ENV or env.startswith(INTERNAL_PREFIXES):
                return True
            name = env[len("MV2T_"):].rstrip("_")
            if not name:
                return True          # a bare 'MV2T_' prefix mention
            return name in declared_cvars \
                or any(p.match(name) for p in dynamic_cvar_pats)

        doc_sources = self.doc_sources
        if doc_sources is None:
            # only meaningful against the full package: the committed
            # docs resolve against the whole cvar registry, not a
            # fixture's subset
            if any(m.relpath.endswith("mvapich2_tpu/mpit.py")
                   for m in modules):
                doc_sources = _default_doc_sources()
            else:
                doc_sources = []
        seen_doc: Set[str] = set()
        for path in doc_sources:
            try:
                with open(path, encoding="utf-8") as fh:
                    text = fh.read()
            except (OSError, UnicodeDecodeError):
                continue
            native = path.endswith((".c", ".cpp", ".cc", ".h"))
            matcher = _GETENV_RE if native else _TOKEN_RE
            rel = os.path.relpath(path, REPO_ROOT)
            if rel.startswith(".."):
                rel = os.path.basename(path)
            for i, line_text in enumerate(text.splitlines(), start=1):
                for m in matcher.finditer(line_text):
                    env = m.group(1) if native else m.group(0)
                    if known(env) or (rel, env) in seen_doc:
                        continue
                    seen_doc.add((rel, env))
                    where = "native getenv" if native else "mention"
                    out.append(Finding(
                        self.id, rel, i,
                        f"{where} '{env}' has no declared cvar — "
                        "register it (utils.config.cvar) or add it to "
                        "INTERNAL_ENV"))
        return out

"""Bounded exhaustive interleaving explorer with DPOR-style sleep sets.

A model is a set of guarded transitions over one shared state dict
(values must be hashable). ``explore`` enumerates interleavings
depth-first:

  * ``reduce=False`` (the default, and what tier-1 runs): plain
    memoized DFS — every reachable state is visited exactly once and
    every invariant is evaluated on every reachable state. Genuinely
    exhaustive within the model's bounds.
  * ``reduce=True``: classic sleep-set pruning on top. After branch
    ``t1`` is fully explored from a state, ``t1`` enters the sleep set
    for the remaining branches and is carried into successor states
    until a dependent transition (write/read overlap on another actor)
    wakes it. Search nodes are memoized on (state, sleep set), which
    keeps the pruning sound for the safety properties checked here —
    tests assert reduced and full mode agree on every model in the
    mutation matrix.

Deadlock (no transition enabled in a non-final state) is always a
violation: the lost-wakeup and numbering-desync bugs the models seed
manifest exactly that way.

Determinism: transitions fire in declaration order; dict states are
frozen to sorted tuples. No wall clock, no randomness — a violation
trace replays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

State = Dict[str, object]
Key = Tuple[Tuple[str, object], ...]


@dataclass(frozen=True)
class Transition:
    """One atomic protocol step of one actor.

    ``reads``/``writes`` name the state keys the step touches — the
    independence relation for sleep-set pruning. Over-approximating is
    safe (less pruning); under-approximating is NOT.
    """
    name: str
    actor: str
    guard: Callable[[State], bool]
    apply: Callable[[State], State]
    reads: FrozenSet[str] = frozenset()
    writes: FrozenSet[str] = frozenset()


@dataclass
class Model:
    name: str
    init: State
    transitions: Sequence[Transition]
    # invariant: state -> error message (None = holds)
    invariants: Sequence[Tuple[str, Callable[[State], Optional[str]]]]
    # states where "nothing enabled" is legal termination
    is_final: Callable[[State], bool] = lambda s: True


@dataclass
class Violation:
    invariant: str            # invariant name, or "deadlock"
    message: str
    state: State
    trace: List[str]          # transition names from init


@dataclass
class Result:
    model: str
    states: int = 0
    fired: int = 0
    complete: bool = True     # False = truncated by max_states
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def violated(self, invariant: str) -> bool:
        return any(v.invariant == invariant for v in self.violations)


def _freeze(state: State) -> Key:
    return tuple(sorted(state.items()))


def _dependent(a: Transition, b: Transition) -> bool:
    if a.actor == b.actor:
        return True
    return bool(a.writes & (b.reads | b.writes)) \
        or bool(b.writes & a.reads)


def explore(model: Model, max_states: int = 500000,
            max_violations: int = 16, reduce: bool = False) -> Result:
    """Exhaustively explore ``model`` within ``max_states`` search
    nodes. Stops early once ``max_violations`` distinct violations are
    collected (each invariant reports at most once per distinct
    message)."""
    res = Result(model.name)
    seen_viol: set = set()

    def check(state: State, trace: List[str]) -> None:
        for name, pred in model.invariants:
            msg = pred(state)
            if msg is not None and (name, msg) not in seen_viol:
                seen_viol.add((name, msg))
                res.violations.append(
                    Violation(name, msg, dict(state), list(trace)))

    # stack entries: (state, key, enabled list, next index, sleep set,
    #                 trace length on entry)
    init = dict(model.init)
    visited: set = set()
    trace: List[str] = []

    def node_key(key: Key, sleep: FrozenSet[int]) -> Tuple:
        return (key, sleep) if reduce else key

    enabled0 = [t for t in model.transitions if t.guard(init)]
    key0 = _freeze(init)
    visited.add(node_key(key0, frozenset()))
    check(init, trace)
    if not enabled0 and not model.is_final(init):
        res.violations.append(Violation("deadlock", "no transition "
                                        "enabled in initial state",
                                        dict(init), []))
    stack: List[list] = [[init, enabled0, 0, frozenset()]]

    while stack:
        if len(res.violations) >= max_violations:
            break
        if res.states >= max_states:
            res.complete = False
            break
        frame = stack[-1]
        state, enabled, idx, sleep = frame
        if idx >= len(enabled):
            stack.pop()
            if trace:
                trace.pop()
            continue
        frame[2] += 1
        t = enabled[idx]
        ti = model.transitions.index(t)
        if reduce and ti in sleep:
            # pruned: an independent sibling subtree already covers it
            continue
        new_state = t.apply(dict(state))
        res.fired += 1
        new_key = _freeze(new_state)
        # sleep set carried into the successor: executed-earlier
        # siblings stay asleep until a dependent transition fires
        new_sleep = frozenset(
            j for j in sleep
            if not _dependent(model.transitions[j], t)) if reduce \
            else frozenset()
        # siblings explored before t at THIS node go to sleep for t's
        # subtree when independent of t
        if reduce:
            for k in range(idx):
                u = enabled[k]
                uj = model.transitions.index(u)
                if not _dependent(u, t):
                    new_sleep |= {uj}
        nk = node_key(new_key, new_sleep)
        trace.append(t.name)
        if nk in visited:
            trace.pop()
            continue
        visited.add(nk)
        res.states += 1
        check(new_state, trace)
        new_enabled = [u for u in model.transitions if u.guard(new_state)]
        if not new_enabled and not model.is_final(new_state):
            if ("deadlock", "dl") not in seen_viol:
                seen_viol.add(("deadlock", "dl"))
                res.violations.append(
                    Violation("deadlock",
                              "no transition enabled in a non-final "
                              "state", dict(new_state), list(trace)))
        stack.append([new_state, new_enabled, 0, new_sleep])
    return res

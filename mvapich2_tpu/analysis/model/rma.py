"""Passive-target epoch model of the one-sided RMA engine
(ops/pallas_rma.py + rma/device.py).

The lock/flush/unlock grammar and the target-side fold pipeline of the
device RMA lane have never run against an adversarial interleaving:
the jax<0.5 interpreter is synchronous dataflow (creditless, one
program order), so interpreter runs validate the data schedule but not
the sync grammar the hardware path depends on. This model is that
grammar's verification net — the one-sided sibling of the ici
chunk-credit model.

The protocol, reduced to its sync skeleton: an **origin** opens an
exclusive passive epoch on the target (MPI_Win_lock), streams C
accumulate chunks through the D-credit slot schedule, flushes (the
completion wave: every fold committed, credit balance restored —
``_RmaStreamer.finish()``), and unlocks. At the target a **folder**
(the target-side agent of the origin's epoch — the DMA landings plus
the VPU fold) processes each landed chunk in two phases, exactly the
kernel's shape: *begin* captures the window operand and computes the
fold (the ``pending_fold`` prefetch + VPU add), *end* commits the
result to the window cell and re-grants the slot credit (the
``pending_store`` wave). Between begin and end the cell is mid-commit:
a concurrent load would tear. A local **reader** at the target takes
the same lock, loads every window cell, and unlocks — the
"concurrent Put + local load" pair of the no-torn-read contract.

What the model proves (exhaustively, within C x D x W bounds):

  * **lock-exclusive** — the origin's passive epoch and the local
    reader never hold the window lock simultaneously;
  * **no-torn-window-read** — the reader never loads a cell while a
    fold commit is in flight on it (the lock + flush grammar is what
    makes this true; there is no per-element interlock);
  * **flush-completes-all-outstanding** — when flush returns, every
    issued chunk's fold has committed and the credit balance is back
    to D (the MPI_Win_flush contract on the chunk-credit wave);
  * **acc-atomicity** — once all folds committed, every window cell
    equals the exact sum of its contributions: no fold ever captured a
    stale operand (read-modify-write per element is atomic);
  * **no-deadlock** — the epoch always completes (explorer built-in).

Mutations (tests/test_modelcheck.py asserts every one is caught by a
named invariant):

  flush_skips_chunk    flush's completion wave waits one chunk short
                       (the finish() loop dropping a pending handle) —
                       flush returns with a fold outstanding
  unlock_before_drain  unlock released before the completion wave (the
                       epoch grammar inverted) — the reader acquires a
                       legitimately free lock and tears a mid-commit
                       cell
  no_target_fold_order the fold of chunk c+1 captures its window
                       operand before chunk c's commit landed (the
                       ``prev_st.wait()`` slot-reuse wait dropped) —
                       a lost update: the cell misses a contribution
  torn_window_read     the local load bypasses the lock protocol
                       entirely (a raw shard read outside the epoch
                       grammar) — it tears a mid-commit cell
  no_lock_wait         the reader's lock acquire ignores the holder
                       (the exclusivity guard dropped) — both sides
                       inside the epoch at once

Payloads are distinct integers (chunk c contributes c+1), so a lost
update or stale fold is visible in the final cell sums, and a torn
cell is the seqlock model's TORN sentinel.
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition
from .seqlock import TORN


def build_passive(chunks: int = 3, depth: int = 2, cells: int = 1,
                  mutation: Optional[str] = None) -> Model:
    """One origin streams ``chunks`` accumulate chunks (chunk c lands
    in window cell c % ``cells``) through a ``depth``-credit slot
    schedule inside a lock/flush/unlock passive epoch, against a
    concurrent local reader of every cell.

    Note: the ``no_target_fold_order`` stale-operand race needs
    ``depth > cells`` — with depth <= cells the credit schedule itself
    keeps two folds of the same cell from being in flight at once, so
    the dropped slot-reuse wait is masked. The default bounds
    (C=3, D=2, W=1) expose it."""
    assert chunks >= 1 and depth >= 1 and cells >= 1
    C, D, W = chunks, depth, min(cells, chunks)

    def cell(c: int) -> int:
        return c % W

    expected = [sum(c + 1 for c in range(C) if cell(c) == w)
                for w in range(W)]

    # origin program: lock, issue 0..C-1, flush, unlock — the mutant
    # inverts the last two (unlock before the completion wave)
    prog = ["lock"] + [("issue", c) for c in range(C)]
    if mutation == "unlock_before_drain":
        prog += ["unlock", "flush"]
    else:
        prog += ["flush", "unlock"]
    flush_idx = prog.index("flush")
    # chunks issued once the origin program counter has passed step i
    issued_at = [0]
    for step in prog:
        issued_at.append(issued_at[-1]
                         + (1 if isinstance(step, tuple) else 0))

    init = {"opc": 0, "rpc": 0, "lo": False, "lr": False, "cr": D,
            "begun": 0, "ended": 0, "res": ()}
    for w in range(W):
        init[f"val{w}"] = 0
    for c in range(C):
        init[f"tmp{c}"] = None

    ts = []

    # ---- origin --------------------------------------------------------
    for i, step in enumerate(prog):
        def mk(i=i, step=step):
            if step == "lock":
                def guard(s):
                    return s["opc"] == i and not s["lo"] and not s["lr"]

                def apply(s):
                    s["lo"] = True
                    s["opc"] = i + 1
                    return s
                return Transition(f"o.lock", "origin", guard, apply,
                                  frozenset({"opc", "lo", "lr"}),
                                  frozenset({"opc", "lo"}))
            if step == "unlock":
                def guard(s):
                    return s["opc"] == i and s["lo"]

                def apply(s):
                    s["lo"] = False
                    s["opc"] = i + 1
                    return s
                return Transition(f"o.unlock", "origin", guard, apply,
                                  frozenset({"opc", "lo"}),
                                  frozenset({"opc", "lo"}))
            if step == "flush":
                def guard(s):
                    if s["opc"] != i:
                        return False
                    if mutation == "flush_skips_chunk":
                        # MUTANT: the completion wave drops one pending
                        # handle — returns a chunk short
                        return s["ended"] >= C - 1
                    return s["ended"] == C and s["cr"] == D

                def apply(s):
                    s["opc"] = i + 1
                    return s
                return Transition(f"o.flush", "origin", guard, apply,
                                  frozenset({"opc", "ended", "cr"}),
                                  frozenset({"opc"}))
            _t, c = step

            def guard(s):
                return s["opc"] == i and s["cr"] > 0

            def apply(s):
                s["cr"] -= 1       # the slot credit of the remote DMA
                s["opc"] = i + 1
                return s
            return Transition(f"o.issue{c}", "origin", guard, apply,
                              frozenset({"opc", "cr"}),
                              frozenset({"opc", "cr"}))
        ts.append(mk())

    # ---- the target-side folder (DMA landings + VPU fold) --------------
    for c in range(C):
        def mkb(c=c):
            vw = f"val{cell(c)}"

            def guard(s):
                if s["begun"] != c or issued_at[s["opc"]] <= c:
                    return False
                if mutation == "no_target_fold_order":
                    return True   # MUTANT: operand prefetch skips the
                    #               previous commit's slot-reuse wait
                return s["ended"] == s["begun"]   # strictly sequential

            def apply(s):
                # capture the committed operand + compute the fold
                s[f"tmp{c}"] = s[vw] + (c + 1)
                s["begun"] = c + 1
                return s
            return Transition(f"f.begin{c}", "folder", guard, apply,
                              frozenset({"begun", "ended", "opc", vw}),
                              frozenset({"begun", f"tmp{c}"}))

        def mke(c=c):
            vw = f"val{cell(c)}"

            def guard(s):
                return s["ended"] == c and s["begun"] > c

            def apply(s):
                s[vw] = s[f"tmp{c}"]   # the commit store lands
                s["ended"] = c + 1
                s["cr"] += 1           # re-grant the slot credit
                return s
            return Transition(f"f.end{c}", "folder", guard, apply,
                              frozenset({"begun", "ended", f"tmp{c}"}),
                              frozenset({"ended", "cr", vw}))
        ts.append(mkb())
        ts.append(mke())

    # ---- the local reader ----------------------------------------------
    # program: lock, read cell 0..W-1, unlock. torn_window_read bypasses
    # the lock protocol entirely (raw loads outside the epoch grammar).
    bypass = mutation == "torn_window_read"

    def r_lock_guard(s):
        if s["rpc"] != 0:
            return False
        if bypass or mutation == "no_lock_wait":
            return True        # MUTANT: no exclusivity wait
        return not s["lo"] and not s["lr"]

    def r_lock_apply(s):
        if not bypass:
            s["lr"] = True
        s["rpc"] = 1
        return s
    ts.append(Transition("r.lock", "reader", r_lock_guard, r_lock_apply,
                         frozenset({"rpc", "lo", "lr"}),
                         frozenset({"rpc", "lr"})))

    for w in range(W):
        def mkr(w=w):
            vw = f"val{w}"

            def guard(s):
                return s["rpc"] == 1 + w

            def apply(s):
                # a cell is mid-commit while any fold targeting it has
                # begun and not ended — a concurrent load tears
                mid = any(cell(c) == w
                          for c in range(s["ended"], s["begun"]))
                s["res"] = s["res"] + (TORN if mid else s[vw],)
                s["rpc"] = 2 + w if w < W - 1 else W + 1
                return s
            return Transition(f"r.read{w}", "reader", guard, apply,
                              frozenset({"rpc", "begun", "ended", vw}),
                              frozenset({"rpc", "res"}))
        ts.append(mkr())

    def r_unlock_guard(s):
        return s["rpc"] == W + 1 and (bypass or s["lr"])

    def r_unlock_apply(s):
        if not bypass:
            s["lr"] = False
        s["rpc"] = W + 2
        return s
    ts.append(Transition("r.unlock", "reader", r_unlock_guard,
                         r_unlock_apply, frozenset({"rpc", "lr"}),
                         frozenset({"rpc", "lr"})))

    # ---- invariants ----------------------------------------------------
    def inv_lock(s):
        if s["lo"] and s["lr"]:
            return ("origin's passive epoch and the local reader hold "
                    "the window lock simultaneously")
        return None

    def inv_torn(s):
        for i, v in enumerate(s["res"]):
            if v is TORN or v == TORN:
                return (f"local load {i} tore a mid-commit window cell "
                        "(fold commit in flight)")
        return None

    def inv_flush(s):
        if s["opc"] > flush_idx:
            if s["ended"] != C:
                return (f"flush returned with {C - s['ended']} fold(s) "
                        "outstanding — MPI_Win_flush must complete all "
                        "outstanding ops")
            if s["cr"] != D:
                return (f"flush returned with credit balance {s['cr']} "
                        f"!= depth {D}")
        return None

    def inv_atomic(s):
        if s["ended"] == C:
            for w in range(W):
                if s[f"val{w}"] != expected[w]:
                    return (f"window cell {w} holds {s[f'val{w}']} != "
                            f"exact sum {expected[w]} — a fold captured "
                            "a stale operand (lost update)")
        return None

    end_o, end_r = len(prog), W + 2

    def final(s):
        return (s["opc"] == end_o and s["rpc"] == end_r
                and s["ended"] == C)

    label = (f"rma-passive(C={C},D={D},W={W},mut={mutation})")
    return Model(label, init, ts,
                 [("lock-exclusive", inv_lock),
                  ("no-torn-window-read", inv_torn),
                  ("flush-completes-all-outstanding", inv_flush),
                  ("acc-atomicity", inv_atomic)],
                 final)

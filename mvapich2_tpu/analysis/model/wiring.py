"""2-stage lazy-wiring model (ShmChannel.ensure_wired/try_wire, PR 9).

The wire state machine, as shipped: every rank publishes its BUILD
cards (bell + CMA probe buffer) at channel construction; stage 0→1
peeks every non-dead peer's build cards, computes this rank's verdict
(its actual capability, forced 0 once any death is known — the
degraded wire), and publishes it; stage 1→2 peeks every non-dead
peer's verdict, applies the unanimous AND, and opens the tier. A rank
SIGKILLed mid-wire can never publish; survivors detect it (lease scan
/ launcher events) and complete DEGRADED with all-False agreements.
A revoke observed before the apply also forces the tier off (the
"no post-revoke wire" rule).

Invariants:
  no-hang              every live rank wires (deadlock = the mid-wire
                       stall class ensure_wired's timeout merely bounds)
  unsafe-enable        a rank never applies tier=1 while some
                       participating rank's real capability is 0 — the
                       mixed-tier corruption class (one rank folds into
                       a flat region another never mapped)
  degraded-all-off     a wire completed with death knowledge applies
                       tier 0 (conservative agreements only)
  clean-agreement      with no deaths and no revoke, all ranks apply
                       the same tier
  no-post-revoke-wire  a wire applied after observing a revoke is off

Mutations:
  skip_unanimity       apply my own verdict instead of the AND
  no_dead_exclude      stage peeks wait for DEAD peers' cards too
  no_degrade           death knowledge doesn't force the agreements off
  verdict_before_cards publish an optimistic verdict without the build-
                       card wait (the not-yet-attached arena class)
  wire_after_revoke    the apply ignores the revoked flag
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .explorer import Model, Transition


def build_wire(n: int = 2, caps: Optional[Sequence[int]] = None,
               crash: bool = False, revoke: bool = False,
               mutation: Optional[str] = None) -> Model:
    """``caps[i]`` is rank i's real capability (CMA probe / arena map
    success). ``crash`` lets the last rank die at any pre-wired step;
    ``revoke`` adds a ULFM revoke any death-aware rank may flood."""
    caps = tuple(caps) if caps is not None else tuple([1] * n)
    assert len(caps) == n
    victim = n - 1
    init = {}
    for i in range(n):
        init[f"cards{i}"] = 0        # build cards published
        init[f"verd{i}"] = -1        # published verdict (-1 = none)
        init[f"tier{i}"] = -1        # applied tier (-1 = unwired)
        init[f"alive{i}"] = 1
        init[f"det{i}"] = 0          # victim-death knowledge
        init[f"deg{i}"] = 0          # wired with death knowledge
        init[f"wrev{i}"] = 0         # wired after observing revoke
    init["revoked"] = 0

    def ts():
        out = []
        for i in range(n):
            out.extend(rank_ts(i))
        if crash:
            out.append(Transition(
                "die", f"r{victim}",
                lambda s: s[f"alive{victim}"] == 1
                and s[f"tier{victim}"] < 0,
                lambda s: (s.__setitem__(f"alive{victim}", 0), s)[1],
                frozenset({f"alive{victim}", f"tier{victim}"}),
                frozenset({f"alive{victim}"})))
            for i in range(n):
                if i == victim:
                    continue
                def g_det(s, i=i):
                    return s[f"alive{i}"] == 1 \
                        and s[f"alive{victim}"] == 0 and s[f"det{i}"] == 0

                def a_det(s, i=i):
                    s[f"det{i}"] = 1
                    return s
                out.append(Transition(
                    f"detect{i}", f"r{i}", g_det, a_det,
                    frozenset({f"alive{i}", f"alive{victim}",
                               f"det{i}"}),
                    frozenset({f"det{i}"})))
        if revoke:
            for i in range(n):
                def g_rev(s, i=i):
                    return s[f"alive{i}"] == 1 and s[f"det{i}"] == 1 \
                        and s["revoked"] == 0

                def a_rev(s, i=i):
                    s["revoked"] = 1
                    return s
                out.append(Transition(
                    f"revoke{i}", f"r{i}", g_rev, a_rev,
                    frozenset({f"alive{i}", f"det{i}", "revoked"}),
                    frozenset({"revoked"})))
        return out

    def rank_ts(i: int):
        def g_build(s):
            return s[f"alive{i}"] == 1 and s[f"cards{i}"] == 0

        def a_build(s):
            s[f"cards{i}"] = 1
            return s

        def peers_ready(s, field: str) -> bool:
            unpublished = 0 if field == "cards" else -1
            for j in range(n):
                if j == i:
                    continue
                if mutation != "no_dead_exclude" and s[f"det{i}"] \
                        and j == victim:
                    continue          # detected-dead peers are excluded
                if s[f"{field}{j}"] == unpublished:
                    return False
            return True

        def g_verdict(s):
            if not (s[f"alive{i}"] == 1 and s[f"cards{i}"] == 1
                    and s[f"verd{i}"] == -1):
                return False
            if mutation == "verdict_before_cards":
                return True           # MUTANT: skip the card wait
            return peers_ready(s, "cards")

        def a_verdict(s):
            if mutation == "verdict_before_cards":
                # MUTANT: optimistic publish before the attach step
                # that would have discovered the real capability
                s[f"verd{i}"] = 1
                return s
            v = caps[i]
            if s[f"det{i}"] and mutation != "no_degrade":
                v = 0                 # degraded wire publishes all-off
            s[f"verd{i}"] = v
            return s

        def g_wire(s):
            return s[f"alive{i}"] == 1 and s[f"verd{i}"] != -1 \
                and s[f"tier{i}"] < 0 and peers_ready(s, "verd")

        def a_wire(s):
            if mutation == "skip_unanimity":
                t = s[f"verd{i}"]     # MUTANT: my verdict, not the AND
            else:
                t = s[f"verd{i}"]
                for j in range(n):
                    if j == i:
                        continue
                    if s[f"det{i}"] and j == victim:
                        continue
                    t = min(t, s[f"verd{j}"])
            if s[f"det{i}"] and mutation != "no_degrade":
                t = 0
                s[f"deg{i}"] = 1
            elif s[f"det{i}"]:
                s[f"deg{i}"] = 1      # MUTANT kept the agreement on
            if s["revoked"]:
                s[f"wrev{i}"] = 1
                if mutation != "wire_after_revoke":
                    t = 0
            s[f"tier{i}"] = t
            return s

        all_keys = frozenset(
            [f"cards{j}" for j in range(n)]
            + [f"verd{j}" for j in range(n)]
            + [f"alive{i}", f"det{i}", "revoked"])
        return [
            Transition(f"build{i}", f"r{i}", g_build, a_build,
                       frozenset({f"alive{i}", f"cards{i}"}),
                       frozenset({f"cards{i}"})),
            Transition(f"verdict{i}", f"r{i}", g_verdict, a_verdict,
                       all_keys | {f"verd{i}"},
                       frozenset({f"verd{i}"})),
            Transition(f"wire{i}", f"r{i}", g_wire, a_wire,
                       all_keys | {f"tier{i}"},
                       frozenset({f"tier{i}", f"deg{i}", f"wrev{i}"})),
        ]

    def inv_unsafe(s):
        for i in range(n):
            if s[f"tier{i}"] == 1:
                bad = [j for j in range(n) if caps[j] == 0]
                if bad:
                    return (f"rank {i} enabled the shared tier while "
                            f"rank(s) {bad} lack the capability — "
                            "mixed-tier dispatch")
        return None

    def inv_degraded(s):
        for i in range(n):
            if s[f"deg{i}"] == 1 and s[f"tier{i}"] == 1:
                return (f"rank {i} wired DEGRADED (knew of a death) "
                        "but still enabled the shared tier")
        return None

    def inv_agreement(s):
        if crash and s[f"alive{victim}"] == 0:
            return None
        if s["revoked"]:
            return None
        tiers = {s[f"tier{i}"] for i in range(n)
                 if s[f"tier{i}"] >= 0}
        if len(tiers) > 1:
            return f"clean run wired mixed tiers {sorted(tiers)}"
        return None

    def inv_revoke(s):
        for i in range(n):
            if s[f"wrev{i}"] == 1 and s[f"tier{i}"] == 1:
                return (f"rank {i} enabled the shared tier in a wire "
                        "applied after the comm was revoked")
        return None

    def final(s):
        return all(s[f"alive{i}"] == 0 or s[f"tier{i}"] >= 0
                   for i in range(n))

    return Model(
        f"wiring(n={n},caps={caps},crash={crash},mut={mutation})",
        init, ts(),
        [("unsafe-enable", inv_unsafe),
         ("degraded-all-off", inv_degraded),
         ("clean-agreement", inv_agreement),
         ("no-post-revoke-wire", inv_revoke)],
        final)

"""Liveness-lease model (heartbeat stamps + throttled scan, PR 6).

Discrete bounded time. Each tick advances the clock AND refreshes the
victim's heartbeat stamp while it lives (the dedicated heartbeat thread
stamps ~10x per timeout, so at tick granularity a live victim is always
fresh). The scanner fires when due; time cannot step over a due scan —
the modeling analog of "every blocking wait runs the throttled scan",
which is what the real code guarantees by scanning from cp_wait_quantum,
flat_wait, and the python progress sleep points.

Properties:
  detect-within-deadline  a crashed victim is flagged failed no later
                          than died_at + 2*timeout
  no-false-positive       a live victim is never flagged; a cleanly
                          departed victim (DEPARTED sentinel) is never
                          flagged

Mutations:
  departed_stale    the scanner treats the Finalize sentinel as a stale
                    stamp (false positive on clean exit)
  throttle_too_long scan throttle exceeds the detection deadline
  inverted_compare  staleness compared with the operands swapped —
                    never detects anything
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

DEPARTED = "DEPARTED"


def build(timeout: int = 2, horizon: int = 10, crash: bool = False,
          depart: bool = False,
          mutation: Optional[str] = None) -> Model:
    throttle = (2 * timeout + 2 if mutation == "throttle_too_long"
                else max(1, timeout // 4))
    init = {"now": 0, "stamp": 0, "alive": 1, "departed": 0,
            "failed": 0, "scan_at": 0, "died_at": -1}

    def g_tick(s):
        # time cannot pass a due scan (the waits all scan)
        return s["now"] < horizon and s["now"] < s["scan_at"]

    def a_tick(s):
        s["now"] += 1
        if s["alive"]:
            s["stamp"] = s["now"]                # heartbeat keeps pace
        return s

    def g_scan(s):
        return s["now"] >= s["scan_at"] and s["now"] < horizon

    def a_scan(s):
        s["scan_at"] = s["now"] + throttle
        st = s["stamp"]
        if st == DEPARTED:
            if mutation == "departed_stale":
                # MUTANT: sentinel read as a numeric stamp of 0
                if s["now"] - 0 > timeout:
                    s["failed"] = 1
            return s
        if mutation == "inverted_compare":
            stale = st - s["now"] > timeout      # MUTANT: swapped
        else:
            stale = s["now"] - st > timeout
        if stale:
            s["failed"] = 1
        return s

    ts = [
        Transition("tick", "clock", g_tick, a_tick,
                   frozenset({"now", "scan_at", "alive"}),
                   frozenset({"now", "stamp"})),
        Transition("scan", "scanner", g_scan, a_scan,
                   frozenset({"now", "scan_at", "stamp"}),
                   frozenset({"scan_at", "failed"})),
    ]
    if crash:
        def g_die(s):
            return s["alive"] == 1 and s["now"] < horizon // 2

        def a_die(s):
            s["alive"] = 0
            s["died_at"] = s["now"]
            return s

        ts.append(Transition("die", "victim", g_die, a_die,
                             frozenset({"alive", "now"}),
                             frozenset({"alive", "died_at"})))
    if depart:
        def g_depart(s):
            return s["alive"] == 1

        def a_depart(s):
            s["alive"] = 0
            s["departed"] = 1
            s["stamp"] = DEPARTED                # Finalize sentinel
            return s

        ts.append(Transition("depart", "victim", g_depart, a_depart,
                             frozenset({"alive"}),
                             frozenset({"alive", "departed", "stamp"})))

    def inv_deadline(s):
        if s["died_at"] >= 0 and not s["failed"] \
                and s["now"] > s["died_at"] + 2 * timeout:
            return (f"victim died at t={s['died_at']} and is still "
                    f"undetected at t={s['now']} (> 2x timeout "
                    f"{timeout})")
        return None

    def inv_false_pos(s):
        if s["failed"] and s["died_at"] < 0:
            who = "cleanly departed" if s["departed"] else "live"
            return f"{who} victim flagged as failed"
        return None

    def final(s):
        return True          # any quiescent point is a legal end

    return Model(f"lease(T={timeout},mut={mutation})", init, ts,
                 [("detect-within-deadline", inv_deadline),
                  ("no-false-positive", inv_false_pos)], final)

"""Exhaustive model of the NBC DAG engine (coll/nbc/engine.py +
coll/nbc/dag.py) — the one protocol surface PR 18 shipped without a
checker of its own.

The engine, reduced to its scheduling skeleton: a **schedule** is a
DAG of vertices (CALL / RECV / SEND / POLL). The scheduler issues every
vertex whose dependency count has drained to zero; CALL completes
inline at issue, RECV/SEND go inflight until a completion wakeup
(``_on_completion``) fires, POLL is *parked* after its async hardware
dispatch launches and is pumped by the progress hook once the hardware
epoch finishes. Each completion decrements its children's dependency
counts (the wakeup edge that keeps the DAG advancing without a
dedicated thread). When every vertex is done the schedule completes and
``nbc_scheds_active`` drains; an error unwind (``_complete(error=...)``)
cancels inflight ops and clears the parked-poll set. Persistent
(MPI_*_init/start) schedules restart: state fully re-initialised, the
exec-cache epoch reused but every vertex re-issued fresh.

Two DAG shapes are modelled, both taken from the engine's real builders:

  ``device``  the device i-collective shape (coll/device.py): one
              deposit CALL, ``segs`` segment POLLs depending on it,
              one finish CALL depending on every POLL
  ``net``     the host shape: RECV + SEND roots feeding a fold CALL

What the model proves (exhaustively, all interleavings of scheduler,
completion wakeups, async hardware, and the progress-hook pump):

  * **nbc-deps-before-issue** — no vertex is ever issued while a
    dependency is outstanding (the DAG order is real, not advisory);
  * **nbc-deposit-before-poll** — on the device shape no segment POLL
    launches before the deposit CALL completed (the operand must be in
    the remote staging slots before any chunk wave starts);
  * **nbc-issue-before-complete** — a completion wakeup only ever
    lands on a vertex that was issued;
  * **nbc-drained-at-finalize** — when the schedule completes (clean
    or error-unwound), no op is inflight, no poll is parked, and the
    ``nbc_scheds_active`` gauge is back to zero;
  * **nbc-exec-epoch-fresh** — a (re)started persistent schedule
    completes only after issuing every vertex in that run: exec-cache
    epoch reuse never reuses vertex *state*;
  * **no-deadlock** — the schedule always completes (explorer
    built-in): the wakeup/pump edges are sufficient for progress.

Mutations (tests/test_modelcheck.py asserts each is caught by a named
invariant):

  issue_ignores_deps     the ready-scan drops the ndeps==0 guard —
                         vertices issue in arbitrary order (finish
                         before its polls, polls before the deposit)
  poll_never_pumped      the progress hook loses the parked-poll set
                         (the _hook pump edge removed) — the schedule
                         hangs exactly like a lost wakeup
  lost_completion_wakeup a RECV/SEND completion fails to decrement its
                         children's dependency counts (_vertex_done's
                         fan-out dropped) — downstream never readies
  unwind_leaves_inflight the error unwind forgets to cancel inflight
                         ops / clear parked polls (_complete's cancel
                         loop dropped) — the schedule "completes" with
                         live ops still attached
  stale_persistent_reuse persistent restart reuses last run's vertex
                         state instead of re-initialising — run 2
                         "completes" having issued nothing
  spurious_completion    a completion wakeup lands on a never-issued
                         vertex (a stale handle from a prior epoch)

The runtime trace grammar of the engine this model abstracts lives in
``TRACE_EVENTS`` below; analysis/conform.py imports it so the NBC
conformance automaton and this model can never drift apart.
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

# vertex kinds — mirrors coll/nbc/dag.py (CALL/RECV/SEND/POLL)
CALL, RECV, SEND, POLL = 0, 1, 2, 3

# The event grammar the live engine emits for this protocol surface
# (trace layer -> event names). coll/nbc/engine.py emits the nbc-layer
# schedule/vertex events; coll/device.py emits the device-layer
# per-segment dispatch instants. The conformance automaton derives its
# grammar from this table — shared source of truth with the model.
TRACE_EVENTS = {
    "nbc": ("sched_start", "vertex_issue", "vertex_complete",
            "sched_complete"),
    "device": ("nbc_dev_issue", "nbc_dev_complete"),
}

INVARIANTS = (
    "nbc-deps-before-issue",
    "nbc-deposit-before-poll",
    "nbc-issue-before-complete",
    "nbc-drained-at-finalize",
    "nbc-exec-epoch-fresh",
)

# vertex states
_WAIT, _INFLIGHT, _PARKED, _DONE, _CANC = 0, 1, 2, 3, 4


def _shape(shape: str, segs: int):
    """(kinds, deps) for the modelled DAG shape."""
    if shape == "device":
        # 0 = deposit CALL, 1..segs = segment POLLs, segs+1 = finish
        kinds = [CALL] + [POLL] * segs + [CALL]
        deps = [()] + [(0,)] * segs + [tuple(range(1, segs + 1))]
        return kinds, deps
    if shape == "net":
        # RECV + SEND roots feeding a fold CALL (the host ibcast /
        # ireduce builder shape collapsed to one stage)
        kinds = [RECV, SEND, CALL]
        deps = [(), (), (0, 1)]
        return kinds, deps
    raise ValueError(f"unknown shape {shape!r}")


def build_nbc(shape: str = "device", segs: int = 2,
              persistent: bool = False, error: bool = False,
              mutation: Optional[str] = None) -> Model:
    """One schedule of the given ``shape`` driven to completion by the
    scheduler / completion-wakeup / async-hardware / progress-hook
    actors; ``persistent`` adds one restart cycle, ``error`` makes the
    first segment POLL's hardware epoch fail (PROC_FAILED shape) so the
    cancel/error unwind runs."""
    assert not (persistent and error), "modelled one axis at a time"
    kinds, deps = _shape(shape, segs)
    V = len(kinds)
    ndeps0 = [len(d) for d in deps]
    children = [[w for w in range(V) if v in deps[w]] for v in range(V)]
    err_vertex = 1 if error else -1    # first segment POLL fails

    init = {"done": 0, "active": 1, "iss": 0,
            "runs": 1 if persistent else 0,
            "dv": 0, "pbd": 0, "spur": 0}
    for v in range(V):
        init[f"st{v}"] = _WAIT
        init[f"nd{v}"] = ndeps0[v]
        if kinds[v] == POLL:
            init[f"hw{v}"] = 0

    ts = []

    def _propagate(s, v):
        for w in children[v]:
            s[f"nd{w}"] -= 1

    # ---- scheduler: issue every ready vertex ---------------------------
    for v in range(V):
        def mk_issue(v=v):
            kind = kinds[v]

            def guard(s):
                if s["done"] != 0 or s[f"st{v}"] != _WAIT:
                    return False
                if mutation == "issue_ignores_deps":
                    return True
                return s[f"nd{v}"] == 0

            def apply(s):
                s["iss"] += 1
                if s[f"nd{v}"] > 0:
                    s["dv"] = 1                      # dep still open
                if kind == POLL and s["st0"] != _DONE \
                        and kinds[0] == CALL:
                    s["pbd"] = 1                     # poll pre-deposit
                if kind == CALL:
                    s[f"st{v}"] = _DONE              # inline completion
                    _propagate(s, v)
                elif kind == POLL:
                    s[f"st{v}"] = _PARKED            # async dispatch
                    s[f"hw{v}"] = 1                  # launched
                else:                                # RECV / SEND
                    s[f"st{v}"] = _INFLIGHT
                return s
            keys = frozenset({"done", f"st{v}", f"nd{v}", "iss", "dv",
                              "pbd", "st0"}
                             | {f"nd{w}" for w in children[v]}
                             | ({f"hw{v}"} if kind == POLL else set()))
            return Transition(f"sched.issue{v}", "sched", guard, apply,
                              keys, keys)
        ts.append(mk_issue())

    # ---- completion wakeups on inflight net ops ------------------------
    for v in range(V):
        if kinds[v] not in (RECV, SEND):
            continue

        def mk_complete(v=v):
            def guard(s):
                return s["done"] == 0 and s[f"st{v}"] == _INFLIGHT

            def apply(s):
                s[f"st{v}"] = _DONE
                if mutation != "lost_completion_wakeup":
                    _propagate(s, v)
                return s
            keys = frozenset({"done", f"st{v}"}
                             | {f"nd{w}" for w in children[v]})
            return Transition(f"net.complete{v}", "net", guard, apply,
                              keys, keys)
        ts.append(mk_complete())

    # spurious completion: a stale handle fires a wakeup on a vertex
    # that was never issued (the mutation the issue-before-complete
    # invariant exists for)
    if mutation == "spurious_completion":
        sv = next(v for v in range(V) if kinds[v] in (RECV, SEND, POLL))

        def sp_guard(s):
            return s["done"] == 0 and s[f"st{sv}"] == _WAIT

        def sp_apply(s):
            s["spur"] = 1
            s[f"st{sv}"] = _DONE
            _propagate(s, sv)
            return s
        keys = frozenset({"done", f"st{sv}", "spur"}
                         | {f"nd{w}" for w in children[sv]})
        ts.append(Transition(f"net.spurious{sv}", "net", sp_guard,
                             sp_apply, keys, keys))

    # ---- async hardware: a launched poll's epoch finishes --------------
    for v in range(V):
        if kinds[v] != POLL:
            continue

        def mk_hw(v=v):
            def guard(s):
                return s["done"] == 0 and s[f"hw{v}"] == 1

            def apply(s):
                s[f"hw{v}"] = 2
                return s
            keys = frozenset({"done", f"hw{v}"})
            return Transition(f"dev.epoch{v}", "dev", guard, apply,
                              keys, keys)
        ts.append(mk_hw())

    # ---- progress hook: pump parked polls whose epoch finished ---------
    for v in range(V):
        if kinds[v] != POLL:
            continue

        def mk_pump(v=v):
            def guard(s):
                if mutation == "poll_never_pumped":
                    return False
                return (s["done"] == 0 and s[f"st{v}"] == _PARKED
                        and s[f"hw{v}"] == 2)

            def apply(s):
                if v == err_vertex:
                    # the poll raises (PROC_FAILED shape): error
                    # unwind — cancel inflight, clear parked polls,
                    # drain the active gauge (_complete(error=...))
                    s["done"] = 2
                    s["active"] -= 1
                    if mutation != "unwind_leaves_inflight":
                        for u in range(V):
                            if s[f"st{u}"] in (_INFLIGHT, _PARKED):
                                s[f"st{u}"] = _CANC
                    else:
                        s[f"st{v}"] = _CANC   # only the raiser clears
                    return s
                s[f"st{v}"] = _DONE
                _propagate(s, v)
                return s
            keys = frozenset({"done", "active", f"st{v}", f"hw{v}"}
                             | {f"st{u}" for u in range(V)}
                             | {f"nd{w}" for w in children[v]})
            return Transition(f"hook.pump{v}", "hook", guard, apply,
                              keys, keys)
        ts.append(mk_pump())

    # ---- schedule completion + persistent restart ----------------------
    def done_guard(s):
        return s["done"] == 0 and all(s[f"st{v}"] == _DONE
                                      for v in range(V))

    def done_apply(s):
        s["done"] = 1
        s["active"] -= 1
        return s
    keys = frozenset({"done", "active"} | {f"st{v}" for v in range(V)})
    ts.append(Transition("sched.complete", "sched", done_guard,
                         done_apply, keys, keys))

    if persistent:
        def re_guard(s):
            return s["done"] == 1 and s["runs"] > 0

        def re_apply(s):
            s["runs"] -= 1
            s["done"] = 0
            s["active"] += 1
            s["iss"] = 0
            if mutation != "stale_persistent_reuse":
                for v in range(V):        # full state re-init (start())
                    s[f"st{v}"] = _WAIT
                    s[f"nd{v}"] = ndeps0[v]
                    if kinds[v] == POLL:
                        s[f"hw{v}"] = 0
            return s
        keys = frozenset({"done", "active", "iss", "runs"}
                         | {f"st{v}" for v in range(V)}
                         | {f"nd{v}" for v in range(V)}
                         | {f"hw{v}" for v in range(V)
                            if kinds[v] == POLL})
        ts.append(Transition("sched.restart", "sched", re_guard,
                             re_apply, keys, keys))

    # ---- invariants ----------------------------------------------------
    def inv_deps(s):
        if s["dv"]:
            return "vertex issued with an outstanding dependency"
        return None

    def inv_deposit(s):
        if s["pbd"]:
            return "segment POLL launched before the deposit CALL done"
        return None

    def inv_issue_before_complete(s):
        if s["spur"]:
            return "completion wakeup on a never-issued vertex"
        return None

    def inv_drained(s):
        if s["done"] == 0:
            return None
        live = [v for v in range(V)
                if s[f"st{v}"] in (_INFLIGHT, _PARKED)]
        if live:
            return (f"schedule completed with live vertices {live} "
                    "(inflight/parked not unwound)")
        if s["active"] != 0:
            return f"nbc_scheds_active={s['active']} after completion"
        return None

    def inv_epoch_fresh(s):
        if s["done"] == 1 and s["iss"] != V:
            return (f"run completed having issued {s['iss']}/{V} "
                    "vertices (stale persistent state reused)")
        return None

    invs = [
        ("nbc-deps-before-issue", inv_deps),
        ("nbc-deposit-before-poll", inv_deposit),
        ("nbc-issue-before-complete", inv_issue_before_complete),
        ("nbc-drained-at-finalize", inv_drained),
        ("nbc-exec-epoch-fresh", inv_epoch_fresh),
    ]

    def is_final(s):
        return s["done"] != 0 and (s["runs"] == 0 or s["done"] == 2)

    label = (f"nbc[{shape} segs={segs}"
             + (" persistent" if persistent else "")
             + (" error" if error else "")
             + (f" mut={mutation}" if mutation else "") + "]")
    return Model(label, init, ts, invs, is_final)

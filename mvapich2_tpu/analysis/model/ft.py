"""ULFM failure-propagation model (ft/ulfm.py + ft/elastic.py, PR 6/11).

The containment pipeline, as shipped: a victim dies mid-wave (its flat
region may hold torn seqlock words); every survivor can detect the
death independently (lease scan / launcher events) — detection unwinds
the survivor's posted recvs ON THE VICTIM with MPIX_ERR_PROC_FAILED.
A survivor blocked on a LIVE peer that diverted into recovery unwinds
only through REVOKE (the PR 6 containment gap class): any rank that
knows of the failure may revoke; the flood delivers to every survivor,
every first receipt RE-floods (delivery despite a mid-flood crash of
the initiator — modeled as the victim revoking one peer and dying),
and receipt both unwinds blocked-on-live operations and sticky-poisons
the comm's flat region. Shrink then re-keys the flat tier on a FRESH
context; a later comm may legally reuse the old ctx id — poison is
what makes that safe.

Invariants:
  eventual-delivery  every survivor learns PROC_FAILED and unblocks
                     (deadlock = a survivor parked forever on a dead
                     or diverted peer)
  rekey-fresh        shrink never re-keys onto a poisoned ctx/lane
  no-torn-rekey      a wave on a reused region never delivers the dead
                     victim's torn words (poison must refuse it first)

Mutations:
  no_revoke_unwind  REVOKE receipt leaves blocked-on-live recvs posted
  no_reflood        receivers don't re-flood (initiator died mid-flood
                    → some survivor never learns)
  detect_disabled   survivors' lease scans never fire
  no_poison         revoke skips the sticky poison
  rekey_same_ctx    shrink re-keys onto the old (poisoned) ctx
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

OLD_CTX, FRESH_CTX = 0, 1


def build_ft(n: int = 3, partial_flood: bool = False,
             reuse: bool = False,
             mutation: Optional[str] = None) -> Model:
    """``n`` ranks; rank n-1 is the victim and dies mid-wave. Rank 0
    is blocked receiving from LIVE rank 1 (which diverts to recovery on
    learning of the failure); every survivor is blocked on the victim.
    ``partial_flood``: the victim initiates the revoke, delivers it to
    exactly one survivor, and dies — re-flood must finish the job.
    ``reuse``: after shrink, a new comm reuses the old ctx id (legal —
    poison is what protects it)."""
    victim = n - 1
    surv = list(range(n - 1))
    init = {"vdead": 0, "torn": 0, "poison": 0, "revoked_any": 0}
    for i in surv:
        init[f"know{i}"] = 0         # PROC_FAILED delivered to i
        init[f"bv{i}"] = 1           # blocked on the victim
        init[f"rev{i}"] = 0          # REVOKE seen by i
        init[f"pend{i}"] = 0         # REVOKE in flight to i
        init[f"newctx{i}"] = -1      # shrink re-key choice
        init[f"waved{i}"] = 0        # post-rekey wave done
        init[f"torn_read{i}"] = 0
    init["bl0"] = 1                  # rank 0 blocked on LIVE rank 1
    init["diverted1"] = 0            # rank 1 committed to recovery

    def ts():
        out = []

        def g_die(s):
            if s["vdead"]:
                return False
            if partial_flood:
                # the victim revokes first (delivering to exactly one
                # survivor) and dies mid-flood
                return s[f"pend{surv[-1]}"] == 1 or s["revoked_any"]
            return True

        def a_die(s):
            s["vdead"] = 1
            s["torn"] = 1            # died mid-wave: torn seqlock words
            return s
        out.append(Transition("die", "victim", g_die, a_die,
                              frozenset({"vdead", "revoked_any",
                                         f"pend{surv[-1]}"}),
                              frozenset({"vdead", "torn"})))
        if partial_flood:
            def g_vrev(s):
                return not s["vdead"] and not s["revoked_any"]

            def a_vrev(s):
                # delivers to ONE survivor only, then the die above
                s["revoked_any"] = 1
                if mutation != "no_poison":
                    s["poison"] = 1
                s[f"pend{surv[-1]}"] = 1
                return s
            out.append(Transition(
                "victim_revoke_partial", "victim", g_vrev, a_vrev,
                frozenset({"vdead", "revoked_any"}),
                frozenset({"revoked_any", "poison",
                           f"pend{surv[-1]}"})))

        for i in surv:
            out.extend(surv_ts(i))
        return out

    def surv_ts(i: int):
        out = []

        def g_detect(s):
            if mutation == "detect_disabled":
                return False
            return s["vdead"] == 1 and s[f"know{i}"] == 0

        def a_detect(s):
            s[f"know{i}"] = 1
            s[f"bv{i}"] = 0          # posted recvs on the victim unwind
            return s
        out.append(Transition(f"detect{i}", f"r{i}", g_detect, a_detect,
                              frozenset({"vdead", f"know{i}"}),
                              frozenset({f"know{i}", f"bv{i}"})))

        if i == 0 and not partial_flood:
            # revoke is an APPLICATION decision, not automatic on
            # detection: exactly one initiator (rank 0 here; the victim
            # itself in the partial_flood config) — non-initiators learn
            # the comm is revoked only through the flood, which is what
            # makes re-flood delivery load-bearing
            def g_revoke(s):
                return s[f"know{i}"] == 1 and s[f"rev{i}"] == 0

            def a_revoke(s):
                s[f"rev{i}"] = 1
                s["revoked_any"] = 1
                if mutation != "no_poison":
                    s["poison"] = 1
                if i == 0 and mutation != "no_revoke_unwind":
                    # _fail_ctx_recvs runs locally at initiation too
                    s["bl0"] = 0
                for j in surv:
                    if j != i and s[f"rev{j}"] == 0:
                        s[f"pend{j}"] = 1
                return s
            out.append(Transition(
                f"revoke{i}", f"r{i}", g_revoke, a_revoke,
                frozenset({f"know{i}", f"rev{i}"} |
                          {f"rev{j}" for j in surv}),
                frozenset({f"rev{i}", "revoked_any", "poison", "bl0"} |
                          {f"pend{j}" for j in surv})))

        def g_deliver(s):
            return s[f"pend{i}"] == 1 and s[f"rev{i}"] == 0

        def a_deliver(s):
            s[f"rev{i}"] = 1
            s[f"know{i}"] = 1        # REVOKE implies failure knowledge
            s[f"bv{i}"] = 0
            if mutation != "no_poison":
                s["poison"] = 1
            if mutation != "no_revoke_unwind":
                if i == 0:
                    s["bl0"] = 0     # blocked-on-live unwinds too
            if mutation != "no_reflood":
                for j in surv:       # first receipt re-floods
                    if j != i and s[f"rev{j}"] == 0:
                        s[f"pend{j}"] = 1
            return s
        out.append(Transition(
            f"deliver{i}", f"r{i}", g_deliver, a_deliver,
            frozenset({f"pend{i}", f"rev{i}"} |
                      {f"rev{j}" for j in surv}),
            frozenset({f"rev{i}", f"know{i}", f"bv{i}", "bl0",
                       "poison"} | {f"pend{j}" for j in surv})))

        if i == 1:
            def g_divert(s):
                return (s[f"know1"] == 1 or s[f"rev1"] == 1) \
                    and s["diverted1"] == 0

            def a_divert(s):
                s["diverted1"] = 1   # never sends to rank 0 again
                return s
            out.append(Transition(
                "divert1", "r1", g_divert, a_divert,
                frozenset({"know1", "rev1", "diverted1"}),
                frozenset({"diverted1"})))

            def g_send(s):
                return s["diverted1"] == 0 and s["bl0"] == 1 \
                    and s[f"know1"] == 0 and s[f"rev1"] == 0

            def a_send(s):
                s["bl0"] = 0         # normal completion
                return s
            out.append(Transition(
                "send1", "r1", g_send, a_send,
                frozenset({"diverted1", "bl0", "know1", "rev1"}),
                frozenset({"bl0"})))

        def g_shrink(s):
            return s[f"rev{i}"] == 1 and s[f"know{i}"] == 1 \
                and s[f"newctx{i}"] < 0 and s[f"bv{i}"] == 0 \
                and (i != 0 or s["bl0"] == 0)

        def a_shrink(s):
            if mutation == "rekey_same_ctx":
                s[f"newctx{i}"] = OLD_CTX    # MUTANT: reuse the key
            else:
                s[f"newctx{i}"] = FRESH_CTX
            return s
        out.append(Transition(
            f"shrink{i}", f"r{i}", g_shrink, a_shrink,
            frozenset({f"rev{i}", f"know{i}", f"newctx{i}",
                       f"bv{i}", "bl0"}),
            frozenset({f"newctx{i}"})))

        def g_wave(s):
            return s[f"newctx{i}"] >= 0 and s[f"waved{i}"] == 0

        def a_wave(s):
            ctx = s[f"newctx{i}"]
            if reuse and mutation != "rekey_same_ctx":
                # a LATER comm legally reuses the old ctx id; poison is
                # the only protection
                ctx = OLD_CTX
            if ctx == OLD_CTX and not s["poison"] and s["torn"]:
                s[f"torn_read{i}"] = 1   # folded the victim's torn words
            s[f"waved{i}"] = 1
            return s
        out.append(Transition(
            f"wave{i}", f"r{i}", g_wave, a_wave,
            frozenset({f"newctx{i}", f"waved{i}", "poison", "torn"}),
            frozenset({f"waved{i}", f"torn_read{i}"})))
        return out

    def inv_rekey(s):
        for i in surv:
            if s[f"newctx{i}"] == OLD_CTX and s["poison"]:
                return (f"rank {i} shrink re-keyed onto the POISONED "
                        "old ctx/lane")
        return None

    def inv_torn(s):
        for i in surv:
            if s[f"torn_read{i}"]:
                return (f"rank {i} delivered the dead victim's torn "
                        "flat words through a reused, unpoisoned "
                        "region")
        return None

    def final(s):
        # eventual delivery: the job only quiesces once every survivor
        # knows, is unblocked, and finished its post-shrink wave
        return all(s[f"know{i}"] == 1 and s[f"bv{i}"] == 0
                   and s[f"waved{i}"] == 1 for i in surv) \
            and s["bl0"] == 0

    return Model(
        f"ft(n={n},partial={partial_flood},reuse={reuse},"
        f"mut={mutation})", init, ts(),
        [("rekey-fresh", inv_rekey), ("no-torn-rekey", inv_torn)],
        final)

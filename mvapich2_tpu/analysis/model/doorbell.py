"""Doorbell wait/wake model (ShmChannel adaptive bell + cp_wait_quantum).

The discipline under test is the advertise-sleep / final-poll / sleep
order on the receiver against the enqueue / read-flag / maybe-ring
order on the sender. The model's interleaving semantics IS sequential
consistency — which is exactly what the seq_cst advertise store the
mv2tlint native pass enforces buys the real code; a relaxed-order
implementation would not be entitled to this model.

  receiver: poll -> (miss) set flag -> FINAL POLL -> sleep -> wake on
            bell, clear flag, consume
  sender:   enqueue -> read flag -> ring iff flag set

Properties: no deadlock (a sleeping receiver with a queued message and
no pending bell is the lost wakeup), and the message is consumed in
every complete run.

Mutations:
  no_final_poll        receiver sleeps without the post-advertise poll
  ring_before_publish  sender samples the flag BEFORE enqueueing and
                       rings based on that stale sample
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition


def build(mutation: Optional[str] = None) -> Model:
    init = {"q": 0, "flag": 0, "bell": 0, "got": 0,
            "rpc": 0, "spc": 0, "splan": 0}

    def g_poll_hit(s):
        return s["rpc"] == 0 and s["q"] > 0 and s["got"] == 0

    def a_poll_hit(s):
        s["q"] -= 1
        s["got"] += 1
        return s

    def g_poll_miss(s):
        return s["rpc"] == 0 and s["q"] == 0 and s["got"] == 0

    def a_poll_miss(s):
        s["rpc"] = 1
        return s

    def a_advertise(s):
        s["flag"] = 1
        # MUTANT: skip the final poll, go straight to sleep
        s["rpc"] = 3 if mutation == "no_final_poll" else 2
        return s

    def g_final_hit(s):
        return s["rpc"] == 2 and s["q"] > 0

    def a_final_hit(s):
        s["flag"] = 0
        s["q"] -= 1
        s["got"] += 1
        s["rpc"] = 0
        return s

    def g_final_miss(s):
        return s["rpc"] == 2 and s["q"] == 0

    def a_final_miss(s):
        s["rpc"] = 3                             # asleep
        return s

    def g_wake(s):
        return s["rpc"] == 3 and s["bell"] > 0

    def a_wake(s):
        s["bell"] = 0
        s["flag"] = 0
        s["rpc"] = 0
        return s

    # sender ----------------------------------------------------------
    if mutation == "ring_before_publish":
        def g_s0(s):
            return s["spc"] == 0

        def a_s0(s):                              # MUTANT: stale sample
            s["splan"] = s["flag"]
            s["spc"] = 1
            return s

        def g_s1(s):
            return s["spc"] == 1

        def a_s1(s):
            s["q"] += 1
            s["spc"] = 2
            return s

        def g_s2(s):
            return s["spc"] == 2

        def a_s2(s):
            if s["splan"]:
                s["bell"] = 1
            s["spc"] = 3
            return s

        sender = [
            Transition("s.sample_flag", "s", g_s0, a_s0,
                       frozenset({"spc", "flag"}),
                       frozenset({"splan", "spc"})),
            Transition("s.enqueue", "s", g_s1, a_s1,
                       frozenset({"spc"}), frozenset({"q", "spc"})),
            Transition("s.maybe_ring", "s", g_s2, a_s2,
                       frozenset({"spc", "splan"}),
                       frozenset({"bell", "spc"})),
        ]
    else:
        def g_s0(s):
            return s["spc"] == 0

        def a_s0(s):
            s["q"] += 1
            s["spc"] = 1
            return s

        def g_s1(s):
            return s["spc"] == 1

        def a_s1(s):
            if s["flag"]:
                s["bell"] = 1
            s["spc"] = 2
            return s

        sender = [
            Transition("s.enqueue", "s", g_s0, a_s0,
                       frozenset({"spc"}), frozenset({"q", "spc"})),
            Transition("s.ring_if_asleep", "s", g_s1, a_s1,
                       frozenset({"spc", "flag"}),
                       frozenset({"bell", "spc"})),
        ]

    ts = [
        Transition("r.poll_hit", "r", g_poll_hit, a_poll_hit,
                   frozenset({"rpc", "q", "got"}),
                   frozenset({"q", "got"})),
        Transition("r.poll_miss", "r", g_poll_miss, a_poll_miss,
                   frozenset({"rpc", "q", "got"}), frozenset({"rpc"})),
        Transition("r.advertise", "r",
                   lambda s: s["rpc"] == 1, a_advertise,
                   frozenset({"rpc"}), frozenset({"flag", "rpc"})),
        Transition("r.final_poll_hit", "r", g_final_hit, a_final_hit,
                   frozenset({"rpc", "q"}),
                   frozenset({"flag", "q", "got", "rpc"})),
        Transition("r.final_poll_miss", "r", g_final_miss, a_final_miss,
                   frozenset({"rpc", "q"}), frozenset({"rpc"})),
        Transition("r.wake", "r", g_wake, a_wake,
                   frozenset({"rpc", "bell"}),
                   frozenset({"bell", "flag", "rpc"})),
    ] + sender

    def inv_lost_wake(s):
        # stronger than bare deadlock: name the bug while it is forming
        if s["rpc"] == 3 and s["q"] > 0 and s["bell"] == 0 \
                and s["spc"] >= (3 if mutation == "ring_before_publish"
                                 else 2):
            return ("receiver asleep with a queued message, sender done, "
                    "no bell pending — lost wakeup")
        return None

    def final(s):
        return s["got"] == 1

    return Model(f"doorbell(mut={mutation})", init, ts,
                 [("no-lost-wake", inv_lost_wake)], final)

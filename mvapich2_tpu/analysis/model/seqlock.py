"""Seqlock flat-wave models (cplane.cpp cp_flat_allreduce / cp_flat_bcast).

State is the flat region reduced to its protocol skeleton: per-rank
slots (in_seq, out_seq, payload), the broadcast block (bseq, payload),
the region poison word. Payload writes are deliberately split into a
TORN step then the value step — the model's stand-in for a non-atomic
multi-byte memcpy — so any interleaving that lets a reader observe a
half-written slot delivers the literal value "TORN" and trips the
``no-torn-read-delivered`` invariant.

Payload values are frozensets of (rank, wave) contributions; a correct
allreduce delivers the full set for its wave, so agreement and
stale-read bugs surface as ``agreement`` violations.

Mutations (build_allreduce):
  stamp_before_copy   writer stamps in_seq BEFORE the payload copy —
                      the leader folds a torn slot
  no_reader_guard     reader copies the bcast block without waiting for
                      bseq >= s — reads mid-write or stale data
  no_overwrite_guard  leader skips the out_seq overwrite guard — wave
                      s+1's fold tears the block under a slow wave-s
                      reader (needs waves=2)
  no_poison           an aborted wave (peer crash) skips the sticky
                      poison stamp — context reuse folds the torn slot

Mutations (build_bcast):
  no_arrival_wave     the root stamps bseq without the fan-in-first
                      arrival wave — a member that reads its numbering
                      base late counts the in-flight wave and waits for
                      a seq nobody will ever stamp (deadlock), the exact
                      desync PR 5 shipped the arrival wave to prevent
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

TORN = "TORN"


def _full(n: int, wave: int) -> frozenset:
    return frozenset((r, wave) for r in range(n))


def build_allreduce(n: int = 2, waves: int = 1, crash: bool = False,
                    mutation: Optional[str] = None) -> Model:
    """n ranks run ``waves`` sequential flat allreduce waves; rank 0 is
    the leader (folds slots into the bcast block). ``crash=True`` adds a
    nondeterministic mid-copy death of rank n-1 plus the abort/poison/
    reuse machinery."""
    assert n >= 2
    ts = []
    init = {"poison": 0, "bseq": 0, "bpay": frozenset(), "aborted": 0,
            "reuse_res": None}
    for r in range(n):
        init[f"in{r}"] = 0
        init[f"out{r}"] = 0
        init[f"pay{r}"] = frozenset()
        init[f"pc{r}"] = 0
        init[f"wave{r}"] = 1          # seq of the wave being executed
        init[f"res{r}"] = ()          # delivered results, one per wave
        init[f"alive{r}"] = 1

    def seq(s, r):
        return s[f"wave{r}"]

    def running(s, r):
        return s[f"alive{r}"] and s[f"wave{r}"] <= waves \
            and not s["aborted"]

    # ---- non-leader ranks -------------------------------------------
    for r in range(1, n):
        def mk(r):
            stamp_first = mutation == "stamp_before_copy"

            def g_begin(s):
                return running(s, r) and s[f"pc{r}"] == 0

            def a_begin(s):
                if stamp_first:
                    s[f"in{r}"] = seq(s, r)       # MUTANT: stamp early
                s[f"pay{r}"] = TORN
                s[f"pc{r}"] = 1
                return s

            def g_copy(s):
                return running(s, r) and s[f"pc{r}"] == 1

            def a_copy(s):
                s[f"pay{r}"] = frozenset({(r, seq(s, r))})
                s[f"pc{r}"] = 2
                return s

            def g_stamp(s):
                return running(s, r) and s[f"pc{r}"] == 2

            def a_stamp(s):
                if not stamp_first:
                    s[f"in{r}"] = seq(s, r)       # release stamp
                s[f"pc{r}"] = 3
                return s

            def g_read(s):
                if not (running(s, r) and s[f"pc{r}"] == 3):
                    return False
                if mutation == "no_reader_guard":
                    return True                   # MUTANT: no bseq wait
                return s["bseq"] >= seq(s, r)

            def a_read(s):
                s[f"res{r}"] = s[f"res{r}"] + (s["bpay"],)
                s[f"pc{r}"] = 4
                return s

            def g_ack(s):
                return running(s, r) and s[f"pc{r}"] == 4

            def a_ack(s):
                s[f"out{r}"] = seq(s, r)
                s[f"wave{r}"] += 1
                s[f"pc{r}"] = 0
                return s

            return [
                Transition(f"r{r}.begin_copy", f"r{r}", g_begin, a_begin,
                           frozenset({f"pc{r}", f"wave{r}", "aborted"}),
                           frozenset({f"pay{r}", f"pc{r}", f"in{r}"})),
                Transition(f"r{r}.end_copy", f"r{r}", g_copy, a_copy,
                           frozenset({f"pc{r}"}),
                           frozenset({f"pay{r}", f"pc{r}"})),
                Transition(f"r{r}.stamp_in", f"r{r}", g_stamp, a_stamp,
                           frozenset({f"pc{r}"}),
                           frozenset({f"in{r}", f"pc{r}"})),
                Transition(f"r{r}.read_bcb", f"r{r}", g_read, a_read,
                           frozenset({f"pc{r}", "bseq", "bpay"}),
                           frozenset({f"res{r}", f"pc{r}"})),
                Transition(f"r{r}.stamp_out", f"r{r}", g_ack, a_ack,
                           frozenset({f"pc{r}"}),
                           frozenset({f"out{r}", f"wave{r}", f"pc{r}"})),
            ]
        ts.extend(mk(r))

    # ---- leader (rank 0) --------------------------------------------
    def g_l_guard(s):
        if not (running(s, 0) and s["pc0"] == 0):
            return False
        if mutation == "no_overwrite_guard":
            return True                           # MUTANT: skip guard
        return all(s[f"out{r}"] >= seq(s, 0) - 1 for r in range(n))

    def a_l_guard(s):
        s["pc0"] = 1
        return s

    def a_l_begin(s):
        s["bpay"] = TORN                          # fold starts: block torn
        s["pc0"] = 2
        return s

    def g_l_fold(s):
        return running(s, 0) and s["pc0"] == 2 and all(
            s[f"in{r}"] >= seq(s, 0) for r in range(1, n))

    def a_l_fold(s):
        acc = frozenset({(0, seq(s, 0))})
        torn = False
        for r in range(1, n):
            if s[f"pay{r}"] == TORN:
                torn = True
            else:
                acc |= s[f"pay{r}"]
        s["bpay"] = TORN if torn else acc
        s["pc0"] = 3
        return s

    def a_l_publish(s):
        s["res0"] = s["res0"] + (s["bpay"],)
        s["bseq"] = seq(s, 0)                     # release stamp
        s["in0"] = seq(s, 0)
        s["out0"] = seq(s, 0)
        s["wave0"] += 1
        s["pc0"] = 0
        return s

    ts.extend([
        Transition("L.overwrite_guard", "r0", g_l_guard, a_l_guard,
                   frozenset({"pc0", "wave0", "aborted"}
                             | {f"out{r}" for r in range(n)}),
                   frozenset({"pc0"})),
        Transition("L.begin_fold", "r0",
                   lambda s: running(s, 0) and s["pc0"] == 1, a_l_begin,
                   frozenset({"pc0"}), frozenset({"bpay", "pc0"})),
        Transition("L.fold", "r0", g_l_fold, a_l_fold,
                   frozenset({"pc0"} | {f"in{r}" for r in range(1, n)}
                             | {f"pay{r}" for r in range(1, n)}),
                   frozenset({"bpay", "pc0"})),
        Transition("L.publish", "r0",
                   lambda s: running(s, 0) and s["pc0"] == 3, a_l_publish,
                   frozenset({"pc0", "bpay"}),
                   frozenset({"res0", "bseq", "in0", "out0", "wave0",
                              "pc0"})),
    ])

    # ---- crash / abort / poison / reuse -----------------------------
    if crash:
        victim = n - 1

        def g_die(s):
            # mid-copy death: the slot is left TORN forever
            return s[f"alive{victim}"] and s[f"pc{victim}"] == 1

        def a_die(s):
            s[f"alive{victim}"] = 0
            return s

        def g_abort(s):
            # the leader's lease scan notices the dead peer while it
            # waits on the fold; the wave dies and (correctly) stamps
            # the sticky region poison
            return s["alive0"] and not s[f"alive{victim}"] \
                and not s["aborted"]

        def a_abort(s):
            s["aborted"] = 1
            if mutation != "no_poison":
                s["poison"] = 1                   # MUTANT skips this
            return s

        def g_reuse(s):
            # a later comm keys the same region (ctx id reuse): the
            # cp_flat_base gate must refuse a poisoned region
            return s["aborted"] and s["reuse_res"] is None

        def a_reuse(s):
            if s["poison"]:
                s["reuse_res"] = "refused"
            else:
                torn = any(s[f"pay{r}"] == TORN for r in range(1, n))
                s["reuse_res"] = TORN if torn else "folded"
            return s

        ts.extend([
            Transition("V.die", f"r{victim}", g_die, a_die,
                       frozenset({f"pc{victim}", f"alive{victim}"}),
                       frozenset({f"alive{victim}"})),
            Transition("L.abort_poison", "r0", g_abort, a_abort,
                       frozenset({f"alive{victim}", "aborted"}),
                       frozenset({"aborted", "poison"})),
            Transition("reuse.probe", "reuse", g_reuse, a_reuse,
                       frozenset({"aborted", "poison", "reuse_res"}
                                 | {f"pay{r}" for r in range(1, n)}),
                       frozenset({"reuse_res"})),
        ])

    # ---- invariants --------------------------------------------------
    def inv_torn(s):
        for r in range(n):
            for v in s[f"res{r}"]:
                if v == TORN:
                    return f"rank {r} delivered a TORN payload"
        if s["reuse_res"] == TORN:
            return "ctx reuse folded a torn slot of the dead wave"
        return None

    def inv_agree(s):
        for r in range(n):
            for w, v in enumerate(s[f"res{r}"], start=1):
                if v != TORN and v != _full(n, w):
                    return (f"rank {r} wave {w} delivered {sorted(v)} "
                            f"!= the full contribution set")
        return None

    def inv_poison(s):
        if s["aborted"] and not s["poison"]:
            return "wave aborted but the region poison is not sticky"
        return None

    def final(s):
        if s["aborted"]:
            return s["reuse_res"] is not None if crash else True
        return all(s[f"wave{r}"] > waves for r in range(n))

    invs = [("no-torn-read-delivered", inv_torn),
            ("agreement", inv_agree)]
    if crash:
        invs.append(("poison-sticky", inv_poison))
    return Model(f"seqlock-allreduce(n={n},waves={waves},"
                 f"crash={crash},mut={mutation})", init, ts, invs, final)


def build_bcast(n: int = 3, mutation: Optional[str] = None) -> Model:
    """One flat bcast wave, root = rank 0, with rank n-1 a LATE member:
    it reads its per-comm numbering base lazily (cp_flat_base) at its
    first collective. The correct protocol's fan-in-first arrival wave
    keeps the root from stamping bseq before everyone arrived; the
    mutation drops it, so the late member's base already counts the
    in-flight wave and it waits on a seq that will never be stamped."""
    assert n >= 2
    late = n - 1
    init = {"bseq": 0, "bpay": frozenset()}
    for r in range(n):
        init[f"in{r}"] = 0
        init[f"pc{r}"] = 0
        init[f"res{r}"] = None
        init[f"base{r}"] = 0 if r != late else None   # late: lazy read

    ts = []

    def g_base(s):
        return s[f"base{late}"] is None

    def a_base(s):
        s[f"base{late}"] = s["bseq"]             # lazy numbering base
        return s

    ts.append(Transition(f"r{late}.read_base", f"r{late}", g_base, a_base,
                         frozenset({"bseq", f"base{late}"}),
                         frozenset({f"base{late}"})))

    # members (non-root): arrive (stamp in_seq), wait bseq, read
    for r in range(1, n):
        def mk(r):
            def g_arrive(s):
                if s[f"pc{r}"] != 0:
                    return False
                if s[f"base{r}"] is None:
                    return False                 # must read base first
                return True

            def a_arrive(s):
                s[f"in{r}"] = s[f"base{r}"] + 1
                s[f"pc{r}"] = 1
                return s

            def g_read(s):
                return s[f"pc{r}"] == 1 \
                    and s["bseq"] >= s[f"base{r}"] + 1

            def a_read(s):
                s[f"res{r}"] = s["bpay"]
                s[f"pc{r}"] = 2
                return s

            return [
                Transition(f"r{r}.arrive", f"r{r}", g_arrive, a_arrive,
                           frozenset({f"pc{r}", f"base{r}"}),
                           frozenset({f"in{r}", f"pc{r}"})),
                Transition(f"r{r}.read", f"r{r}", g_read, a_read,
                           frozenset({f"pc{r}", "bseq", "bpay",
                                      f"base{r}"}),
                           frozenset({f"res{r}", f"pc{r}"})),
            ]
        ts.extend(mk(r))

    # root: (arrival wave) -> write payload -> stamp bseq
    def g_root_wave(s):
        if s["pc0"] != 0:
            return False
        if mutation == "no_arrival_wave":
            return True                          # MUTANT: skip fan-in
        return all(s[f"in{r}"] >= 1 for r in range(1, n))

    def a_root_wave(s):
        s["pc0"] = 1
        return s

    def a_root_write(s):
        s["bpay"] = frozenset({(0, 1)})
        s["pc0"] = 2
        return s

    def a_root_stamp(s):
        s["bseq"] = 1
        s["res0"] = s["bpay"]
        s["pc0"] = 3
        return s

    ts.extend([
        Transition("root.arrival_wave", "r0", g_root_wave, a_root_wave,
                   frozenset({"pc0"} | {f"in{r}" for r in range(1, n)}),
                   frozenset({"pc0"})),
        Transition("root.write", "r0", lambda s: s["pc0"] == 1,
                   a_root_write, frozenset({"pc0"}),
                   frozenset({"bpay", "pc0"})),
        Transition("root.stamp", "r0", lambda s: s["pc0"] == 2,
                   a_root_stamp, frozenset({"pc0", "bpay"}),
                   frozenset({"bseq", "res0", "pc0"})),
    ])

    def inv_data(s):
        for r in range(1, n):
            v = s[f"res{r}"]
            if v is not None and v != frozenset({(0, 1)}):
                return f"rank {r} delivered {v} != the root payload"
        return None

    def final(s):
        return all(s[f"res{r}"] is not None for r in range(n)) \
            and s["pc0"] == 3

    return Model(f"seqlock-bcast(n={n},mut={mutation})", init, ts,
                 [("bcast-data", inv_data)], final)

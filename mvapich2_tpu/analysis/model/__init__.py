"""Shm-protocol model checker.

The native datapath's three lock-free protocols — the seqlock flat-wave
collective (cplane.cpp cp_flat_*), the adaptive doorbell wait/wake
(ShmChannel + cp_wait_quantum), and the liveness-lease failure detector
— re-expressed as small interleaved state machines, explored
exhaustively (bounded) by ``explorer.explore``. The mv2tlint ``native``
pass proves the C sources USE the atomic idioms; this package proves
the PROTOCOLS those idioms implement are actually safe under every
interleaving the memory model allows:

  * no-torn-read-delivered + agreement  (seqlock.build_allreduce)
  * poison stickiness across ctx reuse  (seqlock.build_allreduce crash=)
  * fan-in-first bcast numbering        (seqlock.build_bcast)
  * no lost wakeup                      (doorbell.build)
  * death detected within 2x timeout,
    clean departure never a failure     (lease.build)

The device lane rides the same net: ``ici.build_ring`` models the
chunk-credit flow control of the HBM-streaming remote-DMA engine
(ops/pallas_ici.py) — the handshake the jax<0.5 interpreter can never
execute — proving no-slot-collision, no-lost-credit, agreement and
deadlock freedom for uni- and bidirectional rings under the
global-chunk-counter slot schedule. Its ``quant=True`` variant models
the block-quantized wire (ops/pallas_quant.py: scale word + packed
codes per chunk, dequant-fold at consume): same slot/credit schedule
over the shrunken wire chunks, with agreement tightened to "every
delivered chunk decodes with its sender's scale word" and the
``scale_after_payload`` split-landing break seeded against it.
``ici.build_alltoallv`` extends the net to the MoE-shaped alltoallv
wire (ops/pallas_alltoall.py): per-peer VARIABLE chunk counts on the
global-counter slot schedule with per-step credit waves and full-size
padding chunks — its seeded breaks (slot derived from the local
valid-chunk tally under skew, credit re-grant skipped on a zero-count
peer's padding) are each caught by a named invariant.

The one-sided lane (ops/pallas_rma.py + rma/device.py) adds
``rma.build_passive``: the passive-target epoch — MPI_Win_lock, C
accumulate chunks through the D-credit slot schedule, flush's
completion wave, unlock — against a concurrent local reader at the
target and the two-phase target fold (operand capture + commit store).
It proves lock exclusivity, no torn window read under concurrent
Put + local load, flush-completes-all-outstanding, and per-element
accumulate atomicity; its five seeded breaks (flush one chunk short,
unlock before the completion wave, fold operand prefetch racing the
previous commit, lock-bypassing local load, exclusivity-ignoring
acquire) are each caught by a named invariant.

The CONTROL plane (the one protocol surface PRs 7/11/12 left
uncovered) gets the same treatment before ROADMAP item 4 grows it:

  * ``wiring.build_wire`` — the 2-stage mpeek-driven lazy wire
    (ShmChannel.ensure_wired): no hang, no unsafe/mixed tier enable,
    degraded-all-off on mid-wire death, no post-revoke wire;
  * ``daemon.build_daemon`` — the multi-tenant warm-attach claim cycle
    (flock txn / epoch / truncate-reset / stale sweep / idle expiry),
    with the concurrent-claims admission variant (nsets instances under
    a quota — pre-verified in PR 13, shipped in PR 14), the bounded
    FIFO admission queue, and the exec-cache epoch discipline — the
    model grows in lockstep with runtime/daemon.py;
  * ``ft.build_ft`` — lease-detect → revoke flood (with re-flood) →
    shrink re-key: eventual PROC_FAILED delivery, no survivor parked
    forever on a dead or diverted peer, re-key never reuses a poisoned
    ctx/lane, reused regions never deliver torn words.

The nonblocking lane (coll/nbc/engine.py, PR 18's deposit/POLL/
complete device schedules) gets ``nbc.build_nbc``: the DAG scheduler —
dependency-ordered vertex issue, segment-wise async hardware dispatch,
wakeup-driven completion fan-out, the progress hook pumping parked
polls, persistent start re-init over exec-cache epoch reuse, and the
cancel/error unwind — proving deps-before-issue, deposit-before-poll,
issue-before-complete, drained-at-finalize, epoch freshness, and
deadlock freedom. Its ``TRACE_EVENTS`` table doubles as the runtime
event grammar of analysis/conform.py's NBC conformance automaton, so
the offline proof and the live-trace check share one source of truth.

The three-level hierarchy (PR 20) adds one model per new level:
``ici.build_mesh`` carries the multi-axis mesh phase composition
(RS-x -> RS-y -> AG-y -> AG-x over a px x py chip grid, with the
leaders-per-chip HBM fold in front) at contribution-set granularity —
its axis-phase-order invariant pins "no chip starts an axis's AG
before its own RS of that axis completed", the ordering bug class the
nested sub-shard decomposition makes load-bearing. ``flat2.build_net2``
models the np>64 node-leader bridge (coll/netcoll.py): group fold into
the node leader, seqlock-skeleton lane publish to the root leader's
bridge fold, fan-out of the total — with a node-leader-crash probe
proving an aborted wave poisons the cached split so the next
collective DEGRADES to sched instead of folding the dead lane.

Every model takes ``mutation=<name>`` seeding a realistic protocol
break (stamp-before-copy, missing final poll, throttle past the
deadline, ...); tests/test_modelcheck.py asserts the checker catches
each one and that the unmutated models are violation-free.
"""

from . import daemon, doorbell, flat2, ft, ici, lease, nbc, rma, seqlock, wiring  # noqa: F401,E501
from .explorer import Model, Result, Transition, Violation, explore  # noqa: F401


def mutation_matrix():
    """[(model label, builder kwargs -> Model, mutation name)] — every
    seeded protocol break the checker must catch. Builders are zero-arg
    callables returning the smallest model that exhibits the bug."""
    return [
        ("seqlock-allreduce", lambda: seqlock.build_allreduce(
            n=2, waves=1, mutation="stamp_before_copy"),
         "stamp_before_copy"),
        ("seqlock-allreduce", lambda: seqlock.build_allreduce(
            n=2, waves=1, mutation="no_reader_guard"),
         "no_reader_guard"),
        ("seqlock-allreduce", lambda: seqlock.build_allreduce(
            n=2, waves=2, mutation="no_overwrite_guard"),
         "no_overwrite_guard"),
        ("seqlock-allreduce", lambda: seqlock.build_allreduce(
            n=2, waves=1, crash=True, mutation="no_poison"),
         "no_poison"),
        ("seqlock-bcast", lambda: seqlock.build_bcast(
            n=3, mutation="no_arrival_wave"),
         "no_arrival_wave"),
        ("doorbell", lambda: doorbell.build(mutation="no_final_poll"),
         "no_final_poll"),
        ("doorbell", lambda: doorbell.build(mutation="ring_before_publish"),
         "ring_before_publish"),
        ("lease", lambda: lease.build(depart=True,
                                      mutation="departed_stale"),
         "departed_stale"),
        ("lease", lambda: lease.build(crash=True,
                                      mutation="throttle_too_long"),
         "throttle_too_long"),
        ("lease", lambda: lease.build(crash=True,
                                      mutation="inverted_compare"),
         "inverted_compare"),
        # hierarchical flat tier + multicast bcast (cp_flat2_*)
        ("flat2-hier", lambda: flat2.build_hier_allreduce(
            groups=2, k=2, mutation="xchg_no_guard"),
         "xchg_no_guard"),
        ("flat2-hier", lambda: flat2.build_hier_allreduce(
            groups=2, k=2, mutation="fanout_before_xchg"),
         "fanout_before_xchg"),
        ("flat2-hier", lambda: flat2.build_hier_allreduce(
            groups=2, k=2, crash=True, mutation="no_poison"),
         "no_poison"),
        ("flat2-mcast", lambda: flat2.build_mcast(
            n=3, waves=2, nbuf=1, mutation="publish_before_write"),
         "publish_before_write"),
        ("flat2-mcast", lambda: flat2.build_mcast(
            n=3, waves=2, nbuf=1, mutation="no_overwrite_guard"),
         "no_overwrite_guard"),
        ("flat2-mcast", lambda: flat2.build_mcast(
            n=3, waves=1, nbuf=1, mutation="no_first_sync"),
         "no_first_sync"),
        # three-level hierarchy (PR 20): multi-axis mesh phases with
        # the leaders-per-chip fold, and the net2 node-leader bridge
        ("ici-mesh", lambda: ici.build_mesh(
            px=2, py=2, mutation="ag_before_rs_crossaxis"),
         "ag_before_rs_crossaxis"),
        ("ici-mesh", lambda: ici.build_mesh(
            px=2, py=2, k=2, mutation="leader_fold_skipped"),
         "leader_fold_skipped"),
        ("flat2-net2", lambda: flat2.build_net2(
            groups=2, k=2, mutation="bridge_before_group_fold"),
         "bridge_before_group_fold"),
        ("flat2-net2", lambda: flat2.build_net2(
            groups=2, k=2, mutation="fanout_before_bridge"),
         "fanout_before_bridge"),
        ("flat2-net2", lambda: flat2.build_net2(
            groups=2, k=2, crash=True,
            mutation="leader_crash_no_poison"),
         "leader_crash_no_poison"),
        # chunk-credit remote-DMA ring (ops/pallas_ici.py)
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=4, depth=2, mutation="no_credit_wait"),
         "no_credit_wait"),
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=2, depth=2, mutation="slot_off_by_one"),
         "slot_off_by_one"),
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=2, depth=2, mutation="depth_mismatch"),
         "depth_mismatch"),
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=2, depth=2, mutation="signal_before_copy"),
         "signal_before_copy"),
        ("ici-ring", lambda: ici.build_ring(
            n=3, chunks=2, depth=2, bidir=True,
            mutation="bidir_shared_slot"),
         "bidir_shared_slot"),
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=2, depth=2, mutation="recv_before_send_wave"),
         "recv_before_send_wave"),
        ("ici-ring", lambda: ici.build_ring(
            n=2, chunks=2, depth=2, mutation="scale_after_payload"),
         "scale_after_payload"),
        # MoE-shaped alltoallv wire (ops/pallas_alltoall.py): per-peer
        # variable chunk counts on the global-counter slot schedule
        ("ici-a2av", lambda: ici.build_alltoallv(
            n=2, depth=2, counts=[[0, 1], [3, 0]],
            mutation="skewed_count_slot"),
         "skewed_count_slot"),
        ("ici-a2av", lambda: ici.build_alltoallv(
            n=2, depth=2, counts=[[0, 0], [2, 0]],
            mutation="zero_count_credit_leak"),
         "zero_count_credit_leak"),
        ("ici-a2av", lambda: ici.build_alltoallv(
            n=2, depth=2, counts=[[0, 1], [3, 0]],
            mutation="local_width_wire"),
         "local_width_wire"),
        ("ici-a2av", lambda: ici.build_alltoallv(
            n=2, depth=2, counts=[[0, 0], [2, 0]],
            mutation="zero_count_entry_skip"),
         "zero_count_entry_skip"),
        # NBC DAG scheduler (coll/nbc/engine.py)
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="device", segs=2, mutation="issue_ignores_deps"),
         "issue_ignores_deps"),
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="device", segs=1, mutation="poll_never_pumped"),
         "poll_never_pumped"),
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="net", mutation="lost_completion_wakeup"),
         "lost_completion_wakeup"),
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="device", segs=2, error=True,
            mutation="unwind_leaves_inflight"),
         "unwind_leaves_inflight"),
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="device", segs=1, persistent=True,
            mutation="stale_persistent_reuse"),
         "stale_persistent_reuse"),
        ("nbc-dag", lambda: nbc.build_nbc(
            shape="net", mutation="spurious_completion"),
         "spurious_completion"),
        # passive-target one-sided epoch (ops/pallas_rma.py)
        ("rma-passive", lambda: rma.build_passive(
            chunks=3, depth=2, cells=1, mutation="flush_skips_chunk"),
         "flush_skips_chunk"),
        ("rma-passive", lambda: rma.build_passive(
            chunks=3, depth=2, cells=1, mutation="unlock_before_drain"),
         "unlock_before_drain"),
        ("rma-passive", lambda: rma.build_passive(
            chunks=3, depth=2, cells=1, mutation="no_target_fold_order"),
         "no_target_fold_order"),
        ("rma-passive", lambda: rma.build_passive(
            chunks=3, depth=2, cells=1, mutation="torn_window_read"),
         "torn_window_read"),
        ("rma-passive", lambda: rma.build_passive(
            chunks=3, depth=2, cells=1, mutation="no_lock_wait"),
         "no_lock_wait"),
        # 2-stage lazy wire (ShmChannel.ensure_wired / try_wire)
        ("wiring", lambda: wiring.build_wire(
            2, caps=(1, 0), mutation="skip_unanimity"),
         "skip_unanimity"),
        ("wiring", lambda: wiring.build_wire(
            2, crash=True, mutation="no_dead_exclude"),
         "no_dead_exclude"),
        ("wiring", lambda: wiring.build_wire(
            2, crash=True, mutation="no_degrade"),
         "no_degrade"),
        ("wiring", lambda: wiring.build_wire(
            2, caps=(0, 1), mutation="verdict_before_cards"),
         "verdict_before_cards"),
        ("wiring", lambda: wiring.build_wire(
            3, crash=True, revoke=True, mutation="wire_after_revoke"),
         "wire_after_revoke"),
        # warm-attach daemon claim cycle (runtime/daemon.py)
        ("daemon-claim", lambda: daemon.build_daemon(
            2, crash=True, mutation="no_reset"),
         "no_reset"),
        ("daemon-claim", lambda: daemon.build_daemon(
            3, mutation="release_no_epoch"),
         "release_no_epoch"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, mutation="sweep_live_owner"),
         "sweep_live_owner"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, mutation="expiry_reaps_claimed"),
         "expiry_reaps_claimed"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, crash=True, mutation="sweep_never_fires"),
         "sweep_never_fires"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, concurrent=True, nsets=2, quota=1,
            mutation="over_quota"),
         "over_quota"),
        # the PR 14 multi-tenant surface: bounded FIFO admission queue,
        # concurrency-safe idle expiry, exec-cache epoch discipline
        ("daemon-claim", lambda: daemon.build_daemon(
            2, concurrent=True, nsets=2, quota=1,
            mutation="queue_skips_admission"),
         "queue_skips_admission"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, mutation="queue_drops_waiter"),
         "queue_drops_waiter"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, concurrent=True, nsets=2, quota=2,
            mutation="expiry_checks_set0"),
         "expiry_checks_set0"),
        ("daemon-claim", lambda: daemon.build_daemon(
            2, cache=True, mutation="cache_stale_serve"),
         "cache_stale_serve"),
        # ULFM lease-detect / revoke / shrink propagation (ft/ulfm.py)
        ("ft-ulfm", lambda: ft.build_ft(
            3, mutation="no_revoke_unwind"),
         "no_revoke_unwind"),
        ("ft-ulfm", lambda: ft.build_ft(
            3, partial_flood=True, mutation="no_reflood"),
         "no_reflood"),
        ("ft-ulfm", lambda: ft.build_ft(
            3, mutation="detect_disabled"),
         "detect_disabled"),
        ("ft-ulfm", lambda: ft.build_ft(
            3, reuse=True, mutation="no_poison"),
         "no_poison"),
        ("ft-ulfm", lambda: ft.build_ft(
            3, mutation="rekey_same_ctx"),
         "rekey_same_ctx"),
    ]

"""Chunk-credit model of the HBM-streaming ICI ring (ops/pallas_ici.py).

The credit handshake of the chunked remote-DMA engine has NEVER
executed: the jax<0.5 interpreter is creditless (no remote semaphore
signal), so every interpreter run since PR 8 validated the data
schedule but not the flow control. This model is the handshake's
verification net before the first TPU host run — the device analog of
the seqlock/doorbell/lease models PR 7 built for the host shm
protocols.

The protocol, reduced to its transport skeleton: each rank streams C
chunks per ring direction into its downstream neighbor's D-deep VMEM
slot array, the slot sequence driven by a single **global chunk counter
per direction** — write ``k`` lands in slot ``k % D``, which is exactly
the slot freed by consume ``k - D`` ("write k+D lands in the slot freed
by consume k"). Flow control is ``D`` credits per direction: the sender
takes a credit before the remote DMA of chunk ``k`` and the receiver
re-grants one as it consumes a slot, so a sender runs at most ``D``
chunks ahead and slot reuse needs no per-slot handshake.

Each rank executes the *serialized* program ``stream_step`` actually
runs (one instruction stream per kernel instance): per chunk index
``c`` it issues ``c`` on every direction, then drains ``c-1`` on every
direction. Concurrency comes from rank interleaving and, under the
``signal_before_copy`` mutation, from the split-landing DMA actor. The
clean model lands payload + recv-semaphore signal atomically at issue
time — signal-after-data is a hardware guarantee, and landing as early
as possible is adversarial for the collision invariant (a later landing
only gives the consumer more time), so the abstraction is sound.

What the model proves (exhaustively, within N x C x D bounds, uni- and
bidirectional):

  * **no-slot-collision** — no remote write ever lands in a slot whose
    previous chunk is unconsumed;
  * **no-lost-credit** — per (sender, direction), credits held plus
    chunks in flight always equals exactly D (no leak, no over-grant);
  * **agreement** — every delivered chunk is exactly the upstream
    contribution for that index: no tears, no stale slots, no
    cross-direction mixing;
  * **no-deadlock** — the wave always completes (explorer built-in).

What it cannot prove: the VPU fold arithmetic and the multi-round
reduce-scatter block rotation (interpreter-proven: the 0.4.x emulator
is deterministic dataflow), and Mosaic's lowering of the semaphore ops
themselves — those wait for the first TPU host (ROADMAP item 1).

Mutations (tests/test_modelcheck.py asserts every one is caught by a
named invariant):

  no_credit_wait        the sender skips the credit take — it runs past
                        D chunks ahead and overwrites an unconsumed slot
  slot_off_by_one       writes land in slot (k+1) % D — the receiver
                        waits forever on slot k % D (the one-counter
                        slot discipline, broken)
  depth_mismatch        sender boots with D+1 credits against D slots
                        (a chunk/depth retune applied to one side only)
  signal_before_copy    recv semaphore signaled before the payload
                        lands — the receiver folds a torn chunk
  bidir_shared_slot     both ring directions mapped onto one slot array
                        (the bidir lanes must be disjoint)
  recv_before_send_wave the receiver consumes without waiting the recv
                        semaphore — it folds a stale/empty slot
  scale_after_payload   (quant wire only) the block scale word lands
                        AFTER the packed codes + recv signal — the
                        receiver dequant-folds with a stale scale,
                        outside the declared block-quant bound

Quantized wire variant (``quant=True`` — ops/pallas_quant.py): each
wire chunk carries a block scale word plus the packed code payload,
and the consumer dequant-folds at drain. The slot/credit schedule is
byte-count-blind, so the shrunken wire chunks (~3.9x smaller than the
f32 chunks they encode) ride the SAME transitions — the clean quant
model proves no-slot-collision / no-lost-credit / no-deadlock hold
unchanged, and the agreement invariant tightens to "every delivered
chunk decodes with exactly its sender's scale word", i.e. within the
declared block-quant bound of the exact fold. The clean model lands
scale + codes + signal atomically (one remote DMA of one wire run —
the packed-single-buffer design choice this model justifies);
``scale_after_payload`` is the seeded break of that atomicity, the
bug a two-buffer scale/payload wire would actually have.
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition
from .seqlock import TORN

_FREE = -1     # slot occupant sentinel: never written


def _program(C: int, dirs):
    """The serialized per-rank instruction stream of stream_step:
    issue c on every direction, then drain c-1; trailing drains of the
    last chunk close the wave."""
    prog = []
    for c in range(C):
        for d in dirs:
            prog.append(("issue", c, d))
        if c >= 1:
            for d in dirs:
                prog.append(("drain", c - 1, d))
    for d in dirs:
        prog.append(("drain", C - 1, d))
    return prog


def build_ring(n: int = 2, chunks: int = 2, depth: int = 2,
               bidir: bool = False,
               mutation: Optional[str] = None,
               quant: bool = False) -> Model:
    """``n`` ranks stream ``chunks`` chunks per direction through
    ``depth``-deep slot arrays with ``depth`` credits. ``bidir`` adds
    the counter-clockwise lane (disjoint slots/credits — except under
    the ``bidir_shared_slot`` mutation, where both lanes share array 0
    at every receiver). ``quant`` switches the wire chunk to the
    block-quantized form (scale word + packed codes, dequant-fold at
    consume; see module docstring)."""
    assert n >= 2 and chunks >= 1 and depth >= 1
    C, D = chunks, depth
    dirs = (0, 1) if bidir else (0,)
    if mutation == "bidir_shared_slot":
        assert bidir, "bidir_shared_slot needs the ccw lane"
    if mutation == "scale_after_payload":
        quant = True       # the mutation only exists on the quant wire
    prog = _program(C, dirs)
    # issued/drained counts per (pc, dir) — for the credit invariant
    issued_at = [dict.fromkeys(dirs, 0)]
    drained_at = [dict.fromkeys(dirs, 0)]
    for op, _c, d in prog:
        ni = dict(issued_at[-1])
        nd = dict(drained_at[-1])
        (ni if op == "issue" else nd)[d] += 1
        issued_at.append(ni)
        drained_at.append(nd)

    def dst(r: int, d: int) -> int:
        return (r + 1) % n if d == 0 else (r - 1 + n) % n

    def up(r: int, d: int) -> int:
        return (r - 1 + n) % n if d == 0 else (r + 1) % n

    def slot_arr(d: int) -> int:
        # the mutant collapses both lanes onto one receiver array
        return 0 if mutation == "bidir_shared_slot" else d

    arrays = sorted({slot_arr(d) for d in dirs})

    init = {"collision": 0}
    for r in range(n):
        init[f"pc{r}"] = 0
        for d in dirs:
            init[f"cr{r}_{d}"] = D + 1 if mutation == "depth_mismatch" \
                else D                    # credits held by the sender
            init[f"wp{r}_{d}"] = None     # in-flight write (mutant only)
            init[f"res{r}_{d}"] = ()      # delivered payloads, in order
        for a in arrays:
            for s in range(D):
                # (occupant chunk, payload, signaled, consumed)
                init[f"sl{r}_{a}_{s}"] = (_FREE, frozenset(), False, True)

    def payload(r: int, k: int, d: int) -> frozenset:
        if quant:
            # the quant wire chunk: block scale word + packed codes —
            # both must be the sender's for chunk k, or the dequant
            # fold is outside the declared block-quant bound
            return frozenset({("s", r, k, d), ("q", r, k, d)})
        return frozenset({(r, k, d)})

    ts = []
    for r in range(n):
        for i, (op, c, d) in enumerate(prog):
            def mk(r=r, i=i, op=op, c=c, d=d):
                pc = f"pc{r}"
                peer, upr = dst(r, d), up(r, d)
                a = slot_arr(d)
                cr, wp = f"cr{r}_{d}", f"wp{r}_{d}"
                res = f"res{r}_{d}"
                t = (c + 1) % D if mutation == "slot_off_by_one" \
                    else c % D
                wkey = f"sl{peer}_{a}_{t}"          # issue target
                rkey = f"sl{r}_{a}_{c % D}"          # drain source

                if op == "issue":
                    def guard(s):
                        if s[pc] != i or s[wp] is not None:
                            return False
                        if mutation == "no_credit_wait":
                            return True
                        return s[cr] > 0

                    def apply(s):
                        if mutation != "no_credit_wait":
                            s[cr] -= 1
                        occ, pay, sig, cons = s[wkey]
                        if not cons:
                            s["collision"] = 1       # sticky
                        if mutation == "signal_before_copy":
                            # MUTANT: hand-rolled signal before the
                            # payload is on the wire — readable TORN
                            s[wkey] = (c, TORN, True, False)
                            s[wp] = c
                        elif mutation == "scale_after_payload":
                            # MUTANT: packed codes + recv signal land
                            # first, the block scale word rides a
                            # second landing — readable with the
                            # scale missing/stale
                            s[wkey] = (c, frozenset({("q", r, c, d)}),
                                       True, False)
                            s[wp] = c
                        else:
                            # hardware DMA: payload + signal atomic
                            s[wkey] = (c, payload(r, c, d), True, False)
                        s[pc] = i + 1
                        return s

                    return Transition(
                        f"r{r}.issue{c}.d{d}", f"r{r}", guard, apply,
                        frozenset({pc, wp, cr, wkey}),
                        frozenset({pc, wp, cr, wkey, "collision"}))

                def guard(s):
                    if s[pc] != i:
                        return False
                    if mutation == "recv_before_send_wave":
                        return True          # MUTANT: no recv-sem wait
                    occ, pay, sig, cons = s[rkey]
                    return occ == c and sig and not cons

                def apply(s):
                    occ, pay, sig, cons = s[rkey]
                    s[res] = s[res] + (pay,)
                    s[rkey] = (occ, pay, sig, True)
                    s[f"cr{upr}_{d}"] += 1       # re-grant the credit
                    s[pc] = i + 1
                    return s

                return Transition(
                    f"r{r}.drain{c}.d{d}", f"r{r}", guard, apply,
                    frozenset({pc, rkey}),
                    frozenset({pc, rkey, res, f"cr{upr}_{d}"}))
            ts.append(mk())

        # the async landing actor of the split-write mutants
        if mutation in ("signal_before_copy", "scale_after_payload"):
            for d in dirs:
                def mkland(r=r, d=d):
                    peer = dst(r, d)
                    a = slot_arr(d)
                    wp = f"wp{r}_{d}"
                    skeys = frozenset(f"sl{peer}_{a}_{s}"
                                      for s in range(D))

                    def guard(s):
                        return s[wp] is not None

                    def apply(s):
                        k = s[wp]
                        key = f"sl{peer}_{a}_{k % D}"
                        occ, pay, sig, cons = s[key]
                        if occ == k and pay == TORN:
                            s[key] = (k, payload(r, k, d), sig, cons)
                        elif occ == k \
                                and mutation == "scale_after_payload":
                            # the late scale word finally lands
                            s[key] = (k, pay | {("s", r, k, d)},
                                      sig, cons)
                        s[wp] = None
                        return s

                    return Transition(f"r{r}.land.d{d}", f"dma{r}_{d}",
                                      guard, apply,
                                      frozenset({wp}) | skeys,
                                      frozenset({wp}) | skeys)
                ts.append(mkland())

    # ---- invariants --------------------------------------------------
    end = len(prog)

    def inv_collision(s):
        if s["collision"]:
            return ("a remote write landed in a slot whose previous "
                    "chunk was not consumed")
        return None

    def inv_credit(s):
        for r in range(n):
            for d in dirs:
                issued = issued_at[s[f"pc{r}"]][d]
                outstanding = issued - drained_at[s[f"pc{dst(r, d)}"]][d]
                cr = s[f"cr{r}_{d}"]
                if cr + outstanding != D:
                    return (f"rank {r} dir {d}: credits {cr} + "
                            f"in-flight {outstanding} != depth {D}")
                if cr > D:
                    return (f"rank {r} dir {d}: over-credit {cr} > "
                            f"depth {D}")
        return None

    def inv_agree(s):
        for r in range(n):
            for d in dirs:
                src = up(r, d)
                for i, pay in enumerate(s[f"res{r}_{d}"]):
                    if pay == TORN:
                        return (f"rank {r} dir {d} folded a TORN "
                                f"chunk {i}")
                    if pay != payload(src, i, d):
                        if quant and isinstance(pay, frozenset) \
                                and ("s", src, i, d) not in pay:
                            return (f"rank {r} dir {d} dequant-folded "
                                    f"chunk {i} with a missing/stale "
                                    "scale word — outside the declared "
                                    "block-quant bound of the exact "
                                    "fold")
                        return (f"rank {r} dir {d} chunk {i} delivered "
                                f"{sorted(pay)} != the upstream "
                                "contribution")
        return None

    def final(s):
        return all(s[f"pc{r}"] == end for r in range(n))

    label = (f"ici-ring(n={n},C={C},D={D},"
             f"{'bidir' if bidir else 'uni'}"
             f"{',quant' if quant else ''},mut={mutation})")
    return Model(label, init, ts,
                 [("no-slot-collision", inv_collision),
                  ("no-lost-credit", inv_credit),
                  ("agreement", inv_agree)],
                 final)


_PAD = "PAD"   # wire-padding chunk payload (consumed, never delivered)


def build_alltoallv(n: int, depth: int, counts,
                    mutation: Optional[str] = None) -> Model:
    """Per-peer variable chunk counts on the global-counter slot
    schedule — the MoE-shaped alltoallv wire (ops/pallas_alltoall.py).

    The protocol skeleton: steps ``t = 1..n-1`` of a rotation schedule
    (step ``t``: rank ``r`` streams to ``(r+t) % n`` and receives from
    ``(r-t) % n``). The step-wide wire width ``W_t`` is the MAX chunk
    count over that step's pairs — wire chunks are always full size, so
    a pair below the max streams PADDING chunks that the receiver must
    still consume and credit back (the byte-count-blind slot/credit
    schedule; ``W_t == 0`` steps are skipped mesh-wide). The slot for
    wire chunk ``k`` of step ``t`` is ``G(t,k) % depth`` with ``G`` the
    GLOBAL wire counter (cumulative over steps) — both ends derive it
    from the same counts matrix, never from their local valid-chunk
    tallies. Flow control is a per-step credit wave on the sender's
    per-destination lane: the receiver grants ``depth`` at its step
    entry (so a sender can never run into slots whose previous-step
    occupants the receiver has not drained), re-grants one per consume
    (padding included), and the sender fences its lane back to depth at
    step exit.

    Mutations (tests/test_modelcheck.py asserts each is caught):

      skewed_count_slot      the sender derives the slot from its own
                             VALID-chunk counter (padding chunks do not
                             advance it) — under skewed counts the send
                             and drain slot sequences diverge and a
                             write lands in an unconsumed slot
      zero_count_credit_leak the receiver skips the credit re-grant on
                             padding chunks — the credit window of any
                             below-max pair (a zero-count peer in the
                             extreme) leaks shut and the sender's fence
                             starves
      local_width_wire       the sender sizes its wire from its LOCAL
                             count instead of the step-wide max — no
                             padding chunks on a below-max lane, so the
                             receiver's byte-count-blind drain schedule
                             waits forever on chunks that never launch
                             (the transport-asymmetry deadlock class
                             the pad-to-max wire exists to rule out)
      zero_count_entry_skip  the receiver's step entry skips the
                             depth-D grant when it expects zero VALID
                             chunks from its upstream — but the wire
                             still carries W padding chunks, and the
                             ungranted sender starves at issue
    """
    assert n >= 2 and depth >= 1
    D = depth
    counts = [[int(c) for c in row] for row in counts]
    assert len(counts) == n and all(len(r) == n for r in counts)

    def dst(r: int, t: int) -> int:
        return (r + t) % n

    def src(r: int, t: int) -> int:
        return (r - t + n) % n

    # step-wide wire widths (zero-width steps skipped mesh-wide) and
    # the global wire counter offset of each active step
    steps = []
    G0 = {}
    g = 0
    for t in range(1, n):
        W = max(counts[r][dst(r, t)] for r in range(n))
        if W == 0:
            continue
        steps.append((t, W))
        G0[t] = g
        g += W

    # the serialized per-rank programs: entry grant, issue/drain
    # alternation, exit fence. Identical across ranks (W is step-wide)
    # EXCEPT under the local_width_wire mutant, where a sender streams
    # only its local count and skips the padding issues
    progs = []
    for r in range(n):
        prog = []
        for t, W in steps:
            send_w = W
            if mutation == "local_width_wire":
                send_w = min(W, counts[r][dst(r, t)])
            prog.append(("entry", t, 0))
            for k in range(W):
                if k < send_w:
                    prog.append(("issue", t, k))
                if k >= 1:
                    prog.append(("drain", t, k - 1))
            prog.append(("drain", t, W - 1))
            prog.append(("fence", t, 0))
        progs.append(prog)

    init = {"collision": 0}
    for r in range(n):
        init[f"pc{r}"] = 0
        init[f"vc{r}"] = 0          # valid-chunk tally (mutant's slot)
        init[f"res{r}"] = ()        # delivered valid payloads, in order
        for d in range(n):
            if d != r:
                init[f"cr{r}_{d}"] = 0    # credits held on lane r->d
                init[f"fl{r}_{d}"] = 0    # chunks in flight on r->d
                init[f"win{r}_{d}"] = 0   # receiver-granted window
        for s in range(D):
            init[f"sl{r}_{s}"] = (_FREE, _PAD, True)

    ts = []
    for r in range(n):
        for i, (op, t, k) in enumerate(progs[r]):
            def mk(r=r, i=i, op=op, t=t, k=k):
                pc = f"pc{r}"
                peer, upr = dst(r, t), src(r, t)
                g = G0[t] + k
                cr = f"cr{r}_{peer}"

                if op == "entry":
                    # receiver-side grant: open the upstream's window
                    ucr, uwin = f"cr{upr}_{r}", f"win{upr}_{r}"

                    def guard(s, pc=pc, i=i):
                        return s[pc] == i

                    def apply(s, upr=upr):
                        if not (mutation == "zero_count_entry_skip"
                                and counts[upr][r] == 0):
                            s[ucr] += D
                            s[uwin] += D
                        s[pc] = i + 1
                        return s

                    return Transition(
                        f"r{r}.entry.t{t}", f"r{r}", guard, apply,
                        frozenset({pc}),
                        frozenset({pc, ucr, uwin}))

                if op == "fence":
                    def guard(s, pc=pc, i=i, cr=cr):
                        return s[pc] == i and s[cr] >= D

                    def apply(s, cr=cr, win=f"win{r}_{peer}"):
                        s[cr] -= D
                        s[win] -= D
                        s[pc] = i + 1
                        return s

                    return Transition(
                        f"r{r}.fence.t{t}", f"r{r}", guard, apply,
                        frozenset({pc, cr}),
                        frozenset({pc, cr, f"win{r}_{peer}"}))

                if op == "issue":
                    valid = k < counts[r][peer]
                    fl = f"fl{r}_{peer}"
                    vc = f"vc{r}"
                    skeys = frozenset(f"sl{peer}_{s}" for s in range(D))

                    def guard(s, pc=pc, i=i, cr=cr):
                        return s[pc] == i and s[cr] > 0

                    def apply(s, g=g, valid=valid):
                        s[cr] -= 1
                        s[fl] += 1
                        if mutation == "skewed_count_slot":
                            # MUTANT: slot from the local valid-chunk
                            # tally — pads do not advance it, so skewed
                            # counts desync it from the wire counter
                            slot = s[vc] % D
                        else:
                            slot = g % D
                        if valid:
                            s[vc] += 1
                        wkey = f"sl{peer}_{slot}"
                        occ, pay, cons = s[wkey]
                        if not cons:
                            s["collision"] = 1       # sticky
                        s[wkey] = (g, (r, t, k) if valid else _PAD,
                                   False)
                        s[pc] = i + 1
                        return s

                    return Transition(
                        f"r{r}.issue.t{t}.k{k}", f"r{r}", guard, apply,
                        frozenset({pc, cr, vc}) | skeys,
                        frozenset({pc, cr, fl, vc, "collision"})
                        | skeys)

                # drain: consume wire chunk k of step t from upstream
                rkey = f"sl{r}_{g % D}"
                is_pad = k >= counts[upr][r]
                ucr, ufl = f"cr{upr}_{r}", f"fl{upr}_{r}"
                res = f"res{r}"

                def guard(s, pc=pc, i=i, rkey=rkey, g=g):
                    if s[pc] != i:
                        return False
                    occ, pay, cons = s[rkey]
                    return occ == g and not cons

                def apply(s, rkey=rkey, is_pad=is_pad):
                    occ, pay, cons = s[rkey]
                    if pay != _PAD:
                        s[res] = s[res] + (pay,)
                    s[rkey] = (occ, pay, True)
                    s[ufl] -= 1
                    if not (is_pad
                            and mutation == "zero_count_credit_leak"):
                        s[ucr] += 1      # re-grant (padding included)
                    s[pc] = i + 1
                    return s

                return Transition(
                    f"r{r}.drain.t{t}.k{k}", f"r{r}", guard, apply,
                    frozenset({pc, rkey}),
                    frozenset({pc, rkey, res, ucr, ufl}))
            ts.append(mk())

    # ---- invariants --------------------------------------------------
    ends = [len(p) for p in progs]
    expected = {}
    for r in range(n):
        seq = []
        for t, W in steps:
            u = src(r, t)
            seq += [(u, t, k) for k in range(counts[u][r])]
        expected[r] = tuple(seq)

    def inv_collision(s):
        if s["collision"]:
            return ("a remote write landed in a slot whose previous "
                    "chunk was not consumed")
        return None

    def inv_credit(s):
        for r in range(n):
            for d in range(n):
                if d == r:
                    continue
                cr, fl, win = (s[f"cr{r}_{d}"], s[f"fl{r}_{d}"],
                               s[f"win{r}_{d}"])
                if cr + fl != win:
                    return (f"lane {r}->{d}: credits {cr} + in-flight "
                            f"{fl} != granted window {win}")
                if cr < 0 or win not in (0, D):
                    return (f"lane {r}->{d}: window {win} / credits "
                            f"{cr} outside the depth-{D} discipline")
        return None

    def inv_agree(s):
        for r in range(n):
            got = s[f"res{r}"]
            if got != expected[r][:len(got)]:
                return (f"rank {r} delivered {got} — not a prefix of "
                        f"the counts-matrix order {expected[r]}")
        return None

    def final(s):
        return all(s[f"pc{r}"] == ends[r] for r in range(n))

    label = (f"ici-a2av(n={n},D={D},counts={counts},mut={mutation})")
    return Model(label, init, ts,
                 [("no-slot-collision", inv_collision),
                  ("no-lost-credit", inv_credit),
                  ("agreement", inv_agree)],
                 final)


def build_mesh(px: int = 2, py: int = 2, k: int = 1,
               mutation: Optional[str] = None) -> Model:
    """Multi-axis mesh RS/AG phase model (ops/pallas_ici.py
    ici_all_reduce_mesh + coll/device.py DeviceFoldChannel) at
    contribution-set granularity.

    A ``px`` x ``py`` chip mesh runs the nested phase decomposition the
    multi-axis device allreduce executes: reduce-scatter along x, then
    along y, then all-gather along y, then along x — each axis phase a
    publish/fold wave over that axis's ring. ``k`` ranks per chip adds
    the leaders-per-chip HBM fold in front: co-located member ranks
    stamp their contribution into the chip leader, which folds them
    before any ICI phase runs. Per-chunk slot/credit flow control is
    ``build_ring``'s job — this model carries the PHASE-ORDERING bugs
    of the three-level composition, so payloads are contribution sets
    and each phase is atomic publish + guarded fold.

    The nesting is what makes ordering load-bearing: RS-y operates on
    RS-x's per-column partials, and the axis-k AG gathers sub-shard
    pieces that are only fully reduced once EVERY RS phase has landed.
    A rank that starts an axis's AG before that axis's RS has completed
    on it publishes a cross-axis partial, and the piece its ring peers
    gather is stale forever after.

    Invariants:

      * **axis-phase-order** — no chip starts the AG of an axis (first
        gather-slot publish) before its own RS of that axis completed;
      * **agreement** — every delivered result covers the full px x py
        sub-shard grid and every gathered piece equals the FULL
        contribution set (all chips x all co-located ranks);
      * **no-deadlock** — the wave always completes (explorer built-in).

    Mutations (tests/test_modelcheck.py asserts each is caught):

      ag_before_rs_crossaxis  the chip treats the CROSS axis's RS
                              completion as license to start the axis-y
                              AG — it publishes its gather slot straight
                              after RS-x, before its own RS-y fold, so
                              the slot carries the pre-y row partial
      leader_fold_skipped     the chip leader enters the ICI phases
                              without waiting for (or folding) its
                              co-located members' HBM slots — every
                              delivered shard misses their contributions
    """
    assert px >= 1 and py >= 1 and px * py >= 2 and k >= 1
    if mutation == "leader_fold_skipped":
        assert k >= 2, "leader_fold_skipped needs co-located ranks"
    nc = px * py

    def cx(c: int) -> int:
        return c % px

    def cy(c: int) -> int:
        return c // px

    def xring(c: int):
        return tuple(cy(c) * px + i for i in range(px))

    def yring(c: int):
        return tuple(j * px + cx(c) for j in range(py))

    full = frozenset((c, j) for c in range(nc) for j in range(k))
    shards = frozenset((i, j) for i in range(px) for j in range(py))

    # the serialized per-chip phase program; the mutant hoists the
    # axis-y AG publish to right after the axis-x RS fold
    if mutation == "ag_before_rs_crossaxis":
        steps = ("fold", "rsx_pub", "rsx_fold", "agy_pub", "rsy_pub",
                 "rsy_fold", "agy_fold", "agx_pub", "agx_fold")
    else:
        steps = ("fold", "rsx_pub", "rsx_fold", "rsy_pub", "rsy_fold",
                 "agy_pub", "agy_fold", "agx_pub", "agx_fold")
    end = len(steps)

    init = {}
    for c in range(nc):
        init[f"pc{c}"] = 0
        init[f"acc{c}"] = frozenset({(c, 0)})   # the leader's own share
        init[f"gat{c}"] = frozenset()           # gathered (shard, piece)
        init[f"res{c}"] = None
        for ph in ("rsx", "rsy", "agy", "agx"):
            init[f"{ph}_sl{c}"] = frozenset()
            init[f"{ph}_in{c}"] = 0
        init[f"rsx_done{c}"] = 0
        init[f"rsy_done{c}"] = 0
        for j in range(1, k):
            init[f"min{c}_{j}"] = 0             # member HBM-slot stamp

    ts = []
    for c in range(nc):
        # co-located member ranks: stamp the chip leader's HBM slot.
        # One atomic step — the torn-copy surface is the hbm slot
        # model's job; this model carries the ordering bugs.
        for j in range(1, k):
            def mkm(c=c, j=j):
                key = f"min{c}_{j}"

                def guard(s):
                    return s[key] == 0

                def apply(s):
                    s[key] = 1
                    return s

                return Transition(f"c{c}.m{j}.stamp", f"m{c}_{j}",
                                  guard, apply,
                                  frozenset({key}), frozenset({key}))
            ts.append(mkm())

        for i, stp in enumerate(steps):
            def mk(c=c, i=i, stp=stp):
                pc, acc = f"pc{c}", f"acc{c}"

                if stp == "fold":
                    stamps = [f"min{c}_{j}" for j in range(1, k)]

                    def guard(s):
                        if s[pc] != i:
                            return False
                        if mutation == "leader_fold_skipped":
                            return True      # MUTANT: no member wait
                        return all(s[m] >= 1 for m in stamps)

                    def apply(s):
                        if mutation != "leader_fold_skipped":
                            s[acc] = s[acc] | frozenset(
                                (c, j) for j in range(1, k))
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.fold", f"c{c}", guard,
                                      apply,
                                      frozenset({pc} | set(stamps)),
                                      frozenset({pc, acc}))

                if stp in ("rsx_pub", "rsy_pub"):
                    ph = stp[:3]
                    sl, stamp = f"{ph}_sl{c}", f"{ph}_in{c}"

                    def guard(s):
                        return s[pc] == i

                    def apply(s):
                        s[sl] = s[acc]
                        s[stamp] = 1
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.{stp}", f"c{c}", guard,
                                      apply, frozenset({pc, acc}),
                                      frozenset({pc, sl, stamp}))

                if stp in ("rsx_fold", "rsy_fold"):
                    ph = stp[:3]
                    ring = xring(c) if ph == "rsx" else yring(c)
                    stamps = [f"{ph}_in{p}" for p in ring]
                    slots = [f"{ph}_sl{p}" for p in ring]
                    done = f"{ph}_done{c}"

                    def guard(s):
                        return s[pc] == i \
                            and all(s[m] >= 1 for m in stamps)

                    def apply(s):
                        u = frozenset()
                        for slk in slots:
                            u = u | s[slk]
                        s[acc] = u
                        s[done] = 1
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.{stp}", f"c{c}", guard,
                                      apply,
                                      frozenset({pc} | set(stamps)
                                                | set(slots)),
                                      frozenset({pc, acc, done}))

                if stp == "agy_pub":
                    sl, stamp = f"agy_sl{c}", f"agy_in{c}"

                    def guard(s):
                        return s[pc] == i

                    def apply(s):
                        # publish the (sub-shard, piece) this chip owns
                        s[sl] = frozenset({((cx(c), cy(c)), s[acc])})
                        s[stamp] = 1
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.agy_pub", f"c{c}", guard,
                                      apply, frozenset({pc, acc}),
                                      frozenset({pc, sl, stamp}))

                if stp == "agy_fold":
                    ring = yring(c)
                    stamps = [f"agy_in{p}" for p in ring]
                    slots = [f"agy_sl{p}" for p in ring]
                    gat = f"gat{c}"

                    def guard(s):
                        return s[pc] == i \
                            and all(s[m] >= 1 for m in stamps)

                    def apply(s):
                        u = frozenset()
                        for slk in slots:
                            u = u | s[slk]
                        s[gat] = u
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.agy_fold", f"c{c}", guard,
                                      apply,
                                      frozenset({pc} | set(stamps)
                                                | set(slots)),
                                      frozenset({pc, gat}))

                if stp == "agx_pub":
                    sl, stamp = f"agx_sl{c}", f"agx_in{c}"
                    gat = f"gat{c}"

                    def guard(s):
                        return s[pc] == i

                    def apply(s):
                        s[sl] = s[gat]
                        s[stamp] = 1
                        s[pc] = i + 1
                        return s

                    return Transition(f"c{c}.agx_pub", f"c{c}", guard,
                                      apply, frozenset({pc, gat}),
                                      frozenset({pc, sl, stamp}))

                # agx_fold: gather the row's column-gathers — delivery
                ring = xring(c)
                stamps = [f"agx_in{p}" for p in ring]
                slots = [f"agx_sl{p}" for p in ring]
                res = f"res{c}"

                def guard(s):
                    return s[pc] == i and all(s[m] >= 1 for m in stamps)

                def apply(s):
                    u = frozenset()
                    for slk in slots:
                        u = u | s[slk]
                    s[res] = u
                    s[pc] = i + 1
                    return s

                return Transition(f"c{c}.agx_fold", f"c{c}", guard,
                                  apply,
                                  frozenset({pc} | set(stamps)
                                            | set(slots)),
                                  frozenset({pc, res}))
            ts.append(mk())

    # ---- invariants --------------------------------------------------
    def inv_order(s):
        for c in range(nc):
            if s[f"agy_in{c}"] and not s[f"rsy_done{c}"]:
                return (f"chip {c} started its axis-y AG (published "
                        "the gather slot) before its own axis-y RS "
                        "completed")
            if s[f"agx_in{c}"] and not s[f"rsx_done{c}"]:
                return (f"chip {c} started its axis-x AG before its "
                        "own axis-x RS completed")
        return None

    def inv_agree(s):
        for c in range(nc):
            r = s[f"res{c}"]
            if r is None:
                continue
            got = {sh for sh, _ in r}
            if got != shards:
                return (f"chip {c} delivered shards {sorted(got)} != "
                        f"the full {px}x{py} sub-shard cover")
            for sh, pay in r:
                if pay != full:
                    return (f"chip {c} sub-shard {sh} gathered "
                            f"{sorted(pay)} != the full contribution "
                            "set — a cross-axis partial leaked through "
                            "the AG gather")
        return None

    def final(s):
        return all(s[f"pc{c}"] == end for c in range(nc))

    label = (f"ici-mesh(px={px},py={py},k={k},mut={mutation})")
    return Model(label, init, ts,
                 [("axis-phase-order", inv_order),
                  ("agreement", inv_agree)],
                 final)

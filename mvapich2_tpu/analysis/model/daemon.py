"""Warm-attach daemon claim-cycle model (runtime/daemon.py, PR 9).

The manifest protocol, as shipped: every transaction is one flock'd
read-modify-write (so each model transition is atomic); a claim sweeps
a dead owner's stale epoch, truncate-resets every segment file BEFORE
publishing the claim, bumps the epoch, and records the claimer; a
release is epoch-guarded (a late/double release of a swept-and-
reclaimed set must be a no-op); the daemon's serve loop sweeps dead
owners and idle-expires FREE sets only. Jobs retry a busy claim until
the set frees (the overlapping-jobs shape).

``concurrent=True`` is the ROADMAP item-4a admission variant, modeled
BEFORE it is built: ``nsets`` independent geometry slots under one
manifest with an admission quota — so the invariant set (per-set
exclusivity, per-set epoch freshness, quota) exists before the
multi-tenant daemon does.

Invariants:
  exclusivity      at most one live job holds any set at a time
  epoch-fresh      an attached job never observes a previous epoch's
                   word in its segment (the truncate-reset guarantee)
  no-reap          idle-expiry never unlinks a set a live job holds
  admission        (concurrent) busy sets never exceed the quota
  no-hang          every job eventually claims+releases (a crashed
                   owner's set must become claimable again)

Mutations:
  no_reset             claim skips the truncate-reset
  release_no_epoch     release ignores the epoch guard (double release
                       frees the NEXT claimer's set)
  sweep_live_owner     the stale sweep's alive check is broken
  expiry_reaps_claimed idle-expiry unlinks busy sets too
  sweep_never_fires    stale-epoch sweep disabled (crash → dead set)
  over_quota           (concurrent) admission ignores the quota
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

# job phases
IDLE, CLAIMED, ATTACHED, DONE, CRASHED = 0, 1, 2, 3, 4


def build_daemon(jobs: int = 2, crash: bool = False,
                 concurrent: bool = False, nsets: int = 2,
                 quota: int = 1,
                 mutation: Optional[str] = None) -> Model:
    """``jobs`` claimers cycle claim→write→read→release over one set
    (or, with ``concurrent``, over ``nsets`` sets under ``quota``)."""
    ns = nsets if concurrent else 1
    if not concurrent:
        quota = 1
    init = {}
    for s_ in range(ns):
        init[f"st{s_}"] = 0          # 0 free / 1 busy
        init[f"ep{s_}"] = 0          # manifest epoch
        init[f"own{s_}"] = -1        # owning job (-1 none)
        init[f"seg{s_}"] = 0         # epoch stamped into the files
        init[f"ex{s_}"] = 1          # files exist (idle-expiry unlinks)
    for j in range(jobs):
        init[f"j{j}"] = IDLE
        init[f"jep{j}"] = 0          # epoch of j's claim
        init[f"jset{j}"] = -1        # set j holds
        init[f"obs{j}"] = -1         # epoch word j observed on read
        init[f"rel{j}"] = 0          # releases j has issued

    def busy_count(s):
        return sum(1 for k in range(ns) if s[f"st{k}"] == 1)

    def ts():
        out = []
        for j in range(jobs):
            for k in range(ns):
                out.extend(claim_ts(j, k))
            out.extend(job_ts(j))
            if crash:
                def g_crash(s, j=j):
                    return s[f"j{j}"] in (CLAIMED, ATTACHED)

                def a_crash(s, j=j):
                    s[f"j{j}"] = CRASHED
                    return s
                out.append(Transition(
                    f"crash{j}", f"j{j}", g_crash, a_crash,
                    frozenset({f"j{j}"}), frozenset({f"j{j}"})))
        for k in range(ns):
            out.extend(daemon_ts(k))
        return out

    def claim_ts(j: int, k: int):
        def g_claim(s):
            if s[f"j{j}"] != IDLE:
                return False
            if mutation != "over_quota" and s[f"st{k}"] == 0 \
                    and busy_count(s) >= quota:
                return False          # admission control
            if s[f"st{k}"] == 0:
                return True
            # busy: claimable only via the at-claim stale sweep
            owner = s[f"own{k}"]
            if mutation == "sweep_never_fires":
                return False
            if owner >= 0 and s[f"j{owner}"] == CRASHED:
                return True
            return False

        def a_claim(s):
            if s[f"ex{k}"] == 0:
                s[f"ex{k}"] = 1       # recreate after idle expiry
                s[f"seg{k}"] = 0
            s[f"ep{k}"] += 1
            if mutation != "no_reset":
                s[f"seg{k}"] = 0      # truncate-reset BEFORE publishing
            s[f"st{k}"] = 1
            s[f"own{k}"] = j
            s[f"j{j}"] = CLAIMED
            s[f"jep{j}"] = s[f"ep{k}"]
            s[f"jset{j}"] = k
            return s

        keys = frozenset({f"st{x}" for x in range(ns)}
                         | {f"ep{k}", f"own{k}", f"seg{k}", f"ex{k}",
                            f"j{j}", f"jep{j}", f"jset{j}"}
                         | {f"j{x}" for x in range(jobs)})
        return [Transition(f"claim{j}s{k}", f"j{j}", g_claim, a_claim,
                           keys, frozenset({f"st{k}", f"ep{k}",
                                            f"own{k}", f"seg{k}",
                                            f"ex{k}", f"j{j}",
                                            f"jep{j}", f"jset{j}"}))]

    def job_ts(j: int):
        def g_write(s):
            return s[f"j{j}"] == CLAIMED

        def a_write(s):
            k = s[f"jset{j}"]
            s[f"seg{k}"] = s[f"jep{j}"]  # stamp my epoch's words
            s[f"j{j}"] = ATTACHED
            return s

        def g_read(s):
            # an attacher reads protocol words (ring heads, flat seqs)
            # the moment it maps — before its own first write, which is
            # exactly when a skipped reset leaks the previous epoch
            return s[f"j{j}"] in (CLAIMED, ATTACHED) and s[f"obs{j}"] < 0

        def a_read(s):
            k = s[f"jset{j}"]
            s[f"obs{j}"] = s[f"seg{k}"]
            return s

        def g_release(s):
            if s[f"j{j}"] == ATTACHED and s[f"obs{j}"] >= 0:
                return True
            # the double-release shape: close_light + ShmChannel.close
            # both release; the second must be an epoch-guarded no-op
            return s[f"j{j}"] == DONE and s[f"rel{j}"] == 1

        def a_release(s):
            k = s[f"jset{j}"]
            if mutation == "release_no_epoch" \
                    or s[f"ep{k}"] == s[f"jep{j}"]:
                s[f"st{k}"] = 0
                s[f"own{k}"] = -1
            s[f"j{j}"] = DONE
            s[f"rel{j}"] += 1
            return s

        allk = frozenset({f"st{x}" for x in range(ns)}
                         | {f"ep{x}" for x in range(ns)}
                         | {f"own{x}" for x in range(ns)}
                         | {f"seg{x}" for x in range(ns)}
                         | {f"j{j}", f"jep{j}", f"jset{j}",
                            f"obs{j}", f"rel{j}"})
        return [
            Transition(f"write{j}", f"j{j}", g_write, a_write, allk,
                       frozenset({f"seg{x}" for x in range(ns)}
                                 | {f"j{j}"})),
            Transition(f"read{j}", f"j{j}", g_read, a_read, allk,
                       frozenset({f"obs{j}", f"j{j}"})),
            Transition(f"release{j}", f"j{j}", g_release, a_release,
                       allk,
                       frozenset({f"st{x}" for x in range(ns)}
                                 | {f"own{x}" for x in range(ns)}
                                 | {f"j{j}", f"rel{j}"})),
        ]

    def daemon_ts(k: int):
        def g_sweep(s):
            if s[f"st{k}"] != 1 or mutation == "sweep_never_fires":
                return False
            owner = s[f"own{k}"]
            if owner < 0:
                return False
            if mutation == "sweep_live_owner":
                return s[f"j{owner}"] in (CLAIMED, ATTACHED)  # MUTANT
            return s[f"j{owner}"] == CRASHED

        def a_sweep(s):
            s[f"st{k}"] = 0
            s[f"own{k}"] = -1
            return s

        def g_expire(s):
            if s[f"ex{k}"] == 0:
                return False
            if mutation == "expiry_reaps_claimed":
                return True           # MUTANT: reaps busy sets too
            return s[f"st{k}"] == 0

        def a_expire(s):
            s[f"ex{k}"] = 0
            return s

        jk = frozenset({f"j{x}" for x in range(jobs)})
        return [
            Transition(f"sweep{k}", "daemon", g_sweep, a_sweep,
                       frozenset({f"st{k}", f"own{k}"}) | jk,
                       frozenset({f"st{k}", f"own{k}"})),
            Transition(f"expire{k}", "daemon", g_expire, a_expire,
                       frozenset({f"st{k}", f"ex{k}"}),
                       frozenset({f"ex{k}"})),
        ]

    def holders(s, k):
        return [j for j in range(jobs)
                if s[f"j{j}"] in (CLAIMED, ATTACHED)
                and s[f"jset{j}"] == k and s[f"jep{j}"] == s[f"ep{k}"]]

    def inv_excl(s):
        for k in range(ns):
            h = [j for j in range(jobs)
                 if s[f"j{j}"] in (CLAIMED, ATTACHED)
                 and s[f"jset{j}"] == k]
            if len(h) > 1:
                return (f"set {k} held by jobs {h} simultaneously — "
                        "two jobs mapping one segment set")
        return None

    def inv_fresh(s):
        for j in range(jobs):
            if s[f"obs{j}"] >= 0 and s[f"obs{j}"] not in (0, s[f"jep{j}"]):
                return (f"job {j} (epoch {s[f'jep{j}']}) observed a "
                        f"word of epoch {s[f'obs{j}']} — the previous "
                        "incarnation's protocol state leaked through "
                        "the reset")
        return None

    def inv_reap(s):
        for j in range(jobs):
            if s[f"j{j}"] in (CLAIMED, ATTACHED):
                k = s[f"jset{j}"]
                if s[f"ex{k}"] == 0 and j in holders(s, k):
                    return (f"idle-expiry unlinked set {k} while job "
                            f"{j} holds it")
        return None

    def inv_quota(s):
        if busy_count(s) > quota:
            return (f"{busy_count(s)} busy sets exceed the admission "
                    f"quota {quota}")
        return None

    def final(s):
        return all(s[f"j{j}"] in (DONE, CRASHED) for j in range(jobs))

    invs = [("exclusivity", inv_excl), ("epoch-fresh", inv_fresh),
            ("no-reap", inv_reap)]
    if concurrent:
        invs.append(("admission", inv_quota))
    return Model(
        f"daemon(jobs={jobs},crash={crash},conc={concurrent},"
        f"mut={mutation})", init, ts(), invs, final)

"""Multi-tenant warm-attach daemon model (runtime/daemon.py, PR 9/14).

The manifest protocol, as shipped: every transaction is one flock'd
read-modify-write (so each model transition is atomic); a claim sweeps
a dead owner's stale epoch, truncate-resets every segment file BEFORE
publishing the claim, bumps the epoch, and records the claimer; a
release is epoch-guarded (a late/double release of a swept-and-
reclaimed set must be a no-op); the daemon's serve loop sweeps dead
owners and idle-expires FREE sets only.

The PR 14 multi-tenant protocol is modeled in lockstep:

  * ``nsets`` independent set instances under one admission ``quota``
    (``concurrent=True`` — modeled in PR 13 BEFORE the daemon was
    built, now the shipping shape);
  * the bounded FIFO admission **queue**: a job that cannot be granted
    parks with a ticket; only the live head ticket may claim, and an
    unqueued job may claim directly only while no live waiter is
    parked (``runtime/daemon.py claim()``'s head rule);
  * the **executable cache** (``cache=True``): artifacts are stamped
    with the manifest's exec epoch; a reader must reject any stamp
    other than the current epoch (invalidation = epoch bump, the
    truncate-reset discipline applied to executables).

Invariants:
  exclusivity      at most one live job holds any set at a time
  epoch-fresh      an attached job never observes a previous epoch's
                   word in its segment (the truncate-reset guarantee)
  no-reap          idle-expiry never unlinks a set a live job holds —
                   including while sibling sets/claims are in flight
  admission        (concurrent) busy sets never exceed the quota
  cache-fresh      (cache) a served artifact always carries the cache
                   epoch current at serve time
  no-hang          every job eventually claims+releases (a crashed
                   owner's set must become claimable again; a queued
                   waiter must eventually be granted) — deadlock

Mutations:
  no_reset               claim skips the truncate-reset
  release_no_epoch       release ignores the epoch guard (double
                         release frees the NEXT claimer's set)
  sweep_live_owner       the stale sweep's alive check is broken
  expiry_reaps_claimed   idle-expiry unlinks busy sets too
  sweep_never_fires      stale-epoch sweep disabled (crash → dead set)
  over_quota             admission ignores the quota
  queue_skips_admission  a queued waiter is granted past the quota
  queue_drops_waiter     a parked waiter is never granted (the queue
                         loses entries — no-hang/deadlock)
  expiry_checks_set0     idle-expiry decides from set 0's state alone
                         (the mis-scoped idle check: reaps a busy
                         sibling under concurrency)
  cache_stale_serve      the cache serves an artifact without the
                         epoch check (a jax/profile change keeps
                         feeding the old executable)
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition

# job phases
IDLE, CLAIMED, ATTACHED, DONE, CRASHED, WAITING = 0, 1, 2, 3, 4, 5


def build_daemon(jobs: int = 2, crash: bool = False,
                 concurrent: bool = False, nsets: int = 2,
                 quota: int = 1, cache: bool = False,
                 mutation: Optional[str] = None) -> Model:
    """``jobs`` claimers cycle claim→write→read→release over one set
    (or, with ``concurrent``, over ``nsets`` instances under
    ``quota``), parking in the FIFO admission queue when blocked.
    ``cache`` adds the exec-cache epoch machinery."""
    ns = nsets if concurrent else 1
    if not concurrent:
        quota = 1
    init = {"qn": 0}
    for s_ in range(ns):
        init[f"st{s_}"] = 0          # 0 free / 1 busy
        init[f"ep{s_}"] = 0          # manifest epoch
        init[f"own{s_}"] = -1        # owning job (-1 none)
        init[f"seg{s_}"] = 0         # epoch stamped into the files
        init[f"ex{s_}"] = 1          # files exist (idle-expiry unlinks)
    for j in range(jobs):
        init[f"j{j}"] = IDLE
        init[f"jep{j}"] = 0          # epoch of j's claim
        init[f"jset{j}"] = -1        # set j holds
        init[f"obs{j}"] = -1         # epoch word j observed on read
        init[f"rel{j}"] = 0          # releases j has issued
        init[f"wt{j}"] = -1          # admission-queue ticket (-1 none)
    if crash:
        # bounded-fault convention: at least one survivor — an
        # all-crashed world satisfies every invariant trivially, and
        # a starved survivor must register as a deadlock, not escape
        # by dying too
        init["cb"] = jobs - 1
    if cache:
        init["cgen"] = 1             # manifest exec_epoch
        init["cart"] = 0             # stored artifact's epoch (0 none)
        init["fb"] = 0               # fingerprint bumped yet
        for j in range(jobs):
            init[f"cobs{j}"] = -1    # artifact epoch j was served
            init[f"cgat{j}"] = -1    # cache epoch at j's serve time

    def busy_count(s):
        return sum(1 for k in range(ns) if s[f"st{k}"] == 1)

    def waiters(s):
        return [s[f"wt{i}"] for i in range(jobs)
                if s[f"j{i}"] == WAITING]

    def is_head(s, j):
        w = waiters(s)
        return s[f"wt{j}"] >= 0 and s[f"wt{j}"] == min(w)

    jkeys = frozenset({f"j{x}" for x in range(jobs)}
                      | {f"wt{x}" for x in range(jobs)})

    def ts():
        out = []
        for j in range(jobs):
            for k in range(ns):
                out.extend(claim_ts(j, k))
            out.extend(queue_ts(j))
            out.extend(job_ts(j))
            if cache:
                out.extend(cache_job_ts(j))
            if crash:
                def g_crash(s, j=j):
                    return s["cb"] > 0 \
                        and s[f"j{j}"] in (CLAIMED, ATTACHED, WAITING)

                def a_crash(s, j=j):
                    s[f"j{j}"] = CRASHED
                    s["cb"] -= 1
                    return s
                out.append(Transition(
                    f"crash{j}", f"j{j}", g_crash, a_crash,
                    frozenset({f"j{j}", "cb"}),
                    frozenset({f"j{j}", "cb"})))
        for k in range(ns):
            out.extend(daemon_ts(k))
        if cache:
            out.extend(cache_env_ts())
        return out

    def claim_ts(j: int, k: int):
        def g_claim(s):
            ph = s[f"j{j}"]
            if ph not in (IDLE, WAITING):
                return False
            if ph == IDLE and waiters(s):
                return False     # FIFO: must park behind live waiters
            if ph == WAITING:
                if mutation == "queue_drops_waiter":
                    return False          # MUTANT: queue loses entries
                if not is_head(s, j):
                    return False
            if s[f"st{k}"] == 0:
                if busy_count(s) >= quota \
                        and mutation != "over_quota" \
                        and not (mutation == "queue_skips_admission"
                                 and ph == WAITING):
                    return False          # admission control
                return True
            # busy: claimable only via the at-claim stale sweep (the
            # reclaim frees the capacity it consumes, so no quota gate)
            owner = s[f"own{k}"]
            if mutation == "sweep_never_fires":
                return False
            return owner >= 0 and s[f"j{owner}"] == CRASHED

        def a_claim(s):
            if s[f"ex{k}"] == 0:
                s[f"ex{k}"] = 1       # recreate after idle expiry
                s[f"seg{k}"] = 0
            s[f"ep{k}"] += 1
            if mutation != "no_reset":
                s[f"seg{k}"] = 0      # truncate-reset BEFORE publishing
            s[f"st{k}"] = 1
            s[f"own{k}"] = j
            s[f"j{j}"] = CLAIMED
            s[f"jep{j}"] = s[f"ep{k}"]
            s[f"jset{j}"] = k
            s[f"wt{j}"] = -1          # dequeued on grant
            return s

        keys = frozenset({f"st{x}" for x in range(ns)}
                         | {f"ep{k}", f"own{k}", f"seg{k}", f"ex{k}",
                            f"jep{j}", f"jset{j}"}
                         | jkeys)
        return [Transition(f"claim{j}s{k}", f"j{j}", g_claim, a_claim,
                           keys, frozenset({f"st{k}", f"ep{k}",
                                            f"own{k}", f"seg{k}",
                                            f"ex{k}", f"j{j}",
                                            f"jep{j}", f"jset{j}",
                                            f"wt{j}"}))]

    def queue_ts(j: int):
        # parking is always legal from IDLE: the implementation's
        # claim() enqueues whenever its transaction could not grant,
        # and a spuriously early ticket only strengthens FIFO
        def g_enq(s):
            return s[f"j{j}"] == IDLE

        def a_enq(s):
            s[f"j{j}"] = WAITING
            s[f"wt{j}"] = s["qn"]
            s["qn"] += 1
            return s

        return [Transition(f"enq{j}", f"j{j}", g_enq, a_enq,
                           frozenset({f"j{j}", f"wt{j}", "qn"}),
                           frozenset({f"j{j}", f"wt{j}", "qn"}))]

    def job_ts(j: int):
        def g_write(s):
            return s[f"j{j}"] == CLAIMED

        def a_write(s):
            k = s[f"jset{j}"]
            s[f"seg{k}"] = s[f"jep{j}"]  # stamp my epoch's words
            s[f"j{j}"] = ATTACHED
            return s

        def g_read(s):
            # an attacher reads protocol words (ring heads, flat seqs)
            # the moment it maps — before its own first write, which is
            # exactly when a skipped reset leaks the previous epoch
            return s[f"j{j}"] in (CLAIMED, ATTACHED) and s[f"obs{j}"] < 0

        def a_read(s):
            k = s[f"jset{j}"]
            s[f"obs{j}"] = s[f"seg{k}"]
            return s

        def g_release(s):
            if s[f"j{j}"] == ATTACHED and s[f"obs{j}"] >= 0:
                return True
            # the double-release shape: close_light + ShmChannel.close
            # both release; the second must be an epoch-guarded no-op
            return s[f"j{j}"] == DONE and s[f"rel{j}"] == 1

        def a_release(s):
            k = s[f"jset{j}"]
            if mutation == "release_no_epoch" \
                    or s[f"ep{k}"] == s[f"jep{j}"]:
                s[f"st{k}"] = 0
                s[f"own{k}"] = -1
            s[f"j{j}"] = DONE
            s[f"rel{j}"] += 1
            return s

        allk = frozenset({f"st{x}" for x in range(ns)}
                         | {f"ep{x}" for x in range(ns)}
                         | {f"own{x}" for x in range(ns)}
                         | {f"seg{x}" for x in range(ns)}
                         | {f"j{j}", f"jep{j}", f"jset{j}",
                            f"obs{j}", f"rel{j}"})
        return [
            Transition(f"write{j}", f"j{j}", g_write, a_write, allk,
                       frozenset({f"seg{x}" for x in range(ns)}
                                 | {f"j{j}"})),
            Transition(f"read{j}", f"j{j}", g_read, a_read, allk,
                       frozenset({f"obs{j}", f"j{j}"})),
            Transition(f"release{j}", f"j{j}", g_release, a_release,
                       allk,
                       frozenset({f"st{x}" for x in range(ns)}
                                 | {f"own{x}" for x in range(ns)}
                                 | {f"j{j}", f"rel{j}"})),
        ]

    def cache_job_ts(j: int):
        # populate: an attached job stores an artifact stamped with the
        # CURRENT cache epoch (exec_cache_put under exec_epoch)
        def g_cput(s):
            return s[f"j{j}"] == ATTACHED and s["cart"] == 0

        def a_cput(s):
            s["cart"] = s["cgen"]
            return s

        # serve: exec_cache_get — the epoch is part of the entry name,
        # so a stale-epoch artifact must read as a miss, never a hit
        def g_cget(s):
            if s[f"j{j}"] != ATTACHED or s[f"cobs{j}"] >= 0 \
                    or s["cart"] == 0:
                return False
            if mutation == "cache_stale_serve":
                return True           # MUTANT: no epoch check
            return s["cart"] == s["cgen"]

        def a_cget(s):
            s[f"cobs{j}"] = s["cart"]
            s[f"cgat{j}"] = s["cgen"]
            return s

        ck = frozenset({"cgen", "cart", f"j{j}",
                        f"cobs{j}", f"cgat{j}"})
        return [
            Transition(f"cput{j}", f"j{j}", g_cput, a_cput, ck,
                       frozenset({"cart"})),
            Transition(f"cget{j}", f"j{j}", g_cget, a_cget, ck,
                       frozenset({f"cobs{j}", f"cgat{j}"})),
        ]

    def cache_env_ts():
        # the environment invalidates: a jax upgrade / profile change /
        # explicit --reset-exec-cache bumps the exec epoch exactly like
        # the claim's truncate-reset bumps the set epoch
        def g_refp(s):
            return s["fb"] == 0

        def a_refp(s):
            s["fb"] = 1
            s["cgen"] += 1
            return s

        return [Transition("refp", "env", g_refp, a_refp,
                           frozenset({"fb", "cgen"}),
                           frozenset({"fb", "cgen"}))]

    def daemon_ts(k: int):
        def g_sweep(s):
            if s[f"st{k}"] != 1 or mutation == "sweep_never_fires":
                return False
            owner = s[f"own{k}"]
            if owner < 0:
                return False
            if mutation == "sweep_live_owner":
                return s[f"j{owner}"] in (CLAIMED, ATTACHED)  # MUTANT
            return s[f"j{owner}"] == CRASHED

        def a_sweep(s):
            s[f"st{k}"] = 0
            s[f"own{k}"] = -1
            return s

        def g_expire(s):
            if s[f"ex{k}"] == 0:
                return False
            if mutation == "expiry_reaps_claimed":
                return True           # MUTANT: reaps busy sets too
            if mutation == "expiry_checks_set0":
                return s["st0"] == 0  # MUTANT: mis-scoped idle check
            return s[f"st{k}"] == 0

        def a_expire(s):
            s[f"ex{k}"] = 0
            return s

        jk = frozenset({f"j{x}" for x in range(jobs)})
        return [
            Transition(f"sweep{k}", "daemon", g_sweep, a_sweep,
                       frozenset({f"st{k}", f"own{k}"}) | jk,
                       frozenset({f"st{k}", f"own{k}"})),
            Transition(f"expire{k}", "daemon", g_expire, a_expire,
                       frozenset({f"st{k}", f"ex{k}", "st0"}),
                       frozenset({f"ex{k}"})),
        ]

    def holders(s, k):
        return [j for j in range(jobs)
                if s[f"j{j}"] in (CLAIMED, ATTACHED)
                and s[f"jset{j}"] == k and s[f"jep{j}"] == s[f"ep{k}"]]

    def inv_excl(s):
        for k in range(ns):
            h = [j for j in range(jobs)
                 if s[f"j{j}"] in (CLAIMED, ATTACHED)
                 and s[f"jset{j}"] == k]
            if len(h) > 1:
                return (f"set {k} held by jobs {h} simultaneously — "
                        "two jobs mapping one segment set")
        return None

    def inv_fresh(s):
        for j in range(jobs):
            if s[f"obs{j}"] >= 0 and s[f"obs{j}"] not in (0, s[f"jep{j}"]):
                return (f"job {j} (epoch {s[f'jep{j}']}) observed a "
                        f"word of epoch {s[f'obs{j}']} — the previous "
                        "incarnation's protocol state leaked through "
                        "the reset")
        return None

    def inv_reap(s):
        for j in range(jobs):
            if s[f"j{j}"] in (CLAIMED, ATTACHED):
                k = s[f"jset{j}"]
                if s[f"ex{k}"] == 0 and j in holders(s, k):
                    return (f"idle-expiry unlinked set {k} while job "
                            f"{j} holds it")
        return None

    def inv_quota(s):
        if busy_count(s) > quota:
            return (f"{busy_count(s)} busy sets exceed the admission "
                    f"quota {quota}")
        return None

    def inv_cache(s):
        for j in range(jobs):
            if s[f"cobs{j}"] >= 0 and s[f"cobs{j}"] != s[f"cgat{j}"]:
                return (f"job {j} was served an artifact of cache "
                        f"epoch {s[f'cobs{j}']} while the current "
                        f"epoch was {s[f'cgat{j}']} — a stale "
                        "executable survived the invalidation reset")
        return None

    def final(s):
        return all(s[f"j{j}"] in (DONE, CRASHED) for j in range(jobs))

    invs = [("exclusivity", inv_excl), ("epoch-fresh", inv_fresh),
            ("no-reap", inv_reap)]
    if concurrent:
        invs.append(("admission", inv_quota))
    if cache:
        invs.append(("cache-fresh", inv_cache))
    return Model(
        f"daemon(jobs={jobs},crash={crash},conc={concurrent},"
        f"cache={cache},mut={mutation})", init, ts(), invs, final)

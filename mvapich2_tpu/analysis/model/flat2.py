"""Hierarchical flat-tier + multicast-bcast models (cplane.cpp cp_flat2_*).

Two protocols, reduced to their seqlock skeletons exactly like
``seqlock.py`` (TORN-split payload writes; frozenset contribution
payloads; a sticky poison word):

``build_hier_allreduce``
    The leaders-of-k two-level wave: members fold intra-group into
    their group leader, leaders exchange partials in a leaders-only
    block folded by the ROOT leader (comm rank 0), seq-stamped fan-out
    back through the group blocks. Invariants: no torn read delivered,
    every rank delivers the FULL contribution set (agreement), poison
    sticky across region re-key after a crash.

    Mutations:
      xchg_no_guard       the root leader folds the leaders' exchange
                          slots WITHOUT waiting for their in-stamps —
                          it folds a torn or stale partial
      fanout_before_xchg  a group leader publishes its group block
                          BEFORE reading the leader exchange's total —
                          its members deliver the group partial
      no_poison           an aborted wave (member crash) skips the
                          sticky poison stamp — region re-key/reuse
                          folds the dead wave's torn slot

``build_mcast``
    The pipelined single-writer multicast bcast: the root writes each
    wave's payload ONCE into ring buffer ``wave % nbuf`` and
    release-stamps the region wave counter mseq; readers consume under
    the seqlock discipline and ack with out-stamps. The root may run
    ``nbuf`` waves ahead; buffer overwrite is guarded on every
    reader's out >= wave - nbuf. The comm's FIRST wave synchronizes
    (root waits for every arrival) so a LATE member's lazy numbering-
    base read can never count an in-flight wave.

    Mutations:
      publish_before_write  the root stamps mseq BEFORE the payload
                            copy — a reader consumes the torn buffer
      no_overwrite_guard    the root skips the out-stamp guard — wave
                            s+nbuf tears the buffer under a slow
                            wave-s reader (needs waves > nbuf)
      no_first_sync         the root skips the first-wave arrival
                            wave — the late member's base counts the
                            in-flight wave and it waits on a seq
                            nobody will ever stamp (deadlock), the
                            flat2 analog of the PR 5 bcast desync
"""

from __future__ import annotations

from typing import Optional

from .explorer import Model, Transition
from .seqlock import TORN


def _full(n: int, wave: int) -> frozenset:
    return frozenset((r, wave) for r in range(n))


def build_hier_allreduce(groups: int = 2, k: int = 2,
                         crash: bool = False,
                         mutation: Optional[str] = None) -> Model:
    """``groups`` groups of ``k`` ranks run ONE hierarchical allreduce
    wave (rank g*k is group g's leader; rank 0 the root leader).
    ``crash=True`` adds a mid-copy death of the last member plus the
    abort/poison/reuse machinery from the flat model."""
    assert groups >= 2 and k >= 2
    n = groups * k
    ts = []
    init = {"poison": 0, "mseq": 0, "lbseq": 0, "lbpay": frozenset(),
            "aborted": 0, "reuse_res": None}
    for g in range(groups):
        init[f"gbseq{g}"] = 0
        init[f"gbpay{g}"] = frozenset()
        init[f"lin{g}"] = 0
        init[f"lout{g}"] = 0
        init[f"lpay{g}"] = frozenset()
        init[f"acc{g}"] = None         # leader's private fold
    for r in range(n):
        init[f"in{r}"] = 0
        init[f"out{r}"] = 0
        init[f"pay{r}"] = frozenset()
        init[f"pc{r}"] = 0
        init[f"res{r}"] = None
        init[f"alive{r}"] = 1

    def running(s, r):
        return s[f"alive{r}"] and not s["aborted"]

    # ---- group members (slot j > 0 of each group) -------------------
    for g in range(groups):
        for j in range(1, k):
            r = g * k + j

            def mk(g, r):
                def g_begin(s):
                    return running(s, r) and s[f"pc{r}"] == 0

                def a_begin(s):
                    s[f"pay{r}"] = TORN
                    s[f"pc{r}"] = 1
                    return s

                def a_copy(s):
                    s[f"pay{r}"] = frozenset({(r, 1)})
                    s[f"pc{r}"] = 2
                    return s

                def a_stamp(s):
                    s[f"in{r}"] = 1              # release stamp
                    s[f"pc{r}"] = 3
                    return s

                def g_read(s):
                    return running(s, r) and s[f"pc{r}"] == 3 \
                        and s[f"gbseq{g}"] >= 1

                def a_read(s):
                    s[f"res{r}"] = s[f"gbpay{g}"]
                    s[f"out{r}"] = 1
                    s[f"pc{r}"] = 4
                    return s

                return [
                    Transition(f"m{r}.begin_copy", f"r{r}", g_begin,
                               a_begin,
                               frozenset({f"pc{r}", f"alive{r}",
                                          "aborted"}),
                               frozenset({f"pay{r}", f"pc{r}"})),
                    Transition(f"m{r}.end_copy", f"r{r}",
                               lambda s, r=r: running(s, r)
                               and s[f"pc{r}"] == 1, a_copy,
                               frozenset({f"pc{r}"}),
                               frozenset({f"pay{r}", f"pc{r}"})),
                    Transition(f"m{r}.stamp_in", f"r{r}",
                               lambda s, r=r: running(s, r)
                               and s[f"pc{r}"] == 2, a_stamp,
                               frozenset({f"pc{r}"}),
                               frozenset({f"in{r}", f"pc{r}"})),
                    Transition(f"m{r}.read_gbcb", f"r{r}", g_read, a_read,
                               frozenset({f"pc{r}", f"gbseq{g}",
                                          f"gbpay{g}"}),
                               frozenset({f"res{r}", f"out{r}",
                                          f"pc{r}"})),
                ]
            ts.extend(mk(g, r))

    # ---- group leaders ----------------------------------------------
    # pc: 0 fold-group -> (non-root: 1 publish lslot, 2 wait lbcb)
    #     (root: 1 fold leaders, 2 publish lbcb+mseq)
    #     -> 3 fan-out -> 4 done
    for g in range(groups):
        r = g * k

        def mkl(g, r):
            member_ins = [f"in{g * k + j}" for j in range(1, k)]
            member_pays = [f"pay{g * k + j}" for j in range(1, k)]

            def g_fold(s):
                if not (running(s, r) and s[f"pc{r}"] == 0):
                    return False
                return all(s[m] >= 1 for m in member_ins)

            def a_fold(s):
                acc = frozenset({(r, 1)})
                torn = False
                for m in member_pays:
                    if s[m] == TORN:
                        torn = True
                    else:
                        acc |= s[m]
                s[f"acc{g}"] = TORN if torn else acc
                s[f"pc{r}"] = 1
                return s

            steps = [Transition(f"L{g}.fold_group", f"r{r}", g_fold,
                                a_fold,
                                frozenset({f"pc{r}", f"alive{r}",
                                           "aborted"}
                                          | set(member_ins)
                                          | set(member_pays)),
                                frozenset({f"acc{g}", f"pc{r}"}))]
            if g != 0:
                def a_pub(s):
                    s[f"lpay{g}"] = s[f"acc{g}"]
                    s[f"lin{g}"] = 1             # release stamp
                    s[f"pc{r}"] = 2
                    return s

                def g_readl(s):
                    return running(s, r) and s[f"pc{r}"] == 2 \
                        and s["lbseq"] >= 1

                def a_readl(s):
                    s[f"acc{g}"] = s["lbpay"]
                    s[f"lout{g}"] = 1
                    s[f"pc{r}"] = 3
                    return s

                steps += [
                    Transition(f"L{g}.publish_lslot", f"r{r}",
                               lambda s, r=r: running(s, r)
                               and s[f"pc{r}"] == 1, a_pub,
                               frozenset({f"pc{r}", f"acc{g}"}),
                               frozenset({f"lpay{g}", f"lin{g}",
                                          f"pc{r}"})),
                    Transition(f"L{g}.read_lbcb", f"r{r}", g_readl,
                               a_readl,
                               frozenset({f"pc{r}", "lbseq", "lbpay"}),
                               frozenset({f"acc{g}", f"lout{g}",
                                          f"pc{r}"})),
                ]
            else:
                other_lins = [f"lin{j}" for j in range(1, groups)]
                other_lpays = [f"lpay{j}" for j in range(1, groups)]

                def g_xchg(s):
                    if not (running(s, r) and s[f"pc{r}"] == 1):
                        return False
                    if mutation == "xchg_no_guard":
                        return True              # MUTANT: no in-wait
                    return all(s[x] >= 1 for x in other_lins)

                def a_xchg(s):
                    acc = s[f"acc{g}"]
                    torn = acc == TORN
                    for x in other_lpays:
                        if s[x] == TORN or acc == TORN:
                            torn = True
                        elif not s[x]:
                            # stale (never-published) slot folds as a
                            # MISSING contribution, not a torn one
                            pass
                        else:
                            acc |= s[x]
                    s[f"acc{g}"] = TORN if torn else acc
                    s[f"pc{r}"] = 2
                    return s

                def a_lpub(s):
                    s["lbpay"] = s[f"acc{g}"]
                    s["lbseq"] = 1               # release stamp
                    s["mseq"] = 1                # region wave counter
                    s[f"lin{g}"] = 1
                    s[f"lout{g}"] = 1
                    s[f"pc{r}"] = 3
                    return s

                steps += [
                    Transition("L0.fold_leaders", f"r{r}", g_xchg,
                               a_xchg,
                               frozenset({f"pc{r}", f"acc{g}"}
                                         | set(other_lins)
                                         | set(other_lpays)),
                               frozenset({f"acc{g}", f"pc{r}"})),
                    Transition("L0.publish_lbcb", f"r{r}",
                               lambda s, r=r: running(s, r)
                               and s[f"pc{r}"] == 2, a_lpub,
                               frozenset({f"pc{r}", f"acc{g}"}),
                               frozenset({"lbpay", "lbseq", "mseq",
                                          f"lin{g}", f"lout{g}",
                                          f"pc{r}"})),
                ]

            def g_fanout(s):
                if not running(s, r):
                    return False
                if mutation == "fanout_before_xchg" and g != 0:
                    # MUTANT: the group leader publishes its group
                    # block straight after the intra-group fold
                    return s[f"pc{r}"] == 1
                return s[f"pc{r}"] == 3

            def a_fanout(s):
                s[f"gbpay{g}"] = s[f"acc{g}"]
                s[f"gbseq{g}"] = 1               # release stamp
                s[f"res{r}"] = s[f"acc{g}"]
                s[f"in{r}"] = 1
                s[f"out{r}"] = 1
                s[f"pc{r}"] = 4
                return s

            steps.append(
                Transition(f"L{g}.fanout", f"r{r}", g_fanout, a_fanout,
                           frozenset({f"pc{r}", f"acc{g}"}),
                           frozenset({f"gbpay{g}", f"gbseq{g}",
                                      f"res{r}", f"in{r}", f"out{r}",
                                      f"pc{r}"})))
            return steps
        ts.extend(mkl(g, r))

    # ---- crash / abort / poison / re-key probe ----------------------
    if crash:
        victim = n - 1                   # a member of the last group

        def g_die(s):
            return s[f"alive{victim}"] and s[f"pc{victim}"] == 1

        def a_die(s):
            s[f"alive{victim}"] = 0
            return s

        def g_abort(s):
            return s["alive0"] and not s[f"alive{victim}"] \
                and not s["aborted"]

        def a_abort(s):
            s["aborted"] = 1
            if mutation != "no_poison":
                s["poison"] = 1                  # MUTANT skips this
            return s

        def g_reuse(s):
            # re-key probe: recovery (or ctx reuse) tries to key the
            # region again — cp_flat2_base must refuse when poisoned
            return s["aborted"] and s["reuse_res"] is None

        def a_reuse(s):
            if s["poison"]:
                s["reuse_res"] = "refused"
            else:
                torn = any(s[f"pay{r}"] == TORN for r in range(n))
                s["reuse_res"] = TORN if torn else "folded"
            return s

        ts.extend([
            Transition("V.die", f"r{victim}", g_die, a_die,
                       frozenset({f"pc{victim}", f"alive{victim}"}),
                       frozenset({f"alive{victim}"})),
            Transition("L0.abort_poison", "r0", g_abort, a_abort,
                       frozenset({f"alive{victim}", "aborted"}),
                       frozenset({"aborted", "poison"})),
            Transition("rekey.probe", "rekey", g_reuse, a_reuse,
                       frozenset({"aborted", "poison", "reuse_res"}
                                 | {f"pay{r}" for r in range(n)}),
                       frozenset({"reuse_res"})),
        ])

    # ---- invariants --------------------------------------------------
    def inv_torn(s):
        for r in range(n):
            if s[f"res{r}"] == TORN:
                return f"rank {r} delivered a TORN payload"
        if s["reuse_res"] == TORN:
            return "region re-key folded a torn slot of the dead wave"
        return None

    def inv_agree(s):
        for r in range(n):
            v = s[f"res{r}"]
            if v is not None and v != TORN and v != _full(n, 1):
                return (f"rank {r} delivered {sorted(v)} != the full "
                        "contribution set")
        return None

    def inv_poison(s):
        if s["aborted"] and not s["poison"]:
            return "wave aborted but the region poison is not sticky"
        return None

    def final(s):
        if s["aborted"]:
            return s["reuse_res"] is not None if crash else True
        return all(s[f"res{r}"] is not None for r in range(n))

    invs = [("no-torn-read-delivered", inv_torn),
            ("agreement", inv_agree)]
    if crash:
        invs.append(("poison-sticky", inv_poison))
    return Model(f"flat2-hier-allreduce(g={groups},k={k},crash={crash},"
                 f"mut={mutation})", init, ts, invs, final)


def build_mcast(n: int = 3, waves: int = 2, nbuf: int = 1,
                mutation: Optional[str] = None) -> Model:
    """Root rank 0 runs ``waves`` pipelined multicast bcasts over a
    ``nbuf``-deep buffer ring; rank n-1 is a LATE member whose
    numbering base is read lazily. Wave w publishes ``{(0, w)}`` in
    buffer w % nbuf."""
    assert n >= 2 and waves >= 1 and nbuf >= 1
    late = n - 1
    init = {"mseq": 0, "rw": 1}              # rw = root's current wave
    for b in range(nbuf):
        init[f"mpay{b}"] = frozenset()
    for r in range(1, n):
        init[f"in{r}"] = 0
        init[f"out{r}"] = 0
        init[f"w{r}"] = 1
        init[f"res{r}"] = ()
        init[f"base{r}"] = 0 if r != late else None   # late: lazy read

    ts = []

    def g_base(s):
        return s[f"base{late}"] is None

    def a_base(s):
        s[f"base{late}"] = s["mseq"]             # lazy numbering base
        return s

    ts.append(Transition(f"r{late}.read_base", f"r{late}", g_base,
                         a_base, frozenset({"mseq", f"base{late}"}),
                         frozenset({f"base{late}"})))

    # readers: arrive (in-stamp), wait mseq, consume, ack (out-stamp)
    for r in range(1, n):
        def mk(r):
            def wave_of(s):
                return s[f"base{r}"] + s[f"w{r}"]

            def g_arrive(s):
                return s[f"base{r}"] is not None and s[f"w{r}"] <= waves \
                    and s[f"in{r}"] < wave_of(s)

            def a_arrive(s):
                s[f"in{r}"] = wave_of(s)
                return s

            def g_read(s):
                return s[f"base{r}"] is not None and s[f"w{r}"] <= waves \
                    and s[f"in{r}"] == wave_of(s) \
                    and s["mseq"] >= wave_of(s)

            def a_read(s):
                s[f"res{r}"] = s[f"res{r}"] \
                    + (s[f"mpay{wave_of(s) % nbuf}"],)
                s[f"out{r}"] = wave_of(s)
                s[f"w{r}"] += 1
                return s

            return [
                Transition(f"r{r}.arrive", f"r{r}", g_arrive, a_arrive,
                           frozenset({f"base{r}", f"w{r}", f"in{r}"}),
                           frozenset({f"in{r}"})),
                Transition(f"r{r}.consume", f"r{r}", g_read, a_read,
                           frozenset({f"base{r}", f"w{r}", f"in{r}",
                                      "mseq"}
                                     | {f"mpay{b}" for b in range(nbuf)}),
                           frozenset({f"res{r}", f"out{r}", f"w{r}"})),
            ]
        ts.extend(mk(r))

    # root: per wave — (first-wave sync) -> overwrite guard -> torn
    # write -> value write -> publish stamp. pc encoded in "rpc".
    init["rpc"] = 0

    def g_guard(s):
        if s["rpc"] != 0 or s["rw"] > waves:
            return False
        w = s["rw"]
        if w == 1 and mutation != "no_first_sync":
            if not all(s[f"in{r}"] >= 1 for r in range(1, n)):
                return False
        if mutation != "no_overwrite_guard" and w > nbuf:
            if not all(s[f"out{r}"] >= w - nbuf for r in range(1, n)):
                return False
        return True

    def a_guard(s):
        s["rpc"] = 1
        return s

    def a_begin(s):
        s[f"mpay{s['rw'] % nbuf}"] = TORN
        s["rpc"] = 2
        if mutation == "publish_before_write":
            s["mseq"] = s["rw"]                  # MUTANT: stamp early
        return s

    def a_write(s):
        s[f"mpay{s['rw'] % nbuf}"] = frozenset({(0, s["rw"])})
        s["rpc"] = 3
        return s

    def a_publish(s):
        s["mseq"] = s["rw"]                      # release publish
        s["rw"] += 1
        s["rpc"] = 0
        return s

    ts.extend([
        Transition("root.guard", "r0", g_guard, a_guard,
                   frozenset({"rpc", "rw"}
                             | {f"in{r}" for r in range(1, n)}
                             | {f"out{r}" for r in range(1, n)}),
                   frozenset({"rpc"})),
        Transition("root.begin_write", "r0",
                   lambda s: s["rpc"] == 1, a_begin,
                   frozenset({"rpc", "rw"}),
                   frozenset({"rpc", "mseq"}
                             | {f"mpay{b}" for b in range(nbuf)})),
        Transition("root.end_write", "r0",
                   lambda s: s["rpc"] == 2, a_write,
                   frozenset({"rpc", "rw"}),
                   frozenset({"rpc"}
                             | {f"mpay{b}" for b in range(nbuf)})),
        Transition("root.publish", "r0",
                   lambda s: s["rpc"] == 3, a_publish,
                   frozenset({"rpc", "rw"}),
                   frozenset({"mseq", "rw", "rpc"})),
    ])

    def inv_data(s):
        for r in range(1, n):
            for i, v in enumerate(s[f"res{r}"], start=1):
                if v == TORN:
                    return f"rank {r} consumed a TORN mcast buffer"
                if v != frozenset({(0, i)}):
                    return (f"rank {r} wave {i} consumed {sorted(v)} != "
                            "the root payload of that wave")
        return None

    def final(s):
        return s["rw"] > waves \
            and all(s[f"w{r}"] > waves for r in range(1, n))

    return Model(f"flat2-mcast(n={n},waves={waves},nbuf={nbuf},"
                 f"mut={mutation})", init, ts,
                 [("mcast-data", inv_data)], final)


def build_net2(groups: int = 2, k: int = 2, crash: bool = False,
               mutation: Optional[str] = None) -> Model:
    """The net2 node-leader bridge (coll/netcoll.py): past np=64 the
    comm splits into ``groups`` node groups of ``k`` ranks; members
    fold into their node leader over the node-local flat tier, the
    leaders bridge partials over the KVS/TCP lanes (the ROOT leader,
    group 0's, folds the lane slots and publishes the total), and each
    leader fans the total back out through its group block.

    The bridge lane slot is a seqlock skeleton (TORN-split publish +
    in-stamp) because that is what the TCP-lane exchange actually is:
    a leader can die mid-publish, and the root must never fold a torn
    or unstamped lane. ``crash=True`` adds the node-leader-crash probe:
    the LAST group's leader dies mid-bridge, the root aborts the wave,
    poisons the net2 state, and a re-entry probe models the next
    collective on the comm — it must DEGRADE to the sched path (refuse
    the cached net2 split), never fold the dead wave's lane slots.

    Invariants: no-torn-read-delivered, agreement (every delivered
    result is the full contribution set), poison-sticky (crash only),
    plus the explorer's built-in deadlock freedom.

    Mutations (tests/test_modelcheck.py asserts each is caught):

      bridge_before_group_fold  a leader publishes its bridge lane slot
                                BEFORE folding its group members — the
                                root's total (and every delivered
                                result) misses their contributions
      fanout_before_bridge      a leader fans its group block out
                                straight after the group fold, before
                                reading the bridge total — its members
                                deliver the group partial
      leader_crash_no_poison    the abort after a mid-bridge leader
                                death skips the sticky poison — the
                                next collective re-enters net2 over the
                                dead split instead of degrading
    """
    assert groups >= 2 and k >= 2
    n = groups * k
    gv = groups - 1                      # crash victim: last group

    init = {"poison": 0, "aborted": 0, "reuse_res": None,
            "bseq": 0, "bpay": frozenset()}
    for g in range(groups):
        init[f"acc{g}"] = frozenset({(g * k, 1)})  # leader's own share
        init[f"bl{g}"] = frozenset()     # bridge lane slot
        init[f"blin{g}"] = 0             # lane in-stamp
        init[f"gb{g}"] = frozenset()     # group result block
        init[f"gbseq{g}"] = 0
        init[f"lalive{g}"] = 1
        init[f"pl{g}"] = 0
    for r in range(n):
        init[f"res{r}"] = None
    for g in range(groups):
        for j in range(1, k):
            r = g * k + j
            init[f"pay{r}"] = frozenset()
            init[f"in{r}"] = 0

    def running(s, g):
        return s[f"lalive{g}"] and not s["aborted"]

    ts = []

    # ---- group members: torn-split contribution copy + delivery -----
    for g in range(groups):
        for j in range(1, k):
            r = g * k + j

            def mkm(g=g, r=r):
                def a_begin(s):
                    s[f"pay{r}"] = TORN
                    s[f"pc_m{r}"] = 1
                    return s

                def a_copy(s):
                    s[f"pay{r}"] = frozenset({(r, 1)})
                    s[f"pc_m{r}"] = 2
                    return s

                def a_stamp(s):
                    s[f"in{r}"] = 1
                    s[f"pc_m{r}"] = 3
                    return s

                def g_read(s):
                    return not s["aborted"] and s[f"pc_m{r}"] == 3 \
                        and s[f"gbseq{g}"] >= 1

                def a_read(s):
                    s[f"res{r}"] = s[f"gb{g}"]
                    s[f"pc_m{r}"] = 4
                    return s

                return [
                    Transition(f"m{r}.begin_copy", f"r{r}",
                               lambda s, r=r: not s["aborted"]
                               and s[f"pc_m{r}"] == 0, a_begin,
                               frozenset({f"pc_m{r}", "aborted"}),
                               frozenset({f"pay{r}", f"pc_m{r}"})),
                    Transition(f"m{r}.end_copy", f"r{r}",
                               lambda s, r=r: not s["aborted"]
                               and s[f"pc_m{r}"] == 1, a_copy,
                               frozenset({f"pc_m{r}", "aborted"}),
                               frozenset({f"pay{r}", f"pc_m{r}"})),
                    Transition(f"m{r}.stamp_in", f"r{r}",
                               lambda s, r=r: not s["aborted"]
                               and s[f"pc_m{r}"] == 2, a_stamp,
                               frozenset({f"pc_m{r}", "aborted"}),
                               frozenset({f"in{r}", f"pc_m{r}"})),
                    Transition(f"m{r}.read_gb", f"r{r}", g_read, a_read,
                               frozenset({f"pc_m{r}", "aborted",
                                          f"gbseq{g}", f"gb{g}"}),
                               frozenset({f"res{r}", f"pc_m{r}"})),
                ]
            init[f"pc_m{r}"] = 0
            ts.extend(mkm())

    # ---- leader programs --------------------------------------------
    if mutation == "bridge_before_group_fold":
        nonroot = ("bpub_begin", "bpub_end", "fold", "bread", "fanout")
    elif mutation == "fanout_before_bridge":
        nonroot = ("fold", "bpub_begin", "bpub_end", "fanout", "bread")
    else:
        nonroot = ("fold", "bpub_begin", "bpub_end", "bread", "fanout")
    rootprog = ("fold", "bfold", "btotal", "fanout")

    for g in range(groups):
        r = g * k
        prog = rootprog if g == 0 else nonroot
        for i, stp in enumerate(prog):
            def mk(g=g, r=r, i=i, stp=stp):
                pl, acc = f"pl{g}", f"acc{g}"

                if stp == "fold":
                    stamps = [f"in{g * k + j}" for j in range(1, k)]
                    pays = [f"pay{g * k + j}" for j in range(1, k)]

                    def guard(s):
                        return running(s, g) and s[pl] == i \
                            and all(s[m] >= 1 for m in stamps)

                    def apply(s):
                        a = s[acc]
                        torn = a == TORN
                        for m in pays:
                            if s[m] == TORN or torn:
                                torn = True
                            else:
                                a = a | s[m]
                        s[acc] = TORN if torn else a
                        s[pl] = i + 1
                        return s

                    return Transition(f"L{g}.fold", f"r{r}", guard,
                                      apply,
                                      frozenset({pl, f"lalive{g}",
                                                 "aborted"}
                                                | set(stamps)
                                                | set(pays)),
                                      frozenset({acc, pl}))

                if stp == "bpub_begin":
                    def guard(s):
                        return running(s, g) and s[pl] == i

                    def apply(s):
                        s[f"bl{g}"] = TORN
                        s[pl] = i + 1
                        return s

                    return Transition(f"L{g}.bpub_begin", f"r{r}",
                                      guard, apply,
                                      frozenset({pl, f"lalive{g}",
                                                 "aborted"}),
                                      frozenset({f"bl{g}", pl}))

                if stp == "bpub_end":
                    def guard(s):
                        return running(s, g) and s[pl] == i

                    def apply(s):
                        s[f"bl{g}"] = s[acc]
                        s[f"blin{g}"] = 1        # release stamp
                        s[pl] = i + 1
                        return s

                    return Transition(f"L{g}.bpub_end", f"r{r}", guard,
                                      apply,
                                      frozenset({pl, acc, f"lalive{g}",
                                                 "aborted"}),
                                      frozenset({f"bl{g}", f"blin{g}",
                                                 pl}))

                if stp == "bread":
                    def guard(s):
                        return running(s, g) and s[pl] == i \
                            and s["bseq"] >= 1

                    def apply(s):
                        s[acc] = s["bpay"]
                        s[pl] = i + 1
                        return s

                    return Transition(f"L{g}.bread", f"r{r}", guard,
                                      apply,
                                      frozenset({pl, "bseq", "bpay",
                                                 f"lalive{g}",
                                                 "aborted"}),
                                      frozenset({acc, pl}))

                if stp == "bfold":
                    lins = [f"blin{j}" for j in range(1, groups)]
                    lslots = [f"bl{j}" for j in range(1, groups)]

                    def guard(s):
                        return running(s, g) and s[pl] == i \
                            and all(s[x] >= 1 for x in lins)

                    def apply(s):
                        a = s[acc]
                        torn = a == TORN
                        for x in lslots:
                            if s[x] == TORN or torn:
                                torn = True
                            elif s[x]:
                                a = a | s[x]
                        s[acc] = TORN if torn else a
                        s[pl] = i + 1
                        return s

                    return Transition("L0.bfold", f"r{r}", guard, apply,
                                      frozenset({pl, acc, f"lalive{g}",
                                                 "aborted"}
                                                | set(lins)
                                                | set(lslots)),
                                      frozenset({acc, pl}))

                if stp == "btotal":
                    def guard(s):
                        return running(s, g) and s[pl] == i

                    def apply(s):
                        s["bpay"] = s[acc]
                        s["bseq"] = 1            # release publish
                        s[pl] = i + 1
                        return s

                    return Transition("L0.btotal", f"r{r}", guard,
                                      apply,
                                      frozenset({pl, acc, f"lalive{g}",
                                                 "aborted"}),
                                      frozenset({"bpay", "bseq", pl}))

                # fanout
                def guard(s):
                    return running(s, g) and s[pl] == i

                def apply(s):
                    s[f"gb{g}"] = s[acc]
                    s[f"gbseq{g}"] = 1           # release stamp
                    s[f"res{r}"] = s[acc]
                    s[pl] = i + 1
                    return s

                return Transition(f"L{g}.fanout", f"r{r}", guard, apply,
                                  frozenset({pl, acc, f"lalive{g}",
                                             "aborted"}),
                                  frozenset({f"gb{g}", f"gbseq{g}",
                                             f"res{r}", pl}))
            ts.append(mk())

    # ---- node-leader-crash probe ------------------------------------
    if crash:
        vr = gv * k

        def g_die(s):
            # die before or mid bridge-publish: the lane slot is
            # empty-stale or TORN, its in-stamp never lands
            return s[f"lalive{gv}"] and not s["aborted"] \
                and s[f"pl{gv}"] in (1, 2)

        def a_die(s):
            s[f"lalive{gv}"] = 0
            return s

        def g_abort(s):
            # the root's lane timeout fires on the dead leader
            return s["lalive0"] and not s[f"lalive{gv}"] \
                and not s["aborted"]

        def a_abort(s):
            s["aborted"] = 1
            if mutation != "leader_crash_no_poison":
                s["poison"] = 1                  # MUTANT skips this
            return s

        def g_probe(s):
            # the next collective on the comm hits the cached split
            return s["aborted"] and s["reuse_res"] is None

        def a_probe(s):
            if s["poison"]:
                s["reuse_res"] = "degraded"      # falls back to sched
            else:
                torn = any(s[f"bl{g}"] == TORN for g in range(groups))
                s["reuse_res"] = TORN if torn else "folded"
            return s

        ts.extend([
            Transition("V.die", f"r{vr}", g_die, a_die,
                       frozenset({f"pl{gv}", f"lalive{gv}", "aborted"}),
                       frozenset({f"lalive{gv}"})),
            Transition("L0.abort_poison", "r0", g_abort, a_abort,
                       frozenset({"lalive0", f"lalive{gv}", "aborted"}),
                       frozenset({"aborted", "poison"})),
            Transition("net2.reenter_probe", "reenter", g_probe,
                       a_probe,
                       frozenset({"aborted", "poison", "reuse_res"}
                                 | {f"bl{g}" for g in range(groups)}),
                       frozenset({"reuse_res"})),
        ])

    # ---- invariants --------------------------------------------------
    def inv_torn(s):
        for r in range(n):
            if s[f"res{r}"] == TORN:
                return f"rank {r} delivered a TORN payload"
        if s["reuse_res"] == TORN:
            return ("net2 re-entry folded the dead leader's torn "
                    "bridge lane slot")
        return None

    def inv_agree(s):
        for r in range(n):
            v = s[f"res{r}"]
            if v is not None and v != TORN and v != _full(n, 1):
                return (f"rank {r} delivered {sorted(v)} != the full "
                        "contribution set")
        return None

    def inv_poison(s):
        if s["aborted"] and not s["poison"]:
            return ("net2 wave aborted on a dead node leader but the "
                    "split state is not poisoned — the next collective "
                    "re-enters instead of degrading to sched")
        return None

    def final(s):
        if s["aborted"]:
            return s["reuse_res"] is not None
        return all(s[f"res{r}"] is not None for r in range(n))

    invs = [("no-torn-read-delivered", inv_torn),
            ("agreement", inv_agree)]
    if crash:
        invs.append(("poison-sticky", inv_poison))
    return Model(f"flat2-net2(g={groups},k={k},crash={crash},"
                 f"mut={mutation})", init, ts, invs, final)

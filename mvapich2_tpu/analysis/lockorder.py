"""Runtime lock-order detector (MV2T_LOCKCHECK=1).

The static ``locks`` pass proves guarded state is touched under its
lock; this module catches the failure mode statics can't — two locks
taken in OPPOSITE orders on different code paths (the AB/BA deadlock)
and locks held INTO a blocking progress wait (the handler-waits-on-
traffic-only-it-can-progress hang of PAPER.md §L3).

Mechanism: lock creation sites wrap their lock with ``tracked(lock,
name)``. When MV2T_LOCKCHECK is off this returns the raw lock — ZERO
overhead, same discipline as the trace recorder's one-attribute check.
When on, a ``TrackedLock`` proxy records every successful acquisition
into a per-thread held stack and a per-process acquisition-order graph:

  * edge a->b = "b acquired while a held", deduplicated, with the
    source site (file:line) of BOTH acquisitions;
  * each NEW edge runs a DFS; a path b ~> a closes a cycle = potential
    deadlock. One report per distinct lock set (a hung job must not
    emit one report per iteration), counted in the
    ``lockcheck_cycles`` pvar and written to the mlog stream — the
    same dump path the stall watchdog uses; ``watchdog.build_report``
    appends the monitor's summary so a stall diagnostic carries the
    lock-order evidence automatically.
  * ``check_wait`` (called from ProgressEngine.progress_wait behind a
    single attribute check) reports a thread entering the blocking
    progress wait while holding tracked locks.

Failed try-acquires record nothing (a failed nonblocking probe is
deadlock-safe); reentrant RLock acquisitions add no self-edges.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..utils.mlog import get_logger

log = get_logger("lockcheck")


def _site(depth: int = 2) -> str:
    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except ValueError:  # pragma: no cover
        return "<unknown>"


class LockOrderMonitor:
    """Per-process acquisition-order graph + per-thread held stacks."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (a, b) -> (site a was held from, site b was acquired at)
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._adj: Dict[str, Set[str]] = {}
        self._cycle_keys: Set[frozenset] = set()
        self.cycle_reports: List[str] = []
        self.wait_reports: List[str] = []
        self._wait_threads: Set[int] = set()
        from .. import mpit
        self._pv_edges = mpit.pvar("lockcheck_edges")
        self._pv_cycles = mpit.pvar("lockcheck_cycles")

    # -- held stack -------------------------------------------------------
    def _stack(self) -> List[Tuple[str, str]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquired(self, name: str, site: str) -> None:
        st = self._stack()
        new_edges = []
        with self._mu:
            for held, held_site in st:
                if held == name:
                    continue          # reentrant RLock: no self-edge
                key = (held, name)
                if key not in self._edges:
                    self._edges[key] = (held_site, site)
                    self._adj.setdefault(held, set()).add(name)
                    self._pv_edges.inc()
                    new_edges.append(key)
            for a, b in new_edges:
                self._check_cycle(a, b)
        st.append((name, site))

    def on_released(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                del st[i]
                return

    def held_locks(self) -> List[Tuple[str, str]]:
        return list(self._stack())

    # -- cycle detection (self._mu held) ----------------------------------
    def _check_cycle(self, a: str, b: str) -> None:
        """New edge a->b: a path b ~> a closes a cycle."""
        path = self._find_path(b, a)
        if path is None:
            return
        cycle = [(a, b)] + list(zip(path, path[1:]))
        key = frozenset(n for e in cycle for n in e)
        if key in self._cycle_keys:
            return
        self._cycle_keys.add(key)
        lines = ["# lock-order: potential deadlock cycle "
                 f"({' -> '.join([a, b] + path[1:])})"]
        for x, y in cycle:
            xs, ys = self._edges[(x, y)]
            lines.append(f"  {x} (held from {xs}) -> {y} (acquired at {ys})")
        report = "\n".join(lines)
        self.cycle_reports.append(report)
        self._pv_cycles.inc()
        log.warn("%s", report)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- progress-wait discipline ----------------------------------------
    def check_wait(self, rank: int) -> None:
        """Called on entry to the blocking progress wait: holding any
        tracked lock here risks the handler-deadlock shape. One report
        per thread (the wait is re-entered every blocking MPI call)."""
        st = self._stack()
        if not st:
            return
        tid = threading.get_ident()
        with self._mu:
            if tid in self._wait_threads:
                return
            self._wait_threads.add(tid)
            report = (f"# lock-order: rank {rank} entered progress_wait "
                      f"holding {len(st)} tracked lock(s): "
                      + ", ".join(f"{n} (from {s})" for n, s in st))
            self.wait_reports.append(report)
        log.warn("%s", report)

    # -- dump-path integration -------------------------------------------
    def report(self) -> str:
        """Summary block appended to the stall watchdog's diagnostic."""
        with self._mu:
            lines = [f"## lock-order monitor: {len(self._edges)} edge(s), "
                     f"{len(self.cycle_reports)} cycle(s), "
                     f"{len(self.wait_reports)} held-across-wait "
                     "violation(s)"]
            lines.extend(self.cycle_reports)
            lines.extend(self.wait_reports)
        return "\n".join(lines)


class TrackedLock:
    """Order-recording proxy over a Lock/RLock (lockcheck-on only)."""

    __slots__ = ("_lock", "name", "_mon")

    def __init__(self, lock, name: str, mon: LockOrderMonitor):
        self._lock = lock
        self.name = name
        self._mon = mon

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._mon.on_acquired(self.name, _site())
        return ok

    def release(self) -> None:
        self._lock.release()
        self._mon.on_released(self.name)

    def __enter__(self):
        ok = self._lock.acquire()
        if ok:
            self._mon.on_acquired(self.name, _site())
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __repr__(self):
        return f"TrackedLock({self.name}, {self._lock!r})"


# ---------------------------------------------------------------------------
# process-global monitor (positive result cached; off re-checks the cvar
# so in-process test universes can toggle it between runs)
# ---------------------------------------------------------------------------

_monitor: Optional[LockOrderMonitor] = None
_mk_lock = threading.Lock()


def get_monitor() -> Optional[LockOrderMonitor]:
    global _monitor
    if _monitor is not None:
        return _monitor
    from .. import mpit  # noqa: F401  (declares the LOCKCHECK cvar)
    from ..utils.config import get_config
    if not get_config().get("LOCKCHECK", False):
        return None
    with _mk_lock:
        if _monitor is None:
            _monitor = LockOrderMonitor()
    return _monitor


def tracked(lock, name: str):
    """Wrap ``lock`` for order tracking iff MV2T_LOCKCHECK is on;
    returns the raw lock otherwise (zero overhead off — the lock
    creation site is the only gate)."""
    mon = get_monitor()
    if mon is None:
        return lock
    return TrackedLock(lock, name, mon)


def configure(engine) -> None:
    """Attach (or detach) the monitor on ``engine`` — called from
    Universe.initialize after the config reload, mirroring
    watchdog.configure, so progress_wait pays one attribute check."""
    engine._lockcheck = get_monitor()

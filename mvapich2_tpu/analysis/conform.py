"""Trace conformance checker (bin/mv2tconform) — runtime verification
of live runs against the protocol models.

Every protocol surface is model-checked offline (analysis/model/*: 50+
seeded mutations caught) and every layer emits traces (recorder ring,
ntrace C-plane ring, metrics rows, Perfetto merges) — this module is
the bridge: it replays a *real run's* events through per-protocol
conformance automata whose invariant names are the model checkers'
invariant names, so "the job ran" becomes "the job ran AND obeyed the
invariants the models prove". Strictly offline/post-mortem: the checker
reads merged dumps, Finalize trace files, or (read-only) ntrace/metrics
segments — it never touches a live job's hot path.

Inputs (auto-detected by ``main``):

  * a merged Perfetto JSON written by ``bin/mpitrace`` (pid = rank,
    cat = layer, ``metrics:*`` counter tracks);
  * a trace dump directory / individual ``trace-r*.json`` Finalize
    dumps (recorder snapshot schema, ntrace + metrics rows embedded);
  * a raw ntrace segment (``<stem>.ntrace``, read via
    trace.native.read_ring — works on unlinked-but-open rings);
  * a raw metrics segment (``<stem>.metrics``).

Automata and their invariants (names shared with analysis/model/*):

  flat-wave   fanin-before-fold-before-fanout, mseq-monotone,
              poison-sticky, proc-failed-poison (the failure class: a
              poisoned run is never silently certified clean)
  doorbell    no-lost-wake
  lease       detect-within-deadline (2x MV2T_PEER_TIMEOUT),
              no-false-positive (an expired peer that demonstrably
              departed cleanly — DEPARTED is never a failure)
  nbc-dag     nbc-deposit-before-poll, nbc-issue-before-complete,
              nbc-drained-at-finalize, no-slot-collision (segment
              POLLs launch in slot-schedule order) — event grammar
              imported from analysis/model/nbc.TRACE_EVENTS
  device-lane span-balance over dev_* dispatch spans, ici_* instant
              grammar
  rma-epoch   lock-exclusive, flush-completes-all-outstanding (every
              op dispatch instant lands inside a flush/fence
              completion wave)
  metrics     counter-monotone (fp_* mirror + sampled pvars, incl. the
              daemon claim/epoch counters), gauge-nonnegative
  spans       span-balance + event grammar for the mpi / protocol /
              channel / progress layers

Violations are ``Violation(invariant, message, state, trace)`` — the
model checkers' counterexample format — where ``trace`` is the
replayable event window that produced the violation: feed it back
through ``replay()`` and the same invariant trips.

Tail mode (``check_tail``) runs only the truncation-safe invariants
over a trace-tail window — the stall watchdog calls it on a hang and
names the first violated invariant in its report. Ranks whose ring
wrapped (events == capacity in the dump) get the same relaxation in
full mode: order checks that need the dropped prefix are skipped
rather than mis-fired.

Exit codes (the conformance-stamp contract for perf/bench sessions):
0 = clean, 1 = violations found, 2 = usage error, 3 = unreadable
input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from .model import nbc as _nbc_model

_HIST_CAP = 64          # replay-window cap per automaton scope


@dataclass(frozen=True)
class Event:
    ts: float
    rank: int
    layer: str
    name: str
    ph: str
    args: Optional[Dict[str, Any]] = None


@dataclass
class Violation:
    """Same shape as model.explorer.Violation, plus the automaton that
    tripped — ``trace`` is the replayable counterexample window."""
    invariant: str
    message: str
    state: Dict[str, Any]
    trace: List[str]
    automaton: str = ""
    rank: int = -1


def fmt_event(ev: Event) -> str:
    args = json.dumps(ev.args, sort_keys=True) if ev.args else "{}"
    return (f"{ev.ts:.6f} r{ev.rank} [{ev.layer}] {ev.name} "
            f"{ev.ph} {args}")


def parse_event(line: str) -> Event:
    ts, rank, layer, name, ph, args = line.split(" ", 5)
    return Event(float(ts), int(rank[1:]), layer[1:-1], name, ph,
                 json.loads(args) or None)


def _match(pattern: str, name: str) -> bool:
    if pattern == "*":
        return True
    if pattern.endswith("*"):
        return name.startswith(pattern[:-1])
    if pattern.startswith("*"):
        return name.endswith(pattern[1:])
    return name == pattern


# ---------------------------------------------------------------------------
# automata
# ---------------------------------------------------------------------------

class Automaton:
    """One protocol surface's conformance machine. ``grammar`` is the
    (layer, name-pattern) event vocabulary — the lint event-coverage
    doctor checks every emitted tracer event lands in some automaton's
    grammar. ``tail_safe`` names the invariants that stay sound on a
    truncated window (the watchdog's trace tail / a wrapped ring)."""

    name: str = ""
    grammar: Tuple[Tuple[str, str], ...] = ()
    invariants: Tuple[str, ...] = ()
    tail_safe: FrozenSet[str] = frozenset()

    def __init__(self, tail: bool = False,
                 options: Optional[Dict[str, Any]] = None):
        self.tail = tail
        self.opt = options or {}
        self.truncated: FrozenSet[int] = frozenset(
            self.opt.get("truncated", ()))
        self.ranks: Optional[FrozenSet[int]] = None   # set before finish
        self.violations: List[Violation] = []
        self._hist: Dict[Any, List[Event]] = {}

    # -- driver interface -------------------------------------------------
    def matches(self, ev: Event) -> bool:
        return any(ev.layer == layer and _match(pat, ev.name)
                   for layer, pat in self.grammar)

    def feed(self, ev: Event) -> None:
        raise NotImplementedError

    def finish(self) -> None:
        pass

    # -- helpers ----------------------------------------------------------
    def _strict(self, rank: int) -> bool:
        """Order checks needing the (possibly dropped) prefix."""
        return not self.tail and rank not in self.truncated

    def _note(self, scope: Any, ev: Event) -> None:
        h = self._hist.setdefault(scope, [])
        h.append(ev)
        if len(h) > _HIST_CAP:
            del h[0]

    def _viol(self, invariant: str, message: str, scope: Any = None,
              state: Optional[Dict[str, Any]] = None,
              rank: int = -1) -> None:
        if self.tail and invariant not in self.tail_safe:
            return
        trace = [fmt_event(e) for e in self._hist.get(scope, [])]
        self.violations.append(Violation(
            invariant, message, dict(state or {}), trace,
            automaton=self.name, rank=rank))


class FlatWaveAutomaton(Automaton):
    """The seqlock flat/flat2/mcast collective waves (cplane.cpp) —
    shares poison-sticky with model.seqlock/flat2; the wave order and
    mseq checks are the trace projections of their numbering proofs."""

    name = "flat-wave"
    grammar = (("cplane", "flat_fanin"), ("cplane", "flat_fold"),
               ("cplane", "flat_fanout"), ("cplane", "flat_poison"),
               ("cplane", "flat2_fold"), ("cplane", "flat2_xchg"),
               ("cplane", "flat2_fanout"), ("cplane", "mcast_pub"),
               ("cplane", "mcast_cons"), ("cplane", "coll_dispatch"),
               ("cplane", "net2_*"))
    invariants = ("fanin-before-fold-before-fanout", "mseq-monotone",
                  "poison-sticky", "proc-failed-poison")
    tail_safe = frozenset({"mseq-monotone", "poison-sticky",
                           "proc-failed-poison"})

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._fanin: Dict[Tuple[int, int], set] = {}    # (rank,ctx)->seqs
        self._mseq: Dict[Tuple[int, int, str], int] = {}
        self._ctxs: Dict[int, set] = {}                 # rank -> ctxs seen
        self._poisoned: Dict[int, set] = {}             # rank -> ctx snap

    def feed(self, ev: Event) -> None:
        r = ev.rank
        a1 = (ev.args or {}).get("a1", 0)
        a2 = (ev.args or {}).get("a2", 0)
        if ev.name == "flat_poison":
            # a1 is the poison rc, not a ctx: poison seals every ctx
            # this rank had active — re-key after shrink mints fresh
            # ctxs, which legitimately keep running
            self._note(("poison", r), ev)
            # the poison event also enters every live ctx window on
            # this rank, so a poison-sticky counterexample replays
            for c in self._ctxs.get(r, ()):
                self._note((r, c), ev)
            self._poisoned.setdefault(r, set()).update(
                self._ctxs.get(r, ()))
            self._viol("proc-failed-poison",
                       f"rank {r} poisoned its flat region (rc={a1}) — "
                       "a PROC_FAILED unwind ran; this trace is a "
                       "failure run, not a clean one",
                       scope=("poison", r),
                       state={"rank": r, "rc": a1}, rank=r)
            return
        if ev.name == "coll_dispatch":
            return                       # tier-choice instant, no order
        if ev.name.startswith("net2_"):
            # net2 tier progress instants (coll/netcoll.py: group fold /
            # leader bridge / fan-out) — the sub-plane collectives they
            # drive emit their own flat/flat2 events into this
            # automaton; the net2 markers themselves carry group
            # counts, not ctx/seq numbering
            return
        ctx = a1
        self._ctxs.setdefault(r, set()).add(ctx)
        scope = (r, ctx)
        self._note(scope, ev)
        if ctx in self._poisoned.get(r, ()):
            self._viol("poison-sticky",
                       f"rank {r}: {ev.name} on ctx {ctx} after this "
                       "rank poisoned it — poison must be sticky "
                       "until re-key", scope=scope,
                       state={"rank": r, "ctx": ctx, "event": ev.name},
                       rank=r)
        if ev.name == "flat_fanin":
            self._fanin.setdefault(scope, set()).add(a2)
        elif ev.name in ("flat_fold", "flat_fanout"):
            if self._strict(r) and a2 not in self._fanin.get(scope, ()):
                self._viol("fanin-before-fold-before-fanout",
                           f"rank {r}: {ev.name} seq {a2} on ctx {ctx} "
                           "without this rank's fanin for that wave",
                           scope=scope,
                           state={"rank": r, "ctx": ctx, "seq": a2},
                           rank=r)
        if ev.name in ("flat_fanin", "flat2_fold", "flat2_xchg",
                       "flat2_fanout", "mcast_pub", "mcast_cons"):
            mscope = (r, ctx, ev.name)
            last = self._mseq.get(mscope)
            if last is not None and a2 < last:
                self._viol("mseq-monotone",
                           f"rank {r}: {ev.name} seq went {last} -> "
                           f"{a2} on ctx {ctx} — wave numbering must "
                           "be monotone per region", scope=scope,
                           state={"rank": r, "ctx": ctx,
                                  "seq": a2, "prev": last}, rank=r)
            self._mseq[mscope] = max(a2, last or 0)


class DoorbellAutomaton(Automaton):
    """The adaptive wait/wake doorbell — model.doorbell's
    no-lost-wake, projected onto the merged timeline: a wake implies
    somebody rang."""

    name = "doorbell"
    grammar = (("cplane", "bell_ring"), ("cplane", "bell_wake"),
               ("cplane", "spin_bell"))
    invariants = ("no-lost-wake",)
    tail_safe = frozenset()

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._rings = 0

    def feed(self, ev: Event) -> None:
        self._note("bell", ev)
        if ev.name == "bell_ring":
            self._rings += 1
        elif ev.name == "bell_wake":
            if self._strict(ev.rank) and self._rings == 0:
                self._viol("no-lost-wake",
                           f"rank {ev.rank} woke from the doorbell but "
                           "no ring was ever published before it",
                           scope="bell",
                           state={"rank": ev.rank, "rings": 0},
                           rank=ev.rank)


class LeaseAutomaton(Automaton):
    """The liveness-lease failure detector — model.lease's deadline and
    DEPARTED-never-failed invariants, checked from lease_expire's
    staleness argument and the dump set."""

    name = "lease"
    grammar = (("cplane", "lease_scan"), ("cplane", "lease_expire"))
    invariants = ("detect-within-deadline", "no-false-positive")
    tail_safe = frozenset({"detect-within-deadline"})

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._expired: List[Tuple[int, int, Event]] = []

    def feed(self, ev: Event) -> None:
        self._note("lease", ev)
        if ev.name != "lease_expire":
            return
        peer = (ev.args or {}).get("a1", -1)
        stale_us = (ev.args or {}).get("a2", 0)
        self._expired.append((ev.rank, peer, ev))
        timeout = float(self.opt.get("peer_timeout", 0.0))
        if timeout > 0 and stale_us > 2 * timeout * 1e6:
            self._viol("detect-within-deadline",
                       f"rank {ev.rank} declared peer {peer} dead at "
                       f"staleness {stale_us / 1e6:.3f}s — over the "
                       f"2x deadline of the {timeout:.1f}s lease "
                       "timeout", scope="lease",
                       state={"rank": ev.rank, "peer": peer,
                              "staleness_us": stale_us,
                              "timeout_s": timeout}, rank=ev.rank)

    def finish(self) -> None:
        if self.tail or self.ranks is None:
            return
        # a peer that wrote a Finalize dump departed cleanly — the
        # scan skips DEPARTED stamps, so expiring it is a false
        # positive (the lease model's clean-departure invariant)
        for rank, peer, ev in self._expired:
            if peer in self.ranks:
                self._viol("no-false-positive",
                           f"rank {rank} declared peer {peer} dead, "
                           "but that peer reached Finalize and dumped "
                           "a trace — DEPARTED is never a failure",
                           scope="lease",
                           state={"rank": rank, "peer": peer},
                           rank=rank)


class NbcAutomaton(Automaton):
    """The NBC DAG scheduler — grammar imported from
    model.nbc.TRACE_EVENTS so this automaton and the exhaustive model
    can never drift apart; invariant names are the model's."""

    name = "nbc-dag"
    grammar = tuple((layer, n) for layer, names
                    in sorted(_nbc_model.TRACE_EVENTS.items())
                    for n in names)
    invariants = ("nbc-deposit-before-poll", "nbc-issue-before-complete",
                  "nbc-drained-at-finalize", "no-slot-collision")
    tail_safe = frozenset({"nbc-deposit-before-poll",
                           "nbc-issue-before-complete",
                           "no-slot-collision"})

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        # (rank, sched) -> state; tracking starts at sched_start, so
        # tail windows / wrapped rings simply never adopt half-seen
        # schedules instead of mis-firing on them
        self._sched: Dict[Tuple[int, Any], Dict[str, Any]] = {}
        self._dev: Dict[Tuple[int, str, int], int] = {}   # seg inflight

    def feed(self, ev: Event) -> None:
        r = ev.rank
        args = ev.args or {}
        if ev.layer == "device":
            key = (r, args.get("coll", "?"), args.get("seg", -1))
            scope = ("dev", r, args.get("coll", "?"))
            self._note(scope, ev)
            if ev.name == "nbc_dev_issue":
                self._dev[key] = self._dev.get(key, 0) + 1
            elif ev.name == "nbc_dev_complete":
                left = self._dev.get(key, 0)
                if left <= 0 and self._strict(r):
                    self._viol("nbc-issue-before-complete",
                               f"rank {r}: nbc_dev_complete for "
                               f"{key[1]} seg {key[2]} with no "
                               "outstanding nbc_dev_issue",
                               scope=scope, state={"rank": r,
                                                   "coll": key[1],
                                                   "seg": key[2]},
                               rank=r)
                else:
                    self._dev[key] = left - 1
            return
        sid = args.get("sched")
        scope = (r, sid)
        self._note(scope, ev)
        st = self._sched.get(scope)
        if ev.name == "sched_start":
            self._sched[scope] = {
                "kind": str(args.get("kind", "")),
                "vertices": args.get("vertices", 0),
                "issued": {}, "call_done": False, "done": False,
                "last_poll_vid": None, "start": ev,
            }
            return
        if st is None:
            return                      # start outside the window
        if st["done"]:
            self._viol("nbc-drained-at-finalize",
                       f"rank {r}: {ev.name} on schedule {sid} after "
                       "its sched_complete — completed schedules must "
                       "be inert", scope=scope,
                       state={"rank": r, "sched": sid,
                              "event": ev.name}, rank=r)
            return
        if ev.name == "vertex_issue":
            vid, kind = args.get("vid"), args.get("kind")
            st["issued"][vid] = kind
            if kind == _nbc_model.POLL and st["kind"].startswith("dev-i"):
                if not st["call_done"]:
                    self._viol("nbc-deposit-before-poll",
                               f"rank {r}: segment POLL v{vid} of "
                               f"{st['kind']} sched {sid} issued "
                               "before the deposit CALL completed",
                               scope=scope,
                               state={"rank": r, "sched": sid,
                                      "vid": vid}, rank=r)
                lp = st["last_poll_vid"]
                if lp is not None and vid <= lp:
                    self._viol("no-slot-collision",
                               f"rank {r}: {st['kind']} sched {sid} "
                               f"launched POLL v{vid} after v{lp} — "
                               "segments must launch in slot-schedule "
                               "order", scope=scope,
                               state={"rank": r, "sched": sid,
                                      "vid": vid, "prev": lp}, rank=r)
                st["last_poll_vid"] = vid
        elif ev.name == "vertex_complete":
            vid = args.get("vid")
            if vid not in st["issued"]:
                self._viol("nbc-issue-before-complete",
                           f"rank {r}: completion wakeup on v{vid} of "
                           f"schedule {sid}, which was never issued",
                           scope=scope,
                           state={"rank": r, "sched": sid, "vid": vid},
                           rank=r)
            elif st["issued"][vid] == _nbc_model.CALL:
                st["call_done"] = True
        elif ev.name == "sched_complete":
            st["done"] = True

    def finish(self) -> None:
        if self.tail:
            return
        for (r, sid), st in sorted(self._sched.items(),
                                   key=lambda kv: repr(kv[0])):
            if not st["done"] and r not in self.truncated:
                self._viol("nbc-drained-at-finalize",
                           f"rank {r}: schedule {sid} ({st['kind']}) "
                           "started but never completed — "
                           "nbc_scheds_active not drained at Finalize",
                           scope=(r, sid),
                           state={"rank": r, "sched": sid,
                                  "kind": st["kind"]}, rank=r)


class DeviceLaneAutomaton(Automaton):
    """The device dispatch lane: ici_* kernel-entry instants and dev_*
    dispatch spans (coll/device.py + ops/pallas_ici.py)."""

    name = "device-lane"
    grammar = (("device", "ici_*"), ("device", "dev_*"))
    invariants = ("span-balance",)
    tail_safe = frozenset()

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._open: Dict[Tuple[int, str], int] = {}

    def feed(self, ev: Event) -> None:
        if ev.ph not in ("B", "E"):
            return
        key = (ev.rank, ev.name)
        self._note(key, ev)
        if ev.ph == "B":
            self._open[key] = self._open.get(key, 0) + 1
        else:
            n = self._open.get(key, 0)
            if n <= 0 and self._strict(ev.rank):
                self._viol("span-balance",
                           f"rank {ev.rank}: E for {ev.name} with no "
                           "open B span", scope=key,
                           state={"rank": ev.rank, "name": ev.name},
                           rank=ev.rank)
            else:
                self._open[key] = n - 1

    def finish(self) -> None:
        if self.tail:
            return
        for (r, name), n in sorted(self._open.items()):
            if n > 0 and r not in self.truncated:
                self._viol("span-balance",
                           f"rank {r}: {name} span opened {n}x and "
                           "never closed by Finalize", scope=(r, name),
                           state={"rank": r, "name": name, "open": n},
                           rank=r)


class RmaAutomaton(Automaton):
    """The one-sided passive-target epoch grammar (rma/device.py) —
    model.rma's lock-exclusive and flush-completes-all-outstanding:
    every op dispatch instant must land inside a flush/fence
    completion wave, and the lock epoch never double-opens."""

    name = "rma-epoch"
    grammar = (("device", "rma_*"),)
    invariants = ("lock-exclusive", "flush-completes-all-outstanding")
    tail_safe = frozenset({"lock-exclusive"})

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._locked: Dict[int, set] = {}
        self._wave: Dict[int, int] = {}       # open flush/fence spans

    def feed(self, ev: Event) -> None:
        r = ev.rank
        self._note(r, ev)
        args = ev.args or {}
        if ev.name == "rma_lock":
            t = args.get("rank", -1)
            held = self._locked.setdefault(r, set())
            if t in held:
                self._viol("lock-exclusive",
                           f"rank {r}: MPI_Win_lock on target {t} "
                           "while already holding that epoch",
                           scope=r, state={"rank": r, "target": t},
                           rank=r)
            held.add(t)
        elif ev.name == "rma_unlock":
            t = args.get("rank", -1)
            held = self._locked.setdefault(r, set())
            if t not in held:
                if self._strict(r):
                    self._viol("lock-exclusive",
                               f"rank {r}: MPI_Win_unlock on target "
                               f"{t} without an open lock epoch",
                               scope=r, state={"rank": r, "target": t},
                               rank=r)
            else:
                held.discard(t)
        elif ev.name in ("rma_flush", "rma_fence"):
            if ev.ph == "B":
                self._wave[r] = self._wave.get(r, 0) + 1
            elif ev.ph == "E":
                self._wave[r] = max(0, self._wave.get(r, 0) - 1)
        elif ev.name in ("rma_put", "rma_acc", "rma_get"):
            if self._wave.get(r, 0) <= 0 and self._strict(r):
                self._viol("flush-completes-all-outstanding",
                           f"rank {r}: {ev.name} dispatched outside "
                           "any flush/fence completion wave — ops "
                           "must complete inside the wave that "
                           "accounts for them", scope=r,
                           state={"rank": r, "op": ev.name}, rank=r)


class MetricsAutomaton(Automaton):
    """The sampled metrics rows (fp_* fast-path mirror + python pvars,
    incl. the daemon claim/epoch counters): cumulative series must be
    monotone per (rank, slot) — the trace projection of
    model.daemon's epoch-fresh counter discipline — and level gauges
    never go negative."""

    name = "metrics"
    grammar = (("metrics", "*"),)
    invariants = ("counter-monotone", "gauge-nonnegative")
    tail_safe = frozenset({"counter-monotone", "gauge-nonnegative"})
    GAUGES = ("daemon_claims_active",)

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._last: Dict[Tuple[int, str], int] = {}

    def feed(self, ev: Event) -> None:
        val = (ev.args or {}).get("value", 0)
        key = (ev.rank, ev.name)
        self._note(key, ev)
        if ev.name in self.GAUGES:
            if val < 0:
                self._viol("gauge-nonnegative",
                           f"rank {ev.rank}: gauge {ev.name} went "
                           f"negative ({val})", scope=key,
                           state={"rank": ev.rank, "slot": ev.name,
                                  "value": val}, rank=ev.rank)
            return
        last = self._last.get(key)
        if last is not None and val < last:
            self._viol("counter-monotone",
                       f"rank {ev.rank}: counter {ev.name} went "
                       f"{last} -> {val} — cumulative series must be "
                       "monotone within a job epoch", scope=key,
                       state={"rank": ev.rank, "slot": ev.name,
                              "value": val, "prev": last}, rank=ev.rank)
        self._last[key] = max(val, last or 0)


class SpanAutomaton(Automaton):
    """Grammar + span balance for the python-side layers: mpi entry
    interposition spans, protocol instants, channel packet instants,
    progress waits."""

    name = "spans"
    grammar = (("mpi", "*"),
               ("protocol", "eager_send"), ("protocol", "eager_recv"),
               ("protocol", "rndv_rts"), ("protocol", "rndv_rts_recv"),
               ("protocol", "rndv_cts"), ("protocol", "rndv_fin"),
               ("protocol", "rndv_chunk"),
               ("channel", "*_send"), ("channel", "*_recv"),
               ("channel", "dev_coll_fallback"),
               ("progress", "progress_wait"), ("progress", "idle"),
               ("progress", "wake"),
               ("progress", "stall_watchdog_trip"),
               ("cplane", "eager_tx"), ("cplane", "eager_rx"),
               ("cplane", "rndv_tx"), ("cplane", "rndv_rx"))
    invariants = ("span-balance",)
    tail_safe = frozenset()

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._open: Dict[Tuple[int, str, str], int] = {}

    def feed(self, ev: Event) -> None:
        if ev.ph not in ("B", "E"):
            return
        key = (ev.rank, ev.layer, ev.name)
        self._note(key, ev)
        if ev.ph == "B":
            self._open[key] = self._open.get(key, 0) + 1
        else:
            n = self._open.get(key, 0)
            if n <= 0 and self._strict(ev.rank):
                self._viol("span-balance",
                           f"rank {ev.rank}: [{ev.layer}] E for "
                           f"{ev.name} with no open B span", scope=key,
                           state={"rank": ev.rank, "layer": ev.layer,
                                  "name": ev.name}, rank=ev.rank)
            else:
                self._open[key] = n - 1

    def finish(self) -> None:
        if self.tail:
            return
        for (r, layer, name), n in sorted(self._open.items()):
            if n > 0 and r not in self.truncated:
                self._viol("span-balance",
                           f"rank {r}: [{layer}] {name} span opened "
                           f"{n}x and never closed by Finalize",
                           scope=(r, layer, name),
                           state={"rank": r, "layer": layer,
                                  "name": name, "open": n}, rank=r)


AUTOMATA = (FlatWaveAutomaton, DoorbellAutomaton, LeaseAutomaton,
            NbcAutomaton, DeviceLaneAutomaton, RmaAutomaton,
            MetricsAutomaton, SpanAutomaton)


def build_automata(tail: bool = False,
                   options: Optional[Dict[str, Any]] = None
                   ) -> List[Automaton]:
    return [cls(tail=tail, options=options) for cls in AUTOMATA]


def event_grammars() -> Dict[str, Tuple[str, ...]]:
    """layer -> every automaton name-pattern covering it (the lint
    event-coverage doctor's ground truth)."""
    out: Dict[str, List[str]] = {}
    for cls in AUTOMATA:
        for layer, pat in cls.grammar:
            if pat not in out.setdefault(layer, []):
                out[layer].append(pat)
    return {layer: tuple(pats) for layer, pats in out.items()}


def grammar_covers(layer: str, name: str) -> bool:
    """Is an emitted event name (or emitted prefix pattern like
    ``ici_*``) covered by some automaton's grammar?"""
    pats = event_grammars().get(layer, ())
    if name in pats or "*" in pats:
        return True
    for pat in pats:
        if _match(pat, name):
            return True
        # emitted-pattern vs grammar-pattern: an f-string emission like
        # ici_* is covered by an identical (or wider) grammar prefix
        if (name.endswith("*") and pat.endswith("*")
                and name[:-1].startswith(pat[:-1])):
            return True
    return False


# ---------------------------------------------------------------------------
# the checker driver
# ---------------------------------------------------------------------------

def check_events(events: Sequence[Event], tail: bool = False,
                 options: Optional[Dict[str, Any]] = None,
                 ranks: Optional[FrozenSet[int]] = None
                 ) -> List[Violation]:
    """Replay ``events`` (sorted by ts) through every automaton;
    returns the combined violation list. ``ranks`` is the set of ranks
    that produced Finalize dumps (None = unknown)."""
    autos = build_automata(tail=tail, options=options)
    unknown: Dict[Tuple[str, str], int] = {}
    for ev in sorted(events, key=lambda e: e.ts):
        matched = False
        for a in autos:
            if a.matches(ev):
                a.feed(ev)
                matched = True
        if not matched:
            unknown[(ev.layer, ev.name)] = \
                unknown.get((ev.layer, ev.name), 0) + 1
    out: List[Violation] = []
    for a in autos:
        a.ranks = ranks
        a.finish()
        out.extend(a.violations)
    if unknown and not tail:
        pairs = ", ".join(f"[{l}] {n} (x{c})"
                          for (l, n), c in sorted(unknown.items()))
        out.append(Violation(
            "grammar-coverage",
            f"events outside every automaton's grammar: {pairs} — the "
            "emitter and the conformance grammars have drifted (run "
            "mv2tlint's event-coverage doctor)",
            {"unknown": sorted(f"{l}:{n}" for l, n in unknown)}, [],
            automaton="driver"))
    return out


def check_tail(rank: int, tail_events: Sequence[Sequence[Any]],
               options: Optional[Dict[str, Any]] = None
               ) -> List[Violation]:
    """The stall watchdog's entry point: recorder-format tail rows
    ``(ts, layer, name, ph, args)`` of ONE rank, checked with only the
    truncation-safe invariants armed."""
    evs = [Event(float(ts), rank, layer, name, ph, args or None)
           for ts, layer, name, ph, args in tail_events]
    return check_events(evs, tail=True, options=options)


def replay(v: Violation,
           options: Optional[Dict[str, Any]] = None) -> List[Violation]:
    """Feed a violation's counterexample window back through fresh
    automata — the replayability contract: the same invariant trips."""
    evs = [parse_event(line) for line in v.trace]
    return [w for w in check_events(evs, tail=False, options=options)
            if w.invariant == v.invariant]


# ---------------------------------------------------------------------------
# loaders
# ---------------------------------------------------------------------------

def _dump_to_events(d: Dict[str, Any]) -> Tuple[List[Event], bool]:
    rank = int(d.get("rank", 0))
    evs = [Event(float(ts), rank, layer, name, ph, args or None)
           for ts, layer, name, ph, args in d.get("events", ())]
    for ts, vals in d.get("metrics") or ():
        for slot, val in vals.items():
            evs.append(Event(float(ts), rank, "metrics", slot, "C",
                             {"value": val}))
    cap = d.get("capacity") or 0
    truncated = bool(cap) and len(d.get("events", ())) >= cap
    return evs, truncated


def load_dumps(paths: Sequence[str]
               ) -> Tuple[List[Event], FrozenSet[int], FrozenSet[int]]:
    """trace-r*.json Finalize dumps -> (events, ranks, truncated)."""
    events: List[Event] = []
    ranks, truncated = set(), set()
    for path in paths:
        with open(path) as f:
            d = json.load(f)
        evs, trunc = _dump_to_events(d)
        events.extend(evs)
        ranks.add(int(d.get("rank", 0)))
        if trunc:
            truncated.add(int(d.get("rank", 0)))
    return events, frozenset(ranks), frozenset(truncated)


def load_perfetto(path: str) -> Tuple[List[Event], FrozenSet[int]]:
    """A merged bin/mpitrace JSON -> (events, ranks). Counter tracks
    (``metrics:*``) become metrics-layer events; metadata is skipped.
    Ring-wrap information does not survive the merge, so order checks
    run strict — feed the dump directory instead for wrapped rings."""
    with open(path) as f:
        merged = json.load(f)
    rows = merged.get("traceEvents")
    if rows is None:
        raise ValueError(f"{path}: not a merged trace (no traceEvents)")
    events: List[Event] = []
    ranks = set()
    for row in rows:
        ph = row.get("ph", "")
        if ph == "M":
            continue
        rank = int(row.get("pid", 0))
        ranks.add(rank)
        name = row.get("name", "")
        ts = float(row.get("ts", 0.0)) / 1e6
        if ph == "C":
            slot = name[len("metrics:"):] if name.startswith("metrics:") \
                else name
            events.append(Event(ts, rank, "metrics", slot, "C",
                                {"value": (row.get("args") or {}
                                           ).get("value", 0)}))
            continue
        events.append(Event(ts, rank, row.get("cat", "?"), name, ph,
                            row.get("args") or None))
    return events, frozenset(ranks)


def load_ntrace(path: str) -> List[Event]:
    """A raw ntrace segment (read-only, works unlinked-but-open):
    every ring's events as cplane instants, rank = ring index."""
    from ..trace import native
    events: List[Event] = []
    for i in range(native._rank_count(path)):
        for ts_us, ev, a1, a2 in native.read_ring(path, i):
            events.append(Event(ts_us / 1e6, i, "cplane",
                                native.event_name(ev), "i",
                                {"a1": a1, "a2": a2}))
    return events


def load_metrics_segment(path: str) -> List[Event]:
    """A raw metrics segment: every rank's sample rows as metrics-layer
    counter events."""
    from ..metrics import ring as mring
    events: List[Event] = []
    names = mring.slot_names()
    for i, blob in mring.read_all(path).items():
        for ts_us, vals in blob.get("rows", ()):
            for nm, v in zip(names, vals):
                if nm and v:
                    events.append(Event(ts_us / 1e6, i, "metrics", nm,
                                        "C", {"value": v}))
    return events


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _default_peer_timeout() -> float:
    try:
        from ..utils.config import get_config
        return float(get_config().get("PEER_TIMEOUT", 10.0) or 0.0)
    except Exception:
        return 10.0


def render(violations: List[Violation], nevents: int,
           verbose: bool = False) -> str:
    lines = []
    for v in violations:
        where = f" (rank {v.rank})" if v.rank >= 0 else ""
        lines.append(f"VIOLATION {v.automaton}/{v.invariant}{where}: "
                     f"{v.message}")
        if v.state:
            lines.append(f"  state: {json.dumps(v.state, sort_keys=True)}")
        if v.trace:
            lines.append(f"  counterexample ({len(v.trace)} events):")
            lines.extend(f"    {line}" for line in v.trace)
    nauto = len(AUTOMATA)
    lines.append(f"# mv2tconform: {nevents} events through {nauto} "
                 f"automata, {len(violations)} violation(s)")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mv2tconform",
        description="Replay a run's traces through the protocol "
                    "conformance automata. Exit 0 clean, 1 violations, "
                    "2 usage, 3 unreadable input.")
    ap.add_argument("inputs", nargs="+",
                    help="merged Perfetto JSON, trace dump dir, "
                         "trace-r*.json files, .ntrace or .metrics "
                         "segments (mixable)")
    ap.add_argument("--peer-timeout", type=float, default=None,
                    help="lease timeout seconds for the "
                         "detect-within-deadline check (default: the "
                         "MV2T_PEER_TIMEOUT cvar)")
    ap.add_argument("--tail", action="store_true",
                    help="truncation-safe invariants only (a partial "
                         "window, e.g. a hung job's segments)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable violation list")
    ap.add_argument("-v", "--verbose", action="store_true")
    try:
        opts = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    events: List[Event] = []
    ranks: set = set()
    truncated: set = set()
    ranks_known = False
    try:
        for inp in opts.inputs:
            if os.path.isdir(inp):
                paths = sorted(glob.glob(
                    os.path.join(inp, "trace-r*.json")))
                if not paths:
                    print(f"mv2tconform: no trace-r*.json under {inp}",
                          file=sys.stderr)
                    return 3
                evs, rs, tr = load_dumps(paths)
                events.extend(evs)
                ranks.update(rs)
                truncated.update(tr)
                ranks_known = True
            elif inp.endswith(".ntrace"):
                events.extend(load_ntrace(inp))
            elif inp.endswith(".metrics"):
                events.extend(load_metrics_segment(inp))
            elif inp.endswith(".json"):
                with open(inp) as f:
                    head = f.read(4096)
                if '"traceEvents"' in head:
                    evs, rs = load_perfetto(inp)
                    events.extend(evs)
                    ranks.update(rs)
                    ranks_known = True
                else:
                    evs, rs, tr = load_dumps([inp])
                    events.extend(evs)
                    ranks.update(rs)
                    truncated.update(tr)
                    ranks_known = True
            else:
                print(f"mv2tconform: unrecognized input {inp} (want a "
                      "dir, .json, .ntrace, or .metrics)",
                      file=sys.stderr)
                return 2
    except (OSError, ValueError, KeyError) as e:
        print(f"mv2tconform: cannot read input: {e}", file=sys.stderr)
        return 3

    timeout = opts.peer_timeout
    if timeout is None:
        timeout = _default_peer_timeout()
    violations = check_events(
        events, tail=opts.tail,
        options={"peer_timeout": timeout,
                 "truncated": frozenset(truncated)},
        ranks=frozenset(ranks) if ranks_known else None)
    if opts.as_json:
        print(json.dumps([{
            "automaton": v.automaton, "invariant": v.invariant,
            "rank": v.rank, "message": v.message, "state": v.state,
            "trace": v.trace} for v in violations], indent=2))
    else:
        print(render(violations, len(events), opts.verbose))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""Pass ``locks`` — guarded-by lock discipline.

Attributes registered as guarded-by (via ``# guarded-by: <lock>``
annotations on their initializing assignment, or the seed registry below
for the pre-existing hot structs) may only be touched inside a ``with``
block holding the matching lock. Lock identity is the TERMINAL attribute
name (``mutex`` matches ``self.mutex``, ``eng.mutex``,
``self.engine.mutex``): rank-local state in this codebase is always
guarded by the one lock of that name reachable from the touching scope,
so the cheap syntactic match is exact in practice.

Scope rules:
  * checked: ``self.<attr>`` inside the owning class, and bare module
    globals inside the owning module (cross-object accesses like
    ``nbc.active`` from another module are out of static reach — keep
    such state behind accessor methods).
  * ``__init__`` is exempt (the object is not yet shared).
  * ``# holds: <lock>[, <lock>]`` on a ``def`` line asserts the caller
    contract "runs with these locks held" (e.g. request-completion
    callbacks running under the engine mutex) — the body is checked as
    if the locks were acquired.
  * A ``# guarded-by:`` value may list alternatives with ``|``
    (``_inbox_lock|_inbox_cond`` — a Condition constructed over the
    lock acquires the same mutex).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintPass, SourceModule, terminal_name

# Seed registry for hot structs that predate the annotation syntax:
# (relpath suffix, class name or None for module globals) ->
#     {attr: accepted lock terminal names}
SEED_GUARDS: Dict[Tuple[str, Optional[str]], Dict[str, Set[str]]] = {
    ("mvapich2_tpu/transport/shm.py", "ShmChannel"): {
        "_spill_pending": {"_spill_lock"},
        "_spill_seq": {"_spill_lock"},
        "_backlog": {"_send_lock"},
    },
    ("mvapich2_tpu/coll/nbc/engine.py", "NbcEngine"): {
        "active": {"mutex"},
    },
    ("mvapich2_tpu/trace/recorder.py", None): {
        "_active": {"_lock"},
    },
    ("mvapich2_tpu/transport/arena.py", "ShmArena"): {
        "_free": {"_lock"},
        "_brk": {"_lock"},
        "_outstanding": {"_lock"},
        "_in_use": {"_lock"},
    },
    # The flat-slot collective tier's shared state (cplane.cpp
    # cp_flat_* slots) is seqlock'd in C, out of this pass's reach; its
    # python mirror (coll/flatcoll.py _FlatComm, comm._flat_state) is
    # CONFINED to the collective call path — MPI semantics already
    # forbid concurrent collectives on one comm, so there is no lock to
    # register. What IS registrable: the flat-wait progress callback
    # (transport/shm.py _flat_progress) runs inside the C wait loop and
    # carries a "# mv2tlint: handler" annotation so the blocking pass
    # forbids sleeps/unbounded acquires there.
}

_EXEMPT_METHODS = {"__init__", "__new__", "__init_subclass__"}


def _parse_guard(value: str) -> Set[str]:
    return {p.strip() for p in value.split("|") if p.strip()}


class _Scope:
    """Guard tables for one module: per-class and module-global."""

    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.cls_guards: Dict[str, Dict[str, Set[str]]] = {}
        self.mod_guards: Dict[str, Set[str]] = {}
        for (suffix, cls), attrs in SEED_GUARDS.items():
            if mod.relpath.endswith(suffix):
                if cls is None:
                    for a, locks in attrs.items():
                        self.mod_guards.setdefault(a, set()).update(locks)
                else:
                    g = self.cls_guards.setdefault(cls, {})
                    for a, locks in attrs.items():
                        g.setdefault(a, set()).update(locks)
        # harvest # guarded-by: annotations
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            g = self.cls_guards.setdefault(node.name, {})
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    val = mod.annotation(sub.lineno, "guarded-by")
                    if not val:
                        continue
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            g.setdefault(t.attr, set()).update(
                                _parse_guard(val))
        for node in mod.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                val = mod.annotation(node.lineno, "guarded-by")
                if not val:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.mod_guards.setdefault(t.id, set()).update(
                            _parse_guard(val))


class LockDisciplinePass(LintPass):
    id = "locks"
    doc = ("guarded-by attributes may only be touched inside the "
           "matching with-lock block")

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        for mod in modules:
            scope = _Scope(mod)
            if not scope.cls_guards and not scope.mod_guards:
                continue
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    guards = scope.cls_guards.get(node.name, {})
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._check_fn(mod, sub, f"{node.name}.{sub.name}",
                                           guards, scope.mod_guards, out)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_fn(mod, node, node.name, {},
                                   scope.mod_guards, out)
        return out

    # ------------------------------------------------------------------
    def _check_fn(self, mod: SourceModule, fn, qual: str,
                  guards: Dict[str, Set[str]],
                  mod_guards: Dict[str, Set[str]],
                  out: List[Finding]) -> None:
        if fn.name in _EXEMPT_METHODS:
            return
        held: Set[str] = set()
        holds = mod.annotation(fn.lineno, "holds")
        if holds:
            held |= {p.strip() for p in holds.split(",") if p.strip()}
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  + fn.args.posonlyargs}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        # bare names assigned in the function body shadow module globals
        local_names = {t.id for sub in ast.walk(fn)
                       for t in self._stmt_targets(sub)
                       if isinstance(t, ast.Name)}
        reported: Set[str] = set()

        def note(line: int, attr: str, locks: Set[str]) -> None:
            if attr in reported:
                return
            reported.add(attr)
            f = self.finding(mod, line,
                             f"'{attr}' (guarded-by {'|'.join(sorted(locks))})"
                             f" touched in {qual} without the lock held")
            if f is not None:
                out.append(f)

        def scan(node: ast.AST, held: Set[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    scan(item.context_expr, held)
                    t = terminal_name(item.context_expr)
                    if t is not None:
                        inner.add(t)
                for st in node.body:
                    scan(st, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later under its own contract
                self._check_fn(mod, node, f"{qual}.{node.name}",
                               guards, mod_guards, out)
                return
            if isinstance(node, ast.Lambda):
                return   # no annotation surface; call targets are checked
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in guards \
                        and not (held & guards[node.attr]):
                    note(node.lineno, node.attr, guards[node.attr])
                scan(node.value, held)
                return
            if isinstance(node, ast.Name):
                if node.id in mod_guards and node.id not in params \
                        and node.id not in local_names \
                        and not (held & mod_guards[node.id]):
                    note(node.lineno, node.id, mod_guards[node.id])
                return
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        for st in fn.body:
            scan(st, held)

    @staticmethod
    def _stmt_targets(sub: ast.AST):
        if isinstance(sub, ast.Assign):
            raw = sub.targets
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign, ast.For,
                              ast.NamedExpr)):
            raw = [sub.target]
        elif isinstance(sub, ast.withitem) and sub.optional_vars is not None:
            raw = [sub.optional_vars]
        else:
            return []
        flat = []
        for t in raw:
            if isinstance(t, (ast.Tuple, ast.List)):
                flat.extend(t.elts)
            else:
                flat.append(t)
        return flat

"""Pass ``blocking`` — no blocking calls in progress-engine callbacks.

Packet handlers and progress callbacks run with the engine mutex held,
on whatever thread progressed the engine — often the SENDER's thread via
the async-drain path. A blocking call there (sleep, unbounded lock
acquire, a nested blocking recv/wait) stalls every rank sharing the
engine and is the classic shm-datapath deadlock shape (PAPER.md §L3:
handler waits on traffic only its own engine can progress).

Handler contexts are discovered per module:
  * the callable registered via ``register_handler(pkt, fn)`` /
    ``register_hook(fn)`` / ``req.add_callback(fn)`` — a ``self._x``
    method reference, a bare function name, or the function(s) a lambda
    argument calls;
  * any def annotated ``# mv2tlint: handler``.

Inside a handler body (nested defs excluded — they run later) these are
findings:
  * ``time.sleep(...)``
  * ``.acquire()`` without ``blocking=False`` or a ``timeout=`` bound
  * ``.wait()`` / ``.join()`` without a timeout argument
  * calls to ``progress_wait`` (re-entering the blocking wait)
  * blocking point-to-point/collective entry points: ``recv``,
    ``probe``, ``barrier``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, LintPass, SourceModule, attr_chain

# register_liveness: the failure-containment probes run at blocking
# waits' sleep points — a sleep/blocking call inside one stalls every
# wait in the process, so they are handler-context code too
_REGISTRARS = {"register_handler", "register_hook", "add_callback",
               "register_liveness"}
_BLOCKING_NAMES = {"recv", "probe", "barrier", "progress_wait"}


def _called_names(node: ast.AST) -> Set[str]:
    """Terminal names of everything called inside ``node``."""
    names: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                names.add(fn.attr)
            elif isinstance(fn, ast.Name):
                names.add(fn.id)
    return names


def _has_timeout_bound(call: ast.Call) -> bool:
    if call.args:
        return True           # positional blocking flag / timeout given
    return any(kw.arg in ("timeout", "blocking") for kw in call.keywords)


class BlockingCallPass(LintPass):
    id = "blocking"
    doc = ("no blocking calls (sleep, unbounded acquire/wait, blocking "
           "recv) inside packet handlers and progress callbacks")

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        for mod in modules:
            defs: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(node)
            handlers: Set[str] = set()
            registers_pkts = False
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and "handler" in (mod.annotation(node.lineno,
                                                         "mv2tlint") or ""):
                    handlers.add(node.name)
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                reg = fn.attr if isinstance(fn, ast.Attribute) else \
                    (fn.id if isinstance(fn, ast.Name) else None)
                if reg not in _REGISTRARS:
                    continue
                if reg == "register_handler":
                    registers_pkts = True
                cb_args = node.args[1:] if reg == "register_handler" \
                    else node.args[:1]
                for arg in cb_args:
                    name = None
                    if isinstance(arg, ast.Attribute):
                        name = arg.attr
                    elif isinstance(arg, ast.Name):
                        name = arg.id
                    elif isinstance(arg, ast.Lambda):
                        for n in _called_names(arg.body):
                            if n in defs:
                                handlers.add(n)
                        continue
                    if name is not None and name in defs:
                        handlers.add(name)
            if registers_pkts:
                # handler tables built as data (rma/win.py's loop over
                # (PktType, self._on_x) tuples) hide the callable from
                # the registrar's argument list — in a module that
                # registers packet handlers at all, the _on_* naming
                # convention IS the handler table
                handlers.update(n for n in defs if n.startswith("_on_"))
            for name in sorted(handlers):
                for fndef in defs.get(name, []):
                    self._check_handler(mod, fndef, out)
        return out

    # ------------------------------------------------------------------
    def _check_handler(self, mod: SourceModule, fndef, out: List[Finding]) -> None:
        qual = fndef.name

        def emit(line: int, what: str) -> None:
            f = self.finding(mod, line, f"blocking call '{what}' inside "
                             f"handler/progress-callback '{qual}'")
            if f is not None:
                out.append(f)

        def scan(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return        # deferred execution, not this context
            if isinstance(node, ast.Call):
                what = self._blocking_what(node)
                if what is not None:
                    emit(node.lineno, what)
            for child in ast.iter_child_nodes(node):
                scan(child)

        for st in fndef.body:
            scan(st)

    @staticmethod
    def _blocking_what(call: ast.Call) -> Optional[str]:
        fn = call.func
        chain = attr_chain(fn)
        if chain is not None and chain.split(".")[-2:] == ["time", "sleep"]:
            return "time.sleep"
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            (fn.id if isinstance(fn, ast.Name) else None)
        if name == "sleep" and chain == "sleep":
            return "sleep"
        recv = attr_chain(fn.value) if isinstance(fn, ast.Attribute) else None
        if name == "acquire" and not _has_timeout_bound(call):
            return f"{recv or 'lock'}.acquire() (unbounded)"
        if name in ("wait", "join") and not _has_timeout_bound(call):
            return f"{recv or '<expr>'}.{name}() (no timeout)"
        if name in _BLOCKING_NAMES and isinstance(fn, (ast.Attribute,
                                                       ast.Name)):
            return f"{chain or name}"
        return None

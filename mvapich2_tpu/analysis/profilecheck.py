"""Pass ``profile`` — the tuning-table / arch-profile doctor.

The reference generates 1,377 per-arch tuning headers offline and
trusts them forever; this repo's tables are data (coll/tuning.py
DEFAULT_TABLES + the measured JSON profiles under profiles/), which
means a drifted edge or a typo'd algorithm name is a silent mis-route,
not a compile error — the r5 64 KiB allreduce cliff was exactly a
table constant drifting away from the protocol threshold it mirrored.
Three invariant families, all static:

  * **table shape** — every collective's tuning table carries every
    comm-size class (the classes are harvested from ``_size_class``,
    their single source of truth), and every class's bins are total,
    disjoint and monotone: strictly increasing resolved edges, exactly
    one open (``None``) top bin, every algorithm name registered in
    ``ALGOS`` for that collective.
  * **symbolic edges** — a string edge ("eager", "coll_max",
    "dev_tier_vmem_max", ...) must be a symbol ``_resolve_edge``
    actually resolves (harvested from its comparisons) so a renamed
    threshold cannot leave a dangling alias behind.
  * **profile schema** — every committed ``mv2t-tuning-profile-v1``
    JSON under profiles/ has only known keys: collectives/classes/rows
    as above, ``device_crossovers`` keyed by collective or dev_tier_*
    edge with sane integer values (``dev_tier_vmem_max`` may not exceed
    the hard VMEM wrapper cap of ops/pallas_ring.py), ``kernel_params``
    keyed only by parameters some kernel actually fetches (harvested
    from the ``kernel_param``/``_tuned_default`` call sites), and a
    filename that matches the arch key it claims — a mismatched name
    would simply never auto-load. The first REAL TPU profile commit
    (ROADMAP item 1) is validated by this pass, mechanically.

Everything is parsed from source/JSON — no package import, so the pass
runs in the same process-free mode as the native layout doctor.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set

from .core import (Finding, LintPass, PKG_ROOT, REPO_ROOT, SourceModule,
                   const_int)

PROFILE_DIR = os.path.join(PKG_ROOT, "profiles")
TUNING_PATH = os.path.join(PKG_ROOT, "coll", "tuning.py")
RING_PATH = os.path.join(PKG_ROOT, "ops", "pallas_ring.py")
FORMAT_V1 = "mv2t-tuning-profile-v1"

_PROFILE_KEYS = {"tables", "device_crossovers", "kernel_params",
                 "raw", "raw_device_tiers"}
_DOC_KEYS = {"arch_key", "format", "profile", "comment"}
_DEV_TIER_KEYS = {"dev_tier_vmem_max", "dev_tier_xla_min",
                  "dev_tier_quant_min"}


def _load_module(path: str) -> Optional[SourceModule]:
    try:
        with open(path, encoding="utf-8") as f:
            return SourceModule(path, f.read())
    except (OSError, SyntaxError):
        return None


class _TuningFacts:
    """Statically harvested single-sources-of-truth from coll/tuning.py
    (+ the kernel-param consumers and the VMEM wrapper cap)."""

    def __init__(self, modules: List[SourceModule]):
        self.tables: Dict[str, Dict[str, list]] = {}
        self.tables_line = 0
        self.algos: Dict[str, Set[str]] = {}
        self.symbols: Set[str] = set()
        self.classes: Set[str] = set()
        self.kernel_params: Set[str] = set()
        self.vmem_limit: Optional[int] = None
        self.tuning_mod: Optional[SourceModule] = None

        by_suffix = {m.relpath: m for m in modules}

        def find(suffix: str) -> Optional[SourceModule]:
            for rel, m in by_suffix.items():
                if rel.endswith(suffix):
                    return m
            return None

        tuning = find("tuning.py") or _load_module(TUNING_PATH)
        ring = find("ops/pallas_ring.py") or _load_module(RING_PATH)
        self.tuning_mod = tuning
        if tuning is not None:
            self._harvest_tuning(tuning)
        if ring is not None:
            for node in ast.walk(ring.tree):
                if isinstance(node, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == "VMEM_LIMIT_BYTES"
                                for t in node.targets):
                    self.vmem_limit = const_int(node.value)
        # kernel-param consumers anywhere in the scanned set (falling
        # back to the committed ops/ tree when linting fixtures)
        param_mods = [m for m in modules] or []
        if not any("ops/" in m.relpath for m in param_mods):
            for name in ("pallas_ici.py", "pallas_hbm.py",
                         "pallas_quant.py"):
                m = _load_module(os.path.join(PKG_ROOT, "ops", name))
                if m is not None:
                    param_mods.append(m)
        for m in param_mods:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call):
                    fn = node.func
                    nm = fn.attr if isinstance(fn, ast.Attribute) else \
                        (fn.id if isinstance(fn, ast.Name) else None)
                    if nm in ("kernel_param", "kernel_param_cv",
                              "_tuned_default") \
                            and node.args \
                            and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        self.kernel_params.add(node.args[0].value)

    # ------------------------------------------------------------------
    def _harvest_tuning(self, mod: SourceModule) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                # normalize `X: T = {...}` to the Assign shape below
                node = ast.copy_location(
                    ast.Assign(targets=[node.target], value=node.value),
                    node)
            if isinstance(node, ast.Assign) and node.targets:
                t = node.targets[0]
                if isinstance(t, ast.Name) and t.id == "DEFAULT_TABLES":
                    self.tables = self._eval_tables(node.value)
                    self.tables_line = node.lineno
                elif isinstance(t, ast.Name) and t.id == "ALGOS" \
                        and isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        if isinstance(k, ast.Constant) \
                                and isinstance(v, ast.Dict):
                            self.algos[k.value] = {
                                ik.value for ik in v.keys
                                if isinstance(ik, ast.Constant)}
                elif isinstance(t, ast.Subscript):
                    # ALGOS["allreduce"]["rsa_arena"] = fn
                    inner = t.value
                    if isinstance(inner, ast.Subscript) \
                            and isinstance(inner.value, ast.Name) \
                            and inner.value.id == "ALGOS" \
                            and isinstance(inner.slice, ast.Constant) \
                            and isinstance(t.slice, ast.Constant):
                        self.algos.setdefault(
                            inner.slice.value, set()).add(t.slice.value)
            if isinstance(node, ast.FunctionDef):
                if node.name == "_resolve_edge":
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Compare):
                            for cmp in sub.comparators:
                                if isinstance(cmp, ast.Constant) \
                                        and isinstance(cmp.value, str):
                                    self.symbols.add(cmp.value)
                elif node.name == "_size_class":
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Return) \
                                and isinstance(sub.value, ast.Constant) \
                                and isinstance(sub.value.value, str):
                            self.classes.add(sub.value.value)
                        if isinstance(sub, ast.IfExp):
                            for side in (sub.body, sub.orelse):
                                if isinstance(side, ast.Constant) \
                                        and isinstance(side.value, str):
                                    self.classes.add(side.value)

    def _eval_tables(self, node: ast.AST) -> Dict[str, Dict[str, list]]:
        out: Dict[str, Dict[str, list]] = {}
        if not isinstance(node, ast.Dict):
            return out
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant) and isinstance(v, ast.Dict)):
                continue
            classes: Dict[str, list] = {}
            for ck, cv in zip(v.keys, v.values):
                if not (isinstance(ck, ast.Constant)
                        and isinstance(cv, (ast.List, ast.Tuple))):
                    continue
                rows = []
                for el in cv.elts:
                    if isinstance(el, (ast.Tuple, ast.List)) \
                            and len(el.elts) == 2:
                        bound, algo = el.elts
                        b = None
                        if isinstance(bound, ast.Constant):
                            b = bound.value
                        else:
                            b = const_int(bound)
                        a = algo.value if isinstance(algo, ast.Constant) \
                            else None
                        rows.append((b, a))
                classes[ck.value] = rows
            out[k.value] = classes
        return out

    def resolve(self, bound):
        """Resolved numeric edge for monotonicity checks — symbolic
        names use representative defaults (drift of the VALUE is the
        runtime resolver's business; the doctor checks shape)."""
        reps = {"eager": 32 * 1024, "coll_max": 256 * 1024,
                "dev_tier_vmem_max": 4 * 1024 * 1024,
                "dev_tier_quant_min": 1 << 61,
                "dev_tier_xla_min": 1 << 62}
        if isinstance(bound, str):
            return reps.get(bound)
        return bound


class ProfileDoctorPass(LintPass):
    id = "profile"
    doc = ("tuning tables total/disjoint/monotone with registered "
           "algos + symbolic edges; committed arch-profile JSONs match "
           "the v1 schema (known keys, sane edges, loadable filename)")

    def __init__(self, profile_files: Optional[List[str]] = None):
        # None = every .json under the committed profiles/ directory
        self.profile_files = profile_files

    # ------------------------------------------------------------------
    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        facts = _TuningFacts(modules)
        if facts.tuning_mod is not None and facts.tables:
            self._check_tables(facts, out)
        for path in self._paths():
            self._check_profile(path, facts, out)
        return out

    def _paths(self) -> List[str]:
        if self.profile_files is not None:
            return list(self.profile_files)
        try:
            return sorted(os.path.join(PROFILE_DIR, f)
                          for f in os.listdir(PROFILE_DIR)
                          if f.endswith(".json"))
        except OSError:
            return []

    # -- DEFAULT_TABLES -------------------------------------------------
    def _check_tables(self, facts: _TuningFacts, out: List[Finding]) -> None:
        mod = facts.tuning_mod
        line = facts.tables_line

        def emit(msg: str) -> None:
            f = self.finding(mod, line, msg)
            if f is not None:
                out.append(f)

        for coll, classes in sorted(facts.tables.items()):
            missing = facts.classes - set(classes)
            if missing:
                emit(f"DEFAULT_TABLES[{coll!r}] lacks comm-size "
                     f"class(es) {sorted(missing)} — _size_class can "
                     "select them")
            unknown = set(classes) - facts.classes
            if unknown:
                emit(f"DEFAULT_TABLES[{coll!r}] has unknown comm-size "
                     f"class(es) {sorted(unknown)}")
            for cls, rows in sorted(classes.items()):
                self._check_rows(f"DEFAULT_TABLES[{coll!r}][{cls!r}]",
                                 coll, rows, facts, emit)

    def _check_rows(self, label: str, coll: str, rows, facts, emit) -> None:
        if not rows:
            emit(f"{label} is empty — no bin covers any size")
            return
        prev = -1
        for i, (bound, algo) in enumerate(rows):
            last = i == len(rows) - 1
            if algo is not None and facts.algos.get(coll) is not None \
                    and algo not in facts.algos[coll]:
                emit(f"{label} names unregistered algorithm {algo!r}")
            if bound is None:
                if not last:
                    emit(f"{label} has a non-final open (None) bin — "
                         "rows after it are dead")
                continue
            if isinstance(bound, str):
                if bound not in facts.symbols:
                    emit(f"{label} uses unknown symbolic edge "
                         f"{bound!r} (not resolved by _resolve_edge)")
                    continue
            r = facts.resolve(bound)
            if r is None:
                continue
            if r <= prev:
                emit(f"{label} bin edge {bound!r} is not strictly "
                     "increasing — bins overlap or are empty")
            prev = r
            if last:
                emit(f"{label} last bin is bounded ({bound!r}) — sizes "
                     "above it select nothing (table not total)")

    # -- committed profile JSONs ----------------------------------------
    def _check_profile(self, path: str, facts: _TuningFacts,
                       out: List[Finding]) -> None:
        rel = os.path.relpath(path, REPO_ROOT)
        if rel.startswith(".."):
            rel = os.path.basename(path)

        def emit(msg: str) -> None:
            out.append(Finding(self.id, rel, 0, msg))

        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            emit(f"unreadable profile JSON: {e!s:.80}")
            return
        if not isinstance(doc, dict) or doc.get("format") != FORMAT_V1:
            return          # freeform measurement docs are out of scope
        unknown = set(doc) - _DOC_KEYS
        if unknown:
            emit(f"unknown top-level key(s) {sorted(unknown)}")
        arch = doc.get("arch_key")
        if not (isinstance(arch, str) and arch.count(":") >= 2):
            emit(f"arch_key {arch!r} is not a '<family>:<chip>:<n>' key")
        else:
            want = arch.replace(":", "_").replace(" ", "-") + ".json"
            if os.path.basename(path) != want:
                emit(f"filename {os.path.basename(path)!r} does not "
                     f"match arch_key (want {want!r}) — "
                     "load_default_profile will never find it")
        prof = doc.get("profile")
        if not isinstance(prof, dict):
            emit("no 'profile' object")
            return
        unknown = set(prof) - _PROFILE_KEYS
        if unknown:
            emit(f"unknown profile key(s) {sorted(unknown)} — the "
                 "loader would silently drop them")

        known_colls = set(facts.tables) or None
        for coll, classes in sorted(prof.get("tables", {}).items()):
            if known_colls is not None and coll not in known_colls:
                emit(f"tables[{coll!r}]: unknown collective")
                continue
            if not isinstance(classes, dict):
                emit(f"tables[{coll!r}] is not a class map")
                continue
            for cls, rows in sorted(classes.items()):
                if facts.classes and cls not in facts.classes:
                    emit(f"tables[{coll!r}][{cls!r}]: unknown comm-"
                         "size class")
                    continue
                rows2 = [tuple(r) if isinstance(r, list) and len(r) == 2
                         else (None, None) for r in rows]
                self._check_rows(f"tables[{coll!r}][{cls!r}]", coll,
                                 rows2, facts,
                                 lambda m: emit(m))

        dc = prof.get("device_crossovers", {})
        if isinstance(dc, dict):
            valid = (set(facts.tables) | _DEV_TIER_KEYS) \
                if facts.tables else None
            for key, val in sorted(dc.items()):
                if valid is not None and key not in valid:
                    emit(f"device_crossovers[{key!r}]: neither a "
                         "collective nor a dev_tier_* edge")
                if not isinstance(val, int) or val < -1:
                    emit(f"device_crossovers[{key!r}] = {val!r} is not "
                         "a byte count")
            vmax = dc.get("dev_tier_vmem_max")
            if isinstance(vmax, int) and facts.vmem_limit is not None \
                    and vmax > facts.vmem_limit:
                emit(f"dev_tier_vmem_max {vmax} exceeds the hard VMEM "
                     f"wrapper cap {facts.vmem_limit} "
                     "(ops/pallas_ring.VMEM_LIMIT_BYTES) — the vmem "
                     "tier would refuse every shard in the band")
            qmin = dc.get("dev_tier_quant_min")
            if isinstance(qmin, int) and qmin >= 0 \
                    and isinstance(vmax, int) and qmin < vmax:
                emit(f"dev_tier_quant_min {qmin} sits below the "
                     f"vmem->hbm edge {vmax} — the quantized bin "
                     "would swallow the vmem band (device tier bins "
                     "no longer disjoint)")

        kp = prof.get("kernel_params", {})
        if isinstance(kp, dict):
            for key, val in sorted(kp.items()):
                if facts.kernel_params and key not in facts.kernel_params:
                    emit(f"kernel_params[{key!r}]: no kernel fetches "
                         "this parameter (typo'd key tunes nothing)")
                if not isinstance(val, int) or val <= 0:
                    emit(f"kernel_params[{key!r}] = {val!r} is not a "
                         "positive integer")

"""Pass ``proto`` — control-plane protocol doctors (KVS key flow,
bounded waits, wire-state totality, manifest-version compatibility).

The control plane — the KVS fence-with-cards bootstrap, the 2-stage
lazy-wiring state machine, the warm-attach daemon's manifest cycle —
is string-keyed and convention-bound: a sender publishing
``shm-cabi-<r>`` while the reader peeks ``shm_cabi-<r>`` is not a type
error, it is a silent hang at np=4 three PRs later. Four doctors, all
syntactic because the KVS idiom is declarative (put/mput vs
get/mget/mpeek with literal or f-string keys):

  * **key flow**: every key family written (put / put_many / publish /
    fence ``cards=`` / batched-card containers flowing into put_many)
    is read somewhere (get / get_many / peek / peek_many), and vice
    versa. Write-only families are dead weight or a mis-spelled
    consumer; read-only (never-written) families are a consumer that
    blocks forever. Families differing only in separator spelling
    (``-`` vs ``_``) are flagged as drift — the silent-hang class —
    and subsume their orphan findings.
  * **deadline**: every retry loop around a KVS wait verb (mpeek/mget/
    get/fence) carries a bounded deadline (a compare against a
    ``deadline``/``timeout``-named bound, the MV2T_WIRE_TIMEOUT shape)
    or an explicit ``# proto: bounded-by(<cvar-or-rationale>)``
    annotation on the loop.
  * **wire-state totality**: the ``_wire_stage`` state machine
    (transport/shm.py ensure_wired/try_wire): every stage value ever
    stored must have a handling comparison annotated
    ``# state: wire:<k>``, and every handler's function must carry an
    exit on peer death (a ``dead``/``failed`` reference) — a stage
    with no death exit is a permanent stall when a peer is SIGKILLed
    mid-wire.
  * **version**: every ``*_VERSION`` protocol constant (daemon
    MANIFEST_VERSION, boot card version): consumers must compare
    version fields against the constant, never an integer literal, and
    a constant at N must keep a ``# proto: <stem>-v<k>`` annotated
    compatibility handler for every k < N (the pre-v2 set upgrade in
    runtime/daemon.py is the canonical one).

``proto_state_map()`` exports the harvested key/state maps for the
stall watchdog's and ``bin/mpistat --proto-map``'s control-plane
sections — the control-plane analog of the native pass's
``shared_field_map`` and the device pass's ``device_lane_map``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintPass, SourceModule, attr_chain, const_int

WILD = "<*>"

# verbs whose NAME alone identifies the KVS API (no other type in the
# tree spells them)
_UNIQUE_WRITE = {"put_many", "publish"}
_UNIQUE_READ = {"get_many", "peek_many"}
# ambiguous verbs (dict.get, queue.put, set.add): accepted only on a
# kvs-chained receiver or — second phase — when the key matches a
# family already harvested from an unambiguous site
_AMBIG_WRITE = {"put"}
_AMBIG_READ = {"get", "peek"}
_AMBIG_RW = {"add"}
# read verbs that block (the deadline doctor's wait set); peeks are
# nonblocking probes but a retry LOOP around one is a wait
_WAIT_VERBS = {"get", "get_many", "peek", "peek_many", "fence",
               "fence_begin"}

_BOUND_NAMES = ("deadline", "timeout", "until", "expires", "expiry")
_BOUNDED_BY_RE = re.compile(r"proto:\s*bounded-by\(([^)]+)\)")
_VERSION_RE = re.compile(r"^[A-Z][A-Z0-9_]*_VERSION$")
_STATE_ATTR = "_wire_stage"


def _is_kvs_chain(node: ast.AST) -> bool:
    chain = attr_chain(node)
    return chain is not None and "kvs" in chain.split(".")


def _family(expr: ast.AST, env: Dict[str, ast.AST]) -> Optional[tuple]:
    """Normalize a key expression to a family tuple: literal fragments
    with WILD for interpolations ('shm-cma-', WILD). One level of
    local-variable resolution (segkey = f"shm-seg-{leader}")."""
    if isinstance(expr, ast.Name):
        expr = env.get(expr.id)
        if expr is None:
            return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, ast.JoinedStr):
        parts: List[str] = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif parts and parts[-1] == WILD:
                continue                    # collapse adjacent holes
            else:
                parts.append(WILD)
        return tuple(parts)
    return None


def _families_in_seq(expr: ast.AST,
                     env: Dict[str, ast.AST]) -> List[tuple]:
    """Key families inside a *_many argument: list/tuple literals,
    comprehensions, and `[...] + [...]` concatenations."""
    out: List[tuple] = []
    if isinstance(expr, (ast.List, ast.Tuple)):
        for e in expr.elts:
            f = _family(e, env)
            if f is not None:
                out.append(f)
    elif isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
        f = _family(expr.elt, env)
        if f is not None:
            out.append(f)
    elif isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        out.extend(_families_in_seq(expr.left, env))
        out.extend(_families_in_seq(expr.right, env))
    return out


def _families_in_dict(expr: ast.AST,
                      env: Dict[str, ast.AST]) -> List[tuple]:
    out: List[tuple] = []
    if isinstance(expr, ast.Dict):
        for k in expr.keys:
            f = _family(k, env) if k is not None else None
            if f is not None:
                out.append(f)
    elif isinstance(expr, ast.DictComp):
        f = _family(expr.key, env)
        if f is not None:
            out.append(f)
    return out


def render_family(fam: tuple) -> str:
    return "".join(fam)


def _canonical(fam: tuple) -> tuple:
    """Separator-insensitive spelling for drift detection."""
    return tuple(WILD if p == WILD else
                 p.replace("-", "").replace("_", "").lower()
                 for p in fam)


class _Site:
    __slots__ = ("mod", "line", "verb")

    def __init__(self, mod: SourceModule, line: int, verb: str):
        self.mod = mod
        self.line = line
        self.verb = verb


class _Harvest:
    """Whole-module-set key/wait/state/version harvest (shared by the
    pass and proto_state_map)."""

    def __init__(self, modules: List[SourceModule]):
        self.writes: Dict[tuple, List[_Site]] = {}
        self.reads: Dict[tuple, List[_Site]] = {}
        # KVS wait-verb call lines per (module, function)
        self.wait_calls: List[Tuple[SourceModule, ast.Call, str]] = []
        self.ambig: List[Tuple[SourceModule, ast.Call, str, str,
                               Optional[tuple]]] = []
        self.versions: List[Tuple[SourceModule, str, int, int]] = []
        self.wire_modules: List[SourceModule] = []
        for mod in modules:
            self._one_module(mod)
        # second phase: ambiguous verbs whose key matches a family an
        # unambiguous site already established
        known = set(self.writes) | set(self.reads)
        for mod, call, verb, role, fam in self.ambig:
            if fam is None or fam not in known:
                continue
            site = _Site(mod, call.lineno, verb)
            if role in ("w", "rw"):
                self.writes.setdefault(fam, []).append(site)
            if role in ("r", "rw"):
                self.reads.setdefault(fam, []).append(site)
            if verb in _WAIT_VERBS:
                self.wait_calls.append((mod, call, verb))

    # -- per module ------------------------------------------------------
    def _one_module(self, mod: SourceModule) -> None:
        tree = mod.tree
        # one-level variable resolution (segkey = f"shm-seg-{leader}"):
        # a module-wide env of simple string assignments — scoping is
        # ignored (collisions across functions are vanishingly unlikely
        # for key-shaped strings, and a wrong resolution only shifts
        # which site records the family, never invents one)
        env: Dict[str, ast.AST] = {}
        for st in ast.walk(tree):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name) \
                    and isinstance(st.value,
                                   (ast.Constant, ast.JoinedStr)):
                env[st.targets[0].id] = st.value

        # publication containers: names flowing into put_many(<name>)
        containers: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "put_many" and node.args:
                arg = node.args[0]
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    t = arg.attr if isinstance(arg, ast.Attribute) \
                        else arg.id
                    containers.add(t)

        self._walk_fn(mod, tree, env, containers)

        # versioned-protocol constants
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _VERSION_RE.match(node.targets[0].id):
                v = const_int(node.value)
                if v is not None:
                    self.versions.append((mod, node.targets[0].id, v,
                                          node.lineno))
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == _STATE_ATTR:
                if mod not in self.wire_modules:
                    self.wire_modules.append(mod)
                break

    def _walk_fn(self, mod: SourceModule, fn, env, containers) -> None:
        for node in ast.walk(fn):
            # container subscript stores: self._cards[key] = val
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript):
                tgt = node.targets[0]
                t = tgt.value.attr if isinstance(tgt.value, ast.Attribute) \
                    else (tgt.value.id if isinstance(tgt.value, ast.Name)
                          else None)
                if t in containers:
                    fam = _family(tgt.slice, env)
                    if fam is not None:
                        self.writes.setdefault(fam, []).append(
                            _Site(mod, node.lineno, "put_many"))
                continue
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            verb = node.func.attr
            recv = node.func.value
            line = node.lineno
            if verb in _UNIQUE_WRITE:
                if verb == "put_many" and node.args:
                    for fam in _families_in_dict(node.args[0], env):
                        self.writes.setdefault(fam, []).append(
                            _Site(mod, line, verb))
                elif verb == "publish" and node.args:
                    fam = _family(node.args[0], env)
                    if fam is not None:
                        self.writes.setdefault(fam, []).append(
                            _Site(mod, line, verb))
            elif verb in _UNIQUE_READ:
                if node.args:
                    for fam in _families_in_seq(node.args[0], env):
                        self.reads.setdefault(fam, []).append(
                            _Site(mod, line, verb))
                self.wait_calls.append((mod, node, verb))
            elif verb in ("fence", "fence_begin"):
                if not _is_kvs_chain(recv):
                    continue
                for kw in node.keywords:
                    if kw.arg == "cards":
                        for fam in _families_in_dict(kw.value, env):
                            self.writes.setdefault(fam, []).append(
                                _Site(mod, line, verb))
                self.wait_calls.append((mod, node, verb))
            elif verb in (_AMBIG_WRITE | _AMBIG_READ | _AMBIG_RW):
                fam = _family(node.args[0], env) if node.args else None
                role = ("w" if verb in _AMBIG_WRITE else
                        "r" if verb in _AMBIG_READ else "rw")
                if _is_kvs_chain(recv):
                    if fam is not None:
                        site = _Site(mod, line, verb)
                        if role in ("w", "rw"):
                            self.writes.setdefault(fam, []).append(site)
                        if role in ("r", "rw"):
                            self.reads.setdefault(fam, []).append(site)
                    if verb in _WAIT_VERBS:
                        self.wait_calls.append((mod, node, verb))
                else:
                    self.ambig.append((mod, node, verb, role, fam))


class ProtoPass(LintPass):
    id = "proto"
    doc = ("KVS key-flow doctor (write-only / never-written / drifted "
           "key families), bounded-deadline check on KVS retry loops, "
           "wire-state totality, *_VERSION compatibility")

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        h = _Harvest(modules)

        def emit(mod: SourceModule, line: int, msg: str) -> None:
            f = self.finding(mod, line, msg)
            if f is not None:
                out.append(f)

        # -- key flow ----------------------------------------------------
        by_canon: Dict[tuple, Set[tuple]] = {}
        for fam in set(h.writes) | set(h.reads):
            by_canon.setdefault(_canonical(fam), set()).add(fam)
        drifted: Set[tuple] = set()
        for canon, fams in sorted(by_canon.items()):
            if len(fams) < 2:
                continue
            drifted |= fams
            names = " vs ".join(sorted(render_family(f) for f in fams))
            site = min((s for f in fams
                        for s in h.writes.get(f, []) + h.reads.get(f, [])),
                       key=lambda s: (s.mod.relpath, s.line))
            emit(site.mod, site.line,
                 f"KVS key-family drift: {names} differ only in "
                 "separator spelling — one side will never match the "
                 "other (silent hang)")
        for fam in sorted(set(h.writes) - set(h.reads) - drifted):
            site = h.writes[fam][0]
            emit(site.mod, site.line,
                 f"KVS key family '{render_family(fam)}' is written "
                 f"({site.verb}) but never read anywhere — dead "
                 "publication or a mis-spelled consumer")
        for fam in sorted(set(h.reads) - set(h.writes) - drifted):
            site = h.reads[fam][0]
            emit(site.mod, site.line,
                 f"KVS key family '{render_family(fam)}' is read "
                 f"({site.verb}) but never written anywhere — its "
                 "consumer blocks forever")

        # -- deadline doctor ---------------------------------------------
        out.extend(self._deadline_doctor(modules, h))
        # -- wire-state totality -----------------------------------------
        for mod in h.wire_modules:
            out.extend(self._wire_doctor(mod))
        # -- version compatibility ---------------------------------------
        out.extend(self._version_doctor(modules, h))
        out.sort(key=lambda f: (f.path, f.line, f.msg))
        return out

    # ------------------------------------------------------------------
    def _deadline_doctor(self, modules: List[SourceModule],
                         h: _Harvest) -> List[Finding]:
        out: List[Finding] = []
        wait_lines: Dict[SourceModule, Set[int]] = {}
        for mod, call, _verb in h.wait_calls:
            wait_lines.setdefault(mod, set()).add(call.lineno)
        for mod in modules:
            lines = wait_lines.get(mod, set())
            # functions containing a wait verb (for one-level expansion)
            fn_waits: Set[str] = set()
            fns: Dict[str, ast.AST] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fns[node.name] = node
                    span = {n.lineno for n in ast.walk(node)
                            if hasattr(n, "lineno")}
                    if span & lines:
                        fn_waits.add(node.name)
            if not lines and not fn_waits:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.While):
                    continue
                body_lines = {n.lineno for n in ast.walk(node)
                              if hasattr(n, "lineno")}
                is_wait = bool(body_lines & lines)
                if not is_wait:
                    # one level of same-module call expansion
                    # (ensure_wired's loop drives _wire_step's peeks)
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Call):
                            name = sub.func.attr \
                                if isinstance(sub.func, ast.Attribute) \
                                else (sub.func.id
                                      if isinstance(sub.func, ast.Name)
                                      else None)
                            if name in fn_waits:
                                is_wait = True
                                break
                if not is_wait:
                    continue
                if self._loop_bounded(mod, node):
                    continue
                verb = next((v for m, c, v in h.wait_calls
                             if m is mod and c.lineno in body_lines),
                            "kvs wait")
                f = self.finding(
                    mod, node.lineno,
                    f"unbounded KVS wait: retry loop around '{verb}' "
                    "carries no deadline — add a bounded deadline "
                    "(the MV2T_WIRE_TIMEOUT shape) or annotate "
                    "'# proto: bounded-by(<cvar-or-rationale>)'")
                if f is not None:
                    out.append(f)
        return out

    @staticmethod
    def _loop_bounded(mod: SourceModule, loop: ast.While) -> bool:
        for line in range(loop.lineno,
                          getattr(loop, "end_lineno", loop.lineno) + 1):
            if _BOUNDED_BY_RE.search(mod.comment(line)):
                return True
        for node in ast.walk(loop):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    t = side.attr if isinstance(side, ast.Attribute) \
                        else (side.id if isinstance(side, ast.Name)
                              else None)
                    if t and any(b in t.lower() for b in _BOUND_NAMES):
                        return True
        return False

    # ------------------------------------------------------------------
    _DEATH_NAMES = {"dead", "failed", "failed_ranks",
                    "check_peer_leases", "PeerDeadError"}

    def _wire_doctor(self, mod: SourceModule) -> List[Finding]:
        out: List[Finding] = []
        assigned: Dict[int, int] = {}
        handled: Dict[int, Tuple[int, ast.AST]] = {}
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr == _STATE_ATTR:
                        v = const_int(node.value)
                        if v is not None and v not in assigned:
                            assigned[v] = node.lineno
            elif isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(isinstance(s, ast.Attribute)
                       and s.attr == _STATE_ATTR for s in sides):
                    for s in sides:
                        v = const_int(s)
                        if v is not None and v not in handled:
                            fn = node
                            while fn in parents and not isinstance(
                                    fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                                fn = parents[fn]
                            handled[v] = (node.lineno, fn)

        def emit(line: int, msg: str) -> None:
            f = self.finding(mod, line, msg)
            if f is not None:
                out.append(f)

        for v, line in sorted(assigned.items()):
            if v not in handled:
                emit(line, f"wire state {v} is entered "
                     f"('{_STATE_ATTR} = {v}') but no handler compares "
                     "against it — the state machine is not total "
                     "(a rank parked in it never advances)")
        for v, (line, fn) in sorted(handled.items()):
            ann = mod.annotation(line, "state")
            if ann != f"wire:{v}":
                emit(line, f"wire state {v} handler lacks its "
                     f"'# state: wire:{v}' annotation")
            names = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute):
                    names.add(sub.attr)
                elif isinstance(sub, ast.Name):
                    names.add(sub.id)
            if not (names & self._DEATH_NAMES):
                emit(line, f"wire state {v} handler has no exit on "
                     "peer death (no dead/failed reference in "
                     f"'{getattr(fn, 'name', '<module>')}') — a peer "
                     "SIGKILLed mid-wire parks this state forever")
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _is_version_field(node: ast.AST) -> bool:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args:
            a = node.args[0]
            return isinstance(a, ast.Constant) \
                and a.value in ("version", "v")
        if isinstance(node, ast.Subscript):
            s = node.slice
            return isinstance(s, ast.Constant) \
                and s.value in ("version", "v")
        return False

    def _version_doctor(self, modules: List[SourceModule],
                        h: _Harvest) -> List[Finding]:
        out: List[Finding] = []
        for mod, name, value, line in h.versions:
            stem = name[:-len("_VERSION")].lower()
            for v in range(1, value):
                pat = re.compile(rf"proto:\s*{re.escape(stem)}-v{v}\b")
                if not any(pat.search(c) for c in mod.comments.values()):
                    f = self.finding(
                        mod, line,
                        f"{name} is {value} but no "
                        f"'# proto: {stem}-v{v}' compatibility handler "
                        f"is annotated in {mod.relpath} — every "
                        "consumer must handle every version <= current")
                    if f is not None:
                        out.append(f)
        version_mods = {mod for mod, *_ in h.versions}
        for mod in version_mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Compare):
                    continue
                sides = [node.left] + list(node.comparators)
                if not any(self._is_version_field(s) for s in sides):
                    continue
                for s in sides:
                    if isinstance(s, ast.Constant) \
                            and isinstance(s.value, int) \
                            and not isinstance(s.value, bool):
                        f = self.finding(
                            mod, node.lineno,
                            f"version field compared against the "
                            f"literal {s.value} — compare against the "
                            "*_VERSION constant so a bump cannot "
                            "orphan this consumer")
                        if f is not None:
                            out.append(f)
        return out


# ---------------------------------------------------------------------------
# the exported control-plane map (watchdog / mpistat parity with
# shared_field_map / device_lane_map)
# ---------------------------------------------------------------------------

_state_map_cache: Optional[dict] = None


def proto_state_map(refresh: bool = False) -> dict:
    """Key-flow / wire-state / version map of the committed tree:

        {"keys": {family: {"writes": n, "reads": n,
                           "modules": [...]}},
         "wire_states": {k: {"module", "line", "annotated"}},
         "versions": {name: value},
         "waits": n_bounded_kvs_wait_loops}
    """
    global _state_map_cache
    if _state_map_cache is not None and not refresh:
        return _state_map_cache
    from .core import PKG_ROOT, scan_paths
    modules, _errs = scan_paths([PKG_ROOT])
    h = _Harvest(modules)
    keys: Dict[str, dict] = {}
    for fam in sorted(set(h.writes) | set(h.reads),
                      key=render_family):
        w = h.writes.get(fam, [])
        r = h.reads.get(fam, [])
        keys[render_family(fam)] = {
            "writes": len(w), "reads": len(r),
            "modules": sorted({s.mod.relpath for s in w + r})}
    wire: Dict[int, dict] = {}
    for mod in h.wire_modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(isinstance(s, ast.Attribute)
                       and s.attr == _STATE_ATTR for s in sides):
                    for s in sides:
                        v = const_int(s)
                        if v is not None:
                            wire[v] = {
                                "module": mod.relpath,
                                "line": node.lineno,
                                "annotated": mod.annotation(
                                    node.lineno, "state")
                                == f"wire:{v}"}
    _state_map_cache = {
        "keys": keys,
        "wire_states": wire,
        "versions": {name: value for _m, name, value, _l in h.versions},
        "waits": len({(m.relpath, c.lineno)
                      for m, c, _v in h.wait_calls}),
    }
    return _state_map_cache

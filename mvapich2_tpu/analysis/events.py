"""Pass ``events`` — the trace event-coverage doctor.

The conformance checker (analysis/conform.py) replays a run's traces
through per-protocol automata; an emitted event outside every
automaton's grammar is only caught at *run* time (the driver's
grammar-coverage violation) and only if some job happens to exercise
the site. This pass closes the gap statically — the ``lat_dev_nbc``
silent-drop bug class (PR 18: a recorded name nobody's table knew,
found by hand) becomes a mechanically caught lint finding:

  * every ``tracer.record(layer, name, ...)`` site in the package must
    emit a (layer, name) the conformance grammar covers — f-string
    names become prefix patterns (``f"rma_{kind}"`` -> ``rma_*``), and
    a name passed through a wrapper parameter is resolved one level
    through the wrapper's call sites (the ``_trace_rma`` idiom);
  * every ``_NT_EVENTS`` member (trace/native.py's NTE->region map —
    the python mirror the native pass already proves dense against the
    C enum) must carry a protocol region AND be covered by the
    cplane grammar, so a new NTE_* can't land without a conformance
    automaton learning it;
  * every ``rec_us``/``rec_since`` latency sample must name a
    ``_MET_HISTS`` histogram block — an unknown name is accepted and
    silently dropped by the writer, which is exactly the bug class.

The native.py-dependent checks skip quietly when trace/native.py is
not among the scanned modules (fixture runs)."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .conform import grammar_covers
from .core import Finding, LintPass, SourceModule


def _name_pattern(node: ast.AST) -> Optional[str]:
    """A record-name argument as a literal or prefix pattern; None =
    not resolvable from this expression alone."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        pat = "".join(str(part.value) if isinstance(part, ast.Constant)
                      else "*" for part in node.values)
        stars = pat.count("*")
        if stars == 0:
            return pat
        # one interpolation at either edge keeps its anchor (the
        # f"rma_{kind}" / f"{self.name}_send" idioms); anything
        # messier degrades to the full wildcard
        if stars == 1 and (pat.startswith("*") or pat.endswith("*")):
            return pat
        return "*"
    return None


def _arg(call: ast.Call, idx: int, kw: str) -> Optional[ast.AST]:
    if len(call.args) > idx:
        return call.args[idx]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


class EventCoveragePass(LintPass):
    id = "events"
    doc = ("tracer.record()/NTE/rec_us event names must be covered by "
           "a conformance automaton grammar (analysis/conform.py) and "
           "the metrics histogram table")

    # ------------------------------------------------------------------
    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        callindex = self._call_index(modules)
        native_mod = next((m for m in modules
                           if m.relpath.replace("\\", "/")
                           .endswith("trace/native.py")), None)
        hists = self._literal_tuple(native_mod, "_MET_HISTS") \
            if native_mod else None

        for mod in modules:
            parents = self._parents(mod.tree)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not isinstance(fn, ast.Attribute):
                    continue
                if fn.attr == "record":
                    out.extend(self._check_record(
                        mod, node, parents, callindex))
                elif fn.attr in ("rec_us", "rec_since") and \
                        hists is not None:
                    out.extend(self._check_hist(mod, node, hists))

        if native_mod is not None:
            out.extend(self._check_nt_events(native_mod))
        return [f for f in out if f is not None]

    # ------------------------------------------------------------------
    def _check_record(self, mod: SourceModule, call: ast.Call,
                      parents: Dict[ast.AST, ast.AST],
                      callindex: Dict[str, List[ast.Call]]
                      ) -> List[Optional[Finding]]:
        layer_node = _arg(call, 0, "layer")
        name_node = _arg(call, 1, "name")
        if layer_node is None or name_node is None:
            return []          # not the tracer signature
        layer = _name_pattern(layer_node)
        if layer is None or "*" in layer:
            return []          # dynamic layer: some other API's .record
        names = self._resolve(name_node, call, parents, callindex)
        out = []
        for name in names:
            if not grammar_covers(layer, name):
                out.append(self.finding(
                    mod, call.lineno,
                    f"tracer event [{layer}] {name} is outside every "
                    "conformance automaton's grammar — mv2tconform "
                    "would report it as grammar-coverage drift; teach "
                    "an automaton in analysis/conform.py (or "
                    "model/nbc.TRACE_EVENTS) this name"))
        return out

    def _resolve(self, node: ast.AST, call: ast.Call,
                 parents: Dict[ast.AST, ast.AST],
                 callindex: Dict[str, List[ast.Call]]) -> List[str]:
        """Record-name expression -> emitted name patterns. A bare
        parameter resolves one level through the enclosing function's
        call sites; anything deeper degrades to "*" (covered only by a
        wildcard-grammar layer, e.g. the mpi interposition lane)."""
        pat = _name_pattern(node)
        if pat is not None:
            return [pat]
        if isinstance(node, ast.Name):
            fdef = self._enclosing_def(call, parents)
            if fdef is not None and node.id in \
                    [a.arg for a in fdef.args.args]:
                idx = [a.arg for a in fdef.args.args].index(node.id)
                # drop self for method call sites
                meth = bool(fdef.args.args) and \
                    fdef.args.args[0].arg in ("self", "cls")
                pos = idx - (1 if meth else 0)
                pats = []
                for site in callindex.get(fdef.name, ()):
                    a = _arg(site, pos, node.id)
                    p = _name_pattern(a) if a is not None else None
                    pats.append(p if p is not None else "*")
                if pats:
                    return sorted(set(pats))
        return ["*"]

    # ------------------------------------------------------------------
    def _check_hist(self, mod: SourceModule, call: ast.Call,
                    hists: Tuple[str, ...]) -> List[Optional[Finding]]:
        if not call.args:
            return []
        pat = _name_pattern(call.args[0])
        if pat is None:
            return []
        if pat.endswith("*"):
            ok = any(h.startswith(pat[:-1]) for h in hists)
        else:
            ok = pat in hists
        if ok:
            return []
        return [self.finding(
            mod, call.lineno,
            f"latency sample {pat!r} names no _MET_HISTS histogram "
            "block (trace/native.py) — the writer accepts unknown "
            "names and silently drops the sample (the lat_dev_nbc "
            "bug class)")]

    # ------------------------------------------------------------------
    def _check_nt_events(self, mod: SourceModule
                         ) -> List[Optional[Finding]]:
        out = []
        for assign in ast.walk(mod.tree):
            if not isinstance(assign, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "_NT_EVENTS"
                       for t in assign.targets):
                continue
            if not isinstance(assign.value, (ast.List, ast.Tuple)):
                continue
            for elt in assign.value.elts:
                try:
                    name, region = ast.literal_eval(elt)
                except (ValueError, SyntaxError):
                    out.append(self.finding(
                        mod, elt.lineno,
                        "_NT_EVENTS entry is not a literal "
                        "(name, region) pair"))
                    continue
                if not region:
                    out.append(self.finding(
                        mod, elt.lineno,
                        f"NTE event {name!r} has no protocol region "
                        "in the NTE->region map"))
                if not grammar_covers("cplane", name):
                    out.append(self.finding(
                        mod, elt.lineno,
                        f"NTE event {name!r} is outside every "
                        "conformance automaton's cplane grammar — a "
                        "native emit nobody can verify; teach an "
                        "automaton in analysis/conform.py this name"))
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _call_index(modules: List[SourceModule]
                    ) -> Dict[str, List[ast.Call]]:
        """function-name -> every call site in the package (for the
        one-level wrapper-parameter resolution)."""
        index: Dict[str, List[ast.Call]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname:
                    index.setdefault(fname, []).append(node)
        return index

    @staticmethod
    def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
        return {child: parent for parent in ast.walk(tree)
                for child in ast.iter_child_nodes(parent)}

    @staticmethod
    def _enclosing_def(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                       ) -> Optional[ast.FunctionDef]:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    @staticmethod
    def _literal_tuple(mod: SourceModule, name: str
                       ) -> Optional[Tuple[str, ...]]:
        for assign in ast.walk(mod.tree):
            if isinstance(assign, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in assign.targets):
                try:
                    val = ast.literal_eval(assign.value)
                except (ValueError, SyntaxError):
                    return None
                return tuple(str(v) for v in val)
        return None

"""Pass ``device`` — DMA/semaphore discipline of the Pallas device lane.

The host shm protocol earned its verification net in PR 7 (the
``native`` pass + the model checker); this pass is the device half. The
kernel modules (ops/pallas_ici.py, ops/pallas_ring.py, rma/device.py)
drive raw Mosaic DMA: every ``make_async_copy``/``make_async_remote_copy``
is a contract with the hardware — an unawaited handle is a use-after-free
of a VMEM slot, an unpaired credit semaphore is the 64 MiB deadlock the
interpreter can never reproduce (jax<0.5 interpret mode is creditless).
Five invariant families, all syntactic:

  * **copy/wait pairing** — a handle bound from ``make_async_*copy`` and
    ``.start()``ed must reach a matching wait on every control-flow path
    out of the function (``.wait()``, or ``.wait_send()``+``.wait_recv()``
    for remote copies), or be *parked* into a pending container whose
    drain is checked module-wide. An early ``return`` past a started,
    unwaited handle is a finding — the classic kernel-exit race.
  * **park/drain** — every container that receives parked handles must
    have drain sites (wait on a popped / subscripted / iterated value);
    containers of remote handles must drain BOTH semaphores
    (``wait_send`` and ``wait_recv``, or a full ``wait``). A
    ``pending_*`` map that is never filled nor drained is dead
    device-protocol state (it lies to the watchdog's lane map).
  * **semaphore pairing** — per module, the set of credit semaphores
    that are ``semaphore_signal``ed must equal the set that is
    ``semaphore_wait``ed (a signal-only sem leaks credits; a wait-only
    sem is a guaranteed hang).
  * **interpret gates** — every credit-semaphore op must sit behind an
    explicit creditless gate (an ``if`` on a ``credits``-ish flag or a
    ``sem is None`` check), and the gate (or its def) must be annotated
    ``# device: hw-only`` so hardware-only code is marked in source —
    the 0.4.x interpreter cannot execute remote signals, so unmarked
    credit code is exactly the code no CI run has ever executed.
  * **VMEM budget** — scratch ``pltpu.VMEM((ndir, depth, chunk), ...)``
    allocations are evaluated against every committed configuration
    (the ICI_CHUNK_BYTES / ICI_PIPELINE_DEPTH cvar defaults parsed from
    mpit.py, plus each committed tuning profile's ici_chunk_bytes):
    a chunk-size/depth combination that cannot fit is a lint failure
    here, not a Mosaic OOM on the TPU host.

Annotation grammar (ordinary comments, same line as the code):

    def _grant(self, d):            # device: hw-only
    rdma.start()                    # device: escapes  (handle outlives
                                    # the static scan — last resort)
    x.start()                       # mv2tlint: ignore[device]

``device_lane_map()`` exports the harvested park/drain/semaphore map for
the stall watchdog and ``mpistat --device-map`` — the device analog of
the native pass's ``shared_field_map``.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, LintPass, PKG_ROOT, SourceModule, const_int,
                   parent_map, scan_paths, terminal_name)

_COPY_CTORS = {"make_async_copy": False, "make_async_remote_copy": True}
_WAITS = {"wait", "wait_send", "wait_recv"}
_SEM_OPS = {"semaphore_signal", "semaphore_wait"}

# The scratch-budget ceiling: ~16 MiB of VMEM per core, minus headroom
# for the kernel's own working set (the reduce reads one recv chunk and
# one acc chunk beyond the slot arrays). Itemsize is evaluated at 4
# bytes — the widest dtype the kernels accept with x64 off.
DEVICE_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_BUDGET_ITEMSIZE = 4

PROFILE_DIR = os.path.join(PKG_ROOT, "profiles")


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _sem_operand_name(node: ast.AST) -> Optional[str]:
    """Terminal semaphore name of a ``sem`` / ``sem.at[i]`` operand."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr == "at":
        node = node.value
    return terminal_name(node)


def _credit_gate_test(test: ast.AST) -> bool:
    """True when an ``if`` test reads as a creditless gate: any name
    containing 'credit', or an ``is (not) None`` probe of a *sem name."""
    for sub in ast.walk(test):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            t = terminal_name(sub)
            if t and "credit" in t.lower():
                return True
        if isinstance(sub, ast.Compare) \
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops):
            t = terminal_name(sub.left)
            if t and ("sem" in t.lower() or "credit" in t.lower()):
                return True
    return False


def _is_device_module(mod: SourceModule) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _COPY_CTORS or name in _SEM_OPS or name == "VMEM":
                return True
    return False


# ---------------------------------------------------------------------------
# copy/wait flow analysis (per function)
# ---------------------------------------------------------------------------

class _HState:
    """One tracked handle inside one function."""

    __slots__ = ("line", "remote", "started", "discharged", "waits")

    def __init__(self, line: int, remote: bool):
        self.line = line
        self.remote = remote
        self.started = False
        self.discharged = False
        self.waits: Set[str] = set()

    def copy(self) -> "_HState":
        h = _HState(self.line, self.remote)
        h.started, h.discharged = self.started, self.discharged
        h.waits = set(self.waits)
        return h

    def note_wait(self, kind: str) -> None:
        self.waits.add(kind)
        if "wait" in self.waits:
            self.discharged = True
        elif self.remote and {"wait_send", "wait_recv"} <= self.waits:
            self.discharged = True


def _copy_live(live: Dict[str, _HState]) -> Dict[str, _HState]:
    return {k: v.copy() for k, v in live.items()}


def _merge(a: Dict[str, _HState], b: Dict[str, _HState]) -> Dict[str, _HState]:
    out: Dict[str, _HState] = {}
    for name in set(a) | set(b):
        ha, hb = a.get(name), b.get(name)
        if ha is None or hb is None:
            out[name] = (ha or hb).copy()
            continue
        h = ha.copy()
        h.started = ha.started or hb.started
        h.discharged = ha.discharged and hb.discharged
        h.waits = ha.waits & hb.waits
        out[name] = h
    return out


class DevicePass(LintPass):
    id = "device"
    doc = ("Pallas DMA discipline: copy handles waited on every path, "
           "pending maps drained, credit semaphores paired + hw-only "
           "gated, VMEM scratch budget fits every committed config")

    def __init__(self, profiles: Optional[List[str]] = None):
        # profiles: tuning-profile JSONs whose ici_chunk_bytes feed the
        # budget estimator; None = the committed profiles/ directory
        self.profiles = profiles

    # ------------------------------------------------------------------
    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        dev_mods = [m for m in modules if _is_device_module(m)]
        configs = self._budget_configs(modules)
        for mod in dev_mods:
            parks: Dict[str, dict] = {}
            drains: Dict[str, Set[str]] = {}
            self._check_unbound(mod, out)
            self._harvest_parks_and_flow(mod, parks, out)
            self._harvest_drains(mod, drains)
            self._check_containers(mod, parks, drains, out)
            self._check_dead_pending(mod, parks, drains, out)
            self._check_semaphores(mod, out)
            self._check_vmem_budget(mod, configs, out)
        return out

    # -- unbound constructor calls -------------------------------------
    def _check_unbound(self, mod: SourceModule, out: List[Finding]) -> None:
        parents = parent_map(mod.tree)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) in _COPY_CTORS):
                continue
            par = parents.get(node)
            bound = isinstance(par, (ast.Assign, ast.AnnAssign)) \
                and getattr(par, "value", None) is node
            if bound:
                continue
            if isinstance(par, ast.Return):
                continue        # handed to the caller — their contract
            f = self.finding(mod, node.lineno,
                             f"async copy '{_call_name(node)}' is never "
                             "bound to a handle — its wait is "
                             "unreachable")
            if f is not None:
                out.append(f)

    # -- flow analysis + park harvesting -------------------------------
    def _harvest_parks_and_flow(self, mod: SourceModule,
                                parks: Dict[str, dict],
                                out: List[Finding]) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._flow_fn(mod, node, parks, out)

    def _flow_fn(self, mod: SourceModule, fn, parks: Dict[str, dict],
                 out: List[Finding]) -> None:
        reported: Set[str] = set()

        def report(name: str, h: _HState, line: int) -> None:
            if name in reported:
                return
            reported.add(name)
            kind = "remote copy" if h.remote else "copy"
            f = self.finding(mod, line,
                             f"async {kind} '{name}' (started in "
                             f"'{fn.name}') can exit without a "
                             "matching wait on this path")
            if f is not None:
                out.append(f)

        def park(container: str, remote: bool, line: int) -> None:
            info = parks.setdefault(container, {"remote": False,
                                                "lines": []})
            info["remote"] = info["remote"] or remote
            info["lines"].append(line)

        def handle_call(call: ast.Call, live: Dict[str, _HState]) -> None:
            fnode = call.func
            if not isinstance(fnode, ast.Attribute):
                return
            recv = fnode.value
            name = recv.id if isinstance(recv, ast.Name) else None
            if name is None or name not in live:
                return
            h = live[name]
            if fnode.attr == "start":
                h.started = True
            elif fnode.attr in _WAITS:
                h.note_wait(fnode.attr)

        def stmt(st, live: Dict[str, _HState]) -> Tuple[Dict, bool]:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return live, False      # separate contract
            if isinstance(st, (ast.Assign, ast.AnnAssign)):
                value = st.value
                targets = st.targets if isinstance(st, ast.Assign) \
                    else ([st.target] if st.value is not None else [])
                if isinstance(value, ast.Call) \
                        and _call_name(value) in _COPY_CTORS:
                    remote = _COPY_CTORS[_call_name(value)]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            if not mod.suppressed(st.lineno, self.id) \
                                    and mod.annotation(st.lineno,
                                                       "device") \
                                    != "escapes":
                                live[t.id] = _HState(st.lineno, remote)
                        elif isinstance(t, (ast.Subscript, ast.Attribute)):
                            c = terminal_name(t.value) if isinstance(
                                t, ast.Subscript) else t.attr
                            if c:
                                park(c, remote, st.lineno)
                    return live, False
                if isinstance(value, ast.Name) and value.id in live:
                    for t in targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)):
                            c = terminal_name(t.value) if isinstance(
                                t, ast.Subscript) else t.attr
                            if c:
                                park(c, live[value.id].remote, st.lineno)
                                live[value.id].discharged = True
                return live, False
            if isinstance(st, (ast.Return, ast.Raise)):
                for name, h in live.items():
                    if h.started and not h.discharged:
                        report(name, h, st.lineno)
                return live, True
            if isinstance(st, ast.If):
                lt, et = seq(st.body, _copy_live(live))
                lf, ef = seq(st.orelse, _copy_live(live))
                if et and ef:
                    return live, True
                if et:
                    return lf, False
                if ef:
                    return lt, False
                return _merge(lt, lf), False
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                lb, _eb = seq(st.body, _copy_live(live))
                live = _merge(live, lb)
                if st.orelse:
                    live, ex = seq(st.orelse, live)
                    return live, ex
                return live, False
            if isinstance(st, (ast.With, ast.AsyncWith)):
                return seq(st.body, live)
            if isinstance(st, ast.Try):
                lb, eb = seq(st.body, _copy_live(live))
                merged = lb if not eb else _copy_live(live)
                for handler in st.handlers:
                    lh, eh = seq(handler.body, _copy_live(live))
                    if not eh:
                        merged = _merge(merged, lh)
                if st.orelse:
                    merged, _ = seq(st.orelse, merged)
                if st.finalbody:
                    merged, ex = seq(st.finalbody, merged)
                    return merged, ex
                return merged, False
            # expression statements and everything else: scan calls
            for sub in ast.walk(st):
                if isinstance(sub, ast.Call):
                    handle_call(sub, live)
            return live, False

        def seq(stmts, live: Dict[str, _HState]) -> Tuple[Dict, bool]:
            exited = False
            for st in stmts:
                live, exited = stmt(st, live)
                if exited:
                    break
            return live, exited

        live, exited = seq(fn.body, {})
        if not exited:
            last = fn.body[-1]
            line = getattr(last, "end_lineno", None) or last.lineno
            for name, h in live.items():
                if h.started and not h.discharged:
                    report(name, h, line)

    # -- drains ---------------------------------------------------------
    def _harvest_drains(self, mod: SourceModule,
                        drains: Dict[str, Set[str]]) -> None:
        # name -> container, for `h = X.pop(...)` and `for k, h in
        # X.items()` bindings (possibly wrapped in list()/tuple()/sorted())
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            bound: Dict[str, str] = {}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call):
                    c = self._pop_container(sub.value)
                    if c:
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                bound[t.id] = c
                if isinstance(sub, (ast.For, ast.AsyncFor)):
                    c = self._iter_container(sub.iter)
                    if c is None:
                        continue
                    targets = sub.target.elts if isinstance(
                        sub.target, ast.Tuple) else [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            bound[t.id] = c
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _WAITS):
                    continue
                recv = sub.func.value
                c = None
                if isinstance(recv, ast.Subscript):
                    c = terminal_name(recv.value)
                elif isinstance(recv, ast.Call):
                    c = self._pop_container(recv)
                elif isinstance(recv, ast.Name):
                    c = bound.get(recv.id)
                elif isinstance(recv, ast.Attribute):
                    c = recv.attr
                if c:
                    drains.setdefault(c, set()).add(sub.func.attr)

    @staticmethod
    def _pop_container(call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "pop":
            return terminal_name(fn.value)
        return None

    @staticmethod
    def _iter_container(it: ast.AST) -> Optional[str]:
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("list", "tuple", "sorted") and it.args:
            it = it.args[0]
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "values"):
            return terminal_name(it.func.value)
        return None

    # -- container adequacy ---------------------------------------------
    def _check_containers(self, mod: SourceModule, parks: Dict[str, dict],
                          drains: Dict[str, Set[str]],
                          out: List[Finding]) -> None:
        for name, info in sorted(parks.items()):
            line = info["lines"][0]
            kinds = drains.get(name, set())
            if not kinds:
                f = self.finding(mod, line,
                                 f"handles parked into '{name}' are "
                                 "never drained (no wait on a popped/"
                                 "subscripted/iterated value)")
                if f is not None:
                    out.append(f)
                continue
            if info["remote"] and "wait" not in kinds \
                    and not {"wait_send", "wait_recv"} <= kinds:
                missing = sorted({"wait_send", "wait_recv"} - kinds)
                f = self.finding(mod, line,
                                 f"remote handles parked into '{name}' "
                                 f"drain only {sorted(kinds)} — missing "
                                 f"{missing} (both DMA semaphores must "
                                 "be consumed)")
                if f is not None:
                    out.append(f)

    def _check_dead_pending(self, mod: SourceModule, parks: Dict[str, dict],
                            drains: Dict[str, Set[str]],
                            out: List[Finding]) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Dict) and not value.keys):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                name = t.attr if isinstance(t, ast.Attribute) else \
                    (t.id if isinstance(t, ast.Name) else None)
                if name is None or not name.startswith("pending"):
                    continue
                if name in parks or name in drains:
                    continue
                f = self.finding(mod, node.lineno,
                                 f"pending-handle map '{name}' is never "
                                 "filled or drained — dead device-"
                                 "protocol state (it lies to the "
                                 "watchdog lane map)")
                if f is not None:
                    out.append(f)

    # -- credit semaphores ----------------------------------------------
    def _sem_sites(self, mod: SourceModule):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _call_name(node) in _SEM_OPS \
                    and node.args:
                sem = _sem_operand_name(node.args[0])
                if sem:
                    yield node, _call_name(node), sem

    def _check_semaphores(self, mod: SourceModule,
                          out: List[Finding]) -> None:
        signals: Dict[str, int] = {}
        waits: Dict[str, int] = {}
        for node, op, sem in self._sem_sites(mod):
            (signals if op == "semaphore_signal" else waits).setdefault(
                sem, node.lineno)
        for sem, line in sorted(signals.items()):
            if sem not in waits:
                f = self.finding(mod, line,
                                 f"semaphore '{sem}' is signaled but "
                                 "never waited in this module — leaked "
                                 "credits")
                if f is not None:
                    out.append(f)
        for sem, line in sorted(waits.items()):
            if sem not in signals:
                f = self.finding(mod, line,
                                 f"semaphore '{sem}' is waited but "
                                 "never signaled in this module — a "
                                 "guaranteed hang")
                if f is not None:
                    out.append(f)
        # every credit op behind an annotated creditless gate
        parents = parent_map(mod.tree)
        seen_gates: Set[Tuple[int, str]] = set()
        for node, op, sem in self._sem_sites(mod):
            gate_line = self._gate_line(node, parents)
            if gate_line is None:
                f = self.finding(mod, node.lineno,
                                 f"credit-semaphore op on '{sem}' has "
                                 "no creditless gate — interpret mode "
                                 "(jax<0.5) cannot execute it")
                if f is not None:
                    out.append(f)
                continue
            if (gate_line, sem) in seen_gates:
                continue
            seen_gates.add((gate_line, sem))
            fn = self._enclosing_fn(node, parents)
            annotated = mod.annotation(gate_line, "device") == "hw-only" \
                or (fn is not None
                    and mod.annotation(fn.lineno, "device") == "hw-only")
            if not annotated:
                f = self.finding(mod, gate_line,
                                 f"creditless gate for '{sem}' is not "
                                 "annotated '# device: hw-only' — "
                                 "hardware-only code must be marked")
                if f is not None:
                    out.append(f)

    @staticmethod
    def _enclosing_fn(node: ast.AST, parents):
        while node is not None:
            node = parents.get(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def _gate_line(self, node: ast.AST, parents) -> Optional[int]:
        """Line of the creditless gate covering ``node``: an enclosing
        ``if`` with a credit-ish test, or an earlier top-level
        early-return gate in the same function."""
        cur = node
        fn = None
        while cur is not None:
            par = parents.get(cur)
            if isinstance(par, ast.If) and _credit_gate_test(par.test):
                return par.lineno
            if isinstance(par, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = par
                break
            cur = par
        if fn is None:
            return None
        for st in fn.body:
            if st.lineno >= node.lineno:
                break
            if isinstance(st, ast.If) and _credit_gate_test(st.test) \
                    and st.body and isinstance(st.body[-1], ast.Return):
                return st.lineno
        return None

    # -- VMEM budget -----------------------------------------------------
    def _budget_configs(self, modules: List[SourceModule]):
        """[(label, chunk_bytes, depth)] from the cvar defaults in
        mpit.py and every committed profile's ici_chunk_bytes."""
        chunk_default, depth_default = 256 * 1024, 2
        for mod in modules:
            if not mod.relpath.endswith("mvapich2_tpu/mpit.py"):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and _call_name(node) == "cvar" \
                        and len(node.args) >= 2 \
                        and isinstance(node.args[0], ast.Constant):
                    v = const_int(node.args[1])
                    if node.args[0].value == "ICI_CHUNK_BYTES" \
                            and v is not None:
                        chunk_default = v
                    elif node.args[0].value == "ICI_PIPELINE_DEPTH" \
                            and v is not None:
                        depth_default = v
        configs = [("cvar defaults (mpit.py)", chunk_default,
                    depth_default)]
        paths = self.profiles
        if paths is None:
            try:
                paths = sorted(
                    os.path.join(PROFILE_DIR, f)
                    for f in os.listdir(PROFILE_DIR) if f.endswith(".json"))
            except OSError:
                paths = []
        for p in paths:
            try:
                with open(p) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue          # the profile doctor reports malformed files
            if doc.get("format") != "mv2t-tuning-profile-v1":
                continue
            kp = doc.get("profile", {}).get("kernel_params", {})
            cb = kp.get("ici_chunk_bytes")
            if isinstance(cb, int) and cb > 0:
                configs.append((os.path.basename(p), cb, depth_default))
        return configs

    def _check_vmem_budget(self, mod: SourceModule, configs,
                           out: List[Finding]) -> None:
        bufs = []           # (line, [dim names/ints])
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) == "VMEM" and node.args):
                continue
            shape = node.args[0]
            if not isinstance(shape, ast.Tuple):
                continue
            dims = []
            for el in shape.elts:
                if isinstance(el, ast.Name):
                    dims.append(el.id)
                else:
                    v = const_int(el)
                    dims.append(v if v is not None else 1)
            bufs.append((node.lineno, dims))
        if not bufs:
            return
        for label, chunk_bytes, depth in configs:
            total = 0
            for _line, dims in bufs:
                size = _BUDGET_ITEMSIZE
                for d in dims:
                    if isinstance(d, int):
                        size *= d
                    elif "chunk" in d:
                        size *= max(1, chunk_bytes // _BUDGET_ITEMSIZE)
                    elif "depth" in d:
                        size *= depth
                    elif "ndir" in d or "dir" in d:
                        size *= 2
                    # unknown symbolic dims count as 1 — the estimator
                    # under-approximates rather than cry wolf
                total += size
            if total > DEVICE_VMEM_BUDGET_BYTES:
                f = self.finding(
                    mod, bufs[0][0],
                    f"VMEM scratch budget {total} bytes under config "
                    f"'{label}' (chunk={chunk_bytes}, depth={depth}) "
                    f"exceeds the {DEVICE_VMEM_BUDGET_BYTES}-byte tier "
                    "cap — this combination cannot compile")
                if f is not None:
                    out.append(f)
                break      # one finding per module: name the first
                           # offending config, not every config


# ---------------------------------------------------------------------------
# the exported lane map (watchdog / mpistat parity with shared_field_map)
# ---------------------------------------------------------------------------

_DEVICE_DIRS = ("ops", "rma")
_lane_map_cache: Optional[Dict[str, dict]] = None


def device_lane_map(refresh: bool = False) -> Dict[str, dict]:
    """{name: info} for every pending-handle container and credit
    semaphore of the committed device modules, harvested by the same
    AST walk the lint pass runs — the device analog of the native
    pass's ``shared_field_map``. Keys:

      containers: kind='pending-map', remote, drains=[wait kinds], module
      semaphores: kind='credit-sem', signals/waits (site counts), module
    """
    global _lane_map_cache
    if _lane_map_cache is not None and not refresh:
        return _lane_map_cache
    out: Dict[str, dict] = {}
    p = DevicePass(profiles=[])
    for d in _DEVICE_DIRS:
        root = os.path.join(PKG_ROOT, d)
        if not os.path.isdir(root):
            continue
        modules, _errs = scan_paths([root])
        for mod in modules:
            if not _is_device_module(mod):
                continue
            parks: Dict[str, dict] = {}
            drains: Dict[str, Set[str]] = {}
            p._harvest_parks_and_flow(mod, parks, [])
            p._harvest_drains(mod, drains)
            for name, info in parks.items():
                out[name] = {"kind": "pending-map",
                             "remote": info["remote"],
                             "drains": sorted(drains.get(name, ())),
                             "module": mod.relpath}
            sig: Dict[str, int] = {}
            wai: Dict[str, int] = {}
            for _node, op, sem in p._sem_sites(mod):
                tgt = sig if op == "semaphore_signal" else wai
                tgt[sem] = tgt.get(sem, 0) + 1
            for sem in set(sig) | set(wai):
                key = sem if sem not in out else f"{sem}@{mod.relpath}"
                out[key] = {"kind": "credit-sem",
                            "signals": sig.get(sem, 0),
                            "waits": wai.get(sem, 0),
                            "module": mod.relpath}
    _lane_map_cache = out
    return out

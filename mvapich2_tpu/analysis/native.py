"""Pass ``native`` — atomic discipline + layout consistency for the C
sources of the native datapath.

PRs 5-6 moved the hottest protocol logic into lock-free C
(native/cplane.cpp seqlock flat waves, doorbell waits, liveness leases;
native/mpi/fastpath.c; native/shmring.cpp SPSC rings). This pass gives
those files the same opt-in invariant surface the Python half has had
since PR 4. It is deliberately lexical — a tokenizer plus a
statement splitter, not a C parser — because every checked idiom is
local to one statement and the annotation tells us *which* words are
shared.

Annotation grammar (C comments, attached to the declaration's first
line):

    /* shared: atomic */             every access to the declared word
    /* shared: atomic(<region>) */   must ride __atomic_*/std::atomic
                                     with an EXPLICIT memory order
    /* shared: seqlock(<region>) */  on data: same discipline; on an
                                     accessor function returning a
                                     pointer to protocol words: every
                                     call site must be wrapped by an
                                     atomic load/store (or a vetted
                                     consumer, below)
    /* shared: guarded-by(<lock>) */ accesses only between
                                     pthread_mutex_lock(&..<lock>) and
                                     the matching unlock (or inside a
                                     function annotated /* holds: <lock> */)
    /* shared: counter(<why>) */     plain accesses tolerated — a stats
                                     word with one natural writer; the
                                     rationale is REQUIRED
    /* shared-ok: <why> */           on a function definition: vetted
                                     consumer of shared words (e.g. the
                                     flat_wait park loop) — its call
                                     sites bless the statement
    /* mv2tlint: native-init */      on a function definition: the whole
                                     body is single-threaded
                                     init/teardown, exempt
    // mv2tlint: ignore[native] why  per-line escape (PR-4 syntax)

Sub-checks (all report under pass id ``native``):
  * plain-access    — a shared word touched outside the atomic idiom
                      (covers the "lease/doorbell words must never be
                      plain or volatile-only" rule: volatile carries no
                      idiom token)
  * memory-order    — __atomic_* builtin without an explicit __ATOMIC_
                      order, or a std::atomic method without an explicit
                      std::memory_order (C11 atomic_* generics keep
                      their well-defined seq_cst default)
  * seqlock-pair    — a seqlock region must have BOTH a release-store
                      writer site and an acquire-load reader site, and
                      at least one reader must re-check in a loop
  * layout          — cross-language layout constants: shm_layout.h
                      #defines / the FPC enum vs the Python mirrors
                      (transport/shm.py ring + lease constants and
                      _FP_COUNTERS, transport/base.py packet header,
                      runtime/universe.py CTX_MASK_BASE)

Atomic wrapper functions (fl_load/fl_store) are auto-detected: a
function whose body is a single return of __atomic_load_n/__atomic_store_n
with an explicit order becomes a blessed idiom token.
"""

from __future__ import annotations

import ast
import os
import re
import struct as _struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintPass, REPO_ROOT, SourceModule

# the native file set the tier-1 gate lints (repo-relative)
NATIVE_SOURCES = [
    "native/shm_layout.h",
    "native/shmring.cpp",
    "native/cplane.cpp",
    "native/mpi/fastpath.c",
]

LAYOUT_HEADER = "native/shm_layout.h"

_SHARED_RE = re.compile(r"shared:\s*([a-z-]+)\s*(?:\(([^)]*)\))?")
_SHARED_OK_RE = re.compile(r"shared-ok:\s*(.+)")
_NATIVE_INIT_RE = re.compile(r"mv2tlint:\s*native-init")
_IGNORE_RE = re.compile(r"mv2tlint:\s*ignore(?:\[([a-z, -]+)\])?")

_ATOMIC_BUILTIN_RE = re.compile(r"__atomic_\w+\s*\(")
_STD_METHOD_RE = re.compile(
    r"\.\s*(load|store|exchange|fetch_\w+|compare_exchange\w*)\s*\(")
_CTRL_KEYWORDS = {"if", "while", "for", "switch", "catch", "return",
                  "sizeof", "do", "else"}


# ---------------------------------------------------------------------------
# C source model: comment stripping + statement splitting + function map
# ---------------------------------------------------------------------------

@dataclass
class CStatement:
    line: int                 # first line of the statement
    text: str                 # code text, comments stripped
    func: Optional[str]       # enclosing function name (None = file scope)


@dataclass
class SharedDecl:
    name: str
    kind: str                 # atomic | seqlock | guarded-by | counter
    region: Optional[str]     # seqlock region / atomic group / lock name
    line: int
    pointer: bool = False     # declared as a pointer: only derefs checked
    std_atomic: bool = False  # std::atomic<...>: method discipline
    is_func: bool = False     # accessor function (seqlock pointer source)
    member: bool = False      # struct/class member: accessed via -> or .
                              # only (a bare name is a shadowing local)


class CSource:
    """One C/C++ file: comment map, per-line suppressions, statements."""

    def __init__(self, path: str, text: Optional[str] = None):
        self.path = os.path.abspath(path)
        self.relpath = os.path.relpath(self.path, REPO_ROOT)
        if self.relpath.startswith(".."):
            self.relpath = os.path.basename(self.path)
        if text is None:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                text = f.read()
        self.text = text
        self.code, self.comments = self._split_comments(text)
        # line -> suppressed pass ids ({"*"} = all). A comment suppresses
        # the line it STARTS on (same as the Python side).
        self.ignores: Dict[int, Set[str]] = {}
        for line, c in self.comments.items():
            m = _IGNORE_RE.search(c)
            if m:
                which = m.group(1)
                self.ignores[line] = ({"*"} if which is None else
                                      {p.strip() for p in which.split(",")})
        # preprocessor directives (incl. \-continuations) are not C
        # statements: blank them for the splitter so a macro body cannot
        # merge into the following declaration. Macro bodies are out of
        # the discipline's scope by design.
        nopp = re.sub(r"^[ \t]*#(?:[^\n\\]|\\\n|\\.)*",
                      lambda m: re.sub(r"[^\n]", " ", m.group(0)),
                      self.code, flags=re.M)
        (self.statements, self.func_of_line,
         self.struct_of_line) = self._split_statements(nopp)

    @staticmethod
    def _split_comments(text: str) -> Tuple[str, Dict[int, str]]:
        """Blank out comments (and string literals) in ``code`` while
        preserving offsets; collect comment text keyed by start line."""
        out = list(text)
        comments: Dict[int, str] = {}
        i, n = 0, len(text)
        line = 1

        def blank(a: int, b: int) -> None:
            for k in range(a, b):
                if out[k] != "\n":
                    out[k] = " "

        while i < n:
            c = text[i]
            if c == "\n":
                line += 1
                i += 1
            elif text.startswith("//", i):
                j = text.find("\n", i)
                j = n if j < 0 else j
                comments[line] = (comments.get(line, "") + " "
                                  + text[i + 2:j]).strip()
                blank(i, j)
                i = j
            elif text.startswith("/*", i):
                j = text.find("*/", i + 2)
                j = n if j < 0 else j + 2
                body = text[i + 2:j - 2 if j <= n else n]
                comments[line] = (comments.get(line, "") + " "
                                  + re.sub(r"\s*\n\s*\*?\s*", " ",
                                           body)).strip()
                blank(i, j)
                line += text.count("\n", i, j)
                i = j
            elif c in "\"'":
                q = c
                j = i + 1
                while j < n and text[j] != q:
                    j += 2 if text[j] == "\\" else 1
                j = min(j + 1, n)
                blank(i + 1, j - 1)
                i = j
            else:
                i += 1
        return "".join(out), comments

    @staticmethod
    def _split_statements(code: str):
        """Split stripped code into statements on ; { } with enclosing-
        function tracking (a '{' directly after ')' opens a function
        when we are not already inside one)."""
        statements: List[CStatement] = []
        func_of_line: Dict[int, Optional[str]] = {}
        struct_of_line: Dict[int, bool] = {}
        line = 1
        start_line = 1
        buf: List[str] = []
        func: Optional[str] = None
        func_depth = 0
        struct_depths: List[int] = []   # depths of open struct/class scopes
        depth = 0

        def flush() -> None:
            nonlocal buf, start_line
            text = " ".join("".join(buf).split())
            if text:
                statements.append(CStatement(start_line, text, func))
            buf = []

        for ch in code:
            if ch == "\n":
                func_of_line[line] = func
                struct_of_line[line] = bool(struct_depths)
                line += 1
                if buf:
                    buf.append(" ")
                continue
            if ch == ";":
                flush()
                start_line = line
                continue
            if ch == "{":
                sig = " ".join("".join(buf).split())
                flush()
                start_line = line
                depth += 1
                if re.search(r"\b(struct|class|union)\s+\w*\s*$", sig) \
                        or re.search(r"\b(struct|class|union)\s*$", sig):
                    struct_depths.append(depth)
                elif func is None and sig.endswith(")"):
                    m = re.search(r"(\w+)\s*\([^()]*(?:\([^()]*\)[^()]*)*\)$",
                                  sig)
                    if m and m.group(1) not in _CTRL_KEYWORDS:
                        func = m.group(1)
                        func_depth = depth
                continue
            if ch == "}":
                flush()
                start_line = line
                if func is not None and depth == func_depth:
                    func = None
                if struct_depths and depth == struct_depths[-1]:
                    struct_depths.pop()
                depth = max(0, depth - 1)
                continue
            if not buf:
                if ch in " \t":
                    continue
                start_line = line
            buf.append(ch)
        flush()
        return statements, func_of_line, struct_of_line


# ---------------------------------------------------------------------------
# annotation harvesting
# ---------------------------------------------------------------------------

_DECL_RE = re.compile(
    r"""(?P<type>[A-Za-z_][\w:<>,\s]*?
         (?:\s|\*|&))\s*
        (?P<name>[A-Za-z_]\w*)\s*
        (?P<array>\[[^\]]*\])?\s*
        (?:=[^;]*)?;""", re.VERBOSE)
_FUNC_DEF_RE = re.compile(r"(?P<name>[A-Za-z_]\w*)\s*\([^;{]*\)\s*\{")


def _decl_on(src: CSource, line: int):
    """The declaration the annotation on ``line`` attaches to: the same
    code line, or — when the annotation rides a standalone comment — the
    next non-blank code line; joined across continuations up to the
    first ; or { (whichever comes first)."""
    lines = src.code.split("\n")
    start = line - 1
    while start < len(lines) and not lines[start].strip():
        start += 1
    chunk = " ".join(lines[start:start + 6])
    cuts = [k for k in (chunk.find(";"), chunk.find("{")) if k >= 0]
    if cuts:
        chunk = chunk[:min(cuts) + 1]
    return " ".join(chunk.split())


def harvest(src: CSource) -> Tuple[Dict[str, SharedDecl], Set[str], Set[str]]:
    """(shared decls by name, shared-ok function names, native-init
    function names) from the file's annotations."""
    decls: Dict[str, SharedDecl] = {}
    ok_funcs: Set[str] = set()
    init_funcs: Set[str] = set()
    for line, comment in sorted(src.comments.items()):
        if _NATIVE_INIT_RE.search(comment):
            decl = _decl_on(src, line)
            m = _FUNC_DEF_RE.search(decl)
            if m:
                init_funcs.add(m.group("name"))
            continue
        if _SHARED_OK_RE.search(comment):
            decl = _decl_on(src, line)
            m = _FUNC_DEF_RE.search(decl)
            if m:
                ok_funcs.add(m.group("name"))
            continue
        m = _SHARED_RE.search(comment)
        if not m:
            continue
        kind, region = m.group(1), m.group(2)
        # the annotation may trail a multi-line declaration: find the
        # declaration line by scanning back to the statement start
        decl_line = line
        decl = _decl_on(src, decl_line)
        fm = _FUNC_DEF_RE.search(decl)
        if fm and kind == "seqlock":
            decls[fm.group("name")] = SharedDecl(
                fm.group("name"), kind, region, decl_line, is_func=True)
            continue
        dm = _DECL_RE.search(decl)
        if not dm:
            continue
        name = dm.group("name")
        typ = dm.group("type")
        decls[name] = SharedDecl(
            name, kind, region, decl_line,
            pointer="*" in typ and "atomic" not in typ,
            std_atomic="atomic<" in typ.replace(" ", ""),
            member=src.struct_of_line.get(decl_line, False))
    return decls, ok_funcs, init_funcs


def auto_wrappers(src: CSource) -> Set[str]:
    """Functions whose body is a single __atomic load/store with an
    explicit order (fl_load / fl_store): blessed idiom tokens."""
    out: Set[str] = set()
    by_func: Dict[str, List[CStatement]] = {}
    for st in src.statements:
        if st.func:
            by_func.setdefault(st.func, []).append(st)
    for fn, sts in by_func.items():
        real = [s for s in sts if "__atomic_" in s.text]
        if len(real) >= 1 and len(sts) <= 2 and all(
                "__ATOMIC_" in s.text for s in real):
            out.add(fn)
    return out


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

class NativeSourcePass(LintPass):
    id = "native"
    doc = ("C-plane atomic discipline (shared: annotations), explicit "
           "memory orders, seqlock pairing, cross-language layout")

    def __init__(self, sources: Optional[List[str]] = None,
                 layout: bool = True,
                 layout_header: Optional[str] = None):
        # default: the committed native file set (repo-relative)
        if sources is None:
            sources = [os.path.join(REPO_ROOT, p) for p in NATIVE_SOURCES]
        self.sources = [p for p in sources if os.path.exists(p)]
        self.layout = layout
        self.layout_header = layout_header or os.path.join(REPO_ROOT,
                                                           LAYOUT_HEADER)

    # -- entry ----------------------------------------------------------
    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        seq_sites: Dict[str, Dict[str, List[Tuple[CSource, CStatement]]]] = {}
        for path in self.sources:
            try:
                src = CSource(path)
            except OSError as e:
                out.append(Finding(self.id, os.path.basename(path), 0,
                                   f"unreadable: {e!s:.80}"))
                continue
            self._check_file(src, out, seq_sites)
        self._check_seqlock_pairing(seq_sites, out)
        if self.layout:
            self._check_layout(out)
        out.sort(key=lambda f: (f.path, f.line, f.msg))
        return out

    def _finding(self, src: CSource, line: int, msg: str,
                 out: List[Finding]) -> None:
        ign = src.ignores.get(line)
        if ign and ("*" in ign or self.id in ign):
            return
        out.append(Finding(self.id, src.relpath, line, msg))

    # -- per-file discipline -------------------------------------------
    def _check_file(self, src: CSource, out: List[Finding],
                    seq_sites) -> None:
        decls, ok_funcs, init_funcs = harvest(src)
        wrappers = auto_wrappers(src)
        blessed = ({"__atomic_"} | {w + "(" for w in wrappers}
                   | {f + "(" for f in ok_funcs})

        # counter annotations must carry a rationale
        for d in decls.values():
            if d.kind == "counter" and not (d.region or "").strip():
                self._finding(src, d.line,
                              f"counter '{d.name}' needs an inline "
                              "rationale: shared: counter(<why>)", out)

        # guarded-by lock-window tracking, per function
        lock_state: Dict[Tuple[Optional[str], str], int] = {}

        for st in src.statements:
            text = st.text
            if st.func in init_funcs:
                continue
            # lock windows for guarded-by
            for lm in re.finditer(r"pthread_mutex_(lock|unlock)\s*\(\s*&?"
                                  r"[\w.\->]*?(\w+)\s*\)", text):
                key = (st.func, lm.group(2))
                lock_state[key] = (lock_state.get(key, 0)
                                   + (1 if lm.group(1) == "lock" else -1))

            # memory-order explicitness (file-wide, annotation-free)
            if _ATOMIC_BUILTIN_RE.search(text) and "__ATOMIC_" not in text:
                self._finding(src, st.line,
                              "__atomic_* call without an explicit "
                              f"__ATOMIC_* memory order: '{text[:60]}'",
                              out)
            sm = _STD_METHOD_RE.search(text)
            if sm and "memory_order" not in text \
                    and "__ATOMIC_" not in text:
                self._finding(src, st.line,
                              f"std::atomic .{sm.group(1)}() without an "
                              "explicit std::memory_order: "
                              f"'{text[:60]}'", out)

            # shared-word discipline
            for d in decls.values():
                if d.is_func:
                    # seqlock accessor call sites. A file-scope statement
                    # ending at the parameter list is the accessor's own
                    # definition signature, not a call.
                    for m in re.finditer(rf"\b{d.name}\s*\(", text):
                        if m.start() > 0 and text[m.start() - 1] in "_.":
                            continue
                        if st.func is None and text.endswith(")"):
                            continue
                        before = text[:m.start()]
                        wrapped = any(tok in before for tok in blessed)
                        consumer = any(f + "(" in before
                                       for f in ok_funcs)
                        store = any(w + "(" in before
                                    for w in wrappers
                                    if "store" in w) \
                            or "__atomic_store" in before
                        reg = d.region or "?"
                        seq_sites.setdefault(reg, {}).setdefault(
                            "store" if store else "load", []).append(
                                (src, st, consumer))
                        if not wrapped:
                            self._finding(
                                src, st.line,
                                f"seqlock({reg}) word from {d.name}() "
                                "dereferenced outside the atomic "
                                f"load/store idiom in "
                                f"{st.func or '<file scope>'}", out)
                    continue
                for acc in self._accesses(d, text):
                    if d.kind == "counter":
                        continue            # documented tolerance
                    if d.kind == "guarded-by":
                        lock = d.region or ""
                        held = lock_state.get((st.func, lock), 0) > 0
                        if not held and not self._holds(src, st, lock):
                            self._finding(
                                src, st.line,
                                f"'{d.name}' (guarded-by {lock}) touched "
                                f"in {st.func or '<file scope>'} without "
                                "the lock held", out)
                        continue
                    if d.std_atomic:
                        # method access already covered by the
                        # memory-order check; flag implicit conversions
                        if not re.search(
                                rf"\b{d.name}\s*\.\s*(load|store|exchange|"
                                rf"fetch_\w+|compare_exchange\w*)\s*\(",
                                text):
                            self._finding(
                                src, st.line,
                                f"std::atomic '{d.name}' accessed without "
                                "an explicit-order method in "
                                f"{st.func or '<file scope>'}", out)
                        continue
                    if not any(tok in text for tok in blessed):
                        self._finding(
                            src, st.line,
                            f"shared {d.kind}"
                            f"{'(' + d.region + ')' if d.region else ''} "
                            f"word '{d.name}' plainly accessed in "
                            f"{st.func or '<file scope>'} (must ride "
                            "__atomic_* with an explicit order)", out)

    def _accesses(self, d: SharedDecl, text: str) -> List[int]:
        """Offsets of shared-word accesses of ``d`` in a statement."""
        if d.line and re.search(rf"^[\w:<>,*&\s]*[\s*&]{d.name}\s*(\[|=|;|$)",
                                text) and d.name + "(" not in text:
            # the declaration statement itself (init before sharing)
            if re.match(r"(static\s+)?(volatile\s+)?[\w:<>,]+[\s*&]+"
                        rf"{d.name}", text):
                return []
        pat = (rf"(?:->|\.)\s*{d.name}\s*\["
               if d.pointer and not d.std_atomic
               else rf"(?:->|\.)\s*{d.name}\b")
        hits = [m.start() for m in re.finditer(pat, text)]
        if not hits and not d.member:
            # file-scope globals are accessed bare (a member's bare name
            # is a shadowing local — never the shared word)
            bare = (rf"(?<![\w.>]){d.name}\s*\[" if d.pointer
                    else rf"(?<![\w.>]){d.name}\b(?!\s*\()")
            hits = [m.start() for m in re.finditer(bare, text)]
        return hits

    def _holds(self, src: CSource, st: CStatement, lock: str) -> bool:
        """``/* holds: <lock> */`` annotation on the enclosing function's
        definition line."""
        if st.func is None:
            return False
        for line, comment in src.comments.items():
            m = re.search(r"holds:\s*([\w,\s]+)", comment)
            if m and lock in {p.strip() for p in m.group(1).split(",")}:
                decl = _decl_on(src, line)
                fm = _FUNC_DEF_RE.search(decl)
                if fm and fm.group("name") == st.func:
                    return True
        return False

    # -- seqlock pairing ------------------------------------------------
    def _check_seqlock_pairing(self, seq_sites, out: List[Finding]) -> None:
        for region, sites in seq_sites.items():
            loads = sites.get("load", [])
            stores = sites.get("store", [])
            src = (loads or stores)[0][0] if (loads or stores) else None
            # sites are (CSource, CStatement, consumer_blessed)
            if src is None:
                continue
            if not stores:
                self._finding(src, 0,
                              f"seqlock region '{region}' has readers but "
                              "no release-store writer site", out)
            if not loads:
                self._finding(src, 0,
                              f"seqlock region '{region}' has writers but "
                              "no acquire-load reader site", out)
            if loads and not any(
                    s.text.startswith(("while", "for")) or "while" in s.text
                    or consumer for _, s, consumer in loads):
                self._finding(src, loads[0][1].line,
                              f"seqlock region '{region}' has no reader "
                              "re-check loop (every reader is a one-shot "
                              "load or a vetted wait consumer is missing)",
                              out)

    # -- cross-language layout -----------------------------------------
    def _check_layout(self, out: List[Finding]) -> None:
        hdr_path = self.layout_header
        if not os.path.exists(hdr_path):
            out.append(Finding(self.id, LAYOUT_HEADER, 0,
                               "layout: shm_layout.h missing — the "
                               "cross-language constants have no C source "
                               "of truth"))
            return
        defines, enums, lines = _parse_header(hdr_path)
        rel = os.path.relpath(hdr_path, REPO_ROOT)
        if rel.startswith(".."):
            rel = os.path.basename(hdr_path)

        def bad(name: str, msg: str) -> None:
            out.append(Finding(self.id, rel, lines.get(name, 0),
                               f"layout: {msg}"))

        py = _python_layout()

        pairs = [
            ("MV2T_RING_HDR_BYTES", "shm._HEADER"),
            ("MV2T_RING_WRAP", "shm._WRAP"),
            ("MV2T_RING_ALIGN", "shm._ALIGN"),
            ("MV2T_LEASE_ALIGN", "shm._LEASE_ALIGN"),
            ("MV2T_LEASE_STAMP_BYTES", "shm._LEASE_STAMP"),
            ("MV2T_FPC_SLOTS", "shm._FPC_SLOTS"),
            ("MV2T_CTX_MASK_BASE", "universe.CTX_MASK_BASE"),
            ("MV2T_PKT_HDR_BYTES", "base._PKT_HDR.size"),
            # native trace ring geometry (trace/native.py reads the
            # segment file mechanically — a drifted stride misparses
            # every record)
            ("MV2T_NTR_FILE_HDR", "trace_native._NTR_FILE_HDR"),
            ("MV2T_NTR_HDR_BYTES", "trace_native._NTR_HDR_BYTES"),
            ("MV2T_NTR_EV_BYTES", "trace_native._NTR_EV_BYTES"),
            ("MV2T_NTR_RING_EVENTS", "trace_native._NTR_RING_EVENTS"),
            # hierarchical flat2 geometry (bin/mpistat parses the
            # .fcoll2 file offline from the trace/native.py mirrors)
            ("MV2T_FLAT2_GROUP", "trace_native._FLAT2_GROUP"),
            ("MV2T_FLAT2_NGROUPS", "trace_native._FLAT2_NGROUPS"),
            ("MV2T_FLAT2_MAX", "trace_native._FLAT2_MAX"),
            ("MV2T_FLAT2_MCAST_NBUF", "trace_native._FLAT2_MCAST_NBUF"),
            ("MV2T_FLAT2_LANES", "trace_native._FLAT2_LANES"),
            ("MV2T_FLAT2_SUB_STRIDE", "trace_native._FLAT2_SUB_STRIDE"),
            ("MV2T_FLAT2_REG_STRIDE", "trace_native._FLAT2_REG_STRIDE"),
            # continuous-metrics ring geometry (metrics/ring.py writes
            # AND reads the segment from the trace/native.py mirrors —
            # a drifted stride tears every sampled row)
            ("MV2T_MET_FILE_HDR", "trace_native._MET_FILE_HDR"),
            ("MV2T_MET_HDR_BYTES", "trace_native._MET_HDR_BYTES"),
            ("MV2T_MET_SLOTS", "trace_native._MET_SLOTS"),
            ("MV2T_MET_PV_BASE", "trace_native._MET_PV_BASE"),
            ("MV2T_MET_ROW_BYTES", "trace_native._MET_ROW_BYTES"),
            ("MV2T_MET_RING_ROWS", "trace_native._MET_RING_ROWS"),
            ("MV2T_MET_NHIST", "trace_native._MET_NHIST"),
            ("MV2T_MET_HIST_BUCKETS", "trace_native._MET_HIST_BUCKETS"),
            ("MV2T_MET_HIST_HDR", "trace_native._MET_HIST_HDR"),
            ("MV2T_MET_HIST_BYTES", "trace_native._MET_HIST_BYTES"),
            ("MV2T_MET_RANK_STRIDE", "trace_native._MET_RANK_STRIDE"),
        ]
        for cname, pyname in pairs:
            if cname not in defines:
                bad(cname, f"{cname} not defined in shm_layout.h")
                continue
            if pyname not in py:
                bad(cname, f"python mirror {pyname} not found")
                continue
            if defines[cname] != py[pyname]:
                bad(cname,
                    f"{cname}={defines[cname]} != {pyname}={py[pyname]} "
                    "— C and python disagree on the shared layout")

        if "MV2T_LEASE_DEPARTED" in defines \
                and "shm.ShmChannel._LEASE_DEPARTED" in py:
            c = defines["MV2T_LEASE_DEPARTED"] & 0xFFFFFFFFFFFFFFFF
            p = py["shm.ShmChannel._LEASE_DEPARTED"] & 0xFFFFFFFFFFFFFFFF
            if c != p:
                bad("MV2T_LEASE_DEPARTED",
                    f"MV2T_LEASE_DEPARTED={c:#x} != "
                    f"shm._LEASE_DEPARTED={p:#x}")

        # FPC enum <-> _FP_COUNTERS: dense indices, matching names
        # (the header now carries two enums; each check filters its own
        # prefix so the other's indices can't pollute the slot space)
        fpc_enums = {n: i for n, i in enums.items()
                     if n.startswith("FPC_")}
        counters = py.get("shm._FP_COUNTERS", [])
        if not counters:
            bad("FPC_HITS", "python mirror shm._FP_COUNTERS not found")
        else:
            want = {i: _fpc_to_pvar(n) for n, i in fpc_enums.items()}
            for idx in range(len(counters)):
                if idx not in want:
                    bad("FPC_HITS",
                        f"_FP_COUNTERS[{idx}]={counters[idx]} has no FPC_* "
                        "enum slot in shm_layout.h")
                elif want[idx] != counters[idx]:
                    bad("FPC_HITS",
                        f"FPC slot {idx} is {want[idx]} in shm_layout.h "
                        f"but _FP_COUNTERS[{idx}] is {counters[idx]}")
            for name, idx in fpc_enums.items():
                if idx >= len(counters):
                    bad(name,
                        f"{name}={idx} has no _FP_COUNTERS pvar (python "
                        "side shorter than the C enum)")
            slots = defines.get("MV2T_FPC_SLOTS", 0)
            if slots and len(counters) > slots:
                bad("MV2T_FPC_SLOTS",
                    f"_FP_COUNTERS has {len(counters)} entries but the "
                    f"fpctr array holds MV2T_FPC_SLOTS={slots}")

        # NTE enum <-> trace/native.py _NT_EVENTS: dense indices,
        # matching names (NTE_FLAT_FANIN <-> flat_fanin) — the native
        # trace ring's ids are wire format between C and python
        nte_enums = {n: i for n, i in enums.items()
                     if n.startswith("NTE_")}
        nt_names = py.get("trace_native._NT_EVENTS", [])
        if nte_enums and not nt_names:
            bad("NTE_FLAT_FANIN",
                "python mirror trace/native.py _NT_EVENTS not found")
        elif nte_enums:
            want_nt = {i: _nte_to_name(n) for n, i in nte_enums.items()}
            for idx in range(len(nt_names)):
                if idx not in want_nt:
                    bad("NTE_FLAT_FANIN",
                        f"_NT_EVENTS[{idx}]={nt_names[idx]} has no NTE_* "
                        "enum slot in shm_layout.h")
                elif want_nt[idx] != nt_names[idx]:
                    bad("NTE_FLAT_FANIN",
                        f"NTE slot {idx} is {want_nt[idx]} in "
                        f"shm_layout.h but _NT_EVENTS[{idx}] is "
                        f"{nt_names[idx]}")
            for name, idx in nte_enums.items():
                if idx >= len(nt_names):
                    bad(name,
                        f"{name}={idx} has no _NT_EVENTS entry (python "
                        "side shorter than the C enum)")
            count = defines.get("MV2T_NTE_COUNT", 0)
            if count and count != len(nte_enums):
                bad("MV2T_NTE_COUNT",
                    f"MV2T_NTE_COUNT={count} != {len(nte_enums)} NTE_* "
                    "enum entries")

        # flat-region geometry sanity: derived defines must re-derive
        derived = {
            "MV2T_FLAT_SLOT_STRIDE":
                64 + defines.get("MV2T_FLAT_MAX", 0),
            "MV2T_FLAT_REG_STRIDE":
                defines.get("MV2T_FLAT_REG_HDR", 0)
                + (defines.get("MV2T_FLAT_NSLOTS", 0) + 1)
                * defines.get("MV2T_FLAT_SLOT_STRIDE", 0),
            "MV2T_FLAT_NREG":
                defines.get("MV2T_FLAT_SMALL_CTXS", 0)
                + defines.get("MV2T_FLAT_MASK_CTXS", 0),
            "MV2T_FLAT_FILE_LEN":
                defines.get("MV2T_FLAT_NREG", 0)
                * defines.get("MV2T_FLAT_LANES", 0)
                * defines.get("MV2T_FLAT_REG_STRIDE", 0),
            # hierarchical flat tier geometry (cp_flat2_*): the region
            # is NGROUPS+1 flat-shaped sub-regions + the mcast ring
            "MV2T_FLAT2_MAX_RANKS":
                defines.get("MV2T_FLAT2_GROUP", 0)
                * defines.get("MV2T_FLAT2_NGROUPS", 0),
            "MV2T_FLAT2_SUB_STRIDE":
                64 + (defines.get("MV2T_FLAT2_GROUP", 0) + 1)
                * defines.get("MV2T_FLAT_SLOT_STRIDE", 0),
            "MV2T_FLAT2_MCAST_STRIDE":
                64 + defines.get("MV2T_FLAT2_MAX", 0),
            "MV2T_FLAT2_REG_STRIDE":
                defines.get("MV2T_FLAT2_REG_HDR", 0)
                + (defines.get("MV2T_FLAT2_NGROUPS", 0) + 1)
                * defines.get("MV2T_FLAT2_SUB_STRIDE", 0)
                + defines.get("MV2T_FLAT2_MCAST_NBUF", 0)
                * defines.get("MV2T_FLAT2_MCAST_STRIDE", 0),
            "MV2T_FLAT2_NREG":
                defines.get("MV2T_FLAT2_SMALL_CTXS", 0)
                + defines.get("MV2T_FLAT2_MASK_CTXS", 0),
            "MV2T_FLAT2_FILE_LEN":
                defines.get("MV2T_FLAT2_NREG", 0)
                * defines.get("MV2T_FLAT2_LANES", 0)
                * defines.get("MV2T_FLAT2_REG_STRIDE", 0),
            # continuous-metrics segment: the row is a 16-byte stamp
            # header + the value slots; the per-rank stride covers
            # header + ring + histogram area; the pvar slot window
            # starts right after the verbatim fpctr mirror
            "MV2T_MET_PV_BASE": defines.get("MV2T_FPC_SLOTS", 0),
            "MV2T_MET_ROW_BYTES":
                16 + defines.get("MV2T_MET_SLOTS", 0) * 8,
            "MV2T_MET_HIST_BYTES":
                defines.get("MV2T_MET_HIST_HDR", 0)
                + defines.get("MV2T_MET_HIST_BUCKETS", 0) * 8,
            "MV2T_MET_RANK_STRIDE":
                defines.get("MV2T_MET_HDR_BYTES", 0)
                + defines.get("MV2T_MET_RING_ROWS", 0)
                * defines.get("MV2T_MET_ROW_BYTES", 0)
                + defines.get("MV2T_MET_NHIST", 0)
                * defines.get("MV2T_MET_HIST_BYTES", 0),
        }
        for name, want_v in derived.items():
            if name in defines and defines[name] != want_v:
                bad(name, f"{name}={defines[name]} does not re-derive "
                          f"from its parts ({want_v})")
        # the flat2 payload ceiling shares the flat slot layout: a
        # payload larger than the slot stride's data area would tear
        if defines.get("MV2T_FLAT2_MAX", 0) \
                > defines.get("MV2T_FLAT_MAX", 0):
            bad("MV2T_FLAT2_MAX",
                "MV2T_FLAT2_MAX exceeds MV2T_FLAT_MAX — flat2 sub-region "
                "slots reuse the flat slot stride and cannot hold it")


# ---------------------------------------------------------------------------
# header + python-side parsing helpers
# ---------------------------------------------------------------------------

def _eval_cexpr(expr: str) -> Optional[int]:
    """Evaluate a preprocessor-style integer expression (literals, hex,
    + - * << | ~ and parens; u/l suffixes stripped)."""
    cleaned = re.sub(r"(?<=[0-9a-fA-FxX])[uUlL]+\b", "", expr)
    if not re.fullmatch(r"[\s0-9a-fA-FxX()+\-*<>|~]+", cleaned):
        return None
    try:
        node = ast.parse(cleaned, mode="eval")
        return int(_eval_node(node.body))
    except Exception:
        return None


def _eval_node(n: ast.AST) -> int:
    if isinstance(n, ast.Constant) and isinstance(n.value, int):
        return n.value
    if isinstance(n, ast.BinOp):
        a, b = _eval_node(n.left), _eval_node(n.right)
        op = n.op
        if isinstance(op, ast.Add):
            return a + b
        if isinstance(op, ast.Sub):
            return a - b
        if isinstance(op, ast.Mult):
            return a * b
        if isinstance(op, ast.LShift):
            return a << b
        if isinstance(op, ast.BitOr):
            return a | b
        raise ValueError(op)
    if isinstance(n, ast.UnaryOp):
        v = _eval_node(n.operand)
        if isinstance(n.op, ast.Invert):
            return ~v
        if isinstance(n.op, ast.USub):
            return -v
    raise ValueError(n)


def _parse_header(path: str):
    """(#define values, FPC enum values, name -> line) from
    shm_layout.h. #defines resolve forward references to one another."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    code, _ = CSource._split_comments(text)
    # join continuation lines
    code = code.replace("\\\n", " ")
    defines: Dict[str, int] = {}
    lines: Dict[str, int] = {}
    for i, line in enumerate(code.split("\n"), 1):
        m = re.match(r"\s*#\s*define\s+(\w+)\s+(.+)", line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2).strip()
        for known, v in sorted(defines.items(), key=lambda kv: -len(kv[0])):
            rhs = re.sub(rf"\b{known}\b", str(v), rhs)
        v = _eval_cexpr(rhs)
        if v is not None:
            defines[name] = v
            lines[name] = i
    enums: Dict[str, int] = {}
    for m in re.finditer(r"enum\s*\{(.*?)\}", code, re.S):
        nxt = 0
        for item in m.group(1).split(","):
            item = item.strip()
            if not item:
                continue
            em = re.match(r"(\w+)\s*(?:=\s*(.+))?$", item, re.S)
            if not em:
                continue
            name = em.group(1)
            if em.group(2) is not None:
                v = _eval_cexpr(em.group(2).strip())
                nxt = v if v is not None else nxt
            enums[name] = nxt
            lines.setdefault(
                name,
                next((i for i, l in enumerate(code.split("\n"), 1)
                      if re.search(rf"\b{name}\b", l)), 0))
            nxt += 1
    return defines, enums, lines


def _fpc_to_pvar(enum_name: str) -> str:
    """FPC_FB_DTYPE -> fp_fallback_dtype (the _FP_COUNTERS pvar name)."""
    parts = enum_name.split("_")[1:]          # drop FPC
    parts = ["fallback" if p == "FB" else p.lower() for p in parts]
    return "fp_" + "_".join(parts)


def _nte_to_name(enum_name: str) -> str:
    """NTE_FLAT_FANIN -> flat_fanin (the _NT_EVENTS name)."""
    return "_".join(enum_name.split("_")[1:]).lower()


def _py_const(tree: ast.Module, name: str) -> Optional[object]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    try:
                        return ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
    return None


def _python_layout() -> Dict[str, object]:
    """Python-side layout constants, parsed from source (no imports —
    the lint must run without jax/numpy)."""
    out: Dict[str, object] = {}
    shm_path = os.path.join(REPO_ROOT, "mvapich2_tpu", "transport", "shm.py")
    base_path = os.path.join(REPO_ROOT, "mvapich2_tpu", "transport",
                             "base.py")
    uni_path = os.path.join(REPO_ROOT, "mvapich2_tpu", "runtime",
                            "universe.py")
    try:
        with open(shm_path, encoding="utf-8") as f:
            shm_tree = ast.parse(f.read())
        for n in ("_HEADER", "_WRAP", "_ALIGN", "_LEASE_ALIGN",
                  "_LEASE_STAMP", "_FPC_SLOTS"):
            v = _py_const(shm_tree, n)
            if v is not None:
                out[f"shm.{n}"] = v
        counters = None
        for node in ast.walk(shm_tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_FP_COUNTERS"
                    for t in node.targets):
                try:
                    counters = [pair[0] for pair in
                                ast.literal_eval(node.value)]
                except (ValueError, SyntaxError):
                    counters = None
        if counters:
            out["shm._FP_COUNTERS"] = counters
        for node in ast.walk(shm_tree):
            if isinstance(node, ast.ClassDef) and node.name == "ShmChannel":
                for sub in node.body:
                    if isinstance(sub, ast.Assign) and any(
                            isinstance(t, ast.Name)
                            and t.id == "_LEASE_DEPARTED"
                            for t in sub.targets):
                        try:
                            out["shm.ShmChannel._LEASE_DEPARTED"] = \
                                ast.literal_eval(sub.value)
                        except (ValueError, SyntaxError):
                            pass
    except OSError:
        pass
    try:
        with open(base_path, encoding="utf-8") as f:
            base_tree = ast.parse(f.read())
        for node in ast.walk(base_tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_PKT_HDR"
                    for t in node.targets) \
                    and isinstance(node.value, ast.Call) \
                    and node.value.args:
                fmt = node.value.args[0]
                if isinstance(fmt, ast.Constant) \
                        and isinstance(fmt.value, str):
                    out["base._PKT_HDR.size"] = _struct.calcsize(fmt.value)
    except OSError:
        pass
    nt_path = os.path.join(REPO_ROOT, "mvapich2_tpu", "trace",
                           "native.py")
    try:
        with open(nt_path, encoding="utf-8") as f:
            nt_tree = ast.parse(f.read())
        for n in ("_NTR_FILE_HDR", "_NTR_HDR_BYTES", "_NTR_EV_BYTES",
                  "_NTR_RING_EVENTS", "_FLAT2_GROUP", "_FLAT2_NGROUPS",
                  "_FLAT2_MAX", "_FLAT2_MCAST_NBUF", "_FLAT2_LANES",
                  "_FLAT2_SUB_STRIDE", "_FLAT2_REG_STRIDE",
                  "_MET_FILE_HDR", "_MET_HDR_BYTES", "_MET_SLOTS",
                  "_MET_PV_BASE", "_MET_ROW_BYTES", "_MET_RING_ROWS",
                  "_MET_NHIST", "_MET_HIST_BUCKETS", "_MET_HIST_HDR",
                  "_MET_HIST_BYTES", "_MET_RANK_STRIDE"):
            v = _py_const(nt_tree, n)
            if v is not None:
                out[f"trace_native.{n}"] = v
        for node in ast.walk(nt_tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_NT_EVENTS"
                    for t in node.targets):
                try:
                    out["trace_native._NT_EVENTS"] = [
                        pair[0] for pair in ast.literal_eval(node.value)]
                except (ValueError, SyntaxError):
                    pass
    except OSError:
        pass
    try:
        with open(uni_path, encoding="utf-8") as f:
            uni_tree = ast.parse(f.read())
        v = None
        for node in ast.walk(uni_tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "CTX_MASK_BASE":
                        from .core import const_int
                        v = const_int(node.value)
        if v is not None:
            out["universe.CTX_MASK_BASE"] = v
    except OSError:
        pass
    return out


# ---------------------------------------------------------------------------
# shared-field map (stall-watchdog forensics)
# ---------------------------------------------------------------------------

def shared_field_map(sources: Optional[List[str]] = None) -> Dict[str, dict]:
    """{word name: {kind, region, file, line}} for every ``shared:``
    annotation in the native sources — the watchdog uses it to name
    which protocol region (seqlock/lease/doorbell/...) a dumped word
    belongs to."""
    if sources is None:
        sources = [os.path.join(REPO_ROOT, p) for p in NATIVE_SOURCES]
    out: Dict[str, dict] = {}
    for path in sources:
        if not os.path.exists(path):
            continue
        try:
            src = CSource(path)
        except OSError:
            continue
        decls, _ok, _init = harvest(src)
        for d in decls.values():
            out[d.name] = {
                "kind": d.kind,
                "region": d.region,
                "file": src.relpath,
                "line": d.line,
                "accessor": d.is_func,
            }
    return out

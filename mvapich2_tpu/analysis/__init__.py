"""mv2t-analyze: protocol/concurrency invariant checking.

Two halves, one goal — catch the races and deadlocks that accumulate in
cross-process shm datapaths (PAPER.md §L3/L4) at lint time instead of in
a 4-rank hang:

  * ``bin/mv2tlint`` — an AST-based static checker with pluggable
    passes over the whole package (core.py drives; one module per pass):

        locks       guarded-by lock discipline (# guarded-by: _lock)
        tags        tag-namespace disjointness (*_TAG_BASE ranges)
        events      trace event-coverage doctor: every tracer.record
                    name, NTE_* member, and rec_us/rec_since histogram
                    sample must be known to the conformance grammars
                    (conform.py) / _MET_HISTS — the lat_dev_nbc
                    silent-drop bug class, caught mechanically
        pvars       pvar/cvar registry consistency + naming convention
                    + the native/bin/README env-drift doctor
        blocking    no blocking calls in progress callbacks/pkt handlers
        traceguard  every trace site behind the one-attribute-check idiom
        native      C-plane atomic discipline + cross-language layout
        device      Pallas DMA/semaphore discipline (copy/wait pairing,
                    pending-map drains, credit gates, VMEM budgets)
        profile     tuning-table shape + arch-profile JSON schema
        proto       control-plane protocol doctors: KVS key flow
                    (write-only / never-written / drifted families),
                    bounded KVS retry loops, wire-state totality,
                    *_VERSION compatibility

    Findings ratchet down through a committed suppressions file
    (analysis/baseline.json); ``--strict`` additionally fails on STALE
    suppressions so the baseline can only shrink.

  * ``lockorder`` — a runtime lock-order detector (MV2T_LOCKCHECK=1):
    instrumented lock wrappers build a per-process acquisition-order
    graph, detect cycles (potential deadlock) and held-across-
    progress-wait violations, and report through the stall-watchdog /
    debugger dump path.

  * ``bin/mv2tconform`` (conform.py) — runtime verification: replays a
    real run's traces (bin/mpitrace merges, Finalize dump dirs, raw
    .ntrace/.metrics segments) through per-protocol conformance
    automata whose invariant names are the model checkers'
    (analysis/model/*), with replayable counterexample windows; the
    stall watchdog runs the truncation-safe subset over the trace tail
    on a hang. The NBC automaton's event grammar is imported from
    model/nbc.TRACE_EVENTS, so the offline proof and the runtime check
    cannot drift apart.
"""

from .core import Finding, load_baseline, run_passes, scan_paths  # noqa: F401
from .lockorder import get_monitor, tracked  # noqa: F401

"""Pass ``tags`` — tag-namespace disjointness.

Every subsystem that places traffic on a shared context carves its tags
out of a ``*_TAG_BASE`` constant (coll/nbc/inter.py NBC_TAG_BASE,
ft/ulfm.py _FT_TAG_BASE). A silent overlap — two subsystems deriving
the same wire tag on the same context — mismatches messages across
layers, the worst kind of heisenbug. This pass collects every
module-level ``*_TAG_BASE`` integer constant, widens each to a range
using its ``# tag-span: N`` annotation (default 32768 — the 15-bit
window ``next_coll_tag`` cycles through, which is also what most
namespaces add to their base), and proves:

  * no two namespace ranges overlap,
  * no namespace overlaps the dynamic collective-tag window
    [0, 32768) that ``core/comm.py next_coll_tag`` hands out,
  * every range fits signed-31-bit tag space (the wire format).
"""

from __future__ import annotations

import ast
import re
from typing import List, NamedTuple

from .core import Finding, LintPass, SourceModule, const_int

_NAME_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*_TAG_BASE$")
DEFAULT_SPAN = 32768          # the next_coll_tag 15-bit window
DYNAMIC_WINDOW = ("dynamic next_coll_tag window (core/comm.py)", 0,
                  DEFAULT_SPAN)
TAG_SPACE = 1 << 31


class _Range(NamedTuple):
    name: str
    lo: int
    hi: int
    mod: SourceModule
    line: int

    def label(self) -> str:
        return f"{self.name} [{self.lo:#x}, {self.hi:#x}) ({self.mod.relpath})"


class TagNamespacePass(LintPass):
    id = "tags"
    doc = "*_TAG_BASE namespaces must be disjoint ranges in tag space"

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        ranges: List[_Range] = []
        for mod in modules:
            for node in mod.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Name) and _NAME_RE.match(t.id)):
                        continue
                    base = const_int(node.value)
                    if base is None:
                        f = self.finding(mod, node.lineno,
                                         f"tag base {t.id} is not a "
                                         "compile-time integer constant")
                        if f is not None:
                            out.append(f)
                        continue
                    span_s = mod.annotation(node.lineno, "tag-span")
                    span = DEFAULT_SPAN
                    if span_s is not None:
                        # first token only: prose may follow the number
                        try:
                            span = int(span_s.split()[0], 0)
                        except (ValueError, IndexError):
                            f = self.finding(mod, node.lineno,
                                             f"unparseable tag-span "
                                             f"annotation on {t.id}")
                            if f is not None:
                                out.append(f)
                    ranges.append(_Range(t.id, base, base + span,
                                         mod, node.lineno))
        ranges.sort(key=lambda r: (r.lo, r.name))
        dyn_name, dyn_lo, dyn_hi = DYNAMIC_WINDOW
        for r in ranges:
            if r.hi > TAG_SPACE:
                f = self.finding(r.mod, r.line,
                                 f"{r.label()} exceeds signed-31-bit "
                                 "tag space")
                if f is not None:
                    out.append(f)
            if r.lo < dyn_hi and dyn_lo < r.hi:
                f = self.finding(r.mod, r.line,
                                 f"{r.label()} overlaps the {dyn_name}")
                if f is not None:
                    out.append(f)
        for a, b in zip(ranges, ranges[1:]):
            if b.lo < a.hi:
                f = self.finding(b.mod, b.line,
                                 f"{b.label()} overlaps {a.label()}")
                if f is not None:
                    out.append(f)
        return out

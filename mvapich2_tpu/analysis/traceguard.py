"""Pass ``traceguard`` — trace sites behind the one-attribute-check idiom.

The recorder's cost contract (trace/recorder.py): when tracing is off,
every instrumented site pays exactly ONE attribute check. The compiled
idioms are

    tr = engine.tracer                 if tracer is not None:
    if tr is not None:                     tracer.record(...)
        tr.record(...)

    if (tr := eng.tracer) is not None:
        tr.record(...)

An unguarded ``X.record(...)`` on a tracer either crashes when tracing
is off (tracer is None) or hides a config lookup on the hot path. This
pass finds every ``.record(...)`` call whose receiver looks like a
tracer — a name in {tr, tracer, rec} or an attribute chain ending in
``.tracer`` — and requires an enclosing ``is not None`` guard on that
same receiver (plain if, walrus, ternary) or an early
``if X is None: return`` in the same function.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, LintPass, SourceModule, attr_chain, parent_map

TRACER_NAMES = {"tr", "tracer", "rec"}


def _receiver_key(fn: ast.Attribute) -> Optional[str]:
    """The guarded expression, as a dotted chain, when the receiver is
    tracer-shaped; None otherwise."""
    recv = fn.value
    if isinstance(recv, ast.Name):
        return recv.id if recv.id in TRACER_NAMES else None
    chain = attr_chain(recv)
    if chain is not None and chain.split(".")[-1] == "tracer":
        return chain
    return None


def _test_guards(test: ast.AST, key: str) -> bool:
    """Does ``test`` contain ``<key> is not None`` (walrus included)?"""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], ast.IsNot):
            continue
        comp = node.comparators[0]
        if not (isinstance(comp, ast.Constant) and comp.value is None):
            continue
        left = node.left
        if isinstance(left, ast.NamedExpr):
            if isinstance(left.target, ast.Name) and left.target.id == key:
                return True
            left = left.value
        if attr_chain(left) == key:
            return True
    return False


def _early_return_guard(fndef, key: str, before_line: int) -> bool:
    """``if <key> is None: return`` earlier in the same function body."""
    for st in ast.walk(fndef):
        if not isinstance(st, ast.If) or st.lineno >= before_line:
            continue
        for node in ast.walk(st.test):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], ast.Is) \
                    and isinstance(node.comparators[0], ast.Constant) \
                    and node.comparators[0].value is None \
                    and attr_chain(node.left) == key:
                if any(isinstance(b, ast.Return) for b in st.body):
                    return True
    return False


class TraceGuardPass(LintPass):
    id = "traceguard"
    doc = ("every tracer .record() site sits behind the single "
           "attribute-check 'is not None' idiom")

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        for mod in modules:
            parents = parent_map(mod.tree)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "record"):
                    continue
                key = _receiver_key(node.func)
                if key is None:
                    continue
                if self._guarded(node, key, parents):
                    continue
                f = self.finding(mod, node.lineno,
                                 f"trace site '{key}.record(...)' is not "
                                 "behind an 'is not None' guard "
                                 "(one-attribute-check idiom)")
                if f is not None:
                    out.append(f)
        return out

    @staticmethod
    def _guarded(call: ast.Call, key: str, parents) -> bool:
        node: ast.AST = call
        fndef = None
        while node in parents:
            child, node = node, parents[node]
            if isinstance(node, ast.If) and child in node.body \
                    and _test_guards(node.test, key):
                return True
            if isinstance(node, ast.IfExp) and child is node.body \
                    and _test_guards(node.test, key):
                return True
            if isinstance(node, (ast.BoolOp,)) and \
                    isinstance(node.op, ast.And) and node.values \
                    and child is not node.values[0] \
                    and any(_test_guards(v, key) for v in node.values[:-1]):
                return True
            if fndef is None and isinstance(node, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef)):
                fndef = node
        if fndef is not None:
            return _early_return_guard(fndef, key, call.lineno)
        return False

"""Pass ``traceguard`` — trace sites behind the one-attribute-check idiom.

The recorder's cost contract (trace/recorder.py): when tracing is off,
every instrumented site pays exactly ONE attribute check. The compiled
idioms are

    tr = engine.tracer                 if tracer is not None:
    if tr is not None:                     tracer.record(...)
        tr.record(...)

    if (tr := eng.tracer) is not None:
        tr.record(...)

An unguarded ``X.record(...)`` on a tracer either crashes when tracing
is off (tracer is None) or hides a config lookup on the hot path. This
pass finds every ``.record(...)`` call whose receiver looks like a
tracer — a name in {tr, tracer, rec} or an attribute chain ending in
``.tracer`` — and requires an enclosing ``is not None`` guard on that
same receiver (plain if, walrus, ternary) or an early
``if X is None: return`` in the same function.

Native half (the C analog of the same cost contract): the C-plane trace
ring's emit sites must ride the one-branch ``MV2T_NTRACE(...)`` macro,
never the raw ``nt_emit(...)`` writer — a raw call either crashes when
the ring is unmapped (nt_mine NULL) or hides the gate inline where the
next edit loses it. Checked over the committed native sources:

  * raw-call      — ``nt_emit(`` outside nt_emit's own definition and
                    the exported cp_ntrace_emit wrapper
  * macro-gate    — every ``#define MV2T_NTRACE`` body must carry the
                    runtime gate (the nt_mine NULL check) or be the
                    compiled-out ``((void)0)`` stub

``// mv2tlint: ignore[traceguard]`` suppresses a line, same as the
python side.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional

from .core import Finding, LintPass, SourceModule, attr_chain, parent_map

TRACER_NAMES = {"tr", "tracer", "rec"}

# functions allowed to touch the raw ring writer
_NT_WRITER_FUNCS = {"nt_emit", "cp_ntrace_emit"}
_NT_CALL_RE = re.compile(r"(?<![\w.>])nt_emit\s*\(")
_NT_DEFINE_RE = re.compile(
    r"^[ \t]*#[ \t]*define[ \t]+MV2T_NTRACE\b"
    r"(?P<body>(?:[^\n\\]|\\\n|\\.)*)", re.M)


def _receiver_key(fn: ast.Attribute) -> Optional[str]:
    """The guarded expression, as a dotted chain, when the receiver is
    tracer-shaped; None otherwise."""
    recv = fn.value
    if isinstance(recv, ast.Name):
        return recv.id if recv.id in TRACER_NAMES else None
    chain = attr_chain(recv)
    if chain is not None and chain.split(".")[-1] == "tracer":
        return chain
    return None


def _test_guards(test: ast.AST, key: str) -> bool:
    """Does ``test`` contain ``<key> is not None`` (walrus included)?"""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], ast.IsNot):
            continue
        comp = node.comparators[0]
        if not (isinstance(comp, ast.Constant) and comp.value is None):
            continue
        left = node.left
        if isinstance(left, ast.NamedExpr):
            if isinstance(left.target, ast.Name) and left.target.id == key:
                return True
            left = left.value
        if attr_chain(left) == key:
            return True
    return False


def _early_return_guard(fndef, key: str, before_line: int) -> bool:
    """``if <key> is None: return`` earlier in the same function body."""
    for st in ast.walk(fndef):
        if not isinstance(st, ast.If) or st.lineno >= before_line:
            continue
        for node in ast.walk(st.test):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], ast.Is) \
                    and isinstance(node.comparators[0], ast.Constant) \
                    and node.comparators[0].value is None \
                    and attr_chain(node.left) == key:
                if any(isinstance(b, ast.Return) for b in st.body):
                    return True
    return False


class TraceGuardPass(LintPass):
    id = "traceguard"
    doc = ("every tracer .record() site sits behind the single "
           "attribute-check 'is not None' idiom; native MV2T_NTRACE "
           "emits stay behind the compiled/env gate")

    def __init__(self, native_sources: Optional[List[str]] = None):
        # None = the committed native tree (same default file set as
        # the native pass); [] disables the native half (pure-python
        # fixture runs)
        if native_sources is None:
            from .core import REPO_ROOT
            from .native import NATIVE_SOURCES
            native_sources = [os.path.join(REPO_ROOT, p)
                              for p in NATIVE_SOURCES]
        self.native_sources = [p for p in native_sources
                               if os.path.exists(p)]

    def run(self, modules: List[SourceModule]) -> List[Finding]:
        out: List[Finding] = self._run_native()
        for mod in modules:
            parents = parent_map(mod.tree)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "record"):
                    continue
                key = _receiver_key(node.func)
                if key is None:
                    continue
                if self._guarded(node, key, parents):
                    continue
                f = self.finding(mod, node.lineno,
                                 f"trace site '{key}.record(...)' is not "
                                 "behind an 'is not None' guard "
                                 "(one-attribute-check idiom)")
                if f is not None:
                    out.append(f)
        return out

    # -- native half (MV2T_NTRACE gate discipline) ----------------------
    def _run_native(self) -> List[Finding]:
        out: List[Finding] = []
        for path in self.native_sources:
            try:
                from .native import CSource
                src = CSource(path)
            except OSError:
                continue
            self._check_native(src, out)
        return out

    def _check_native(self, src, out: List[Finding]) -> None:
        def finding(line: int, msg: str) -> None:
            ign = src.ignores.get(line)
            if ign and ("*" in ign or self.id in ign):
                return
            out.append(Finding(self.id, src.relpath, line, msg))

        # raw-call: nt_emit() outside the writer/wrapper definitions.
        # A file-scope statement ending at the parameter list is the
        # writer's own declaration/prototype, not a call.
        for st in src.statements:
            if not _NT_CALL_RE.search(st.text):
                continue
            if st.func in _NT_WRITER_FUNCS:
                continue
            if st.func is None and st.text.endswith(")"):
                continue
            finding(st.line,
                    "raw nt_emit() call in "
                    f"{st.func or '<file scope>'} — native trace emits "
                    "must ride the one-branch MV2T_NTRACE(...) macro "
                    "(compiled/env gate)")

        # macro-gate: every MV2T_NTRACE definition carries the runtime
        # gate (nt_mine NULL check) or is the compiled-out stub
        for m in _NT_DEFINE_RE.finditer(src.text):
            body = m.group("body")
            if "nt_mine" in body or re.search(r"\(void\)\s*0", body):
                continue
            line = src.text.count("\n", 0, m.start()) + 1
            finding(line,
                    "MV2T_NTRACE macro definition lacks the one-branch "
                    "runtime gate (nt_mine check) and is not the "
                    "((void)0) compiled-out stub")

    @staticmethod
    def _guarded(call: ast.Call, key: str, parents) -> bool:
        node: ast.AST = call
        fndef = None
        while node in parents:
            child, node = node, parents[node]
            if isinstance(node, ast.If) and child in node.body \
                    and _test_guards(node.test, key):
                return True
            if isinstance(node, ast.IfExp) and child is node.body \
                    and _test_guards(node.test, key):
                return True
            if isinstance(node, (ast.BoolOp,)) and \
                    isinstance(node.op, ast.And) and node.values \
                    and child is not node.values[0] \
                    and any(_test_guards(v, key) for v in node.values[:-1]):
                return True
            if fndef is None and isinstance(node, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef)):
                fndef = node
        if fndef is not None:
            return _early_return_guard(fndef, key, call.lineno)
        return False

from . import base, local, progress

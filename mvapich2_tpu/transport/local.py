"""In-process threaded fabric.

The unit-test / THREAD-ranks transport: every rank is a thread in one
process, packets hop between engines' inboxes, and the zero-copy rendezvous
path passes numpy buffer references directly (the logical extreme of the
reference's SMP channel, ch3_smp_progress.c — same address space instead of
a shared segment). Also the fastest way to run the MPICH-style test corpus.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional

import numpy as np

from .base import Channel, Packet
from .progress import ProgressEngine


class LocalFabric:
    """Shared switchboard: world rank -> engine."""

    def __init__(self, nranks: int):
        self.nranks = nranks
        self.engines: Dict[int, ProgressEngine] = {}
        self._lock = threading.Lock()
        # exposed buffers for the RGET path: handle -> ndarray
        self._exposed: Dict[int, np.ndarray] = {}
        self._handle_ids = itertools.count(1)

    def register(self, rank: int, engine: ProgressEngine) -> None:
        with self._lock:
            self.engines[rank] = engine

    def deliver(self, dest: int, pkt: Packet) -> None:
        eng = self.engines.get(dest)
        if eng is None:
            raise RuntimeError(f"no engine for rank {dest}")
        eng.enqueue_incoming(pkt)

    def expose(self, arr: np.ndarray) -> int:
        h = next(self._handle_ids)
        with self._lock:
            self._exposed[h] = arr
        return h

    def pull(self, handle: int) -> np.ndarray:
        with self._lock:
            return self._exposed[handle]

    def release(self, handle: int) -> None:
        with self._lock:
            self._exposed.pop(handle, None)


class LocalChannel(Channel):
    name = "local"
    supports_rget = True

    def __init__(self, fabric: LocalFabric, my_rank: int):
        self.fabric = fabric
        self.my_rank = my_rank

    def send_packet(self, dest_world: int, pkt: Packet) -> None:
        if pkt.data is not None:
            # Eager payloads are copied at injection so the sender's buffer
            # is immediately reusable (MPI eager semantics; the vbuf copy).
            # Self-sends included: the protocol may hand a live VIEW of
            # the user buffer (zero-copy eager), which the user can
            # overwrite the moment the send completes locally.
            pkt.data = np.array(pkt.data, dtype=np.uint8, copy=True)
        # no wire blob on the thread fabric: the payload size is the
        # honest byte count (delivery is a reference hop, recv side has
        # no channel pass — send-side accounting covers the traffic)
        self.account_send(dest_world, pkt.nbytes)
        self.fabric.deliver(dest_world, pkt)

    def poll(self) -> bool:
        return False  # delivery is push-based into the engine inbox

    def expose_buffer(self, array: np.ndarray):
        return self.fabric.expose(array)

    def pull_buffer(self, src_world: int, handle, nbytes: int) -> np.ndarray:
        src = self.fabric.pull(handle)
        return src[:nbytes]

    def release_buffer(self, handle) -> None:
        self.fabric.release(handle)

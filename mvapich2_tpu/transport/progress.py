"""Per-rank progress engine — the hot loop every blocking call funnels into.

Analog of MPIDI_CH3I_Progress (SURVEY §3.5,
/root/reference/src/mpid/ch3/channels/mrail/src/rdma/ch3_progress.c:186):

    loop { drain inbox; poll channels; run progress hooks; sleep-or-spin }

Design differences from the reference, driven by the runtime model:
  * One engine per rank. In the in-process ("local") fabric, rank peers are
    threads and deliver packets by appending to this engine's inbox and
    signalling its condition variable — so blocking waits are event-driven,
    not spin-polls. Socket/shm channels are polled like the reference's CQs.
  * All rank-local protocol state (matching queues, requests, windows) is
    mutated only while holding ``mutex`` — the analog of MPICH's coarse
    global CS (SURVEY §5.2) — which the owning thread holds for the duration
    of an MPI call.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.errors import MPIException, MPI_ERR_INTERN
from ..core.request import Request
from ..utils.mlog import get_logger
from .base import Channel, Packet, PktType

log = get_logger("progress")


class ProgressEngine:
    def __init__(self, rank: int):
        from ..analysis.lockorder import tracked
        self.rank = rank
        self.mutex = tracked(threading.RLock(), f"engine[{rank}].mutex")
        self._inbox = collections.deque()  # guarded-by: _inbox_lock|_inbox_cond
        self._inbox_lock = tracked(threading.Lock(),
                                   f"engine[{rank}]._inbox_lock")
        self._inbox_cond = threading.Condition(self._inbox_lock)
        # bumped on every wakeup/enqueue: the blocking wait re-checks it
        # so a notify that lands between the final poll and the wait is
        # never lost (it would otherwise cost a full idle timeout)
        self._wake_gen = 0
        # self-pipe: wakeup() must also interrupt a wait blocked in
        # select() on channel fds (condvars can't); the pending byte is
        # level-triggered, so a wakeup that lands before the select
        # starts still ends it immediately
        import os as _os
        try:
            self._wake_r, self._wake_w = _os.pipe2(_os.O_NONBLOCK)
        except (AttributeError, OSError):  # pragma: no cover
            self._wake_r = self._wake_w = None
        self.channels: List[Channel] = []
        # pkt type -> handler(pkt); populated by protocol/rma layers
        self.pkt_handlers: Dict[int, Callable[[Packet], None]] = {}
        # packet types that must make progress even while the owning rank
        # is idle (passive-target RMA: the target of a lock/accumulate may
        # be busy computing or already past its last MPI call). Delivery
        # of such a packet triggers an inline drain from the delivering
        # thread — the software analog of the NIC servicing RDMA ops
        # without target CPU involvement (SURVEY §2.2 one-sided over RDMA).
        self.async_types: set = set()
        # req_id -> Request, for CTS/FIN/RESP lookup
        self.outstanding: Dict[int, Request] = {}
        # registered progress hooks (nonblocking-coll scheduler, RMA flush)
        self.hooks: List[Callable[[], bool]] = []
        self.shutdown = False
        # retired-work counters (drain_all reports the delta so Finalize
        # can log leftover traffic it had to flush)
        self.retired_pkts = 0
        self.retired_hooks = 0
        # trace/watchdog attach points (trace/recorder.py sets tracer,
        # trace/watchdog.py arms _stall_limit; both from
        # Universe.initialize after the config reload). None/None keeps
        # the hot paths at one attribute check when observability is off.
        self.tracer = None
        self.universe = None
        self._stall_limit: Optional[float] = None
        self._stall_tripped = False
        # lock-order monitor attach point (analysis/lockorder.configure,
        # from Universe.initialize); None keeps the wait path at one
        # attribute check when MV2T_LOCKCHECK is off
        self._lockcheck = None
        self._in_wait = False
        # liveness probe (failure containment): a callback run at the
        # blocking wait's sleep point that checks co-located peers'
        # heartbeat leases and feeds expiries into the ULFM sink, so a
        # dead peer unwinds this wait instead of hanging it. None keeps
        # the wait path at one attribute check when leases are off.
        self._liveness = None
        from .. import mpit
        self._pv_polls = mpit.pvar("progress_polls",
                                   mpit.PVAR_CLASS_COUNTER, "progress",
                                   "progress-engine poll passes "
                                   "(all ranks in this process)")

    # -- wiring -----------------------------------------------------------
    def add_channel(self, ch: Channel) -> None:
        ch.attach(self)
        self.channels.append(ch)

    def register_handler(self, ptype: PktType, fn: Callable,
                         asynchronous: bool = False) -> None:
        self.pkt_handlers[int(ptype)] = fn
        if asynchronous:
            self.async_types.add(int(ptype))

    def register_hook(self, fn: Callable[[], bool]) -> None:
        """Register a progress hook, run (mutex-held) at the end of every
        poll pass; it returns True when it made progress. Wakeup
        contract: any event that can make a hook's work runnable — a
        request completion (complete_request), an inbound packet
        (enqueue_incoming) — rings this engine's doorbell, so a waiter
        blocked in progress_wait re-polls immediately instead of
        sleeping out its backoff interval. The NBC scheduler
        (coll/nbc/engine.py) leans on exactly this: vertex completions
        advance schedules from their completion callbacks and the
        doorbell ends the waiter's sleep."""
        self.hooks.append(fn)

    def remove_hook(self, fn: Callable[[], bool]) -> None:
        try:
            self.hooks.remove(fn)
        except ValueError:
            pass

    def register_liveness(self, fn: Optional[Callable[[], int]]) -> None:
        """Install the liveness probe run at blocking waits' sleep
        points (``fn() -> peers newly declared dead``). Probes are
        handler-context code for the blocking lint pass: they run inside
        every wait, so they must never sleep or block."""
        self._liveness = fn

    # -- packet delivery (any thread) -------------------------------------
    def enqueue_incoming(self, pkt: Packet) -> None:
        with self._inbox_cond:
            self._inbox.append(pkt)
            self._wake_gen += 1
            self._inbox_cond.notify_all()
        if int(pkt.type) in self.async_types:
            self._async_drain()

    def _async_drain(self) -> None:
        """Inline inbox drain from the delivering thread. FIFO is
        preserved because the full inbox is drained in order. Safe from
        any thread — all rank-local protocol state is engine-mutex-guarded
        and reply sends that loop back to the deliverer's own engine
        re-enter through its RLock. Loops until the inbox is observed
        empty: a bare try-lock would strand a packet when the current
        mutex holder has already passed its own drain check."""
        while not self.shutdown:
            with self._inbox_lock:
                if not self._inbox:
                    return
            if self.mutex.acquire(blocking=False):
                try:
                    self._drain_inbox(swallow_errors=True)
                finally:
                    self.mutex.release()
                continue    # re-check: an append may have raced the drain
            # mutex holder is mid-progress and will (re)drain — wake it in
            # case it is parked in the idle wait, then yield and re-check
            self.wakeup()
            time.sleep(0.0001)

    def wakeup(self) -> None:
        with self._inbox_cond:
            self._wake_gen += 1
            self._inbox_cond.notify_all()
        if self._wake_w is not None:
            import os as _os
            try:
                _os.write(self._wake_w, b"x")
            except OSError:
                pass   # pipe full: a wakeup byte is already pending

    # -- completion (owning thread, mutex held) ---------------------------
    def complete_request(self, req: Request) -> None:
        with self.mutex:
            self.outstanding.pop(req.req_id, None)
            req._fire()
        self.wakeup()

    def track(self, req: Request) -> Request:
        self.outstanding[req.req_id] = req
        return req

    # -- the loop ---------------------------------------------------------
    def _dispatch(self, pkt: Packet) -> None:
        fn = self.pkt_handlers.get(int(pkt.type))
        if fn is None:
            raise MPIException(MPI_ERR_INTERN,
                               f"no handler for packet {pkt.type.name}")
        fn(pkt)

    def _drain_inbox(self, swallow_errors: bool = False) -> int:
        """``swallow_errors`` is set on the async-delivery path: a handler
        exception there would otherwise unwind into the *sender's* call
        stack (or a channel thread) and abandon the rest of the inbox —
        log it and keep draining instead."""
        n = 0
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    break
                pkt = self._inbox.popleft()
            try:
                self._dispatch(pkt)
            except Exception:
                if not swallow_errors:
                    raise
                log.error("async handler for %s failed", pkt.type,
                          exc_info=True)
            n += 1
        return n

    def progress_poke(self) -> bool:
        """One nonblocking pass (MPID_Progress_test analog)."""
        with self.mutex:
            self._pv_polls.inc()
            npkts = self._drain_inbox()
            chan_did = False
            for ch in self.channels:
                if ch.poll():
                    chan_did = True
            npkts += self._drain_inbox()
            nhooks = 0
            for hook in list(self.hooks):
                if hook():
                    nhooks += 1
            self.retired_pkts += npkts
            self.retired_hooks += nhooks
        return bool(npkts or chan_did or nhooks)

    def progress_wait(self, pred: Callable[[], bool],
                      timeout: Optional[float] = None) -> None:
        """Poll/sleep until ``pred()`` — MPID_Progress_wait analog."""
        tr = self.tracer
        # _in_wait: read by the liveness probe so a lease detection that
        # lands while a blocking wait is parked counts into the
        # wait_deadline_trips pvar (detections during plain pokes don't)
        self._in_wait = True
        try:
            if tr is None and self._stall_limit is None:
                return self._progress_wait(pred, timeout, None, None)
            stall_at = None
            if self._stall_limit is not None and not self._stall_tripped:
                stall_at = time.monotonic() + self._stall_limit
            if tr is not None:
                tr.record("progress", "progress_wait", "B")
            try:
                return self._progress_wait(pred, timeout, tr, stall_at)
            finally:
                if tr is not None:
                    tr.record("progress", "progress_wait", "E")
        finally:
            self._in_wait = False

    def _progress_wait(self, pred: Callable[[], bool],
                       timeout: Optional[float], tr,
                       stall_at: Optional[float]) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        spin = 0
        while True:
            with self.mutex:
                if pred():
                    return
            # Advertise intent to sleep BEFORE the final empty poll: a
            # sender writing after that poll sees the flag and rings the
            # doorbell; one writing before it is caught by the poll
            # (ShmChannel's adaptive bell — senders skip the doorbell
            # syscall for awake receivers).
            for ch in self.channels:
                ch.pre_wait()
            gen = self._wake_gen   # sampled before the final poll
            try:
                if self.progress_poke():
                    spin = 0
                with self.mutex:
                    if pred():
                        return
                spin += 1
                if self._lockcheck is not None:
                    # about to block: holding any tracked lock here is
                    # the handler-deadlock shape (lock-order monitor)
                    self._lockcheck.check_wait(self.rank)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("progress_wait timed out")
                if self._liveness is not None and spin >= 2:
                    # deadline-by-lease: past the first backoff step the
                    # wait is genuinely idle — check whether a peer we
                    # may be waiting on has gone dark. A detection
                    # completes the dependent requests (ULFM sweep), so
                    # the next pred() check unwinds this wait with
                    # MPIX_ERR_PROC_FAILED; unrelated waits keep going.
                    if self._liveness():
                        continue      # re-check pred before sleeping
                if stall_at is not None and not self._stall_tripped \
                        and time.monotonic() > stall_at:
                    # one-shot hang diagnostic (queue snapshot, requests,
                    # NBC schedules, trace tail) — the wait itself keeps
                    # going; the watchdog observes, it does not unwind
                    from ..trace import watchdog as _wd
                    _wd.trip(self)
                # Idle strategy: block on the union of the channels'
                # wakeup fds (shm doorbells, tcp sockets) so a peer's
                # send wakes us via a direct context switch. Never
                # busy-yield and never spin while holding the core: on
                # an oversubscribed host sched_yield only reschedules at
                # the next tick (~350 us measured) and every extra spin
                # delays the peer, while fd wakeup costs ~2 us.
                # Push-only channels (threaded fabric) use the inbox
                # condition instead. Futile wake->poll cycles back off
                # exponentially (0.5 ms -> 8 ms): every futile poll on
                # an oversubscribed core steals CPU from exactly the
                # peer whose send we are waiting on, and the doorbell /
                # condvar still ends the sleep early.
                idle_t = min(0.0005 * (1 << min(spin - 1, 4)), 0.008)
                if tr is not None:
                    tr.record("progress", "idle", "B", spin=spin)
                woken = False
                import select as _select
                fds = []
                for ch in self.channels:
                    fds.extend(ch.wait_fds())
                if fds:
                    if self._wake_r is not None:
                        fds.append(self._wake_r)
                    try:
                        r, _, _ = _select.select(fds, [], [], idle_t)
                    except (OSError, ValueError):
                        pass
                    else:
                        woken = bool(r)
                        if self._wake_r in r:
                            import os as _os
                            try:
                                _os.read(self._wake_r, 4096)
                            except OSError:
                                pass
                else:
                    with self._inbox_cond:
                        if not self._inbox and self._wake_gen == gen:
                            self._inbox_cond.wait(timeout=idle_t)
                        else:
                            woken = True
                if tr is not None:
                    tr.record("progress", "idle", "E")
                    if woken:
                        tr.record("progress", "wake", "i")
            finally:
                for ch in self.channels:
                    ch.post_wait()

    def drain_all(self, timeout: float = 5.0) -> int:
        """Progress until no work remains (used at Finalize/quiesce).
        Returns how much leftover work it retired — packets dispatched
        plus hook advances — so Finalize can log traffic that was still
        in flight when the application called it."""
        p0, h0 = self.retired_pkts, self.retired_hooks
        end = time.monotonic() + timeout
        idle = 0
        while time.monotonic() < end:
            if self.progress_poke():
                idle = 0
            else:
                idle += 1
                if idle > 3:
                    break
                time.sleep(0.0002)
        return (self.retired_pkts - p0) + (self.retired_hooks - h0)

    def close(self) -> None:
        self.shutdown = True
        for ch in self.channels:
            ch.close()
        self.wakeup()
        if self._wake_r is not None:
            import os as _os
            for fd in (self._wake_r, self._wake_w):
                try:
                    _os.close(fd)
                except OSError:
                    pass
            self._wake_r = self._wake_w = None

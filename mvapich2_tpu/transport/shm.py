"""Shared-memory channel for co-located rank processes.

The SMP channel (SURVEY §2.2 ch3_smp_progress.c analog): a per-node mmap'd
segment of SPSC rings for every (src, dst) pair, written by the native C++
fast path (native/shmring.cpp, loaded via ctypes). A pure-Python
implementation of the identical layout serves as fallback when the .so
can't be built. Bootstrap (who creates the segment, name exchange) rides
the KVS like everything else.

Zero-copy rendezvous: large messages use the RGET protocol with a
size-ordered handle ladder — CMA (the receiver reads the sender's user
buffer via process_vm_readv when the unanimous bootstrap probe passed),
the persistent per-node scratch arena (transport/arena.py — one block
allocation per send, reused across sends), and only as the last resort
the legacy per-send scratch file. Oversize python packets (spills) stage
through the arena too, reclaimed via its spill-consumed counters.
"""

from __future__ import annotations

import collections
import ctypes
import mmap
import os
import select
import socket
import struct
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..utils.config import cvar, get_config
from ..utils.mlog import get_logger
from .arena import ShmArena, cma_read
from .base import Channel, Packet, decode_packet, encode_packet

log = get_logger("shm")

cvar("SHM_RING_BYTES", 0, int, "shm",
     "Per-(src,dst)-pair ring size in bytes (analog of "
     "MV2_SMP_QUEUE_LENGTH). 0 = auto: sized by co-located rank count "
     "(4 MiB for <=2, 2 MiB for <=4, 1 MiB beyond) so a 64-deep window "
     "of eager-size payloads stays in flight without backpressure.")
cvar("USE_CPLANE", 1, int, "shm",
     "Use the native C data plane (envelope matching in C) when the native "
     "ring is available. 0 falls back to python-side matching.")
cvar("CPLANE_DEBUG", 0, int, "shm",
     "Native C-plane debug tracing to stderr (read by cplane.cpp's "
     "cp_debug() straight from the env at attach, so it must be set at "
     "launch; any non-empty value enables).")
cvar("USE_CMA", 1, int, "shm",
     "Use cross-memory-attach (process_vm_readv) for large intra-node "
     "messages when the bootstrap probe succeeds (the CMA/LiMIC2 path of "
     "ch3_smp_progress.c:525). 0 forces the staged rendezvous.")
cvar("WIRE_TIMEOUT", 120.0, float, "shm",
     "Deadline in seconds for the blocking per-node wire gate "
     "(ensure_wired): how long a collective/rendezvous entry waits for "
     "every co-located rank to publish its wiring cards before failing "
     "with MPI_ERR_INTERN. Lazy wiring only blocks where all "
     "participants are known to arrive (collectives, rendezvous).")
cvar("PEER_TIMEOUT", 10.0, float, "ft",
     "Liveness-lease timeout in seconds: a co-located peer whose "
     "heartbeat stamp (refreshed by a dedicated thread, so compute-"
     "silent ranks stay alive) goes stale past this is declared dead — "
     "blocking waits in the datapath unwind with MPIX_ERR_PROC_FAILED "
     "instead of hanging. 0 disables lease detection. Containment "
     "latency for a SIGKILLed peer is <= 2x this value.")

from .. import mpit as _mpit  # noqa: E402  (after cvar decls, same registry)

# Plane counters (the mv2_mpit.c:17-39 channel-counter analog). Declared
# at import so tools can enumerate them; finish_wiring() rebinds the
# sources to the live plane.
_PV_PLANE_DECLS = [
    ("cplane_eager_tx", "eager sends injected by the C plane"),
    ("cplane_eager_rx", "eager receives matched in the C plane"),
    ("cplane_fwd_py",
     "packets forwarded to the python protocol layer (fast-path misses)"),
    ("cplane_rndv_tx", "CMA rendezvous sends exposed by the C plane"),
    ("cplane_rndv_rx", "CMA rendezvous pulls completed by the C plane"),
]
for _n, _d in _PV_PLANE_DECLS:
    _mpit.pvar(_n, _mpit.PVAR_CLASS_COUNTER, "shm", _d)

# startup-path observability: every node wire is counted as eager
# (bootstrap/spawn forced it) or lazy (deferred to the first operation
# that needed the agreement — the on-demand CM model)
pv_wiring_eager = _mpit.pvar(
    "wiring_eager", _mpit.PVAR_CLASS_COUNTER, "shm",
    "shm channels wired eagerly at bootstrap "
    "(MV2T_LAZY_WIRING=0 or the spawn path)")
pv_wiring_lazy = _mpit.pvar(
    "wiring_lazy", _mpit.PVAR_CLASS_COUNTER, "shm",
    "shm channels wired on demand, at the first rendezvous/collective "
    "that needed the per-node agreement")

# Fast-path observability (native/mpi/fastpath.c + the flat collective
# tier in cplane.cpp). Index order mirrors cplane.cpp's FPC_* enum; the
# counters live in the plane (cp_fp_counters) so both the C ABI's
# fastpath and python-rank flat collectives feed the same slots.
_FP_COUNTERS = [
    ("fp_hits", "pt2pt operations completed on the C fast path"),
    ("fp_gil_takes",
     "python progress passes taken from the C fast path's hot loop"),
    ("fp_fallback_dtype", "fast-path fallbacks: datatype not carryable"),
    ("fp_fallback_comm", "fast-path fallbacks: comm not plane-owned"),
    ("fp_fallback_size", "fast-path fallbacks: payload above fp_threshold"),
    ("fp_fallback_plane", "fast-path fallbacks: plane missing or failed"),
    ("fp_coll_flat", "collectives completed on the flat-slot shm tier"),
    ("fp_coll_sched", "collectives completed on the C pt2pt schedules"),
    ("fp_wait_spin", "fast-path blocking waits satisfied during the spin"),
    ("fp_wait_bell",
     "fast-path blocking waits satisfied after the doorbell sleep"),
    ("fp_flat_progress",
     "python progress callbacks fired from flat-collective waits"),
    ("fp_dead_peer",
     "peers declared dead by the C-plane lease scan (flat waits and "
     "wait quanta)"),
    ("fp_coll_flat2",
     "collectives completed on the hierarchical flat tier / multicast "
     "bcast (cp_flat2_*)"),
]
for _n, _d in _FP_COUNTERS:
    _mpit.pvar(_n, _mpit.PVAR_CLASS_COUNTER, "fastpath", _d)

# ring framing + flags-segment layout constants. The C side's numbers
# live in native/shm_layout.h; the mv2tlint `native` pass checks the two
# sets byte-for-byte (MV2T_RING_HDR_BYTES <-> _HEADER, ...), so a drift
# is a lint failure instead of a silent protocol break.
_HEADER = 128            # per-ring control block (MV2T_RING_HDR_BYTES)
_WRAP = 0xFFFFFFFF       # wrap marker (MV2T_RING_WRAP)
_ALIGN = 8               # ring message alignment (MV2T_RING_ALIGN)
_LEASE_ALIGN = 8         # flags segment: pad sleep bytes to this
_LEASE_STAMP = 8         # bytes per liveness-lease stamp (u64)
_FPC_SLOTS = 16          # fast-path counter mirror slots per rank
                         # (MV2T_FPC_SLOTS — the flags-segment tail that
                         # makes fp_* counters attachable by bin/mpistat)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_lib = None
_lib_tried = False


def _load_native():
    """Load (building if needed) the C++ ring library."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    # sanitizer lane (bin/runtests --tsan): every consumer in the job —
    # this ctypes loader AND fastpath.c's dlopen — must map the SAME
    # instrumented ring, so the override is one env var for both
    so = os.environ.get("MV2T_SHMRING_SO") or os.path.join(
        _REPO, "native", "libshmring.so")
    # always run make (no-op when fresh): an existence check would keep
    # loading a stale .so after shmring.cpp edits. fcntl.flock serializes
    # co-launched ranks racing on the shared build target. An override
    # points at a prebuilt variant (the sanitizer lane owns its build).
    try:
        if os.environ.get("MV2T_SHMRING_SO"):
            if not os.path.exists(so):
                raise OSError(f"MV2T_SHMRING_SO does not exist: {so}")
        else:
            import fcntl
            native_dir = os.path.join(_REPO, "native")
            with open(os.path.join(native_dir, ".build.lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    subprocess.run(["make", "-C", native_dir,
                                    "libshmring.so"],
                                   capture_output=True, timeout=120,
                                   check=True)
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
    except Exception as e:
        if not os.path.exists(so):
            log.warn("native shmring build failed (%s); python fallback", e)
            return None
        log.warn("shmring rebuild failed (%s); using existing .so", e)
    try:
        lib = ctypes.CDLL(so)
        lib.sr_attach.restype = ctypes.c_void_p
        lib.sr_attach.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_long, ctypes.c_int]
        lib.sr_send.restype = ctypes.c_int
        lib.sr_send.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_char_p, ctypes.c_long]
        lib.sr_peek.restype = ctypes.c_long
        lib.sr_peek.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.sr_recv.restype = ctypes.c_long
        lib.sr_recv.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_long]
        lib.sr_detach.argtypes = [ctypes.c_void_p]
        lib.sr_capacity.restype = ctypes.c_long
        lib.sr_capacity.argtypes = [ctypes.c_void_p]
        _bind_cplane(lib)
        _lib = lib
    except OSError as e:  # pragma: no cover
        log.warn("cannot load libshmring.so (%s); python fallback", e)
        _lib = None
    return _lib


def _bind_cplane(lib) -> None:
    """ctypes signatures for the native data plane (native/cplane.cpp)."""
    L = ctypes
    lib.cp_create.restype = L.c_void_p
    lib.cp_create.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_char_p]
    lib.cp_destroy.argtypes = [L.c_void_p]
    lib.cp_register_global.argtypes = [L.c_void_p]
    lib.cp_set_bell.argtypes = [L.c_void_p, L.c_int, L.c_char_p]
    lib.cp_set_world.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_set_wait_fd.argtypes = [L.c_void_p, L.c_int]
    lib.cp_ctx_enable.argtypes = [L.c_void_p, L.c_int]
    lib.cp_ctx_disable.argtypes = [L.c_void_p, L.c_int]
    lib.cp_inject.argtypes = [L.c_void_p, L.c_int, L.c_char_p, L.c_long]
    lib.cp_send_eager.restype = L.c_longlong
    lib.cp_send_eager.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                  L.c_int, L.c_void_p, L.c_long, L.c_longlong]
    lib.cp_irecv.restype = L.c_longlong
    lib.cp_irecv.argtypes = [L.c_void_p, L.c_void_p, L.c_long, L.c_int,
                             L.c_int, L.c_int]
    lib.cp_req_state.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_req_status.argtypes = [L.c_void_p, L.c_longlong,
                                  L.POINTER(L.c_int), L.POINTER(L.c_int),
                                  L.POINTER(L.c_longlong), L.POINTER(L.c_int),
                                  L.POINTER(L.c_int)]
    lib.cp_req_buf.argtypes = [L.c_void_p, L.c_longlong,
                               L.POINTER(L.c_void_p), L.POINTER(L.c_longlong)]
    lib.cp_req_free.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_req_orphan.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_cancel_recv.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_complete_assist.argtypes = [L.c_void_p, L.c_longlong, L.c_longlong,
                                       L.c_int, L.c_int, L.c_int]
    lib.cp_error_req.argtypes = [L.c_void_p, L.c_longlong, L.c_int]
    lib.cp_advance.argtypes = [L.c_void_p]
    lib.cp_coll_gather.restype = L.c_int
    lib.cp_coll_gather.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                   L.c_void_p, L.c_void_p, L.c_long,
                                   L.c_void_p]
    lib.cp_py_pending.argtypes = [L.c_void_p]
    lib.cp_py_peek.restype = L.c_long
    lib.cp_py_peek.argtypes = [L.c_void_p]
    lib.cp_py_pop.restype = L.c_long
    lib.cp_py_pop.argtypes = [L.c_void_p, L.c_char_p, L.c_long]
    lib.cp_assist_pending.argtypes = [L.c_void_p]
    lib.cp_assist_peek.restype = L.c_long
    lib.cp_assist_peek.argtypes = [L.c_void_p]
    lib.cp_assist_pop.restype = L.c_long
    lib.cp_assist_pop.argtypes = [L.c_void_p, L.POINTER(L.c_longlong),
                                  L.c_char_p, L.c_long]
    lib.cp_probe.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int, L.c_int,
                             L.POINTER(L.c_int), L.POINTER(L.c_int),
                             L.POINTER(L.c_longlong), L.POINTER(L.c_longlong)]
    lib.cp_mrecv_start.restype = L.c_longlong
    lib.cp_mrecv_start.argtypes = [L.c_void_p, L.c_longlong, L.c_void_p,
                                   L.c_long]
    lib.cp_cancel_send.argtypes = [L.c_void_p, L.c_longlong, L.c_int]
    lib.cp_cancel_result.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_cancel_forget.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_mark_failed.argtypes = [L.c_void_p, L.c_int]
    lib.cp_any_failed.argtypes = [L.c_void_p]
    lib.cp_rank_failed.argtypes = [L.c_void_p, L.c_int]
    # liveness leases + flat-region forensics (failure containment)
    lib.cp_set_peer_timeout.argtypes = [L.c_void_p, L.c_longlong]
    lib.cp_lease_age_us.restype = L.c_longlong
    lib.cp_lease_age_us.argtypes = [L.c_void_p, L.c_int]
    lib.cp_lease_scan.argtypes = [L.c_void_p]
    lib.cp_flat_poisoned.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat_poison_region.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat_slot_state.argtypes = [L.c_void_p, L.c_int, L.c_int,
                                       L.c_int, L.POINTER(L.c_longlong),
                                       L.POINTER(L.c_longlong)]
    lib.cp_posted_count.argtypes = [L.c_void_p]
    lib.cp_posted_get.argtypes = [L.c_void_p, L.c_int,
                                  L.POINTER(L.c_longlong), L.POINTER(L.c_int),
                                  L.POINTER(L.c_int), L.POINTER(L.c_int)]
    lib.cp_unexpected_count.argtypes = [L.c_void_p]
    lib.cp_stats.argtypes = [L.c_void_p, L.POINTER(L.c_ulonglong),
                             L.POINTER(L.c_ulonglong),
                             L.POINTER(L.c_ulonglong)]
    lib.cp_wait_quantum.argtypes = [L.c_void_p, L.c_longlong, L.c_long,
                                    L.c_long]
    lib.cp_send_rndv.restype = L.c_longlong
    lib.cp_send_rndv.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                 L.c_int, L.c_void_p, L.c_longlong]
    lib.cp_rndv_wire.restype = L.c_longlong
    lib.cp_rndv_wire.argtypes = [L.c_longlong]
    lib.cp_coll_tag.argtypes = [L.c_void_p, L.c_int]
    lib.cp_set_cma.argtypes = [L.c_void_p, L.c_int]
    lib.cp_cma_enabled.argtypes = [L.c_void_p]
    lib.cp_set_wired.argtypes = [L.c_void_p]
    lib.cp_wired.argtypes = [L.c_void_p]
    lib.cp_congested.argtypes = [L.c_void_p, L.c_int]
    lib.cp_rndv_stats.argtypes = [L.c_void_p, L.POINTER(L.c_ulonglong),
                                  L.POINTER(L.c_ulonglong)]
    # flat-slot collective tier + fast-path counters
    lib.cp_flat_attach.argtypes = [L.c_void_p, L.c_char_p, L.c_int]
    lib.cp_flat_ok.argtypes = [L.c_void_p]
    lib.cp_flat_disable.argtypes = [L.c_void_p]
    lib.cp_flat_base.restype = L.c_longlong
    lib.cp_flat_base.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat_op_ok.argtypes = [L.c_int, L.c_int]
    lib.cp_flat_payload_max.restype = L.c_long
    lib.cp_flat_nslots.restype = L.c_int
    lib.cp_flat_lanes.restype = L.c_int
    lib.cp_flat_allreduce.argtypes = [
        L.c_void_p, L.c_int, L.c_int, L.c_int, L.c_int, L.c_longlong,
        L.c_int, L.c_int, L.c_void_p, L.c_void_p, L.c_longlong,
        L.c_longlong]
    lib.cp_flat_reduce.argtypes = [
        L.c_void_p, L.c_int, L.c_int, L.c_int, L.c_int, L.c_longlong,
        L.c_int, L.c_int, L.c_int, L.c_void_p, L.c_void_p, L.c_longlong,
        L.c_longlong]
    lib.cp_flat_bcast.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                  L.c_int, L.c_longlong, L.c_int,
                                  L.c_void_p, L.c_longlong]
    lib.cp_flat_barrier.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                    L.c_int, L.c_longlong]
    lib.cp_flat_set_progress_cb.argtypes = [L.c_void_p, L.c_void_p]
    # hierarchical flat tier + multicast bcast (cp_flat2_*)
    lib.cp_flat2_attach.argtypes = [L.c_void_p, L.c_char_p, L.c_int]
    lib.cp_flat2_ok.argtypes = [L.c_void_p]
    lib.cp_flat2_disable.argtypes = [L.c_void_p]
    lib.cp_flat2_base.restype = L.c_longlong
    lib.cp_flat2_base.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat2_payload_max.restype = L.c_long
    lib.cp_flat2_group.restype = L.c_int
    lib.cp_flat2_max_ranks.restype = L.c_int
    lib.cp_flat2_lanes.restype = L.c_int
    lib.cp_flat2_poisoned.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat2_poison_region.argtypes = [L.c_void_p, L.c_int, L.c_int]
    lib.cp_flat2_slot_state.argtypes = [L.c_void_p, L.c_int, L.c_int,
                                        L.c_int, L.c_int,
                                        L.POINTER(L.c_longlong),
                                        L.POINTER(L.c_longlong)]
    lib.cp_flat2_allreduce.argtypes = [
        L.c_void_p, L.c_int, L.c_int, L.c_int, L.c_int, L.c_longlong,
        L.c_int, L.c_int, L.c_void_p, L.c_void_p, L.c_longlong,
        L.c_longlong]
    lib.cp_flat2_reduce.argtypes = [
        L.c_void_p, L.c_int, L.c_int, L.c_int, L.c_int, L.c_longlong,
        L.c_int, L.c_int, L.c_int, L.c_void_p, L.c_void_p, L.c_longlong,
        L.c_longlong]
    lib.cp_flat2_bcast.argtypes = [L.c_void_p, L.c_int, L.c_int, L.c_int,
                                   L.c_int, L.c_longlong, L.c_int,
                                   L.c_void_p, L.c_longlong, L.c_int]
    lib.cp_flat2_barrier.argtypes = [L.c_void_p, L.c_int, L.c_int,
                                     L.c_int, L.c_int, L.c_longlong]
    lib.cp_fp_counter.restype = L.c_ulonglong
    lib.cp_fp_counter.argtypes = [L.c_void_p, L.c_int]
    # native trace ring (MV2T_NTRACE; trace/native.py drains the file)
    lib.cp_ntrace_attach.argtypes = [L.c_void_p, L.c_char_p, L.c_int]
    lib.cp_ntrace_ok.argtypes = [L.c_void_p]
    lib.cp_ntrace_emit.argtypes = [L.c_void_p, L.c_int, L.c_longlong,
                                   L.c_longlong]


class _PyRing:
    """Pure-Python twin of the C++ layout (single segment mmap)."""

    def __init__(self, path: str, nranks: int, ring_bytes: int,
                 create: bool):
        total = nranks * nranks * ring_bytes
        flags = os.O_CREAT | os.O_RDWR if create else os.O_RDWR
        self.fd = os.open(path, flags, 0o600)
        if create:
            os.ftruncate(self.fd, total)
        self.mm = mmap.mmap(self.fd, total)
        if create:
            self.mm[:total] = b"\x00" * total
        self.nranks = nranks
        self.ring_bytes = ring_bytes
        self.cap = ring_bytes - _HEADER

    def _off(self, src: int, dst: int) -> int:
        return (src * self.nranks + dst) * self.ring_bytes

    def _head(self, off: int) -> int:
        return struct.unpack_from("<Q", self.mm, off)[0]

    def _tail(self, off: int) -> int:
        return struct.unpack_from("<Q", self.mm, off + 8)[0]

    def send(self, src: int, dst: int, payload: bytes) -> int:
        off = self._off(src, dst)
        cap = self.cap
        need = (4 + len(payload) + _ALIGN - 1) & ~(_ALIGN - 1)
        if need + _ALIGN >= cap:
            return -1
        head, tail = self._head(off), self._tail(off)
        used = tail - head
        pos = tail % cap
        contig = cap - pos
        base = off + _HEADER
        if contig < need:
            if used + contig + need > cap:
                return 0
            struct.pack_into("<I", self.mm, base + pos, _WRAP)
            tail += contig
            struct.pack_into("<Q", self.mm, off + 8, tail)
            pos = 0
        elif used + need > cap:
            return 0
        struct.pack_into("<I", self.mm, base + pos, len(payload))
        self.mm[base + pos + 4:base + pos + 4 + len(payload)] = payload
        struct.pack_into("<Q", self.mm, off + 8, tail + need)
        return 1

    def recv(self, src: int, dst: int) -> Optional[bytes]:
        off = self._off(src, dst)
        cap = self.cap
        base = off + _HEADER
        while True:
            head, tail = self._head(off), self._tail(off)
            if head == tail:
                return None
            pos = head % cap
            ln = struct.unpack_from("<I", self.mm, base + pos)[0]
            if ln == _WRAP or cap - pos < 4:
                head += cap - pos
                struct.pack_into("<Q", self.mm, off, head)
                continue
            data = bytes(self.mm[base + pos + 4:base + pos + 4 + ln])
            need = (4 + ln + _ALIGN - 1) & ~(_ALIGN - 1)
            struct.pack_into("<Q", self.mm, off, head + need)
            return data

    def close(self):
        self.mm.close()
        os.close(self.fd)


class _NativeRing:
    def __init__(self, lib, path: str, nranks: int, ring_bytes: int,
                 create: bool):
        self.lib = lib
        self.h = lib.sr_attach(path.encode(), nranks, ring_bytes,
                               1 if create else 0)
        if not self.h:
            raise OSError(f"sr_attach failed for {path}")
        self._rbuf = ctypes.create_string_buffer(ring_bytes)

    def send(self, src: int, dst: int, payload: bytes) -> int:
        return self.lib.sr_send(self.h, src, dst, payload, len(payload))

    def recv(self, src: int, dst: int) -> Optional[bytes]:
        # sr_recv itself returns <=0 on empty, so no sr_peek round-trip;
        # _rbuf is ring-sized and anything larger goes the __bigmsg__
        # path, so the buffer always fits
        got = self.lib.sr_recv(self.h, src, dst, self._rbuf, len(self._rbuf))
        if got <= 0:
            return None
        # string_at copies exactly `got` bytes; ._rbuf.raw would copy
        # the whole ring-sized buffer per message
        return ctypes.string_at(self._rbuf, got)

    def close(self):
        self.lib.sr_detach(self.h)


class ShmChannel(Channel):
    name = "shm"
    supports_rget = True

    def __init__(self, my_rank: int, local_ranks: List[int], kvs,
                 ring_bytes: Optional[int] = None, boot_card=None,
                 daemon_claim=None):
        self.my_rank = my_rank           # world rank
        self.local_ranks = sorted(local_ranks)
        self.local_index = {r: i for i, r in enumerate(self.local_ranks)}
        self.n_local = len(self.local_ranks)
        self.kvs = kvs
        # deferred card publication: everything this constructor would
        # kvs.put travels in ONE batched put_many at the end (the
        # serial-RTT collapse of the batched bootstrap)
        self._cards: Dict[str, str] = {}
        # boot_card: the node leader's light-boot segment card
        # (runtime/boot.py) — pre-created zero-filled files every rank
        # attaches without ordering on the leader's world build.
        # daemon_claim: the leader's warm-attach claim to release at
        # close (runtime/daemon.py).
        self._boot_mode = boot_card is not None
        self._daemon = bool(boot_card and boot_card.get("daemon"))
        self._daemon_claim = daemon_claim
        if ring_bytes is None:
            if boot_card is not None:
                # the leader sized the segment at light boot; geometry
                # is part of the versioned card, never recomputed
                ring_bytes = int(boot_card["ring_bytes"])
            else:
                ring_bytes = get_config()["SHM_RING_BYTES"]
            if not ring_bytes:
                # auto (the vbuf-pool sizing discipline of ibv_param.c):
                # with few co-located ranks the n^2 segment is cheap,
                # and a deeper ring keeps a 64-message window of
                # eager-size payloads in flight without backpressure
                # (64 x 64 KiB = 4 MiB). Deterministic in n_local, so
                # every rank computes the same segment layout.
                if self.n_local <= 2:
                    ring_bytes = 4 << 20
                elif self.n_local <= 4:
                    ring_bytes = 2 << 20
                else:
                    ring_bytes = 1 << 20
        ring_bytes = (ring_bytes + 7) & ~7
        leader = self.local_ranks[0]
        self._owner = my_rank == leader
        segkey = f"shm-seg-{leader}"
        if boot_card is not None:
            # pre-created at light boot: zero-filled IS the initialized
            # ring state, so every rank (owner included) attaches with
            # create=0 — no memset, no ordering
            path = boot_card["ring"]
            self._ring = self._make_ring(path, ring_bytes, create=False)
            if self._owner:
                self._cards[segkey] = path
        elif self._owner:
            base = "/dev/shm" if os.path.isdir("/dev/shm") \
                else tempfile.gettempdir()
            path = os.path.join(
                base, f"mv2t-shm-{os.getpid()}-{uuid.uuid4().hex[:8]}")
            self._ring = self._make_ring(path, ring_bytes, create=True)
            kvs.put(segkey, path)
        else:
            path = kvs.get(segkey)
            self._ring = self._make_ring(path, ring_bytes, create=False)
        self.path = path
        # -- persistent per-node scratch arena (transport/arena.py) ------
        # created (or daemon-attached) by the leader alongside the ring
        # segment; replaces the per-send scratch files for RGET exposure
        # and oversize spills. Followers attach during wiring — the
        # leader's card is guaranteed published by then — and usability
        # is agreed unanimously (like CMA) so sender and receiver always
        # dispatch handles identically.
        self.arena: Optional[ShmArena] = None
        self.cma_ok = False          # python-level CMA verdict (post-wire)
        self._arena_ready = False    # set after the unanimous agreement
        base = os.path.dirname(path)
        arena_key = f"shm-arena-{leader}"
        if self._owner:
            try:
                if self._daemon:
                    # warm attach: the claimed (reset) arena file; the
                    # zeroed spill grid is the created state
                    apath = boot_card["arena"]
                    self.arena = ShmArena(apath, self.n_local,
                                          self.local_index[my_rank],
                                          int(boot_card["part_bytes"]),
                                          create=True, exclusive=False)
                else:
                    ShmArena.sweep_stale(base)
                    apath = os.path.join(
                        base,
                        f"mv2t-arena-{os.getpid()}-{uuid.uuid4().hex[:8]}")
                    self.arena = ShmArena(apath, self.n_local,
                                          self.local_index[my_rank],
                                          create=True)
                self._cards[arena_key] = f"{apath}:{self.arena.part_bytes}"
            except Exception as e:
                log.warn("arena create failed (%s); scratch-file "
                         "rendezvous", e)
                self._cards[arena_key] = ""
        # exposure table: wire handle -> keepalive (ndarray for CMA,
        # ArenaHandle for arena blocks) — the registration-cache handle
        # table; leak-checked at close()
        self._exposed: Dict[tuple, object] = {}
        self._expose_tok = 0
        # arena-staged spill bookkeeping: dst local index -> deque of
        # (seq, ArenaHandle), reclaimed when the receiver's consumed
        # counter passes seq
        self._spill_pending: Dict[int, collections.deque] = {}
        self._spill_seq: Dict[int, int] = {}
        # spill bookkeeping lock: plane-mode sends bypass _send_lock (the
        # C injector owns ordering) but still stage spills here
        from ..analysis.lockorder import tracked
        self._spill_lock = tracked(threading.Lock(),
                                   f"shm[{my_rank}]._spill_lock")
        self._backlog: Dict[int, collections.deque] = {}
        # serializes the ring producer + backlog: the SPSC ring assumes
        # one producer per (src,dst) pair, but sends arrive from any
        # user thread (MPI-IO worker, THREAD_MULTIPLE) while poll()
        # flushes the backlog under the engine mutex. Channel-local and
        # never held across a wait, so no cross-engine cycle.
        self._send_lock = tracked(threading.Lock(),
                                  f"shm[{my_rank}]._send_lock")
        # Doorbell: a per-rank unix datagram socket. Senders fire one
        # best-effort datagram after each ring write so a receiver blocked
        # in wait_for_event wakes immediately — sched_yield on an
        # oversubscribed core only reschedules at the next tick (~350 us
        # measured), while a blocking-read wakeup is ~2 us. This is the
        # nemesis fastbox-signal discipline.
        self._bell = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        bell_path = f"{path}.bell-{my_rank}"
        try:
            os.unlink(bell_path)
        except OSError:
            pass
        self._bell.bind(bell_path)
        self._bell.setblocking(False)
        self._bell_path = bell_path
        self._cards[f"shm-bell-{my_rank}"] = bell_path
        # CMA probe buffer: published with the build cards; the wire
        # step reads a neighbor's copy to decide whether
        # process_vm_readv works here (kept alive for the channel
        # lifetime). Bell-card presence implies probe-card presence —
        # they ride the same batched put.
        self._cma_probe = np.frombuffer(
            f"mv2t-cma-{my_rank:012d}".encode(), dtype=np.uint8).copy()
        self._cards[f"shm-cma-{my_rank}"] = (
            f"{os.getpid()}:{self._cma_probe.ctypes.data}"
            f":{self._cma_probe.size}")
        self._peer_bells: Dict[int, str] = {}
        # liveness-lease timeout (cached: the probe runs at blocking
        # waits' sleep points; config is reloaded before channels wire)
        self._peer_timeout = float(
            get_config().get("PEER_TIMEOUT", 0.0) or 0.0)
        # Adaptive bell: a shared byte per local rank, set while that
        # rank is parked in the engine's blocking wait. Senders skip the
        # doorbell syscall (~0.15 ms on an oversubscribed host) for
        # awake receivers — those are polling anyway. The engine's
        # pre_wait (advertise) -> final poll -> sleep order makes the
        # skip race-free.
        # flags segment layout: [n_local sleep bytes][pad to 8][n_local
        # u64 liveness-lease stamps]. The lease tail is the heartbeat
        # surface of the failure-containment layer: every rank's stamp
        # is refreshed by a dedicated thread (plus the C plane's
        # advance_locked), and every blocking wait — python progress
        # waits, C flat waves, C wait quanta — scans peers' stamps
        # against MV2T_PEER_TIMEOUT so a SIGKILLed peer is a detectable
        # event instead of a hang. cplane.cpp maps the same layout.
        flags_path = boot_card["flags"] if boot_card is not None \
            else f"{path}.flags"
        lease_off = (self.n_local + _LEASE_ALIGN - 1) & ~(_LEASE_ALIGN - 1)
        flags_len = lease_off + _LEASE_STAMP * self.n_local \
            + 8 * _FPC_SLOTS * self.n_local
        if boot_card is not None:
            pass    # pre-created (zeroed) at light boot; just map it
        elif self._owner:
            # write-then-rename so followers never see a short file
            with open(flags_path + ".tmp", "wb") as f:
                f.write(b"\0" * flags_len)
            os.replace(flags_path + ".tmp", flags_path)
        else:
            deadline = time.monotonic() + 30.0
            while not (os.path.exists(flags_path)
                       and os.path.getsize(flags_path) >= flags_len):
                if time.monotonic() > deadline:
                    raise OSError(f"shm flags segment never appeared: "
                                  f"{flags_path}")
                time.sleep(0.001)
        self._flags_path = flags_path
        self._flags_f = open(flags_path, "r+b")
        self._flags = mmap.mmap(self._flags_f.fileno(), flags_len)
        self._lease = np.frombuffer(self._flags, dtype=np.uint64,
                                    count=self.n_local, offset=lease_off)
        # per-rank fast-path counter mirror (the flags-segment tail):
        # cp_create points the plane's fpctr at this rank's row, so the
        # same slots are readable here for every co-located rank — the
        # surface bin/mpistat attaches to from outside the job
        self._fpc_mirror = np.frombuffer(
            self._flags, dtype=np.uint64,
            count=self.n_local * _FPC_SLOTS,
            offset=lease_off + _LEASE_STAMP * self.n_local)
        self._lease_scan_at = 0.0      # python-probe throttle
        self._failed_seen: set = set() # C-detections already reconciled
        self._lease_stamp()
        # heartbeat thread: the stamp must stay fresh through compute-
        # silent stretches (a rank deep in user code makes no progress
        # calls), so refreshing only from the progress loop would
        # false-kill busy peers. ~10 stamps per timeout period.
        # continuous-metrics sampler state: declared BEFORE the thread
        # starts (the loop re-reads self._sampler every wake; the
        # sampler itself attaches later in __init__, after the plane)
        self._sampler = None
        self._metrics_path = f"{path}.metrics"
        self._metrics_f = None
        self._metrics_mm = None
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name=f"mv2t-lease-hb-{my_rank}")
        self._hb_thread.start()
        # -- native data plane (native/cplane.cpp) -----------------------
        # C-side envelope matching for plane-owned contexts: created when
        # the native ring is live. Everything LOCAL — world map, global
        # registration for the C fast path, lease timeout, flat progress
        # hook — happens here; only the parts that need peers' cards
        # (bells, the CMA/arena/flat agreement) wait for ensure_wired().
        # Pre-wire the plane still carries eager traffic: an unset bell
        # just means a parked receiver wakes on its poll timeout.
        self.plane = None
        self._plane_recvs: Dict[int, object] = {}   # cp req id -> Request
        self._plane_cancels: Dict[int, object] = {} # sreq id -> SendRequest
        self.plane_client = None                    # Pt2ptProtocol hook
        self._ring_cap = 0
        self._flat_path = boot_card["flat"] if boot_card is not None \
            else f"{path}.fcoll"
        # hierarchical flat tier + multicast bcast segment (cp_flat2_*);
        # older boot cards / daemon manifests may predate it
        self._flat2_path = (boot_card.get("flat2")
                            if boot_card is not None else None) \
            or f"{path}.fcoll2"
        # native trace ring segment (beside the ring file; daemon mode
        # puts it beside the claimed ring, reset implicitly by the
        # monotonic timestamps — trace/native.py drops zero-ts slots)
        self._ntrace_path = f"{path}.ntrace"
        self._ntrace_f = None          # this rank's own fd on the ring
        self._flat_cb = None           # keepalive for the ctypes callback
        self.cabi_ranks = set()        # local ranks that are C-ABI procs
        if self.using_native and get_config()["USE_CPLANE"]:
            lib = self._ring.lib
            self.plane = lib.cp_create(self._ring.h, self.local_index[my_rank],
                                       self.n_local, flags_path.encode())
            self._ring_cap = lib.sr_capacity(self._ring.h)
            if self.plane:
                lib.cp_set_wait_fd(self.plane, self._bell.fileno())
                if self._owner:
                    # flat-slot collective segment (cp_flat_*): sparse
                    # per-context regions; created by the leader before
                    # its build cards publish, so followers can attach
                    # during wiring without racing the creation
                    lib.cp_flat_attach(self.plane,
                                       self._flat_path.encode(), 1)
                    # hierarchical tier segment: same sparse/idempotent
                    # creation discipline (zero IS initialized)
                    lib.cp_flat2_attach(self.plane,
                                        self._flat2_path.encode(), 1)
                for r in self.local_ranks:
                    lib.cp_set_world(self.plane, self.local_index[r], r)
                # python-rank progress hook for flat-collective waits: a
                # rank parked in a flat wave still runs forwarded python
                # work (rendezvous assists) so peers cannot deadlock.
                # Runs INSIDE cp_flat_* wait loops, so it must never
                # block (a sleep here stalls the whole node's wave).
                import ctypes as _ct

                def _flat_progress():  # mv2tlint: handler
                    from ..runtime import universe as uni
                    try:
                        u = uni.current_universe()
                        if u is not None:
                            u.engine.progress_poke()
                    except Exception:
                        pass
                self._flat_cb = _ct.CFUNCTYPE(None)(_flat_progress)
                lib.cp_flat_set_progress_cb(
                    self.plane, _ct.cast(self._flat_cb, _ct.c_void_p))
                # arm the C-side lease scans (flat waves, wait quanta)
                # with the same timeout the python probe uses
                lib.cp_set_peer_timeout(self.plane,
                                        int(self._peer_timeout * 1e6))
                lib.cp_register_global(self.plane)
                # native trace ring: armed when the MV2T_NTRACE cvar is
                # set (or follows MV2T_TRACE when left at its -1
                # default). Zero-filled is the initialized state, so
                # every rank creates/attaches without ordering; events
                # drain at Finalize into the Perfetto merge and live
                # into the watchdog/mpistat tails (trace/native.py).
                from ..trace import native as _nt
                if _nt.ntrace_enabled():
                    lib.cp_ntrace_attach(self.plane,
                                         self._ntrace_path.encode(), 1)
                    # hold our own fd on the ring: the segment OWNER
                    # unlinks the file at its close, which can precede
                    # a slower rank's Finalize drain (teardown skew) —
                    # an unlinked-but-open inode stays readable, so
                    # this rank's trace lane cannot silently vanish
                    try:
                        self._ntrace_f = open(self._ntrace_path, "rb")
                    except OSError:
                        self._ntrace_f = None
                # bind the plane counters' sources to this live plane:
                # fast-path hit-rate is the one number that says
                # whether a workload actually rides the C path — it
                # must be observable even before the node wires (eager
                # traffic flows pre-wire). Totals from earlier planes
                # in this process (latched at close) stay included.
                for idx, (name, desc) in enumerate(_PV_PLANE_DECLS):
                    pv = _mpit.pvar(name, _mpit.PVAR_CLASS_COUNTER,
                                    "shm", desc)
                    base = pv._value
                    pv.source = (lambda i=idx, b=base:
                                 b + float(self.plane_stats()[i]))
                for idx, (name, desc) in enumerate(_FP_COUNTERS):
                    pv = _mpit.pvar(name, _mpit.PVAR_CLASS_COUNTER,
                                    "fastpath", desc)
                    base = pv._value
                    pv.source = (lambda i=idx, b=base:
                                 b + float(self.fp_counter(i)))
        # -- continuous-metrics segment (<ring>.metrics) ------------------
        # per-rank time-series ring + histogram mirrors for the always-on
        # telemetry layer (mvapich2_tpu/metrics). Creation needs no
        # ordering: O_CREAT + ftruncate zero-fills, zero rows are the
        # uninitialized state readers skip, and each rank scrubs only
        # its OWN region (daemon sets reuse files across epochs). The
        # sampler rides the heartbeat thread started above.
        from .. import metrics as _metrics
        if _metrics.enabled():
            try:
                from ..metrics import ring as _mring
                from ..metrics import sampler as _msampler
                need = _mring.file_len(self.n_local)
                fd = os.open(self._metrics_path,
                             os.O_RDWR | os.O_CREAT, 0o600)
                try:
                    if os.fstat(fd).st_size < need:
                        os.ftruncate(fd, need)
                    self._metrics_f = os.fdopen(fd, "r+b")
                except OSError:
                    os.close(fd)
                    raise
                self._metrics_mm = mmap.mmap(self._metrics_f.fileno(),
                                             need)
                _metrics.ensure_live()

                def _fpc_row(idx=self.local_index[my_rank]):
                    m = self._fpc_mirror
                    if m is None:
                        return ()
                    return m[idx * _FPC_SLOTS:(idx + 1) * _FPC_SLOTS]
                smp = _msampler.Sampler(
                    self._metrics_mm, self.local_index[my_rank],
                    fpc_row=_fpc_row, now_us=self._now_us)
                # first row inline, BEFORE the heartbeat thread can see
                # the sampler (single-writer: after this handoff only
                # the hb loop ticks, until close's final tick)
                smp.maybe_tick()
                self._sampler = smp
            except OSError:
                self._sampler = None
        # -- lazy per-peer wiring state ----------------------------------
        # the deferred half of bootstrap: bells + the unanimous CMA/
        # arena/flat agreement complete on the first operation that
        # needs them (ensure_wired / opportunistic try_wire)
        self._wired = False
        self._wire_stage = 0           # 0=idle, 1=verdict published
        self._wire_eager = False       # attribution for the wiring pvars
        self._wire_try_at = 0.0        # opportunistic-probe throttle
        self._wire_deadline = 0.0      # live ensure_wired deadline
                                       # (watchdog control-plane report)
        from ..analysis.lockorder import tracked as _tracked
        self._wire_lock = _tracked(threading.Lock(),
                                   f"shm[{my_rank}]._wire_lock")
        # one batched publication for every build card (bell, CMA probe,
        # segment/arena paths) — peers' wire step peeks these
        if self._cards:
            kvs.put_many(self._cards)

    def plane_eager_max(self) -> int:
        """Largest eager payload the plane can carry: an eager blob is a
        61-byte header + payload and must fit the shm ring (with margin
        for the ring's own length/align overhead). The single source of
        truth for the clamp applied by both the python protocol layer
        and the C fast path's cached threshold."""
        return self._ring_cap - 128 if self._ring_cap else 0

    def fp_counter(self, idx: int) -> int:
        """One fast-path counter slot from the plane (index order =
        cplane.cpp FPC_* = _FP_COUNTERS)."""
        if not self.plane:
            return 0
        return int(self._ring.lib.cp_fp_counter(self.plane, idx))

    def fpc_snapshot(self, world_rank: int):
        """All _FPC_SLOTS counter slots of a CO-LOCATED rank, read from
        the flags segment's shm mirror (a stale/torn snapshot is fine —
        stat surface, one natural writer per slot). None when the rank
        is not local."""
        i = self.local_index.get(world_rank)
        if i is None or self._fpc_mirror is None:
            return None
        row = self._fpc_mirror[i * _FPC_SLOTS:(i + 1) * _FPC_SLOTS]
        return [int(v) for v in row]

    def ntrace_active(self) -> bool:
        """Is the native trace ring armed on this plane?"""
        return bool(self.plane
                    and self._ring.lib.cp_ntrace_ok(self.plane))

    def plane_stats(self):
        """(eager_tx, eager_rx, fwd_py, rndv_tx, rndv_rx) from the C
        plane."""
        if not self.plane:
            return (0, 0, 0, 0, 0)
        tx = ctypes.c_ulonglong()
        rx = ctypes.c_ulonglong()
        fwd = ctypes.c_ulonglong()
        rtx = ctypes.c_ulonglong()
        rrx = ctypes.c_ulonglong()
        self._ring.lib.cp_stats(self.plane, tx, rx, fwd)
        self._ring.lib.cp_rndv_stats(self.plane, rtx, rrx)
        return (tx.value, rx.value, fwd.value, rtx.value, rrx.value)

    # -- liveness leases (failure containment) ---------------------------
    _LEASE_DEPARTED = 0xFFFFFFFFFFFFFFFF

    @staticmethod
    def _now_us() -> int:
        return int(time.clock_gettime(time.CLOCK_MONOTONIC) * 1e6)

    def _lease_stamp(self, value: Optional[int] = None) -> None:
        try:
            self._lease[self.local_index[self.my_rank]] = np.uint64(
                self._now_us() if value is None else value)
        except (ValueError, TypeError):
            pass                      # mapping already closed

    def _hb_loop(self) -> None:
        period = max(0.02, min(1.0, self._peer_timeout / 10.0)) \
            if self._peer_timeout > 0 else 0.5
        while True:
            # the metrics sampler rides this thread (no thread of its
            # own): clamp the wait to its interval and offer a tick on
            # every wake — re-read each pass, the sampler attaches
            # after the thread starts and detaches at close
            smp = self._sampler
            p = period if smp is None or smp.dead \
                else min(period, smp.interval)
            if self._hb_stop.wait(p):
                return
            self._lease_stamp()
            if smp is not None:
                smp.maybe_tick()

    def lease_age(self, world_rank: int) -> Optional[float]:
        """Seconds since ``world_rank``'s heartbeat stamp; None when the
        rank never stamped (bootstrap) or departed cleanly (Finalize)."""
        i = self.local_index.get(world_rank)
        if i is None:
            return None
        v = int(self._lease[i])
        if v == 0 or v == self._LEASE_DEPARTED:
            return None
        return max(0.0, (self._now_us() - v) / 1e6)

    def lease_report(self) -> List[str]:
        """One line per co-located rank for the stall-watchdog dump."""
        out = []
        u = getattr(self.engine, "universe", None) \
            if hasattr(self, "engine") else None
        failed = getattr(u, "failed_ranks", set()) if u is not None else set()
        for w in self.local_ranks:
            i = self.local_index[w]
            v = int(self._lease[i])
            if w == self.my_rank:
                state = "self"
            elif v == 0:
                state = "never-stamped"
            elif v == self._LEASE_DEPARTED:
                state = "departed"
            else:
                state = f"age {(self._now_us() - v) / 1e6:.2f}s"
            if w in failed:
                state += " FAILED"
            out.append(f"world {w} (ring {i}): {state}")
        return out

    def check_peer_leases(self) -> int:  # mv2tlint: handler
        """Liveness probe run from the progress engine's idle path (and
        registered via register_liveness): declare co-located peers dead
        when their lease goes stale past MV2T_PEER_TIMEOUT. Must never
        block — it runs at the blocking waits' sleep points. Returns how
        many peers were newly declared dead."""
        if self._peer_timeout <= 0:
            return 0
        now = time.monotonic()
        if now < self._lease_scan_at:
            return self._reconcile_plane_failures()
        self._lease_scan_at = now + max(0.01, self._peer_timeout / 4.0)
        eng = getattr(self, "engine", None)
        u = getattr(eng, "universe", None) if eng is not None else None
        if u is None:
            return 0
        ndead = 0
        for w in self.local_ranks:
            if w == self.my_rank or w in u.failed_ranks:
                continue
            age = self.lease_age(w)
            if age is not None and age > self._peer_timeout:
                from ..core.errors import PeerDeadError
                from ..faults import pv_dead_peer
                from ..ft import ulfm
                err = PeerDeadError(w, age, "liveness probe")
                log.warn("%s", err)
                u.last_peer_dead = err
                pv_dead_peer.inc()
                if getattr(eng, "_in_wait", False):
                    from ..faults import pv_deadline
                    pv_deadline.inc()
                ulfm.mark_failed(u, w)
                if self.plane and w in self.local_index:
                    self._failed_seen.add(w)
                ndead += 1
        ndead += self._reconcile_plane_failures()
        return ndead

    def _reconcile_plane_failures(self) -> int:  # mv2tlint: handler
        """Feed C-side lease detections (cp_lease_scan inside flat waves
        and wait quanta) into the python ULFM sink, so posted recvs and
        in-flight rendezvous unwind with MPIX_ERR_PROC_FAILED on both
        ABIs. One atomic read when nothing has failed."""
        if not self.plane:
            return 0
        lib = self._ring.lib
        if not lib.cp_any_failed(self.plane):
            return 0
        u = getattr(getattr(self, "engine", None), "universe", None)
        if u is None:
            return 0
        ndead = 0
        for w in self.local_ranks:
            if w == self.my_rank or w in self._failed_seen:
                continue
            if lib.cp_rank_failed(self.plane, self.local_index[w]):
                self._failed_seen.add(w)
                if w not in u.failed_ranks:
                    from ..faults import pv_dead_peer, pv_deadline
                    from ..ft import ulfm
                    pv_dead_peer.inc()
                    # the C lease scan runs ONLY inside blocking waits
                    # (flat waves, wait quanta): every reconciled C
                    # detection is a wait-deadline trip by construction
                    pv_deadline.inc()
                    ulfm.mark_failed(u, w)
                    ndead += 1
        return ndead

    def _probe_cma(self) -> bool:
        """Can this process read a co-resident rank's memory via
        process_vm_readv? Reads a neighbor's published probe buffer and
        checks the bytes (the runtime capability probe the reference
        performs for CMA/LiMIC2 availability)."""
        idx = self.local_ranks.index(self.my_rank)
        left = self.local_ranks[idx - 1]
        if left == self.my_rank:
            return True          # single local rank: self-copy path
        try:
            pid, addr, n = map(
                int, self.kvs.get(f"shm-cma-{left}").split(":"))
        except Exception:
            return False
        expect = f"mv2t-cma-{left:012d}".encode()
        if n != len(expect):
            return False
        buf = ctypes.create_string_buffer(n)

        class IoVec(ctypes.Structure):
            _fields_ = [("iov_base", ctypes.c_void_p),
                        ("iov_len", ctypes.c_size_t)]

        try:
            libc = ctypes.CDLL(None, use_errno=True)
            libc.process_vm_readv.restype = ctypes.c_ssize_t
            libc.process_vm_readv.argtypes = [
                ctypes.c_int, ctypes.POINTER(IoVec), ctypes.c_ulong,
                ctypes.POINTER(IoVec), ctypes.c_ulong, ctypes.c_ulong]
            liov = IoVec(ctypes.cast(buf, ctypes.c_void_p), n)
            riov = IoVec(addr, n)
            got = libc.process_vm_readv(pid, ctypes.byref(liov), 1,
                                        ctypes.byref(riov), 1, 0)
        except Exception:
            return False
        ok = got == n and buf.raw[:n] == expect
        if not ok:
            log.warn("CMA probe failed (read %s from pid %d); using the "
                     "staged rendezvous path", got, pid)
        return ok

    # -- lazy per-peer wiring (the deferred half of bootstrap) -----------
    #
    # The eager model wired every peer at Init behind a global fence.
    # Now a channel is BUILT (segments mapped, plane registered, eager
    # pt2pt live) the moment its constructor returns, and the peer-
    # dependent half — bells, the unanimous CMA/arena/flat agreement,
    # the C-ABI membership table — completes on the first operation that
    # needs it. Two stages, both driven by batched KVS peeks:
    #
    #   stage 0->1: every co-located rank's BUILD cards (bell + CMA
    #     probe) are visible -> set bells, probe the neighbor, attach
    #     the follower-side arena/flat segments, publish my VERDICT
    #     card (one batched put).
    #   stage 1->2: every rank's verdict is visible -> apply the
    #     unanimous agreements, rebind the plane pvars, wired.
    #
    # Blocking (ensure_wired) is only entered where every participant
    # is known to arrive — collective dispatch and rendezvous — so an
    # idle peer can never deadlock a wire. Everything else degrades:
    # eager sends ride the ring bell-less, rendezvous exposes fall back
    # to the scratch-file ladder until try_wire upgrades them.

    def try_wire(self, force: bool = False) -> bool:
        """Opportunistic nonblocking wire attempt (throttled). Called
        from the progress poll path and rendezvous entries; never
        blocks and never waits on a lock."""
        if self._wired:
            return True
        now = time.monotonic()
        if not force and now < self._wire_try_at:
            return False
        if not self._wire_lock.acquire(blocking=False):
            return self._wired
        try:
            self._wire_try_at = time.monotonic() + 0.01
            return self._wire_step()
        finally:
            self._wire_lock.release()

    def ensure_wired(self, eager: bool = False) -> None:
        """Blocking wire gate: complete the per-node agreement or raise.
        Unwinds with MPIX_ERR_PROC_FAILED when a co-located peer dies
        mid-wire (lease scan / launcher events), and with MPI_ERR_INTERN
        after MV2T_WIRE_TIMEOUT — never a silent hang."""
        if self._wired:
            return
        self._wire_eager = eager or self._wire_eager
        deadline = time.monotonic() + max(
            1.0, float(get_config().get("WIRE_TIMEOUT", 120.0)))
        self._wire_deadline = deadline
        while True:
            with self._wire_lock:
                if self._wire_step():
                    return
            # containment: a peer killed mid-wire must unwind this wait
            if self._peer_timeout > 0:
                self.check_peer_leases()
            u = getattr(self.engine, "universe", None) \
                if hasattr(self, "engine") else None
            if u is not None and u.failed_ranks:
                dead = [r for r in self.local_ranks
                        if r != self.my_rank and r in u.failed_ranks]
                if dead:
                    from ..core.errors import PeerDeadError
                    raise PeerDeadError(dead[0], 0.0, "node wire gate")
            if time.monotonic() > deadline:
                from ..core.errors import MPIException, MPI_ERR_INTERN
                raise MPIException(
                    MPI_ERR_INTERN,
                    f"shm wire gate timed out after MV2T_WIRE_TIMEOUT: "
                    f"co-located ranks {self.local_ranks} never all "
                    f"published wiring cards (stage {self._wire_stage})")
            time.sleep(0.001)

    def finish_wiring(self) -> None:
        """Eager wiring (spawn bootstrap and MV2T_LAZY_WIRING=0): the
        pre-lazy entry point, kept as the blocking gate with eager
        attribution."""
        self.ensure_wired(eager=True)

    def _wire_step(self) -> bool:  # holds: _wire_lock
        """One nonblocking advance of the wire state machine."""
        if self._wired:
            return True
        from .. import faults
        faults.fire("wire")    # chaos: crash/delay mid-wire
        u = getattr(self.engine, "universe", None) \
            if hasattr(self, "engine") else None
        failed = getattr(u, "failed_ranks", None) or set()
        # a peer that died mid-wire can never publish its cards: the
        # wire completes DEGRADED without it — conservative all-False
        # agreements (eager + scratch-file rendezvous keep working),
        # never a permanent stage-1 stall
        dead = [r for r in self.local_ranks
                if r != self.my_rank and r in failed]
        peers = [r for r in self.local_ranks
                 if r != self.my_rank and r not in failed]
        if self._wire_stage == 0:   # state: wire:0
            vals = self.kvs.peek_many(
                [f"shm-bell-{r}" for r in peers]
                + [f"shm-cma-{r}" for r in peers])
            if any(v is None for v in vals):
                return False    # some peer has not built its world yet
            lib = self._ring.lib if self.plane else None
            for r, addr in zip(peers, vals[:len(peers)]):
                self._peer_bells[r] = addr
                if lib is not None:
                    lib.cp_set_bell(self.plane, self.local_index[r],
                                    addr.encode())
            # CMA is enabled only by UNANIMOUS agreement: every
            # co-resident rank publishes its probe verdict (can it read
            # a neighbor, is USE_CMA set) and reads everyone else's.
            # The receiver performs the pull, so a single incapable/
            # opted-out rank must disable the protocol for the whole
            # node. The arena and flat verdicts ride the same exchange:
            # a rank whose mapping failed would receive handles (or
            # join waves) it cannot dereference.
            # degraded wire skips the probe: the left neighbor may BE
            # the dead rank (probe card never published) and the
            # verdict is forced False at apply anyway
            my_ok = not dead and bool(get_config()["USE_CMA"]) \
                and self._probe_cma()
            if self.arena is None and not self._owner:
                self._attach_follower_arena()
            my_arena = self.arena is not None
            my_flat = False
            my_flat2 = False
            if self.plane:
                if not self._owner:
                    lib.cp_flat_attach(self.plane,
                                       self._flat_path.encode(), 0)
                    lib.cp_flat2_attach(self.plane,
                                        self._flat2_path.encode(), 0)
                my_flat = bool(lib.cp_flat_ok(self.plane))
                my_flat2 = bool(lib.cp_flat2_ok(self.plane))
            # C-ABI membership: a comm with any C-ABI rank must use the
            # C fast path's collective-tier cap (FP_COLL_MAX) on every
            # member — coll/api.py._plane_coll_max reads this set. A
            # pure python comm keeps the tuning tier above the eager
            # size (interpreter-hop schedules lose to the arena tier).
            from .. import cshim as _cshim
            my_cabi = _cshim.is_cabi_process()
            self._my_verdicts = (my_ok, my_arena, my_flat, my_flat2)
            self.kvs.put_many({
                f"shm-cma-ok-{self.my_rank}": "1" if my_ok else "0",
                f"shm-arena-ok-{self.my_rank}": "1" if my_arena else "0",
                f"shm-flat-ok-{self.my_rank}": "1" if my_flat else "0",
                f"shm-flat2-ok-{self.my_rank}": "1" if my_flat2 else "0",
                f"shm-cabi-{self.my_rank}": "1" if my_cabi else "0",
            })
            self.cabi_ranks = {self.my_rank} if my_cabi else set()
            self._wire_stage = 1
        if self._wire_stage == 1:   # state: wire:1
            vals = self.kvs.peek_many(
                [f"shm-cma-ok-{r}" for r in peers]
                + [f"shm-arena-ok-{r}" for r in peers]
                + [f"shm-flat-ok-{r}" for r in peers]
                + [f"shm-flat2-ok-{r}" for r in peers]
                + [f"shm-cabi-{r}" for r in peers])
            if any(v is None for v in vals):
                return False    # some peer has not published its verdict
            n = len(peers)
            my_ok, my_arena, my_flat, my_flat2 = self._my_verdicts
            all_ok = my_ok and all(v == "1" for v in vals[:n])
            all_arena = my_arena and all(v == "1" for v in vals[n:2 * n])
            all_flat = my_flat and all(v == "1" for v in vals[2 * n:3 * n])
            all_flat2 = my_flat2 and all(
                v == "1" for v in vals[3 * n:4 * n])
            if dead:
                # degraded wire: a local rank died before its verdict
                # landed — no unanimous agreement can include it
                all_ok = all_arena = all_flat = all_flat2 = False
                self.cabi_ranks.update(dead)
            for r, v in zip(peers, vals[4 * n:]):
                if v != "0":
                    # unknown counts as C-ABI: the conservative verdict
                    # is the shared FP_COLL_MAX cap
                    self.cabi_ranks.add(r)
            self._apply_wire(all_ok, all_arena, all_flat, my_flat,
                             all_flat2, my_flat2)
        return self._wired

    def _attach_follower_arena(self) -> None:
        """Follower-side arena attach, run inside the wire step: the
        leader's card is published with its build cards (bell presence
        implies card presence), so this never blocks."""
        try:
            card = self.kvs.peek_many(
                [f"shm-arena-{self.local_ranks[0]}"])[0]
            if card:
                apath, part = card.rsplit(":", 1)
                self.arena = ShmArena(apath, self.n_local,
                                      self.local_index[self.my_rank],
                                      int(part), create=False)
        except Exception as e:
            log.warn("arena attach failed (%s); scratch-file rendezvous",
                     e)
            self.arena = None

    def _apply_wire(self, all_ok: bool, all_arena: bool, all_flat: bool,
                    my_flat: bool, all_flat2: bool = False,
                    my_flat2: bool = False) -> None:  # holds: _wire_lock
        """Stage 2: apply the unanimous agreements and go live."""
        self.cma_ok = all_ok
        if not all_arena and self.arena is not None:
            self.arena.close(unlink=self._owner and not self._daemon)
            self.arena = None
        self._arena_ready = self.arena is not None
        if self.plane:
            lib = self._ring.lib
            if not all_flat and my_flat:
                lib.cp_flat_disable(self.plane)
            if not all_flat2 and my_flat2:
                lib.cp_flat2_disable(self.plane)
            if all_ok:
                lib.cp_set_cma(self.plane, 1)
            # open the C fast path's collective dispatch LAST: every
            # agreement verdict above must be visible first (release
            # store; fpc_enter's acquire load pairs with it)
            lib.cp_set_wired(self.plane)
        self._wired = True
        (pv_wiring_eager if self._wire_eager else pv_wiring_lazy).inc()
        log.info("node wire complete (cma=%s arena=%s flat=%s flat2=%s, "
                 "%s)", all_ok, all_arena, all_flat, all_flat2,
                 "eager" if self._wire_eager else "lazy")

    def _make_ring(self, path: str, ring_bytes: int, create: bool):
        lib = _load_native()
        if lib is not None:
            try:
                return _NativeRing(lib, path, self.n_local, ring_bytes,
                                   create)
            except OSError as e:
                log.warn("native ring attach failed (%s); python", e)
        return _PyRing(path, self.n_local, ring_bytes, create)

    @property
    def using_native(self) -> bool:
        return isinstance(self._ring, _NativeRing)

    # -- channel API ------------------------------------------------------
    def _ring_bell(self, dest_world: int) -> None:
        if self._flags[self.local_index[dest_world]] == 0:
            return    # receiver awake and polling: no doorbell needed
        addr = self._peer_bells.get(dest_world)
        if addr is None:
            addr = self.kvs.get(f"shm-bell-{dest_world}")
            self._peer_bells[dest_world] = addr
        try:
            self._bell.sendto(b"x", addr)
        except OSError:
            pass    # full/raced doorbell is fine; receiver polls anyway

    def send_packet(self, dest_world: int, pkt: Packet) -> None:
        blob = encode_packet(pkt)
        from .. import faults
        kind = faults.fire("shm_send")
        if kind == "drop":
            return                    # lost on the (simulated) wire
        if kind == "truncate":
            blob = blob[:max(1, len(blob) // 2)]
        self._inject_blob(dest_world, blob)
        if kind == "duplicate":
            self._inject_blob(dest_world, blob)

    def _inject_blob(self, dest_world: int, blob: bytes) -> None:
        # python-injected traffic only; the C plane's eager fast path
        # bypasses send_packet entirely and keeps its own counters
        # (cplane_eager_tx et al.)
        self.account_send(dest_world, len(blob))
        dst_i = self.local_index[dest_world]
        if self.plane:
            # plane mode: the C injector owns ordering + backlog; spill
            # oversize blobs first so inject never sees one
            if len(blob) > self._ring_cap:
                blob = self._spill_oversize(blob, dst_i)
            self._ring.lib.cp_inject(self.plane, dst_i, blob, len(blob))
            return
        src_i = self.local_index[self.my_rank]
        with self._send_lock:
            bl = self._backlog.setdefault(dst_i, collections.deque())
            if bl:
                bl.append(blob)
                self._flush(dst_i)
            else:
                rc = self._ring.send(src_i, dst_i, blob)
                if rc == 0:
                    bl.append(blob)  # ring full: backlog, flush from poll
                elif rc < 0:
                    # larger than the ring: stream via an arena/file spill
                    note = self._spill_oversize(blob, dst_i)
                    if self._ring.send(src_i, dst_i, note) == 0:
                        bl.append(note)
        self._ring_bell(dest_world)

    def wait_for_event(self, timeout: float) -> None:
        try:
            r, _, _ = select.select([self._bell], [], [],
                                    min(timeout, 0.002))
        except OSError:
            return
        self._drain_bell()

    def _drain_bell(self) -> None:
        while True:
            try:
                self._bell.recv(4096)
            except OSError:
                break

    def wait_fds(self):
        return [self._bell]

    def pre_wait(self) -> None:
        self._flags[self.local_index[self.my_rank]] = 1

    def post_wait(self) -> None:
        self._flags[self.local_index[self.my_rank]] = 0

    def _spill_oversize(self, blob: bytes, dst_i: int) -> bytes:
        """Spill a larger-than-ring message to the arena (falling back to
        a scratch file); returns the small ring note pointing at it.
        Never waits for ring space — a spin here would run under
        _send_lock and block poll() from draining inbound rings
        (cross-rank deadlock); a full ring just backlogs the note like
        any other blob. Arena blocks are reclaimed lazily once the
        receiver's spill-consumed counter passes the note's sequence
        number (_reclaim_spills)."""
        if self._arena_ready:
            self._reclaim_spills()
            h = self.arena.alloc(len(blob))
            if h is not None:
                self.arena.view(h.off, len(blob))[:] = \
                    np.frombuffer(blob, dtype=np.uint8)
                with self._spill_lock:
                    seq = self._spill_seq.get(dst_i, 0) + 1
                    self._spill_seq[dst_i] = seq
                    self._spill_pending.setdefault(
                        dst_i, collections.deque()).append((seq, h))
                # 0xFE discriminator: arena spill note (0xFF = file)
                return b"\xfe" + struct.pack(
                    "<qqq", self.local_index[self.my_rank], h.off,
                    len(blob))
        path = self.path + f".big-{self.my_rank}-{uuid.uuid4().hex[:8]}"
        with open(path, "wb") as f:
            f.write(blob)
        # 0xFF discriminator: not a valid PktType first byte
        return b"\xff" + path.encode()

    def _reclaim_spills(self) -> None:
        """Free arena spill blocks whose notes the receiver has consumed
        (its counter in the arena header passed their sequence)."""
        my_i = self.local_index[self.my_rank]
        with self._spill_lock:
            for dst_i, pend in self._spill_pending.items():
                if not pend:
                    continue
                c = self.arena.spill_consumed(my_i, dst_i)
                while pend and pend[0][0] <= c:
                    self.arena.free(pend.popleft()[1])

    def _consume_spill_note(self, blob) -> bytes:
        """Dereference an inbound spill note (0xFE arena / 0xFF file)."""
        if blob[0] == 0xFE:
            src_i, off, n = struct.unpack_from("<qqq", blob, 1)
            data = bytes(self.arena.view(off, n))
            self.arena.bump_spill(src_i, self.local_index[self.my_rank])
            return data
        path = bytes(blob[1:]).decode()
        with open(path, "rb") as f:
            data = f.read()
        os.unlink(path)
        return data

    def _flush(self, dst_i: int) -> None:  # holds: _send_lock
        bl = self._backlog.get(dst_i)
        if bl is None:
            return
        src_i = self.local_index[self.my_rank]
        while bl:
            rc = self._ring.send(src_i, dst_i, bl[0])
            if rc == 0:
                return
            blob = bl.popleft()
            if rc < 0:
                note = self._spill_oversize(blob, dst_i)
                if self._ring.send(src_i, dst_i, note) == 0:
                    bl.appendleft(note)   # keep FIFO order, retry later
                    return

    def poll(self) -> bool:
        # opportunistic lazy-wiring probe (throttled; one time read +
        # attr check when wired): upgrades pt2pt-only workloads to the
        # full agreement without any blocking gate
        if not self._wired:
            self.try_wire()
        if self.plane:
            return self._poll_plane()
        my_i = self.local_index[self.my_rank]
        self._drain_bell()
        did = False
        with self._send_lock:
            for dst_i in list(self._backlog):
                self._flush(dst_i)
        # racy truthiness gate is intentional: a stale read only delays
        # reclaim one poll; _reclaim_spills itself takes _spill_lock
        if self._spill_pending:  # mv2tlint: ignore[locks]
            self._reclaim_spills()
        from .. import faults
        for src_i in range(self.n_local):
            if src_i == my_i:
                continue
            while True:
                blob = self._ring.recv(src_i, my_i)
                if blob is None:
                    break
                if blob[0] in (0xFE, 0xFF):    # oversize spill note
                    blob = self._consume_spill_note(blob)
                if faults.fire("shm_recv") == "drop":
                    continue           # inbound packet lost
                self.account_recv(len(blob))
                self.engine.enqueue_incoming(decode_packet(blob))
                did = True
        if self._peer_timeout > 0:
            self.check_peer_leases()
        return did

    # -- plane mode -------------------------------------------------------
    def _poll_plane(self) -> bool:
        """Progress pass in plane mode: the C engine drains the rings and
        matches plane-owned envelopes; this drains what it forwarded —
        python-owned packets, rendezvous assists, cancel results — and
        finalizes any completed plane receives the engine is tracking."""
        lib = self._ring.lib
        self._drain_bell()
        did = lib.cp_advance(self.plane) > 0
        # liveness on the poll path too (throttled): pokers that never
        # reach progress_wait — the ULFM agreement's poke/sleep loop,
        # spin-waiters — still detect dead peers; this also reconciles
        # C-side detections (flat waves, wait quanta) into the ULFM
        # sink. One atomic read + one time read when healthy.
        if self._peer_timeout > 0:
            self.check_peer_leases()
        else:
            self._reconcile_plane_failures()
        # racy truthiness gate, same justification as poll()
        if self._spill_pending:  # mv2tlint: ignore[locks]
            self._reclaim_spills()
        from .. import faults
        while lib.cp_py_pending(self.plane):
            n = lib.cp_py_peek(self.plane)
            if n <= 0:
                break
            buf = ctypes.create_string_buffer(n)
            got = lib.cp_py_pop(self.plane, buf, n)
            if got <= 0:
                break
            blob = buf.raw[:got]
            if blob[0] in (0xFE, 0xFF):  # oversize spill note (py-owned)
                blob = self._consume_spill_note(blob)
            if faults.fire("shm_recv") == "drop":
                continue               # inbound packet lost
            self.engine.enqueue_incoming(decode_packet(blob))
            did = True
        client = self.plane_client
        while client is not None and lib.cp_assist_pending(self.plane):
            n = lib.cp_assist_peek(self.plane)
            if n <= 0:
                break
            rid = ctypes.c_longlong()
            buf = ctypes.create_string_buffer(n)
            got = lib.cp_assist_pop(self.plane, rid, buf, n)
            if got <= 0:
                break
            client.on_plane_assist(self, rid.value,
                                   decode_packet(buf.raw[:got]))
            did = True
        if self._plane_cancels:
            for sid in list(self._plane_cancels):
                res = lib.cp_cancel_result(self.plane, sid)
                if res >= 0:
                    req = self._plane_cancels.pop(sid)
                    lib.cp_cancel_forget(self.plane, sid)
                    if client is not None:
                        client.on_plane_cancel_result(req, bool(res))
        if self._plane_recvs:
            for cpid in list(self._plane_recvs):
                req = self._plane_recvs.get(cpid)
                if req is not None and req._poll_plane():
                    did = True
        return did

    # registration hooks used by the protocol layer
    def plane_track_recv(self, cpid: int, req) -> None:
        self._plane_recvs[cpid] = req

    def plane_untrack_recv(self, cpid: int) -> None:
        self._plane_recvs.pop(cpid, None)

    def plane_track_cancel(self, sreq_id: int, req) -> None:
        self._plane_cancels[sreq_id] = req

    # -- zero-copy rendezvous (RGET handle ladder: CMA > arena > file) ----
    def expose_buffer(self, array: np.ndarray):
        """Register a send buffer for remote pull. Handle ladder, best
        first: ("cma", pid, addr, tok) — the receiver reads the live
        buffer via process_vm_readv (zero staging copies); ("arena", off,
        tok) — one copy into a persistent arena block; ("file", path) —
        the legacy per-send scratch file, kept as the exhaustion/fallback
        path. The keepalive (buffer ref / ArenaHandle) lives in the
        _exposed handle table until release_buffer."""
        arr = np.ascontiguousarray(array).view(np.uint8).reshape(-1)
        if arr.size == 0:
            return ("null",)
        if not self._wired:
            # a rendezvous is the natural upgrade point: both ends are
            # live. Nonblocking — while unwired the ladder degrades to
            # the scratch-file path, which needs no agreement.
            self.try_wire(force=True)
        if self.cma_ok:
            self._expose_tok += 1
            h = ("cma", os.getpid(), arr.ctypes.data, self._expose_tok)
            self._exposed[h] = arr
            return h
        if self._arena_ready:
            ah = self.arena.alloc(arr.size)
            if ah is not None:
                self.arena.view(ah.off, arr.size)[:] = arr
                self._expose_tok += 1
                h = ("arena", ah.off, self._expose_tok)
                self._exposed[h] = ah
                return h
        path = self.path + f".rget-{self.my_rank}-{uuid.uuid4().hex[:8]}"
        with open(path, "wb") as f:
            f.write(arr.tobytes())
        return ("file", path)

    def pull_buffer(self, src_world: int, handle, nbytes: int) -> np.ndarray:
        """RGET: read the peer's exposed buffer. CMA and arena pulls are
        chunked (MV2T_RNDV_CHUNK) with a trace instant per chunk; the
        arena/file paths return views anchored to the shared/mapped
        memory (no staging copy — the caller reduces/unpacks straight
        out of the mapping before the FIN releases it)."""
        from .. import faults
        faults.fire("rndv_chunk")     # crash/delay mid-pull (RGET)
        tr = getattr(self.engine, "tracer", None) \
            if hasattr(self, "engine") else None
        kind = handle[0] if isinstance(handle, tuple) else "path"
        if kind == "cma":
            _, pid, addr, _tok = handle
            out = np.empty(nbytes, dtype=np.uint8)
            cma_read(pid, addr, out, chunk=get_config()["RNDV_CHUNK"],
                     tracer=tr)
            return out
        if kind == "arena":
            if tr is not None:
                tr.record("protocol", "rndv_chunk", "i", dir="arena",
                          bytes=nbytes)
            return self.arena.view(handle[1], nbytes)
        path = handle[1] if kind == "file" else handle
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
        # a frombuffer view anchored to the mapping: the caller unpacks/
        # reduces out of it immediately, so no .copy() staging hop — the
        # view holds the mapping alive (unlink-while-mapped is fine)
        return np.frombuffer(mm, dtype=np.uint8, count=nbytes)

    def release_buffer(self, handle) -> None:
        if isinstance(handle, tuple):
            kind = handle[0]
            if kind == "cma" or kind == "null":
                self._exposed.pop(handle, None)
                return
            if kind == "arena":
                ah = self._exposed.pop(handle, None)
                if ah is not None:
                    self.arena.free(ah)
                return
            handle = handle[1]    # ("file", path)
        try:
            os.unlink(handle)
        except OSError:
            pass

    # a cancelled-and-retracted rendezvous send never gets its FIN; the
    # cancel-resp path releases the exposure through this alias
    unexpose_buffer = release_buffer

    def close(self) -> None:
        if self.plane:
            # latch final counters into the owned pvar values so tools
            # reading after teardown still see the job's totals
            try:
                stats = self.plane_stats()
                for (name, _), v in zip(_PV_PLANE_DECLS, stats):
                    pv = _mpit.pvar(name)
                    pv.source = None
                    pv._value += float(v)   # _value held the prior total
                for i, (name, _) in enumerate(_FP_COUNTERS):
                    pv = _mpit.pvar(name)
                    pv.source = None
                    pv._value += float(self.fp_counter(i))
            except Exception:
                pass
            try:
                self._ring.lib.cp_destroy(self.plane)
            except Exception:
                pass
            self.plane = None
        # clean departure: stamp the lease sentinel (AFTER cp_destroy so
        # a last advance_locked can't overwrite it) and stop the
        # heartbeat — peers must read "departed", never "dead"
        self._hb_stop.set()
        self._lease_stamp(self._LEASE_DEPARTED)
        # final metrics tick BEFORE detaching: a job shorter than one
        # sampling interval still publishes >= 1 row + its histograms
        smp, self._sampler = self._sampler, None
        if smp is not None:
            try:
                smp.tick()
            except Exception:
                pass
        if self._metrics_mm is not None:
            try:
                self._metrics_mm.close()
            except (OSError, ValueError, BufferError):
                pass
            self._metrics_mm = None
        if self.arena is not None:
            # Finalize leak check: every exposure must have been released
            # by its FIN/cancel; pending spills may legitimately await
            # reclaim, so free them silently first.
            with self._spill_lock:
                for pend in self._spill_pending.values():
                    while pend:
                        self.arena.free(pend.popleft()[1])
            if self._exposed or self.arena.outstanding:
                u = getattr(self.engine, "universe", None) \
                    if hasattr(self, "engine") else None
                if u is not None and u.failed_ranks:
                    # dead peers never FIN: their exposures/blocks are
                    # reclaimed state, not leaks (counted, not warned)
                    n = len(self._exposed) + self.arena.outstanding
                    for h in list(self._exposed):
                        self.release_buffer(h)
                    _mpit.pvar("arena_reclaimed_dead").inc(n)
                    log.info("reclaimed %d arena exposures/blocks "
                             "stranded by failed ranks %s", n,
                             sorted(u.failed_ranks))
                else:
                    log.warn("arena handle leak at close: %d exposures, "
                             "%d arena blocks live", len(self._exposed),
                             self.arena.outstanding)
            self.arena.close(unlink=self._owner and not self._daemon)
        try:
            self._bell.close()
            os.unlink(self._bell_path)
        except OSError:
            pass
        try:
            self._lease = None     # release the buffer exports first
            self._fpc_mirror = None
            self._flags.close()
            self._flags_f.close()
        except (OSError, ValueError, BufferError):
            pass
        try:
            self._ring.close()
        except Exception:
            pass
        if self._owner:
            if self._daemon_claim is not None:
                # warm-attach mode: the segment files belong to the node
                # daemon — release the claim (next job resets + reuses)
                from ..runtime import daemon as _daemon
                _daemon.release(self._daemon_claim)
            elif not self._daemon:
                for path in (self.path, self._flags_path,
                             self._flat_path, self._flat2_path,
                             self._ntrace_path, self._metrics_path):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        if self._ntrace_f is not None:
            try:
                self._ntrace_f.close()
            except OSError:
                pass
            self._ntrace_f = None
        if self._metrics_f is not None:
            try:
                self._metrics_f.close()
            except OSError:
                pass
            self._metrics_f = None

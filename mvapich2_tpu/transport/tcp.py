"""TCP channel — the sock-channel analog for process-mode ranks.

Design notes vs the reference (SURVEY §2.2):
  * Connections are made **on demand** at first send to a peer, like the
    mrail on-demand CM (common/src/cm/cm.c:1520) — no N² connect storm at
    init. Each connection is unidirectional (initiator -> acceptor), which
    removes the simultaneous-connect dedup handshake entirely.
  * Outgoing data is queued and flushed from poll() with nonblocking
    writes — the backlog-queue/credit pattern of ibv_send.c:320-360 — so a
    rank never blocks in send_packet while its peer is also mid-send
    (head-of-line deadlock on bidirectional large messages).
  * Wire frame: [4B header length][pickled header][payload bytes].
"""

from __future__ import annotations

import collections
import errno
import selectors
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.mlog import get_logger
from .base import Channel, Packet, decode_packet, encode_packet

log = get_logger("tcp")

_LEN = struct.Struct("<I")


class _Conn:
    """One inbound or outbound stream with reassembly state."""

    __slots__ = ("sock", "rbuf", "stage", "outq", "osent")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.stage = 0          # 0: reading len, 1: reading header+payload
        self.outq: collections.deque = collections.deque()
        self.osent = 0


class TcpChannel(Channel):
    name = "tcp"
    supports_rget = False

    def __init__(self, my_rank: int, kvs):
        self.my_rank = my_rank
        self.kvs = kvs
        self.sel = selectors.DefaultSelector()
        self.listener = self._take_or_bind_listener()
        self.listener.setblocking(False)
        self.sel.register(self.listener, selectors.EVENT_READ, "accept")
        host, port = self.listener.getsockname()[:2]
        kvs.put(f"tcp-addr-{my_rank}", f"{host}:{port}")
        self._out: Dict[int, _Conn] = {}      # dest rank -> conn
        self._in: List[_Conn] = []
        self._closed = False
        # serializes outbound conn state (outq + flush cursor): sends
        # come from any user thread (e.g. the MPI-IO worker) while
        # poll()'s backlog flush runs under the engine mutex — without
        # this lock the two interleave and corrupt the stream. A plain
        # channel-local lock (never held while waiting on a peer) so it
        # cannot join a cross-engine wait cycle.
        self._slock = threading.Lock()

    @staticmethod
    def _take_or_bind_listener() -> socket.socket:
        """With the node daemon on, adopt a pre-bound listening socket
        from its pool (SCM_RIGHTS handoff) — bootstrap wiring attaches
        instead of constructing, the same move the segment claim made
        for shm. Any failure falls back to a private bind, bit-
        identical to MV2T_DAEMON=0."""
        from ..runtime import daemon   # also declares the DAEMON cvar
        from ..utils.config import get_config
        if int(get_config().get("DAEMON", 0) or 0):
            lst = daemon.take_listener()
            if lst is not None:
                log.dbg(1, "adopted daemon-served listen socket %s",
                        lst.getsockname())
                return lst
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", 0))
        lst.listen(128)
        return lst

    # -- outgoing ---------------------------------------------------------
    def _connect(self, dest: int) -> _Conn:
        addr = self.kvs.get(f"tcp-addr-{dest}")
        host, port = addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        conn = _Conn(s)
        self._out[dest] = conn
        self.sel.register(s, selectors.EVENT_READ, ("out", conn))
        return conn

    def send_packet(self, dest_world: int, pkt: Packet) -> None:
        blob = encode_packet(pkt)
        with self._slock:
            conn = self._out.get(dest_world) or self._connect(dest_world)
            conn.outq.append(_LEN.pack(len(blob)))
            conn.outq.append(blob)
            self._flush(conn)
        self.account_send(dest_world, 4 + len(blob))

    def _flush(self, conn: _Conn) -> bool:
        """Nonblocking flush of the backlog; True if fully drained."""
        while conn.outq:
            buf = conn.outq[0]
            off = conn.osent
            try:
                n = conn.sock.send(memoryview(buf)[off:])
            except (BlockingIOError, InterruptedError):
                return False
            except OSError as e:  # peer died
                log.error("send to peer failed: %s", e)
                conn.outq.clear()
                return True
            conn.osent += n
            if conn.osent >= len(buf):
                conn.outq.popleft()
                conn.osent = 0
            if n == 0:
                return False
        return True

    # -- incoming ---------------------------------------------------------
    def _on_readable(self, conn: _Conn) -> bool:
        try:
            chunk = conn.sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            chunk = b""
        if not chunk:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.sock.close()
            return False
        conn.rbuf.extend(chunk)
        any_pkt = False
        while self._try_extract(conn):
            any_pkt = True
        return any_pkt

    def _try_extract(self, conn: _Conn) -> bool:
        buf = conn.rbuf
        if len(buf) < 4:
            return False
        blen = _LEN.unpack_from(buf, 0)[0]
        if len(buf) < 4 + blen:
            return False
        pkt = decode_packet(bytes(buf[4:4 + blen]))
        del buf[:4 + blen]
        self.account_recv(4 + blen)
        self.engine.enqueue_incoming(pkt)
        return True

    # -- progress ---------------------------------------------------------
    def poll(self) -> bool:
        if self._closed:
            return False
        did = False
        for key, _ in self.sel.select(timeout=0):
            data = key.data
            if data == "accept":
                try:
                    s, _ = self.listener.accept()
                except OSError:
                    continue
                s.setblocking(False)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn = _Conn(s)
                self._in.append(conn)
                self.sel.register(s, selectors.EVENT_READ, ("in", conn))
                did = True
            else:
                _, conn = data
                if self._on_readable(conn):
                    did = True
        with self._slock:
            for conn in list(self._out.values()):
                if conn.outq:
                    self._flush(conn)
                    did = True
        return did

    def wait_for_event(self, timeout: float) -> None:
        self.sel.select(timeout=timeout)

    def wait_fds(self):
        if self._closed:
            return []
        # snapshot: another thread (MPI_THREAD_MULTIPLE spawn/connect,
        # threads/spawn/th_taskmaster.c) can add a connection while the
        # progress thread builds the fd list
        fds = [self.listener]
        fds.extend(c.sock for c in list(self._in))
        fds.extend(c.sock for c in list(self._out.values()))
        return fds

    def close(self) -> None:
        # flush best-effort before teardown
        import time
        deadline = time.monotonic() + 2.0
        while any(c.outq for c in self._out.values()) and \
                time.monotonic() < deadline:
            with self._slock:
                for c in list(self._out.values()):
                    self._flush(c)
        self._closed = True
        for conn in list(self._out.values()) + self._in:
            try:
                conn.sock.close()
            except OSError:
                pass
        try:
            self.listener.close()
            self.sel.close()
        except OSError:
            pass

"""Persistent per-node scratch arena — the registration-cache analog.

One mmap'd /dev/shm region per node, created at bootstrap alongside the
shm ring segment and carved into size-classed blocks with a handle
table. It replaces the per-send scratch files the staged rendezvous used
to create (two full copies plus open/write/unlink syscalls per transfer,
the cost cliff BENCH_OSU_r05 shows at the eager->rendezvous switch): a
block is allocated once, reused across sends, and freed when the FIN
arrives — the steady-state reuse discipline of MVAPICH2's registration
cache (dreg.c) applied to a shared scratch pool.

Layout (offsets are file-absolute so they travel on the wire):

    spill-consumed grid   n*n u64   receiver's count of consumed arena
                                    spill notes per (src,dst) pair
    partition 0           PART bytes  owned by local rank 0
    ...
    partition n-1         PART bytes  owned by local rank n-1

Each rank allocates ONLY from its own partition (size-classed free
lists, local bookkeeping, no cross-process allocator locks) and any rank
may read any offset — the receiver of an RTS maps the handle straight to
a view of this mapping. Allocation/free are thread-safe within the
owning process (MPI-IO workers, THREAD_MULTIPLE).

The module also owns the cross-memory-attach read helper (the
process_vm_readv path of ch3_smp_progress.c:525) and the rendezvous
pipeline knobs/counters shared by transport/shm.py and pt2pt/protocol.py.
"""

from __future__ import annotations

import ctypes
import os
import re
import threading
from typing import Dict, List, Optional

import numpy as np

from ..utils.config import cvar, get_config
from ..utils.mlog import get_logger

log = get_logger("arena")

cvar("ARENA_BYTES", 0, int, "shm",
     "Per-rank partition size of the persistent per-node scratch arena "
     "in bytes. 0 = auto (256 MiB for 2 co-located ranks, 128 MiB for "
     "3-4, 32 MiB beyond — sized so a 64-deep window of 4 MiB sends, "
     "the OSU bw shape, stays in the arena). tmpfs allocates pages "
     "lazily, so the partition costs resident memory only for what the "
     "live traffic actually touches. Allocations larger than the "
     "partition fall back to the scratch-file path.")
cvar("RNDV_CHUNK", 256 * 1024, int, "pt2pt",
     "Pipeline chunk size in bytes for the chunked rendezvous (arena "
     "slot length / CMA read granularity — the MV2_RNDV_CHUNK analog of "
     "the RGET pipelining in ibv_rndv.c).")
cvar("RNDV_DEPTH", 4, int, "pt2pt",
     "Pipeline depth (arena slots in flight) of the chunked rendezvous: "
     "the sender refills slot k while the receiver drains slot k-1.")

from .. import mpit as _mpit  # noqa: E402  (after cvar decls, same registry)

pv_allocs = _mpit.pvar("arena_allocs", _mpit.PVAR_CLASS_COUNTER, "shm",
                       "blocks allocated from the per-node scratch arena")
pv_hwm = _mpit.pvar("arena_bytes_hwm", _mpit.PVAR_CLASS_HIGHWATERMARK,
                    "shm", "high-watermark of arena bytes in use")
pv_pipeline = _mpit.pvar("rndv_pipeline_chunks", _mpit.PVAR_CLASS_COUNTER,
                         "pt2pt",
                         "chunks moved by the pipelined rendezvous")
pv_cma_bytes = _mpit.pvar("rndv_cma_bytes", _mpit.PVAR_CLASS_COUNTER,
                          "pt2pt",
                          "bytes read via cross-memory attach "
                          "(process_vm_readv)")
pv_reclaimed_dead = _mpit.pvar(
    "arena_reclaimed_dead", _mpit.PVAR_CLASS_COUNTER, "shm",
    "arena blocks/segments reclaimed from dead ranks (failure sweep, "
    "Finalize leak-check tolerance, stale-segment sweep)")

_PAGE = 4096


# ---------------------------------------------------------------------------
# cross-memory attach (CMA) read
# ---------------------------------------------------------------------------

class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p),
                ("iov_len", ctypes.c_size_t)]


_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        lib = ctypes.CDLL(None, use_errno=True)
        lib.process_vm_readv.restype = ctypes.c_ssize_t
        lib.process_vm_readv.argtypes = [
            ctypes.c_int, ctypes.POINTER(_IoVec), ctypes.c_ulong,
            ctypes.POINTER(_IoVec), ctypes.c_ulong, ctypes.c_ulong]
        _libc = lib
    return _libc


def cma_read(pid: int, addr: int, out: np.ndarray, chunk: int = 0,
             tracer=None) -> None:
    """Read ``out.nbytes`` bytes from ``addr`` in process ``pid`` via
    process_vm_readv, ``chunk`` bytes per syscall (0 = one shot). Counts
    into rndv_cma_bytes; emits one trace instant per chunk so the
    pipeline overlap is visible in mpitrace."""
    lib = _get_libc()
    total = out.nbytes
    if total == 0:
        return
    step = chunk if chunk and chunk < total else total
    base = out.ctypes.data
    off = 0
    while off < total:
        n = min(step, total - off)
        liov = _IoVec(base + off, n)
        riov = _IoVec(addr + off, n)
        got = lib.process_vm_readv(pid, ctypes.byref(liov), 1,
                                   ctypes.byref(riov), 1, 0)
        if got != n:
            raise OSError(ctypes.get_errno(),
                          f"process_vm_readv({pid}) read {got}/{n}")
        if tracer is not None:
            tracer.record("protocol", "rndv_chunk", "i", dir="cma",
                          offset=off, bytes=n)
        off += n
    pv_cma_bytes.inc(total)


# ---------------------------------------------------------------------------
# the arena
# ---------------------------------------------------------------------------

class ArenaHandle:
    """One allocated block (the registration-cache entry analog)."""

    __slots__ = ("off", "cls", "nbytes")

    def __init__(self, off: int, cls: int, nbytes: int):
        self.off = off
        self.cls = cls          # size-class bytes (pow2 >= nbytes)
        self.nbytes = nbytes

    def __repr__(self):
        return f"ArenaHandle(off={self.off}, cls={self.cls})"


def _auto_part_bytes(n_local: int) -> int:
    if n_local <= 2:
        return 256 << 20
    if n_local <= 4:
        return 128 << 20
    return 32 << 20


class ShmArena:
    """One rank's mapping of the per-node scratch arena."""

    MIN_CLASS = 64 * 1024

    def __init__(self, path: str, n_local: int, my_index: int,
                 part_bytes: Optional[int] = None, create: bool = False,
                 exclusive: bool = True):
        """``create`` initializes a fresh arena; ``exclusive=False``
        relaxes O_EXCL for the warm-attach path (runtime/daemon.py),
        where the file pre-exists but was reset to all-zeroes — which
        IS the created state (empty spill grid, per-process brk)."""
        if part_bytes is None or part_bytes <= 0:
            part_bytes = int(get_config()["ARENA_BYTES"]) \
                or _auto_part_bytes(n_local)
        part_bytes = (part_bytes + _PAGE - 1) & ~(_PAGE - 1)
        hdr = (n_local * n_local * 8 + _PAGE - 1) & ~(_PAGE - 1)
        total = hdr + n_local * part_bytes
        import mmap as _mmap
        flags = os.O_RDWR
        if create:
            flags |= os.O_CREAT | (os.O_EXCL if exclusive else 0)
        self.fd = os.open(path, flags, 0o600)
        if create:
            os.ftruncate(self.fd, total)   # tmpfs: zero-filled
        self.mm = _mmap.mmap(self.fd, total)
        self.path = path
        self.n_local = n_local
        self.my_index = my_index
        self.part_bytes = part_bytes
        self._buf = np.frombuffer(self.mm, dtype=np.uint8)
        self._spill = self._buf[:n_local * n_local * 8].view(np.uint64)
        self._part_lo = hdr + my_index * part_bytes
        self._part_hi = self._part_lo + part_bytes
        self._brk = self._part_lo
        self._free: Dict[int, List[int]] = {}
        from ..analysis.lockorder import tracked
        self._lock = tracked(threading.Lock(), f"arena[{my_index}]._lock")
        self._outstanding = 0
        self._in_use = 0

    # -- slot allocator (owner-local) ------------------------------------
    @classmethod
    def _class_of(cls, nbytes: int) -> int:
        c = cls.MIN_CLASS
        while c < nbytes:
            c <<= 1
        return c

    def alloc(self, nbytes: int) -> Optional[ArenaHandle]:
        """A block of >= ``nbytes`` from my partition, or None when the
        partition is exhausted (caller falls back to the scratch-file
        path — never blocks, never deadlocks)."""
        if nbytes <= 0:
            nbytes = 1
        from .. import faults
        if faults.fire("arena_alloc") == "drop":
            return None     # simulated exhaustion: caller's fallback path
        c = self._class_of(nbytes)
        if c > self.part_bytes:
            return None
        with self._lock:
            fl = self._free.get(c)
            if fl:
                off = fl.pop()
            elif self._brk + c <= self._part_hi:
                off = self._brk
                self._brk += c
            else:
                return None
            self._outstanding += 1
            self._in_use += c
            pv_allocs.inc()
            pv_hwm.mark(self._in_use)
            return ArenaHandle(off, c, nbytes)

    def free(self, h: ArenaHandle) -> None:
        with self._lock:
            self._free.setdefault(h.cls, []).append(h.off)
            self._outstanding -= 1
            self._in_use -= h.cls

    def view(self, off: int, nbytes: int) -> np.ndarray:
        """A uint8 view of the shared mapping (any rank's region)."""
        return self._buf[off:off + nbytes]

    @property
    def outstanding(self) -> int:
        """Live handle count (the Finalize leak check)."""
        with self._lock:
            return self._outstanding

    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return self._in_use

    # -- spill-consumed counters (oversize python packets staged here) ---
    def spill_consumed(self, src_i: int, dst_i: int) -> int:
        return int(self._spill[src_i * self.n_local + dst_i])

    def bump_spill(self, src_i: int, dst_i: int) -> None:
        # single writer per cell (only dst bumps for src), so a plain
        # load-add-store is race-free
        self._spill[src_i * self.n_local + dst_i] += 1

    # -- lifecycle -------------------------------------------------------
    def close(self, unlink: bool = False) -> None:
        try:
            self._buf = None
            self._spill = None
            self.mm.close()
        except (BufferError, ValueError):
            pass   # numpy views still alive — leave the mapping to GC
        try:
            os.close(self.fd)
        except OSError:
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    @staticmethod
    def sweep_stale(dir_: str) -> int:
        """Crash cleanup: unlink arena segments whose creating process is
        gone (a SIGKILLed leader can't unlink its own). Called by the
        next leader to bootstrap on this node. Returns the sweep count."""
        n = 0
        try:
            names = os.listdir(dir_)
        except OSError:
            return 0
        for name in names:
            # arena segments AND per-job ring stems with their dotted
            # siblings (.flags/.fcoll/.fcoll2/.ntrace) — a SIGKILLed
            # leader leaves them all, and the sparse collective
            # segments' touched pages are real tmpfs memory
            m = re.match(r"mv2t-(?:arena|shm)-(\d+)-", name)
            if not m:
                continue
            pid = int(m.group(1))
            try:
                os.kill(pid, 0)
                continue             # creator alive
            except ProcessLookupError:
                pass
            except OSError:
                continue             # alive but not ours
            try:
                os.unlink(os.path.join(dir_, name))
                n += 1
            except OSError:
                pass
        if n:
            pv_reclaimed_dead.inc(n)
            log.info("swept %d stale arena segment(s) from %s", n, dir_)
        return n

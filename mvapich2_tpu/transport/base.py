"""Channel interface — the L3→L2 seam.

Analog of the CH3 channel API (SURVEY §1: MPIDI_CH3_iStartMsg / iSendv /
Rndv_transfer / MPIDI_CH3I_Progress, declared in
/root/reference/src/mpid/ch3/include/mpidimpl.h:1510-1640). A channel moves
opaque packets between world ranks; the protocol layer above it implements
matching and eager/rendezvous semantics. Channels in-tree:

  * local  — in-process threaded fabric (unit tests; nemesis-shm analog)
  * tcp    — sockets between rank processes (sock channel analog)
  * shm    — shared-memory rings between co-located processes (mrail SMP
             analog; C++ fast path)
  * ici    — the TPU path: collectives don't go through packets at all but
             lower to XLA ops on the device mesh (SURVEY §5.8)
"""

from __future__ import annotations

import enum
from typing import Any, Dict, Optional

import numpy as np


class PktType(enum.IntEnum):
    """Wire packet types — analog of MPIDI_CH3_Pkt_type_t
    (/root/reference/src/mpid/ch3/include/mpidpkt.h:96-182)."""

    EAGER_SEND = 1
    RNDV_RTS = 2           # request-to-send (no payload)
    RNDV_CTS = 3           # clear-to-send (receiver matched)
    RNDV_DATA = 4          # RPUT/R3 payload chunk
    RNDV_FIN = 5           # transfer complete
    RNDV_APUB = 6          # pipelined arena rendezvous: chunk published
    RNDV_AACK = 7          # pipelined arena rendezvous: chunk consumed
    # one-sided (SURVEY §2.1 RMA)
    RMA_PUT = 10
    RMA_GET = 11
    RMA_GET_RESP = 12
    RMA_ACC = 13
    RMA_GET_ACC = 14
    RMA_GET_ACC_RESP = 15
    RMA_CAS = 16
    RMA_CAS_RESP = 17
    RMA_FOP = 18
    RMA_FOP_RESP = 19
    RMA_LOCK = 20
    RMA_LOCK_GRANTED = 21
    RMA_UNLOCK = 22
    RMA_FLUSH = 23
    RMA_FLUSH_ACK = 24
    RMA_PSCW_POST = 25
    RMA_PSCW_COMPLETE = 26
    # control
    BARRIER_CTL = 30
    REVOKE = 31            # ULFM comm revoke propagation
    SHUTDOWN = 32
    CANCEL_SEND_REQ = 33   # retract an unmatched send (mpidpkt.h CANCEL)
    CANCEL_SEND_RESP = 34
    # CMA rendezvous — consumed entirely inside the C plane
    # (native/cplane.cpp): RTS carries (pid, address); the receiver
    # pulls via process_vm_readv and answers FIN (status in offset)
    RNDV_RTS_CMA = 40
    RNDV_FIN_CMA = 41


class Packet:
    """One wire message. ``data`` is a contiguous uint8 ndarray or None."""

    __slots__ = ("type", "src_world", "ctx", "comm_src", "tag", "nbytes",
                 "data", "sreq_id", "rreq_id", "protocol", "offset", "extra")

    def __init__(self, type: PktType, src_world: int, ctx: int = 0,
                 comm_src: int = 0, tag: int = 0, nbytes: int = 0,
                 data: Optional[np.ndarray] = None, sreq_id: int = 0,
                 rreq_id: int = 0, protocol: str = "", offset: int = 0,
                 extra: Optional[Dict[str, Any]] = None):
        self.type = type
        self.src_world = src_world
        self.ctx = ctx
        self.comm_src = comm_src
        self.tag = tag
        self.nbytes = nbytes
        self.data = data
        self.sreq_id = sreq_id
        self.rreq_id = rreq_id
        self.protocol = protocol
        self.offset = offset
        self.extra = extra

    def __repr__(self):
        return (f"Packet({self.type.name}, src={self.src_world}, "
                f"ctx={self.ctx}, tag={self.tag}, nbytes={self.nbytes})")


# ---------------------------------------------------------------------------
# binary wire codec
# ---------------------------------------------------------------------------
# Fixed struct header + optional pickled `extra` + raw payload, replacing
# whole-packet pickling: on the small-message path pickle.dumps/loads and
# its extra payload copy were ~30% of the per-message cost (the vbuf
# header of mpidpkt.h, in spirit). Layout:
#   _PKT_HDR | extra (exlen bytes, pickle) | payload (rest of the blob)
# `protocol` is an 8-byte NUL-padded field (RGET/RPUT/R3 fit).

import pickle as _pickle
import struct as _struct

_PKT_HDR = _struct.Struct("<Biiiiqqqq8si")
PKT_HDR_SIZE = _PKT_HDR.size

# Wire-carried plane ownership (native/cplane.cpp PLANE_CTX_FLAG): the
# sender sets bit 30 of ctx on EAGER/RTS packets whose communicator is
# plane-owned; the C matcher claims exactly those. decode_packet strips
# it so a python fallback receiver (no native plane) still matches.
PLANE_CTX_FLAG = 1 << 30


def encode_packet(pkt: "Packet") -> bytes:
    """Serialize to one contiguous blob (single payload copy)."""
    ex = b"" if pkt.extra is None else _pickle.dumps(pkt.extra, 5)
    hdr = _PKT_HDR.pack(int(pkt.type), pkt.src_world, pkt.ctx,
                        pkt.comm_src, pkt.tag, pkt.nbytes, pkt.sreq_id,
                        pkt.rreq_id, pkt.offset,
                        pkt.protocol.encode("ascii"), len(ex))
    if pkt.data is None:
        return hdr + ex
    # b"".join accepts buffer-protocol objects: the payload (an ndarray
    # or memoryview) is copied exactly once, into the blob
    return b"".join((hdr, ex, memoryview(pkt.data).cast("B")))


def decode_packet(blob) -> "Packet":
    """Inverse of encode_packet; ``blob`` is bytes or a memoryview."""
    (ptype, src_world, ctx, comm_src, tag, nbytes, sreq_id, rreq_id,
     offset, proto, exlen) = _PKT_HDR.unpack_from(blob, 0)
    pos = PKT_HDR_SIZE
    extra = None
    if exlen:
        extra = _pickle.loads(bytes(blob[pos:pos + exlen]))
        pos += exlen
    data = None
    if len(blob) > pos:
        data = np.frombuffer(blob, dtype=np.uint8, offset=pos)
    return Packet(PktType(ptype), src_world, ctx & ~PLANE_CTX_FLAG,
                  comm_src, tag, nbytes, data, sreq_id, rreq_id,
                  proto.rstrip(b"\0").decode("ascii"), offset, extra)


class Channel:
    """Transport ABC — the seam where mrail/nemesis/psm/sock plug in."""

    name = "abstract"
    # True if RTS packets may carry a zero-copy handle the receiver can pull
    # from directly (RGET analog). Local/shm channels support this.
    supports_rget = False
    # True for channels whose progress is pure memory polling (shm): the
    # engine spins instead of sleeping — the reference's CQ polling
    # discipline (SURVEY §3.5: "this polling loop is THE cpu hot loop").
    busy_poll = False

    def attach(self, engine) -> None:
        """Bind to the owning rank's progress engine."""
        self.engine = engine

    # -- traffic accounting (MPI_T per-channel counters + trace events) ---
    def _acct_pvars(self):
        """Lazily-declared per-channel-name pvars (the mv2_mpit.c channel
        counter discipline: bytes/messages per direction). Shared by
        every instance of a channel class in the process — same
        aggregation scope as every other pvar here."""
        pv = getattr(self, "_acct_pv", None)
        if pv is None:
            from .. import mpit
            n = self.name
            pv = (mpit.pvar(f"chan_{n}_msgs_sent",
                            mpit.PVAR_CLASS_COUNTER, "channel",
                            f"packets sent on the {n} channel"),
                  mpit.pvar(f"chan_{n}_bytes_sent",
                            mpit.PVAR_CLASS_COUNTER, "channel",
                            f"wire bytes sent on the {n} channel"),
                  mpit.pvar(f"chan_{n}_msgs_recv",
                            mpit.PVAR_CLASS_COUNTER, "channel",
                            f"packets received on the {n} channel"),
                  mpit.pvar(f"chan_{n}_bytes_recv",
                            mpit.PVAR_CLASS_COUNTER, "channel",
                            f"wire bytes received on the {n} channel"))
            self._acct_pv = pv
        return pv

    def account_send(self, dest_world: int, nbytes: int) -> None:
        pv = self._acct_pvars()
        pv[0].inc()
        pv[1].inc(nbytes)
        eng = getattr(self, "engine", None)
        if eng is not None and (tr := eng.tracer) is not None:
            tr.record("channel", f"{self.name}_send", "i",
                      dest=dest_world, bytes=nbytes)

    def account_recv(self, nbytes: int) -> None:
        pv = self._acct_pvars()
        pv[2].inc()
        pv[3].inc(nbytes)
        eng = getattr(self, "engine", None)
        if eng is not None and (tr := eng.tracer) is not None:
            tr.record("channel", f"{self.name}_recv", "i", bytes=nbytes)

    def account_rndv_chunk(self, t0: float) -> None:
        """Rendezvous chunk-batch completion: elapsed seconds since the
        caller's ``t0`` into the lat_rndv_chunk histogram. Callers gate
        on ``metrics.LIVE`` themselves (same one-attribute-check
        discipline as the tracer sites), so the off-path cost is the
        caller's check, not a call."""
        from .. import metrics as _metrics
        mx = _metrics.LIVE
        if mx is not None:
            mx.rec_since("lat_rndv_chunk", t0)

    def send_packet(self, dest_world: int, pkt: Packet) -> None:
        raise NotImplementedError

    def poll(self) -> bool:
        """Advance I/O; return True if any packet was processed."""
        raise NotImplementedError

    def wait_for_event(self, timeout: float) -> None:
        """Block up to ``timeout`` seconds for inbound traffic (may return
        early spuriously). Default: busy-poll granularity sleep."""
        import time
        time.sleep(min(timeout, 0.0002))

    def wait_fds(self):
        """File objects that become readable when this channel has inbound
        traffic; the engine selects on the union across channels so a
        blocked rank wakes immediately (doorbells/sockets)."""
        return []

    def pre_wait(self) -> None:
        """Called by the engine BEFORE its last empty poll ahead of a
        blocking wait — a channel can advertise 'receiver sleeping' so
        senders know a doorbell is needed (see ShmChannel's adaptive
        bell). The order closes the race: advertise, then final poll,
        then sleep."""

    def post_wait(self) -> None:
        """Called after the blocking wait returns."""

    # -- zero-copy rendezvous hooks (RGET path) ---------------------------
    def expose_buffer(self, array: np.ndarray) -> Any:
        """Register a send buffer for remote pull; returns an opaque handle
        carried in the RTS (the rkey analog, gen2/ibv_rndv.c:171)."""
        raise NotImplementedError

    def pull_buffer(self, src_world: int, handle: Any, nbytes: int) -> np.ndarray:
        """RGET: read the peer's exposed buffer."""
        raise NotImplementedError

    def release_buffer(self, handle: Any) -> None:
        pass

    def close(self) -> None:
        pass

"""C-ABI shim: the Python side of native/mpi/libmpi.c.

The reference's hard boundary is the MPI C ABI (SURVEY §7 hard part (a):
"the OSU benchmarks are C programs"). libmpi.so embeds CPython and calls
the functions here; handles cross the boundary as small integers, buffers
as writable memoryviews over the caller's memory (zero-copy in/out via
numpy frombuffer).

Handle tables: comm 0 = MPI_COMM_WORLD, 1 = MPI_COMM_SELF, dynamic ids
from 2. Datatype/op codes are fixed enums mirrored in native/mpi/mpi.h.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

import numpy as np

from . import mpi
from .core import datatype as dt
from .core import op as opmod
from .core.errors import MPIException
from .core.status import ANY_SOURCE, ANY_TAG, PROC_NULL
from .coll.api import IN_PLACE
from .runtime import universe as uni
from .utils.config import cvar, get_config

cvar("CSHIM_PROFILE", "", str, "debug",
     "When set, cProfile the C-ABI shim for the whole job and "
     "write per-rank pstats dumps to <value>.rank<r> at Finalize.")
cvar("UNIVERSE_SIZE", 0, int, "runtime",
     "MPI_UNIVERSE_SIZE override (spawn capacity); 0 = default "
     "world+8 (process-mode spawn forks children freely).")

# ---------------------------------------------------------------------------
# handle tables (mirror the enum values in native/mpi/mpi.h)
# ---------------------------------------------------------------------------

_DTYPES = {
    0: np.dtype(np.uint8),     # MPI_BYTE
    1: np.dtype(np.int8),      # MPI_CHAR
    2: np.dtype(np.int32),     # MPI_INT
    3: np.dtype(np.float32),   # MPI_FLOAT
    4: np.dtype(np.float64),   # MPI_DOUBLE
    5: np.dtype(np.int64),     # MPI_LONG / MPI_LONG_LONG
    6: np.dtype(np.uint64),    # MPI_UNSIGNED_LONG
    7: np.dtype(np.int16),     # MPI_SHORT
    8: np.dtype(np.uint8),     # MPI_UNSIGNED_CHAR
    9: np.dtype(np.int64),     # MPI_AINT
    10: np.dtype(np.uint32),   # MPI_UNSIGNED
    11: np.dtype(np.uint16),   # MPI_UNSIGNED_SHORT
    12: np.dtype(np.longdouble),  # MPI_LONG_DOUBLE (16B on x86-64)
    13: np.dtype(np.bool_),    # MPI_C_BOOL
    # MINLOC/MAXLOC pair types: layout matches the C structs
    # {T val; int loc;} including padding (align=True)
    14: np.dtype([("val", np.float32), ("loc", np.int32)], align=True),
    15: np.dtype([("val", np.float64), ("loc", np.int32)], align=True),
    16: np.dtype([("val", np.int64), ("loc", np.int32)], align=True),
    17: np.dtype([("val", np.int32), ("loc", np.int32)], align=True),
    18: np.dtype([("val", np.int16), ("loc", np.int32)], align=True),
    19: np.dtype([("val", np.longdouble), ("loc", np.int32)], align=True),
    # distinct handles for the LP64 aliases (mpi.h): same storage,
    # per-name identity for Type_get_name / get_envelope
    20: np.dtype(np.int64),    # MPI_LONG
    21: np.dtype(np.int8),     # MPI_SIGNED_CHAR
    22: np.dtype(np.int64),    # MPI_OFFSET
    23: np.dtype(np.int64),    # MPI_COUNT
    24: np.dtype(np.int8),     # MPI_INT8_T
    25: np.dtype(np.int16),    # MPI_INT16_T
    26: np.dtype(np.int32),    # MPI_INT32_T
    27: np.dtype(np.int64),    # MPI_INT64_T
    28: np.dtype(np.uint8),    # MPI_UINT8_T
    29: np.dtype(np.uint16),   # MPI_UINT16_T
    30: np.dtype(np.uint32),   # MPI_UINT32_T
    31: np.dtype(np.uint64),   # MPI_UINT64_T
    32: np.dtype(np.int32),    # MPI_WCHAR (wchar_t is int32 on linux)
    33: np.dtype(np.complex64),     # MPI_C_FLOAT_COMPLEX
    34: np.dtype(np.complex128),    # MPI_C_DOUBLE_COMPLEX
    35: np.dtype(np.clongdouble),   # MPI_C_LONG_DOUBLE_COMPLEX
    36: np.dtype(np.bool_),         # MPI_CXX_BOOL
    37: np.dtype(np.complex64),     # MPI_CXX_FLOAT_COMPLEX
    38: np.dtype(np.complex128),    # MPI_CXX_DOUBLE_COMPLEX
    39: np.dtype(np.clongdouble),   # MPI_CXX_LONG_DOUBLE_COMPLEX
    40: np.dtype(np.uint8),         # MPI_PACKED
}

# MPI-1 bound markers: zero-size pseudo-types legal only inside
# Type_struct member lists (MPI-1 §3.12.3); mpi.h MPI_LB/MPI_UB
_MARKER_LB = 41
_MARKER_UB = 42

_OPS = {
    0: opmod.SUM, 1: opmod.PROD, 2: opmod.MAX, 3: opmod.MIN,
    4: opmod.LAND, 5: opmod.LOR, 6: opmod.BAND, 7: opmod.BOR,
    8: opmod.BXOR, 9: opmod.LXOR, 10: opmod.MINLOC, 11: opmod.MAXLOC,
    12: opmod.REPLACE, 13: opmod.NO_OP,
}

# derived datatypes: integer handles from 100 (MPI_Type_* constructors)
_DERIVED_BASE = 100
_derived: Dict[int, dt.Datatype] = {}
_next_derived = _DERIVED_BASE


_PAIR_DT = {14: dt.FLOAT_INT, 15: dt.DOUBLE_INT, 16: dt.LONG_INT,
            17: dt.TWOINT, 18: dt.SHORT_INT, 19: dt.LONG_DOUBLE_INT}


def _dt(code: int) -> dt.Datatype:
    """Datatype object for a C handle (builtin enum or derived)."""
    if code >= _DERIVED_BASE:
        return _derived[code]
    if code in _PAIR_DT:      # size 12 != extent 16 etc. (§5.9.4 pairs)
        return _PAIR_DT[code]
    return dt.from_numpy_dtype(_DTYPES[code])

_lock = threading.Lock()
_comms: Dict[int, object] = {}
_reqs: Dict[int, object] = {}
_wins: Dict[int, object] = {}
_next_comm = 2
_next_req = 1
_next_win = 1



def _group(h: int):
    """Group object for a C handle; MPI_GROUP_EMPTY (-2) is predefined."""
    if h == -2:
        from .core.group import Group
        return Group([])
    g = _groups.get(h)
    if g is None:
        from .core.errors import MPI_ERR_GROUP
        raise MPIException(MPI_ERR_GROUP, f"invalid group handle {h}")
    return g

def _comm(h: int):
    if h == 0:
        c = uni.current_universe().comm_world
    elif h == 1:
        c = uni.current_universe().comm_self
    else:
        c = _comms.get(h)
    if c is None:
        # freed or never-allocated handle: a reportable MPI error, not
        # a KeyError crash (errors/comm/cfree.c barriers a freed dup)
        from .core.errors import MPI_ERR_COMM
        raise MPIException(MPI_ERR_COMM, f"invalid communicator {h}")
    if c.__dict__.get("_cabi_handle") is None:
        # the C handle, for layers that must share per-comm state with
        # the C fast path (coll/flatcoll.py call numbering)
        c._cabi_handle = h
    return c


def _arr(view, count: int, dtcode: int) -> np.ndarray:
    """Zero-copy numpy array over the C caller's buffer (basic types
    only — paths without explicit derived-type handling fail loudly
    instead of silently reinterpreting bytes)."""
    if dtcode >= _DERIVED_BASE:
        from .core.errors import MPI_ERR_TYPE
        raise MPIException(MPI_ERR_TYPE,
                           "derived datatype not supported on this path")
    d = _DTYPES[dtcode]
    if view is None:
        # NULL buffer: legal for zero-count operations (MPI-3.1 §3.2.2)
        from .core.errors import MPI_ERR_BUFFER
        if count > 0:
            raise MPIException(MPI_ERR_BUFFER,
                               "NULL buffer with nonzero count")
        return np.empty(0, dtype=d)
    return np.frombuffer(view, dtype=d, count=count)


def _send_args(view, count: int, dtcode: int):
    """(buf, kwargs) for a pt2pt call honoring derived datatypes."""
    if dtcode >= _DERIVED_BASE:
        return (np.frombuffer(view, np.uint8),
                {"count": count, "datatype": _derived[dtcode]})
    return _arr(view, count, dtcode), {}


# -- MPI_BOTTOM (absolute addressing, MPI-3.1 §4.1.5) -----------------------
# The C side passes view=None for a NULL buffer pointer: the datatype's
# displacements are then absolute process addresses (built from
# MPI_Get_address, e.g. reference test/mpi/pt2pt/bottom.c). The wire
# format is the same packed stream a relative derived send produces
# (ch3u_eager.c:208 operates on (char*)buf + dt_true_lb the same way) —
# gather/scatter just runs against absolute memory through ctypes.

def _bottom_spans(count: int, dtcode: int):
    # precondition: dtcode is derived (callers gate on _DERIVED_BASE;
    # basic-type MPI_BOTTOM with count>0 errors in _arr)
    if count == 0:
        return None, []
    d = _derived[dtcode]
    return d, d.flatten(count)


def _bottom_gather(count: int, dtcode: int, base: int = 0) -> np.ndarray:
    import ctypes
    d, spans = _bottom_spans(count, dtcode)
    out = np.empty(d.size * count if d else 0, np.uint8)
    pos = 0
    for off, ln in spans:
        # spans are an (N,2) int64 ndarray; ctypes needs exact ints
        off, ln = int(off) + base, int(ln)
        src = (ctypes.c_ubyte * ln).from_address(off)
        out[pos:pos + ln] = np.frombuffer(src, np.uint8)
        pos += ln
    return out


def _bottom_scatter(tmp: np.ndarray, count: int, dtcode: int,
                    base: int = 0) -> None:
    import ctypes
    _, spans = _bottom_spans(count, dtcode)
    pos = 0
    for off, ln in spans:
        off, ln = int(off) + base, int(ln)
        dst = (ctypes.c_ubyte * ln).from_address(off)
        np.frombuffer(dst, np.uint8)[:] = tmp[pos:pos + ln]
        pos += ln


def _needs_abs(view, count: int, dtcode: int) -> bool:
    """True when a non-NULL buffer must go through the absolute-address
    (ctypes) path: the datatype reaches bytes BEFORE the buffer pointer
    (negative typemap displacements / negative extent tiling —
    datatype/unusual-noncontigs.c sends from sendbuf+2 with such
    types). The pointer-view pack/unpack cannot express those."""
    return (bool(view) and count > 0 and dtcode >= _DERIVED_BASE
            and _derived[dtcode].needs_abs(count))


def _view_addr(view) -> int:
    """The raw address a C-boundary memoryview starts at (the user's
    buffer pointer; type_span keeps these views ≥1 byte so the address
    survives for abs-path types)."""
    a = np.frombuffer(view, np.uint8)
    return int(a.ctypes.data)


def _bottom_tmp(count: int, dtcode: int) -> np.ndarray:
    d, _ = _bottom_spans(count, dtcode)
    return np.zeros(d.size * count if d else 0, np.uint8)


def _send_args_b(view, count: int, dtcode: int):
    """_send_args plus the send-side MPI_BOTTOM case: pre-pack from the
    absolute addresses at post time (MPI forbids touching the send
    buffer until completion, so the gathered snapshot is the message —
    valid for every send mode, including nonblocking posts)."""
    if not view and dtcode >= _DERIVED_BASE:
        return _bottom_gather(count, dtcode), {}
    if _needs_abs(view, count, dtcode):
        return _bottom_gather(count, dtcode, _view_addr(view)), {}
    return _send_args(view, count, dtcode)


class _BottomRecvReq:
    """Completion wrapper for MPI_BOTTOM receives: the payload lands in
    a temp packed buffer, scattered to the absolute addresses when the
    request completes (wait/test both funnel through wait)."""

    def __init__(self, inner, tmp, count, dtcode, base=0):
        self._inner = inner
        self._tmp = tmp
        self._count = count
        self._dtcode = dtcode
        self._base = base
        self._scattered = False

    def wait(self):
        st = self._inner.wait()
        if not self._scattered:
            self._scattered = True
            if not getattr(self._inner, "cancelled", False):
                _bottom_scatter(self._tmp, self._count, self._dtcode,
                                self._base)
        return st

    def test(self):
        return self._inner.test()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _peer(c) -> int:
    """Elements multiplier for the 'other side' of a collective: the
    remote group's size on intercommunicators (MPI-3.1 §5.2.2), the
    comm size otherwise."""
    return c.remote_size if getattr(c, "is_inter", False) else c.size


def _esz(dtcode: int) -> int:
    """Packed (type-signature) bytes per element. MPI_DATATYPE_NULL
    (negative) maps to 1: it only appears with zero counts/NULL
    buffers (nonblocking.c calls every collective that way)."""
    if dtcode < 0:
        return 1
    return _dt(dtcode).size if dtcode >= _DERIVED_BASE \
        else _DTYPES[dtcode].itemsize


def _gather_in(view, off_elems: int, count: int, dtcode: int) -> np.ndarray:
    """Packed uint8 bytes of `count` elements starting at element offset
    `off_elems` of the caller's buffer (extent-strided for derived)."""
    raw = np.frombuffer(view, np.uint8)
    if dtcode < _DERIVED_BASE:
        esz = _DTYPES[dtcode].itemsize
        return raw[off_elems * esz:(off_elems + count) * esz]
    d = _derived[dtcode]
    seg = raw[off_elems * d.extent:]
    return np.asarray(d.pack(seg, count)).view(np.uint8).reshape(-1)


def _scatter_out(view, off_elems: int, count: int, dtcode: int,
                 data_u8) -> None:
    """Write `count` packed elements into the caller's buffer at element
    offset `off_elems` (unpacking through the datatype for derived).
    count==0 writes nothing — the buffer may be a legal NULL (empty,
    read-only bytes at the C boundary)."""
    if count <= 0:
        return
    raw = np.frombuffer(view, np.uint8)
    if dtcode < _DERIVED_BASE:
        esz = _DTYPES[dtcode].itemsize
        raw[off_elems * esz:(off_elems + count) * esz] = data_u8
    else:
        d = _derived[dtcode]
        d.unpack(np.asarray(data_u8), raw[off_elems * d.extent:], count)


def _red_view(view, count: int, dtcode: int):
    """(typed contiguous array, writeback) for a reduction operand.
    Basic types are zero-copy; homogeneous derived types are packed to a
    contiguous typed temp (written back by the returned callable);
    heterogeneous derived types are rejected (MPI-3.1 §5.9.2 restricts
    predefined ops to suitable types)."""
    if dtcode < _DERIVED_BASE:
        return _arr(view, count, dtcode), None
    d = _derived[dtcode]
    if d.basic is None:
        from .core.errors import MPI_ERR_TYPE
        raise MPIException(MPI_ERR_TYPE,
                           "reduction on heterogeneous derived type")
    raw = np.frombuffer(view, np.uint8)
    arr = np.asarray(d.pack(raw, count)).view(d.basic)

    def writeback():
        d.unpack(arr.view(np.uint8), raw, count)
    return arr, writeback


# ---------------------------------------------------------------------------
# init / world
# ---------------------------------------------------------------------------

# True once this process entered MPI through the C ABI (libmpi.so ->
# init() below). Python-side dispatch must then assume the C fast path
# co-dispatches on every comm (coll/api.py _plane_coll_max).
_cabi_process = False


def is_cabi_process() -> bool:
    return _cabi_process


def init() -> int:
    global _cabi_process
    _cabi_process = True
    # debugging aid (MV2_DEBUG-style): SIGUSR1 dumps all Python thread
    # stacks of a rank — how a hung conformance run is diagnosed
    try:
        import faulthandler
        import signal as _sig
        faulthandler.register(_sig.SIGUSR1, all_threads=True)
    except (ImportError, AttributeError, ValueError):
        pass
    if get_config().get("CSHIM_PROFILE", ""):
        import cProfile
        global _profiler
        _profiler = cProfile.Profile()
        _profiler.enable()
    mpi.Init()
    return 0


def adopt_boot() -> int:
    """Deferred world build for a light-booted C-ABI rank
    (mvapich2_tpu.cabi_boot): MPI_Init already ran the light boot; the
    first forwarded call lands here to construct the Universe. Same
    body as init() minus the signal hook (cabi_boot installed it)."""
    global _cabi_process
    _cabi_process = True
    if get_config().get("CSHIM_PROFILE", ""):
        import cProfile
        global _profiler
        if _profiler is None:
            _profiler = cProfile.Profile()
            _profiler.enable()
    mpi.Init()
    return 0


_profiler = None


def finalize() -> int:
    mpi.Finalize()
    if _profiler is not None:
        _profiler.disable()
        import pstats
        path = get_config().get("CSHIM_PROFILE", "") + \
            f".rank{os.environ.get('MV2T_RANK', '0')}"
        with open(path, "w") as f:
            pstats.Stats(_profiler, stream=f).sort_stats(
                "cumulative").print_stats(40)
    return 0


def initialized() -> int:
    return 1 if mpi.Initialized() else 0


def comm_rank(ch: int) -> int:
    return _comm(ch).rank


def comm_size(ch: int) -> int:
    return _comm(ch).size


def abort(ch: int, code: int) -> int:
    mpi.Abort(None, code)
    return 0


def comm_split(ch: int, color: int, key: int) -> int:
    global _next_comm
    c = _comm(ch).split(color if color >= 0 else None, key)
    if c is None:          # MPI_UNDEFINED color: no handle slot burned
        return -1
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


def comm_dup(ch: int) -> int:
    global _next_comm
    c = _comm(ch).dup()
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


def _drop_worker(ch: int) -> None:
    with _lock:
        w = _workers.pop(ch, None)
    if w is not None:
        w.q.put(None)          # worker thread exits after the queue drains


def comm_free(ch: int) -> int:
    global _parent_handle
    _drop_worker(ch)
    with _lock:
        c = _comms.pop(ch, None)
    if c is not None:
        c.free()
    if ch == _parent_handle:
        # freed/disconnected parent: get_parent now yields MPI_COMM_NULL
        _parent_handle = None
        u = uni.current_universe()
        if u is not None:
            u.parent_intercomm = None
    return 0


def comm_plane_info(ch: int):
    """C fast-path comm descriptor (native/mpi/fastpath.c): returns
    (pt2pt ctx, my rank, size, [plane ring index per comm rank]) when the
    communicator is plane-owned and intra, else None."""
    try:
        c = _comm(ch)
    except Exception:
        return None
    u = c.u
    pc = getattr(u, "plane_channel", None)
    if pc is None or not pc.plane or not getattr(c, "_plane_owned", False) \
            or c.is_inter:
        return None
    idx = []
    for r in range(c.size):
        w = c.group.world_of_rank(r)
        i = pc.local_index.get(w, -1)
        if i < 0:
            return None
        idx.append(i)
    return (c.ctx_pt2pt, c.rank, c.size, idx)


def type_spans(dtcode: int):
    """Datatype layout for the C span engine (native/mpi/fastpath.c):
    (elem_size, extent, [off0, len0, ...], basic_item_size) for ONE
    element, or None when the type is unsuitable (zero size, span-count
    blowup). basic_item_size is the uniform signature granularity (0 if
    heterogeneous) — the C recv path rejects deliveries that split a
    basic item (errors/pt2pt/truncmsg2.c signature mismatch).
    Derived handles are never reused (monotonic), so C may cache this
    forever — MPI_Type_free keeps the definition alive by design."""
    import numpy as _np
    try:
        d = _dt(dtcode)
    except Exception:
        return None
    arr = _np.asarray(d.spans, dtype=_np.int64).reshape(-1, 2)
    if d.size <= 0 or len(arr) == 0 or len(arr) > 1024:
        return None
    if d.min_off < 0 or d.extent < 0:
        # negative displacements: the C engine's span walk is unsigned
        # from the buffer pointer — leave these to the shim's abs path
        return None
    basic = 0
    if d.basic is not None and not d.basic.names:
        basic = int(d.basic.itemsize)
    else:
        from .core.datatype import element_size_seq
        seq = element_size_seq(d)
        if seq is not None and len(set(seq)) == 1:
            basic = int(seq[0])
    return (int(d.size), int(d.extent),
            [int(x) for x in arr.reshape(-1)], basic)


def plane_eager_threshold() -> int:
    from .utils.config import get_config
    t = int(get_config()["SMP_EAGERSIZE"])
    u = uni.current_universe()
    pch = getattr(u, "plane_channel", None) if u is not None else None
    if pch is not None and pch.plane_eager_max():
        t = min(t, pch.plane_eager_max())
    return t


def plane_coll_max() -> int:
    """FP_COLL_MAX for the C fast path's collective gate (fpc_enter) —
    the same source of truth as coll/api.py's plane-tier gate, so every
    rank of a mixed C/python job reaches the identical dispatch."""
    from .utils.config import get_config
    return int(get_config()["FP_COLL_MAX"])


def plane_congest_min() -> int:
    """RNDV_CONGEST_MIN for the C fast path's protocol choice (same
    source of truth as the python layer's congestion switch)."""
    from .utils.config import get_config
    try:
        return int(get_config()["RNDV_CONGEST_MIN"])
    except KeyError:
        return 8192


def plane_progress() -> int:
    """One python progress pass, driven from a C fast-path wait loop."""
    u = uni.current_universe()
    if u is None:
        return 0
    return 1 if u.engine.progress_poke() else 0


def get_processor_name() -> str:
    return mpi.Get_processor_name()


# ---------------------------------------------------------------------------
# pt2pt
# ---------------------------------------------------------------------------

def send(view, count: int, dtcode: int, dest: int, tag: int,
         ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    _comm(ch).send(buf, dest, tag, **kw)
    return 0


def recv(view, count: int, dtcode: int, source: int, tag: int,
         ch: int):
    """Returns (source, tag, count_bytes)."""
    if not view and dtcode >= _DERIVED_BASE:
        tmp = _bottom_tmp(count, dtcode)
        st = _comm(ch).recv(tmp, source, tag)
        _bottom_scatter(tmp, count, dtcode)
        return (st.source, st.tag, st.count)
    if _needs_abs(view, count, dtcode):
        tmp = _bottom_tmp(count, dtcode)
        st = _comm(ch).recv(tmp, source, tag)
        _bottom_scatter(tmp, count, dtcode, _view_addr(view))
        return (st.source, st.tag, st.count)
    buf, kw = _send_args(view, count, dtcode)
    st = _comm(ch).recv(buf, source, tag, **kw)
    return (st.source, st.tag, st.count)


def isend(view, count: int, dtcode: int, dest: int, tag: int,
          ch: int) -> int:
    global _next_req
    buf, kw = _send_args_b(view, count, dtcode)
    r = _comm(ch).isend(buf, dest, tag, **kw)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def irecv(view, count: int, dtcode: int, source: int, tag: int,
          ch: int) -> int:
    global _next_req
    if not view and dtcode >= _DERIVED_BASE:
        tmp = _bottom_tmp(count, dtcode)
        r = _BottomRecvReq(_comm(ch).irecv(tmp, source, tag), tmp,
                           count, dtcode)
    elif _needs_abs(view, count, dtcode):
        tmp = _bottom_tmp(count, dtcode)
        r = _BottomRecvReq(_comm(ch).irecv(tmp, source, tag), tmp,
                           count, dtcode, _view_addr(view))
    else:
        buf, kw = _send_args(view, count, dtcode)
        r = _comm(ch).irecv(buf, source, tag, **kw)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def wait(rh: int):
    """Returns (source, tag, count_bytes, persistent, cancelled).
    Persistent
    requests stay allocated (inactive) after completion (MPI-3.1 §3.9);
    others are deallocated. Wait on an INACTIVE persistent request
    returns at once with an empty status (§3.7.3)."""
    with _lock:
        r = _reqs.get(rh)
    if r is None:
        return (-1, -2, 0, 0, 0)
    persistent = bool(getattr(r, "persistent", False))
    if persistent and not getattr(r, "_c_active", False):
        return (-1, -2, 0, 1, 0)
    st = r.wait()
    if persistent:
        r._c_active = False
    if not persistent:
        with _lock:
            _reqs.pop(rh, None)
    cancelled = 1 if (st is not None
                      and getattr(st, "cancelled", False)) \
        or getattr(r, "cancelled", False) else 0
    if st is None:
        return (-1, -2, 0, 1 if persistent else 0, cancelled)
    return (st.source, st.tag, st.count, 1 if persistent else 0,
            cancelled)


def test(rh: int):
    """Returns (flag, persistent, source, tag, count_bytes, cancelled).
    Test on an INACTIVE persistent request returns flag=1, empty status
    (§3.7.3)."""
    with _lock:
        r = _reqs.get(rh)
    if r is None:
        return (1, 0, -1, -2, 0, 0)
    persistent = bool(getattr(r, "persistent", False))
    if persistent and not getattr(r, "_c_active", False):
        return (1, 1, -1, -2, 0, 0)
    done = r.test()
    if not done:
        return (0, 0, -1, -2, 0, 0)
    if not persistent:
        with _lock:
            _reqs.pop(rh, None)
    st = r.wait()
    if persistent:
        r._c_active = False
    cancelled = 1 if (st is not None
                      and getattr(st, "cancelled", False)) \
        or getattr(r, "cancelled", False) else 0
    if st is None:
        return (1, 1 if persistent else 0, -1, -2, 0, cancelled)
    return (1, 1 if persistent else 0, st.source, st.tag, st.count,
            cancelled)


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def barrier(ch: int) -> int:
    _comm(ch).barrier()
    return 0


def bcast(view, count: int, dtcode: int, root: int, ch: int) -> int:
    c = _comm(ch)
    if dtcode >= _DERIVED_BASE:
        payload = np.array(_gather_in(view, 0, count, dtcode)) \
            if c.rank == root else np.empty(count * _esz(dtcode), np.uint8)
        c.bcast(payload, root=root)
        if c.rank != root:
            _scatter_out(view, 0, count, dtcode, payload)
        return 0
    c.bcast(_arr(view, count, dtcode), root=root)
    return 0


def allreduce(sview, rview, count: int, dtcode: int, opcode: int,
              ch: int) -> int:
    c = _comm(ch)
    rb, wb = _red_view(rview, count, dtcode)
    if sview is None:                       # MPI_IN_PLACE
        sb = rb.copy()
    else:
        sb, _ = _red_view(sview, count, dtcode)
    c.allreduce(sb, rb, op=_OPS[opcode])
    if wb is not None:
        wb()
    return 0


def reduce(sview, rview, count: int, dtcode: int, opcode: int, root: int,
           ch: int) -> int:
    c = _comm(ch)
    rb, wb = _red_view(rview, count, dtcode) if rview \
        else (None, None)
    if sview is None:          # MPI_IN_PLACE: root contributes recvbuf
        sb = rb.copy() if rb is not None else None
    else:
        sb, _ = _red_view(sview, count, dtcode)
    c.reduce(sb, rb, op=_OPS[opcode], root=root)
    if wb is not None:
        wb()
    return 0


def allgather(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
              ch: int) -> int:
    c = _comm(ch)
    n = _peer(c)
    if sdt >= _DERIVED_BASE or rdt >= _DERIVED_BASE:
        return allgatherv(sview, rview, scount, sdt, [rcount] * n,
                          [i * rcount for i in range(n)], rdt, ch)
    rb = _arr(rview, rcount * n, rdt)
    sb = _arr(sview, scount, sdt) if sview is not None \
        else rb[c.rank * rcount:(c.rank + 1) * rcount].copy()
    c.allgather(sb, rb, count=rcount)
    return 0


def alltoall(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
             ch: int) -> int:
    c = _comm(ch)
    if sdt >= _DERIVED_BASE or rdt >= _DERIVED_BASE:
        if sview is None:                   # MPI_IN_PLACE: sendcount and
            sview = bytes(np.frombuffer(rview, np.uint8))
            scount, sdt = rcount, rdt       # sendtype are ignored (§5.8)
        n = _peer(c)
        return alltoallv(sview, rview, [scount] * n,
                         [i * scount for i in range(n)],
                         [rcount] * n, [i * rcount for i in range(n)],
                         sdt, rdt, ch)
    n = _peer(c)
    rb = _arr(rview, rcount * n, rdt)
    sb = _arr(sview, scount * n, sdt) if sview is not None \
        else rb.copy()
    c.alltoall(sb, rb, count=rcount)
    return 0


def gather(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
           root: int, ch: int) -> int:
    """Always routed through the byte-level gatherv: the root-side
    datatype is root-only-significant, so per-rank branching on it
    would put the root and the contributors in DIFFERENT algorithms
    (linear vs binomial) — messages cross-match and corrupt data
    (scatter2.c's derived-at-root pattern)."""
    c = _comm(ch)
    n = _peer(c)
    return gatherv(sview, rview, scount, sdt, [rcount] * n,
                   [i * rcount for i in range(n)], rdt, root, ch)


def scatter(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
            root: int, ch: int) -> int:
    """Always routed through the byte-level scatterv — see gather()."""
    c = _comm(ch)
    n = _peer(c)
    if rview is None:
        # IN_PLACE root: recvcount/recvtype are ignored (§5.6)
        rcount, rdt = 0, sdt
    return scatterv(sview, rview, [scount] * n,
                    [i * scount for i in range(n)], sdt, rcount,
                    rdt, root, ch)


def reduce_scatter_block(sview, rview, rcount: int, dtcode: int,
                         opcode: int, ch: int) -> int:
    c = _comm(ch)
    if _is_inter(c):
        # sendbuf holds rcount*local_size elements (redscatbkinter.c)
        sb, _ = _red_view(sview, rcount * c.size, dtcode)
        rb, wb = _red_view(rview, rcount, dtcode)
        c.reduce_scatter_block(sb, rb, op=_OPS[opcode], count=rcount)
        if wb is not None:
            wb()
        return 0
    if sview is None:
        # MPI_IN_PLACE: input is the full size*rcount array in recvbuf;
        # the result lands in its first rcount elements (MPI-3.1 §5.10)
        sb, _ = _red_view(rview, rcount * c.size, dtcode)
        rb = np.empty(rcount * (sb.size // (rcount * c.size) if rcount
                                else 1), sb.dtype)
        c.reduce_scatter_block(sb.copy(), rb, op=_OPS[opcode],
                               count=rcount)
        _scatter_out(rview, 0, rcount, dtcode, rb.view(np.uint8))
        return 0
    sb, _ = _red_view(sview, rcount * c.size, dtcode)
    rb, wb = _red_view(rview, rcount, dtcode)
    c.reduce_scatter_block(sb, rb, op=_OPS[opcode], count=rcount)
    if wb is not None:
        wb()
    return 0


# ---------------------------------------------------------------------------
# groups (PSCW sync in the OSU one-sided benchmarks)
# ---------------------------------------------------------------------------

_groups: Dict[int, object] = {}
_next_group = 1


def comm_group(ch: int) -> int:
    global _next_group
    with _lock:
        h = _next_group
        _next_group += 1
        _groups[h] = _comm(ch).group
    return h


def group_incl(gh: int, ranks) -> int:
    return _new_group_handle(_group(gh).incl(list(ranks)))


def group_free(gh: int) -> int:
    with _lock:
        _groups.pop(gh, None)
    return 0


# ---------------------------------------------------------------------------
# one-sided (the OSU one-sided benchmark surface)
# ---------------------------------------------------------------------------

def win_allocate(size: int, disp_unit: int, ch: int):
    """Returns (win_handle, base_memoryview)."""
    global _next_win
    w = _comm(ch).win_allocate(size, disp_unit=disp_unit)
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    base = w.base if w.base is not None else np.empty(0, np.uint8)
    return (h, memoryview(base))


def win_create(view, disp_unit: int, ch: int) -> int:
    """Window over the C caller's memory (zero-copy frombuffer)."""
    global _next_win
    base = np.frombuffer(view, dtype=np.uint8) if view is not None \
        else np.empty(0, np.uint8)
    w = _comm(ch).win_create(base, disp_unit=disp_unit)
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    return h


def win_create_dynamic(ch: int) -> int:
    global _next_win
    w = _comm(ch).win_create_dynamic()
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    return h


def win_attach(wh: int, view, c_addr: int) -> int:
    """Dynamic-window attach. The C caller addresses targets by raw
    pointer (MPI_Get_address); our Win.attach assigns its own address, so
    record the C address alias too."""
    arr = np.frombuffer(view, dtype=np.uint8)
    w = _wins[wh]
    addr = w.attach(arr)
    alias = getattr(w, "_c_addr_alias", None)
    if alias is None:
        alias = {}
        w._c_addr_alias = alias
    alias[c_addr] = addr
    return 0


def win_detach(wh: int, c_addr: int) -> int:
    w = _wins[wh]
    alias = getattr(w, "_c_addr_alias", {})
    addr = alias.pop(c_addr, c_addr)
    try:
        w.detach(addr)
    except Exception:
        pass
    return 0


def win_lock_all(wh: int) -> int:
    _wins[wh].lock_all()
    return 0


def win_unlock_all(wh: int) -> int:
    _wins[wh].unlock_all()
    return 0


def win_flush_local(wh: int, rank: int) -> int:
    _wins[wh].flush_local(rank)
    return 0


def win_post(wh: int, gh: int) -> int:
    _wins[wh].post(_group(gh))
    return 0


def win_start(wh: int, gh: int) -> int:
    _wins[wh].start(_group(gh))
    return 0


def win_complete(wh: int) -> int:
    _wins[wh].complete()
    return 0


def win_wait(wh: int) -> int:
    _wins[wh].wait()
    return 0


def win_free_check(wh: int) -> int:
    """Phase 1 of MPI_Win_free at the C boundary: validate the epoch
    state WITHOUT destroying anything, so attribute delete callbacks
    (run C-side between the phases) still see a live window."""
    w = _wins.get(wh)
    if w is not None and not w.freed:
        w.check_free()
    return 0


def win_free(wh: int) -> int:
    with _lock:
        w = _wins.pop(wh, None)
    if w is not None:
        w.free()
    return 0


def win_lock(wh: int, lock_type: int, rank: int) -> int:
    from .rma.win import LOCK_EXCLUSIVE, LOCK_SHARED
    _wins[wh].lock(rank, LOCK_EXCLUSIVE if lock_type == 1 else LOCK_SHARED)
    return 0


def win_unlock(wh: int, rank: int) -> int:
    _wins[wh].unlock(rank)
    return 0


def win_fence(wh: int) -> int:
    _wins[wh].fence()
    return 0


def win_flush(wh: int, rank: int) -> int:
    _wins[wh].flush(rank)
    return 0


def _dt_obj(dtcode: int):
    """Datatype object for a C type code — one resolver (_dt) for the
    whole shim so pair types always carry their CANONICAL typemaps
    (size 20 for LONG_DOUBLE_INT), never the padded numpy struct
    layout; RMA accumulate restaging depends on signature-packed
    sizes (rma/atomic_get.c)."""
    return _dt(dtcode)


def _rma_args(oview, count: int, dtcode: int):
    """(buf, kwargs) for a window op honoring derived origin types.
    A NULL origin with a derived (absolute-typemap) type is MPI_BOTTOM:
    gather the bytes from absolute addresses (rma/put_bottom.c)."""
    if dtcode >= _DERIVED_BASE:
        if not oview:
            # MPI_BOTTOM origin: gather the packed bytes from absolute
            # addresses; the op then runs on contiguous BYTE data
            return _bottom_gather(count, dtcode), {}
        if _needs_abs(oview, count, dtcode):
            return _bottom_gather(count, dtcode, _view_addr(oview)), {}
        return (np.frombuffer(oview, np.uint8),
                {"count": count, "origin_dt": _derived[dtcode]})
    # predefined types also carry their canonical typemap: re-deriving
    # from the numpy dtype would widen pair types to the PADDED struct
    # layout (LONG_DOUBLE_INT 20 -> 32 bytes) and corrupt accumulate
    # restaging at the target (rma/atomic_get.c)
    return _arr(oview, count, dtcode), \
        {"count": count, "origin_dt": _dt_obj(dtcode)}


def put(wh: int, oview, count: int, dtcode: int, target: int,
        tdisp: int, tcount: int = -1, tdtcode: int = -1) -> int:
    buf, kw = _rma_args(oview, count, dtcode)
    if tdtcode >= 0:
        kw["target_dt"] = _dt_obj(tdtcode)
        kw["target_count"] = tcount if tcount >= 0 else count
    _wins[wh].put(buf, target, tdisp, **kw)
    return 0


def get(wh: int, oview, count: int, dtcode: int, target: int,
        tdisp: int, tcount: int = -1, tdtcode: int = -1) -> int:
    buf, kw = _rma_args(oview, count, dtcode)
    if tdtcode >= 0:
        kw["target_dt"] = _dt_obj(tdtcode)
        kw["target_count"] = tcount if tcount >= 0 else count
    _wins[wh].get(buf, target, tdisp, **kw)
    if dtcode >= _DERIVED_BASE and count:
        if not oview:
            _bottom_scatter(buf, count, dtcode)  # MPI_BOTTOM destination
        elif _needs_abs(oview, count, dtcode):
            _bottom_scatter(buf, count, dtcode, _view_addr(oview))
    return 0


# ---------------------------------------------------------------------------
# send modes / combined sendrecv / probe (MPI_Ssend, MPI_Sendrecv, ...)
# ---------------------------------------------------------------------------

def ssend(view, count: int, dtcode: int, dest: int, tag: int,
          ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    _comm(ch).ssend(buf, dest, tag, **kw)
    return 0


def bsend(view, count: int, dtcode: int, dest: int, tag: int,
          ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    _comm(ch).bsend(buf, dest, tag, **kw)
    return 0


def rsend(view, count: int, dtcode: int, dest: int, tag: int,
          ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    _comm(ch).rsend(buf, dest, tag, **kw)
    return 0


def ibsend(view, count: int, dtcode: int, dest: int, tag: int,
           ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    return _new_req(_comm(ch).isend(buf, dest, tag, mode="buffered",
                                    **kw))


def irsend(view, count: int, dtcode: int, dest: int, tag: int,
           ch: int) -> int:
    buf, kw = _send_args_b(view, count, dtcode)
    return _new_req(_comm(ch).isend(buf, dest, tag, **kw))


def issend(view, count: int, dtcode: int, dest: int, tag: int,
           ch: int) -> int:
    global _next_req
    buf, kw = _send_args_b(view, count, dtcode)
    r = _comm(ch).issend(buf, dest, tag, **kw)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def _check_probe_rank(c, source: int) -> None:
    """An out-of-range probe source is MPI_ERR_RANK, reported BEFORE
    blocking (errors/pt2pt/proberank.c probes rank -80 and expects a
    code, not a hang)."""
    if source in (ANY_SOURCE, PROC_NULL):
        return
    if not 0 <= source < c.size:
        from .core.errors import MPI_ERR_RANK
        raise MPIException(MPI_ERR_RANK, f"bad probe source {source}")


def probe(source: int, tag: int, ch: int):
    """Blocking probe; returns (source, tag, count_bytes)."""
    c = _comm(ch)
    _check_probe_rank(c, source)
    st = c.probe(source, tag)
    return (st.source, st.tag, st.count)


def iprobe(source: int, tag: int, ch: int):
    """Returns (flag, source, tag, count_bytes)."""
    c = _comm(ch)
    _check_probe_rank(c, source)
    st = c.iprobe(source, tag)
    if st is None:
        return (0, -1, -1, 0)
    return (1, st.source, st.tag, st.count)


# ---------------------------------------------------------------------------
# persistent requests (MPI_Send_init / MPI_Recv_init / MPI_Start)
# ---------------------------------------------------------------------------

def _reject_bottom_persistent(view, count, dtcode):
    if ((not view or _needs_abs(view, count, dtcode))
            and dtcode >= _DERIVED_BASE and count):
        from .core.errors import MPI_ERR_BUFFER
        raise MPIException(MPI_ERR_BUFFER,
                           "MPI_BOTTOM/absolute-typemap buffers with "
                           "persistent requests are not supported "
                           "(pack at Start would be needed)")


def send_init(view, count: int, dtcode: int, dest: int, tag: int,
              ch: int, mode: str = "standard") -> int:
    _reject_bottom_persistent(view, count, dtcode)
    buf, kw = _send_args(view, count, dtcode)
    if mode != "standard":
        kw["mode"] = mode
    return _new_req(_comm(ch).send_init(buf, dest, tag, **kw))


def recv_init(view, count: int, dtcode: int, source: int, tag: int,
              ch: int) -> int:
    global _next_req
    _reject_bottom_persistent(view, count, dtcode)
    buf, kw = _send_args(view, count, dtcode)
    r = _comm(ch).recv_init(buf, source, tag, **kw)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def start(rh: int) -> int:
    r = _reqs[rh]
    r.start()
    r._c_active = True
    return 0


def testall(handles):
    """All-or-nothing MPI_Testall (MPI-3.1 §3.7.5: no request is
    modified unless all complete). Returns (flag, [(src, tag, count,
    persistent), ...])."""
    def _inactive(r):
        return getattr(r, "persistent", False) and \
            not getattr(r, "_c_active", False)

    with _lock:
        rs = [_reqs.get(h) for h in handles]
    # inactive persistent handles count as complete-with-empty-status
    if not all(r is None or _inactive(r) or r.test() for r in rs):
        return (0, [])
    out = []
    for h, r in zip(handles, rs):
        if r is None:
            out.append((-1, -2, 0, 0, 0))
            continue
        if _inactive(r):
            out.append((-1, -2, 0, 1, 0))
            continue
        persistent = bool(getattr(r, "persistent", False))
        st = r.wait()
        if persistent:
            r._c_active = False
        else:
            with _lock:
                _reqs.pop(h, None)
        canc = 1 if (st is not None and getattr(st, "cancelled", False)) \
            or getattr(r, "cancelled", False) else 0
        if st is None:
            out.append((-1, -2, 0, 1 if persistent else 0, canc))
        else:
            out.append((st.source, st.tag, st.count,
                        1 if persistent else 0, canc))
    return (1, out)


def waitany(handles):
    """Blocking MPI_Waitany over live handles: returns (pos, src, tag,
    count, persistent) with pos = index into `handles`, or pos = -1 when
    every handle is null/absent. Blocks on the progress engine's
    condition variable instead of busy-polling."""
    from .core import request as rq
    with _lock:
        pairs = [(i, _reqs.get(h)) for i, h in enumerate(handles)]
    # Waitany IGNORES null and inactive-persistent handles (§3.7.5);
    # all-ignored returns MPI_UNDEFINED
    live = [(i, r) for i, r in pairs
            if r is not None and not (getattr(r, "persistent", False) and
                                      not getattr(r, "_c_active", False))]
    if not live:
        return (-1, -1, -2, 0, 0, 0)
    idx = rq.waitany([r for _, r in live])
    i, r = live[idx]
    persistent = bool(getattr(r, "persistent", False))
    st = r.wait()
    if persistent:
        r._c_active = False
    else:
        with _lock:
            _reqs.pop(handles[i], None)
    canc = 1 if (st is not None and getattr(st, "cancelled", False)) \
        or getattr(r, "cancelled", False) else 0
    if st is None:
        return (i, -1, -2, 0, 1 if persistent else 0, canc)
    return (i, st.source, st.tag, st.count, 1 if persistent else 0, canc)


def request_free(rh: int) -> int:
    with _lock:
        r = _reqs.pop(rh, None)
    if r is not None and getattr(r, "persistent", False):
        r.free()
    return 0


# ---------------------------------------------------------------------------
# v-collectives + scan family
# ---------------------------------------------------------------------------

def allgatherv(sview, rview, scount: int, sdt: int, rcounts, displs,
               rdt: int, ch: int) -> int:
    """Byte-based: counts/displs scale by the type's packed size /
    extent, so basic AND derived datatypes take one path."""
    c = _comm(ch)
    rcounts = list(rcounts)
    displs = list(displs)
    esz = _esz(rdt)
    if sview is None:                     # MPI_IN_PLACE
        sb = np.array(_gather_in(rview, displs[c.rank], rcounts[c.rank],
                                 rdt))
    else:
        sb = _gather_in(sview, 0, scount, sdt)
    tmp = np.empty(sum(rcounts) * esz, np.uint8)
    c.allgatherv(sb, tmp, [n * esz for n in rcounts])
    off = 0
    for i in range(_peer(c)):
        n = rcounts[i]
        _scatter_out(rview, displs[i], n, rdt, tmp[off: off + n * esz])
        off += n * esz
    return 0


def alltoallv(sview, rview, scounts, sdispls, rcounts, rdispls,
              sdt: int, rdt: int, ch: int) -> int:
    c = _comm(ch)
    if sview is None:
        # MPI_IN_PLACE (§5.8): send from the recv buffer with the recv
        # layout (the C side passes NULL count/displ vectors)
        sview, scounts, sdispls, sdt = rview, rcounts, rdispls, rdt
    scounts, sdispls = list(scounts), list(sdispls)
    rcounts, rdispls = list(rcounts), list(rdispls)
    esz_s, esz_r = _esz(sdt), _esz(rdt)
    # pack per-destination segments contiguously (displs may be sparse)
    segs = [_gather_in(sview, sdispls[j], scounts[j], sdt)
            for j in range(_peer(c))]
    sb = np.concatenate(segs) if segs else np.empty(0, np.uint8)
    sdispls_b = np.concatenate(
        [[0], np.cumsum([n * esz_s for n in scounts])[:-1]]).tolist()
    rtmp = np.empty(sum(rcounts) * esz_r, np.uint8)
    rdispls_b = np.concatenate(
        [[0], np.cumsum([n * esz_r for n in rcounts])[:-1]]).tolist()
    c.alltoallv(sb, [n * esz_s for n in scounts], sdispls_b,
                rtmp, [n * esz_r for n in rcounts], rdispls_b)
    for i in range(_peer(c)):
        _scatter_out(rview, rdispls[i], rcounts[i], rdt,
                     rtmp[rdispls_b[i]: rdispls_b[i] + rcounts[i] * esz_r])
    return 0


def _gather_bytes(raw: np.ndarray, off_bytes: int, count: int,
                  dtcode: int) -> np.ndarray:
    """Packed bytes of `count` elements at BYTE offset `off_bytes`
    (alltoallw displacements are bytes, not elements)."""
    return _gather_in(raw[off_bytes:], 0, count, dtcode)


def _scatter_bytes(raw: np.ndarray, off_bytes: int, count: int,
                   dtcode: int, data_u8) -> None:
    _scatter_out(raw[off_bytes:], 0, count, dtcode, data_u8)


def alltoallw(sview, rview, scounts, sdispls, stypes,
              rcounts, rdispls, rtypes, ch: int) -> int:
    """MPI_Alltoallw: per-peer datatypes, byte displacements (§5.8).
    Pack every outgoing segment through its datatype, move the bytes
    with the comm's alltoallv, unpack per-peer on the way out."""
    c = _comm(ch)
    if sview is None:              # MPI_IN_PLACE: recv layout describes both
        sview, scounts, sdispls, stypes = rview, rcounts, rdispls, rtypes
    scounts, sdispls, stypes = list(scounts), list(sdispls), list(stypes)
    rcounts, rdispls, rtypes = list(rcounts), list(rdispls), list(rtypes)
    raw_s = np.frombuffer(sview, np.uint8)
    raw_r = np.frombuffer(rview, np.uint8)
    n = _peer(c)
    segs = [_gather_bytes(raw_s, sdispls[j], scounts[j], stypes[j])
            for j in range(n)]
    sb = (np.concatenate([np.ascontiguousarray(s) for s in segs])
          if segs else np.empty(0, np.uint8))
    sbytes = [scounts[j] * _esz(stypes[j]) for j in range(n)]
    rbytes = [rcounts[j] * _esz(rtypes[j]) for j in range(n)]
    sdispls_b = np.concatenate([[0], np.cumsum(sbytes)[:-1]]).tolist()
    rdispls_b = np.concatenate([[0], np.cumsum(rbytes)[:-1]]).tolist()
    rtmp = np.empty(sum(rbytes), np.uint8)
    c.alltoallv(sb, sbytes, sdispls_b, rtmp, rbytes, rdispls_b)
    for i in range(n):
        _scatter_bytes(raw_r, rdispls[i], rcounts[i], rtypes[i],
                       rtmp[rdispls_b[i]: rdispls_b[i] + rbytes[i]])
    return 0


def reduce_local(inview, inoutview, count: int, dtcode: int,
                 opcode: int) -> int:
    """MPI_Reduce_local (MPI-3.1 §5.9.7): inout = op(in, inout), purely
    local — no communication."""
    ib, _ = _red_view(inview, count, dtcode)
    ob, wb = _red_view(inoutview, count, dtcode)
    ob[...] = _OPS[opcode](ib, ob)
    if wb is not None:
        wb()
    return 0


def gatherv(sview, rview, scount: int, sdt: int, rcounts, displs,
            rdt: int, root: int, ch: int) -> int:
    c = _comm(ch)
    if _is_inter(c):
        from .core.status import ROOT as _ROOT, PROC_NULL as _PN
        if root == _ROOT:
            rcounts, displs = list(rcounts), list(displs)
            esz = _esz(rdt)
            bcounts = [n * esz for n in rcounts]
            tmp = np.empty(sum(bcounts), np.uint8)
            c.gatherv(b"", tmp, bcounts, root=root)
            off = 0
            for i, n in enumerate(rcounts):
                _scatter_out(rview, displs[i], n, rdt,
                             tmp[off: off + n * esz])
                off += n * esz
        elif root == _PN:
            c.gatherv(b"", None, [0], root=root)
        else:
            sb = _gather_in(sview, 0, scount, sdt)
            c.gatherv(sb, None, [int(sb.size)], root=root)
        return 0
    sb = _gather_in(sview, 0, scount, sdt) if sview is not None \
        else None
    if c.rank == root:
        rcounts, displs = list(rcounts), list(displs)
        esz = _esz(rdt) if rdt >= 0 else 1
        if sb is None:     # MPI_IN_PLACE: contribution already in place
            sb = np.array(_gather_in(rview, displs[root],
                                     rcounts[root], rdt)) \
                if rview is not None and rcounts[root] > 0 \
                else np.empty(0, np.uint8)
        tmp = np.empty(sum(rcounts) * esz, np.uint8)
        c.gatherv(sb, tmp, [n * esz for n in rcounts], root=root)
        if rview is not None:
            off = 0
            for i, n in enumerate(rcounts):
                _scatter_out(rview, displs[i], n, rdt,
                             tmp[off: off + n * esz])
                off += n * esz
    else:
        # non-root: rcounts/displs are not significant (MPI-3.1 §5.5);
        # the linear algorithm only reads counts[rank] = my byte count
        if sb is None:     # NULL sendbuf: legal for zero contributions
            sb = np.empty(0, np.uint8)
        c.gatherv(sb, None, [sb.size] * c.size, root=root)
    return 0


def scatterv(sview, rview, scounts, displs, sdt: int, rcount: int,
             rdt: int, root: int, ch: int) -> int:
    c = _comm(ch)
    esz = _esz(rdt) if rview is not None else 0
    if _is_inter(c):
        from .core.status import ROOT as _ROOT, PROC_NULL as _PN
        if root == _ROOT:
            scounts, displs = list(scounts), list(displs)
            esz_s = _esz(sdt)
            segs = [_gather_in(sview, displs[j], scounts[j], sdt)
                    for j in range(c.remote_size)]
            sb = np.concatenate(segs) if segs else np.empty(0, np.uint8)
            displs_b = np.concatenate(
                [[0],
                 np.cumsum([n * esz_s for n in scounts])[:-1]]).tolist()
            c.scatterv(sb, [n * esz_s for n in scounts], displs_b,
                       np.empty(0, np.uint8), root=root)
        elif root == _PN:
            c.scatterv(None, [0], None, np.empty(0, np.uint8), root=root)
        else:
            rtmp = np.empty(rcount * esz, np.uint8)
            c.scatterv(None, [rcount * esz], None, rtmp, root=root)
            _scatter_out(rview, 0, rcount, rdt, rtmp)
        return 0
    rtmp = np.empty(rcount * esz, np.uint8) if rview is not None else None
    if c.rank == root:
        scounts = list(scounts)
        displs = list(displs)
        esz_s = _esz(sdt) if sdt >= 0 else 1
        segs = ([_gather_in(sview, displs[j], scounts[j], sdt)
                 for j in range(c.size)] if sview is not None else
                [np.empty(0, np.uint8)] * c.size)
        sb = np.concatenate(segs) if segs else np.empty(0, np.uint8)
        displs_b = np.concatenate(
            [[0], np.cumsum([n * esz_s for n in scounts])[:-1]]).tolist()
        c.scatterv(sb, [n * esz_s for n in scounts], displs_b,
                   rtmp if rtmp is not None else IN_PLACE, root=root)
    else:
        # non-root: sendcounts/displs are not significant (MPI-3.1 §5.6);
        # counts=None makes the algorithm size the receive from recvbuf
        c.scatterv(None, None, None, rtmp, root=root)
    if rview is not None:
        _scatter_out(rview, 0, rcount, rdt, rtmp)
    return 0


def reduce_scatter(sview, rview, rcounts, dtcode: int, opcode: int,
                   ch: int) -> int:
    """MPI_Reduce_scatter with per-rank counts: allreduce + slice (the
    irregular-counts generalization of reduce_scatter_block)."""
    c = _comm(ch)
    rcounts = list(rcounts)
    total = sum(rcounts)
    if _is_inter(c):
        # intercomm: sendbuf holds the REMOTE side's total; my slice is
        # rcounts[local rank] of the remote group's reduction
        send_elems = 0 if sview is None else \
            len(np.frombuffer(sview, np.uint8)) // _esz(dtcode)
        sb, _ = _red_view(sview, send_elems, dtcode)
        rb, wb = _red_view(rview, rcounts[c.rank], dtcode)
        c.reduce_scatter(sb, rb, rcounts, op=_OPS[opcode])
        if wb is not None:
            wb()
        return 0
    if sview is None:
        # MPI_IN_PLACE: input is the full `total` array in recvbuf
        sview = bytes(np.frombuffer(rview, np.uint8))
    sb, _ = _red_view(sview, total, dtcode)
    tmp = np.empty_like(sb)
    c.allreduce(sb, tmp, op=_OPS[opcode])
    epb = sb.size // total if total else 1   # basic elems per MPI elem
    off = sum(rcounts[: c.rank]) * epb
    mine = tmp[off: off + rcounts[c.rank] * epb]
    _scatter_out(rview, 0, rcounts[c.rank], dtcode, mine.view(np.uint8))
    return 0


def scan(sview, rview, count: int, dtcode: int, opcode: int,
         ch: int) -> int:
    c = _comm(ch)
    rb, wb = _red_view(rview, count, dtcode)
    sb = rb.copy() if sview is None else _red_view(sview, count, dtcode)[0]
    c.scan(sb, rb, op=_OPS[opcode])
    if wb is not None:
        wb()
    return 0


def exscan(sview, rview, count: int, dtcode: int, opcode: int,
           ch: int) -> int:
    c = _comm(ch)
    rb, wb = _red_view(rview, count, dtcode)
    sb = rb.copy() if sview is None else _red_view(sview, count, dtcode)[0]
    c.exscan(sb, rb, op=_OPS[opcode])
    if wb is not None:
        wb()
    return 0


# ---------------------------------------------------------------------------
# comm/group extras
# ---------------------------------------------------------------------------

_COMPARE = {"ident": 0, "congruent": 1, "similar": 2, "unequal": 3}


def comm_compare(ch1: int, ch2: int) -> int:
    return _COMPARE[_comm(ch1).compare(_comm(ch2))]


def comm_create(ch: int, gh: int) -> int:
    global _next_comm
    c = _comm(ch).create(_group(gh))
    if c is None:
        return -1
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


def group_size(gh: int) -> int:
    return _group(gh).size


def group_rank(gh: int) -> int:
    from .core.status import UNDEFINED
    g = _group(gh)
    r = g.rank_of_world(uni.current_universe().world_rank)
    return r if r != UNDEFINED else -32766


def group_excl(gh: int, ranks) -> int:
    return _new_group_handle(_group(gh).excl(list(ranks)))


def group_translate_ranks(gh1: int, ranks, gh2: int):
    from .core.status import UNDEFINED
    out = _group(gh1).translate_ranks(list(ranks), _group(gh2))
    return [(-32766 if r in (None, UNDEFINED) else r) for r in out]


# ---------------------------------------------------------------------------
# derived datatypes (MPI_Type_* constructors)
# ---------------------------------------------------------------------------

def _new_derived(d: dt.Datatype) -> int:
    global _next_derived
    with _lock:
        h = _next_derived
        _next_derived += 1
        _derived[h] = d
    return h


def type_contiguous(count: int, oldcode: int) -> int:
    return _new_derived(dt.create_contiguous(count, _dt(oldcode)))


def type_vector(count: int, blocklength: int, stride: int,
                oldcode: int) -> int:
    return _new_derived(dt.create_vector(count, blocklength, stride,
                                         _dt(oldcode)))


def type_create_hvector(count: int, blocklength: int, stride_bytes: int,
                        oldcode: int) -> int:
    return _new_derived(dt.create_hvector(count, blocklength, stride_bytes,
                                          _dt(oldcode)))


def type_indexed(blocklengths, displacements, oldcode: int) -> int:
    return _new_derived(dt.create_indexed(list(blocklengths),
                                          list(displacements),
                                          _dt(oldcode)))


def type_create_struct(blocklengths, disp_bytes, oldcodes) -> int:
    # MPI_LB/MPI_UB markers (MPI-1 §3.12.3): they carry no data but pin
    # the bounds — lb = min displacement of any LB entry, ub = max of
    # any UB entry; the rest of the struct is built from real members.
    blocklengths, disp_bytes = list(blocklengths), list(disp_bytes)
    oldcodes = list(oldcodes)
    lb_pins = [d for d, c in zip(disp_bytes, oldcodes) if c == _MARKER_LB]
    ub_pins = [d for d, c in zip(disp_bytes, oldcodes) if c == _MARKER_UB]
    if lb_pins or ub_pins:
        real = [(bl, d, c) for bl, d, c in
                zip(blocklengths, disp_bytes, oldcodes)
                if c not in (_MARKER_LB, _MARKER_UB)]
        blocklengths = [r[0] for r in real]
        disp_bytes = [r[1] for r in real]
        oldcodes = [r[2] for r in real]
    types = [_dt(c) for c in oldcodes]
    base = dt.create_struct(blocklengths, disp_bytes, types)
    if lb_pins or ub_pins:
        lb = min(lb_pins) if lb_pins else base.lb
        ub = max(ub_pins) if ub_pins else base.ub
        base = dt.create_resized(base, lb, ub - lb)
    return _new_derived(base)


def type_create_resized(oldcode: int, lb: int, extent: int) -> int:
    return _new_derived(dt.create_resized(_dt(oldcode), lb, extent))


def type_commit(code: int) -> int:
    if code >= _DERIVED_BASE:
        _derived[code].commit()
    return 0


def type_free(code: int) -> int:
    """MPI_Type_free: the user handle dies, but operations posted with
    the type may still be in flight (MPI-3.1 §4.1.9 reference
    semantics) — keep the definition; only attributes are dropped.
    (indexed-misc.c frees types whose sends are still pending.)"""
    with _lock:
        d = _derived.get(code)
        if d is not None:
            d._freed = True
    return 0


def type_size(code: int) -> int:
    if code in (_MARKER_LB, _MARKER_UB):
        return 0
    return _dt(code).size


def type_span(code: int, count: int) -> int:
    """Bytes a buffer must provide for `count` extent-strided elements
    starting at byte 0 — true-extent aware: a derived type's last
    element may trail past its extent (e.g. a column vector type)."""
    if count <= 0 or code in (_MARKER_LB, _MARKER_UB):
        return 0
    if code < _DERIVED_BASE:
        return count * _DTYPES[code].itemsize
    d = _derived[code]
    if d.needs_abs(count):
        # abs-path type: data reaches before the buffer pointer; the
        # C-boundary view is only consulted for its base address
        # (_view_addr), so keep it non-empty and cheap
        return 1
    tlb, text = type_true_extent(code)
    return (count - 1) * d.extent + max(tlb + text, d.extent, 0)


_COMBINERS = {"named": 0, "contiguous": 1, "vector": 2, "hvector": 3,
              "indexed": 4, "hindexed": 5, "struct": 6, "subarray": 7,
              "resized": 8, "indexed_block": 9, "dup": 10,
              "hindexed_block": 11, "darray": 12}


def type_get_envelope(code: int):
    """Returns (combiner_code, num_ints, num_aints, num_types) — the
    MPI_Type_get_envelope counts."""
    env = _dt(code).get_envelope()
    return (_COMBINERS.get(env[0], 0), len(env[1]), len(env[2]),
            len(env[3]))


def type_extent(code: int):
    """Returns (lb, extent) in bytes."""
    if code in (_MARKER_LB, _MARKER_UB):
        return (0, 0)
    d = _dt(code)
    return (d.lb, d.extent)


# ---------------------------------------------------------------------------
# RMA atomics (MPI_Accumulate / MPI_Fetch_and_op / MPI_Compare_and_swap)
# ---------------------------------------------------------------------------

def accumulate(wh: int, oview, count: int, dtcode: int, target: int,
               tdisp: int, opcode: int, tcount: int = -1,
               tdtcode: int = -1) -> int:
    buf, kw = _rma_args(oview, count, dtcode)
    if tdtcode >= 0:
        kw["target_dt"] = _dt_obj(tdtcode)
        kw["target_count"] = tcount if tcount >= 0 else count
    _wins[wh].accumulate(buf, target, tdisp, op=_OPS[opcode], **kw)
    return 0


def get_accumulate(wh: int, oview, rview, ocount: int, odtcode: int,
                   rcount: int, rdtcode: int, target: int, tdisp: int,
                   tcount: int, tdtcode: int, opcode: int) -> int:
    """Full three-geometry MPI_Get_accumulate: origin packs with
    (ocount, odt), the fetch scatters into (rcount, rdt), the target
    applies with (tcount, tdt). Absolute-typemap (negative-lb) and
    MPI_BOTTOM origin/result buffers route through the ctypes path,
    same as send/recv/put/get: gather to packed bytes before the call,
    scatter after it completes (the wrapper is blocking)."""
    rd = _dt_obj(rdtcode)
    td = _dt_obj(tdtcode)
    if odtcode < 0:
        # MPI_NO_OP: origin triple is ignored per MPI-3.1 §11.3.4 and
        # arrives as MPI_DATATYPE_NULL (rma/get_accumulate.c's GACC/
        # NO_OP rounds)
        obuf, od, ocount = None, None, 0
    elif oview and _needs_abs(oview, ocount, odtcode):
        obuf = _bottom_gather(ocount, odtcode, _view_addr(oview))
        od, ocount = dt.create_contiguous(len(obuf), dt.BYTE), 1
    elif not oview and odtcode >= _DERIVED_BASE and ocount:
        obuf = _bottom_gather(ocount, odtcode)       # MPI_BOTTOM origin
        od, ocount = dt.create_contiguous(len(obuf), dt.BYTE), 1
    else:
        od = _dt_obj(odtcode)
        obuf = np.frombuffer(oview, np.uint8) if oview else None
    abs_r = (_needs_abs(rview, rcount, rdtcode)
             or (not rview and rdtcode >= _DERIVED_BASE and rcount))
    if abs_r:
        tmp = _bottom_tmp(rcount, rdtcode)
        rbuf, rd_eff, rcnt_eff = tmp, \
            dt.create_contiguous(len(tmp), dt.BYTE), 1
    else:
        rbuf, rd_eff, rcnt_eff = np.frombuffer(rview, np.uint8), rd, \
            rcount
    _wins[wh].get_accumulate(obuf, rbuf, target, tdisp, op=_OPS[opcode],
                             count=rcnt_eff, origin_dt=rd_eff,
                             target_dt=td, odt=od, ocount=ocount,
                             tcount=tcount)
    if abs_r:
        _bottom_scatter(tmp, rcount, rdtcode,
                        _view_addr(rview) if rview else 0)
    return 0


def fetch_and_op(wh: int, oview, rview, dtcode: int, target: int,
                 tdisp: int, opcode: int) -> int:
    # NULL origin is legal for MPI_NO_OP (empty-bytes at the boundary).
    # The MPI handle's canonical typemap must ride along: resolving
    # from the numpy struct dtype instead would widen pair types to
    # their PADDED layout (LONG_DOUBLE_INT 20 -> 32 bytes) and corrupt
    # the RMW restaging (rma/atomic_get.c Test #1/#2).
    obuf = _arr(oview, 1, dtcode) if oview else \
        np.zeros(1, _DTYPES[dtcode])
    rbuf = _arr(rview, 1, dtcode)
    _wins[wh].fetch_and_op(obuf, rbuf, target, tdisp, op=_OPS[opcode],
                           datatype=_dt_obj(dtcode))
    return 0


def compare_and_swap(wh: int, oview, cview, rview, dtcode: int,
                     target: int, tdisp: int) -> int:
    obuf = _arr(oview, 1, dtcode)
    cbuf = _arr(cview, 1, dtcode)
    rbuf = _arr(rview, 1, dtcode)
    _wins[wh].compare_and_swap(obuf, cbuf, rbuf, target, tdisp,
                               datatype=_dt_obj(dtcode))
    return 0


def win_flush_all(wh: int) -> int:
    _wins[wh].flush_all()
    return 0


def win_flush_local_all(wh: int) -> int:
    _wins[wh].flush_local_all()
    return 0


def win_sync(wh: int) -> int:
    _wins[wh].sync()
    return 0


# ---------------------------------------------------------------------------
# info objects (MPI_Info_*)
# ---------------------------------------------------------------------------

_infos: Dict[int, object] = {}
_next_info = 1


def _info(ih: int):
    if ih == -2:               # MPI_INFO_ENV (MPI-3.1 §9.1.1)
        import sys
        from .core.info import Info
        u = uni.current_universe()
        return Info({
            "command": sys.argv[0] if sys.argv else "",
            "argv": " ".join(sys.argv[1:]),
            "maxprocs": str(u.world_size),
            "soft": str(u.world_size),
            "host": __import__("socket").gethostname(),
            "arch": __import__("platform").machine(),
            "wdir": __import__("os").getcwd(),
            "thread_level": "MPI_THREAD_SERIALIZED",
        })
    return _infos[ih]


def info_create() -> int:
    global _next_info
    from .core.info import Info
    with _lock:
        h = _next_info
        _next_info += 1
        _infos[h] = Info()
    return h


def info_free(ih: int) -> int:
    _infos.pop(ih, None)
    return 0


def info_set(ih: int, key: str, value: str) -> int:
    _info(ih).set(key, value)
    return 0


def info_get(ih: int, key: str):
    """None when unset (C side turns that into flag=0)."""
    return _info(ih).get(key)


def info_delete(ih: int, key: str) -> int:
    _infos[ih].delete(key)
    return 0


def info_dup(ih: int) -> int:
    global _next_info
    with _lock:
        h = _next_info
        _next_info += 1
        _infos[h] = _info(ih).dup()
    return h


def info_nkeys(ih: int) -> int:
    return _info(ih).nkeys


def info_nthkey(ih: int, n: int) -> str:
    return _info(ih).nthkey(n)


# ---------------------------------------------------------------------------
# communicator extras: names, create_group, split_type, intercomms
# ---------------------------------------------------------------------------

def _new_comm_handle(c) -> int:
    global _next_comm
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


_named_comms: set = set()


def comm_set_name(ch: int, name: str) -> int:
    _comm(ch).set_name(name)
    _named_comms.add(ch)
    return 0


def comm_get_name(ch: int) -> str:
    """Predefined comms have their standard names; user comms are
    unnamed ("" per MPI-3.1 §6.8) until MPI_Comm_set_name — the
    internal synthesized names (comm.name) are not exposed here."""
    if ch in _named_comms:
        return _comm(ch).get_name()
    if ch == 0:
        return "MPI_COMM_WORLD"
    if ch == 1:
        return "MPI_COMM_SELF"
    return ""


def comm_create_group(ch: int, gh: int, tag: int) -> int:
    c = _comm(ch).create_group(_group(gh), tag)
    if c is None:
        return -1
    return _new_comm_handle(c)


def comm_split_type(ch: int, split_type: int, key: int) -> int:
    if split_type == -32766:      # MPI_UNDEFINED: still collective —
        c = _comm(ch).split(None, key)   # participate with no color
        assert c is None
        return -1
    if split_type != 0:           # only MPI_COMM_TYPE_SHARED is defined
        from .core.errors import MPI_ERR_ARG
        raise MPIException(MPI_ERR_ARG,
                           f"unknown split_type {split_type}")
    c = _comm(ch).split_type_shared(key)
    return _new_comm_handle(c)


def comm_test_inter(ch: int) -> int:
    from .core.intercomm import Intercomm
    return 1 if isinstance(_comm(ch), Intercomm) else 0


def comm_remote_size(ch: int) -> int:
    c = _comm(ch)
    if not hasattr(c, "remote_size"):
        from .core.errors import MPI_ERR_COMM
        raise MPIException(MPI_ERR_COMM,
                           "remote_size on an intracommunicator")
    return c.remote_size


def intercomm_create(local_ch: int, local_leader: int, peer_ch: int,
                     remote_leader: int, tag: int) -> int:
    from .core.intercomm import intercomm_create as icreate
    # peer_comm is significant only at the leaders (MPI-3.1 §6.6.2):
    # non-leaders legally pass MPI_COMM_NULL (-1)
    peer = _comm(peer_ch) if peer_ch >= 0 else None
    ic = icreate(_comm(local_ch), local_leader, peer, remote_leader, tag)
    return _new_comm_handle(ic)


def intercomm_merge(ch: int, high: int) -> int:
    c = _comm(ch).merge(bool(high))
    return _new_comm_handle(c)


# ---------------------------------------------------------------------------
# group set operations
# ---------------------------------------------------------------------------

def _new_group_handle(g) -> int:
    if g.size == 0:
        return -2              # MPI_GROUP_EMPTY is predefined
    global _next_group
    with _lock:
        h = _next_group
        _next_group += 1
        _groups[h] = g
    return h


def group_range_incl(gh: int, ranges) -> int:
    return _new_group_handle(
        _group(gh).range_incl([tuple(r) for r in ranges]))


def group_range_excl(gh: int, ranges) -> int:
    return _new_group_handle(
        _group(gh).range_excl([tuple(r) for r in ranges]))


def group_union(gh1: int, gh2: int) -> int:
    return _new_group_handle(_group(gh1).union(_group(gh2)))


def group_intersection(gh1: int, gh2: int) -> int:
    return _new_group_handle(_group(gh1).intersection(_group(gh2)))


def group_difference(gh1: int, gh2: int) -> int:
    return _new_group_handle(_group(gh1).difference(_group(gh2)))


_COMPARE = {"ident": 0, "congruent": 1, "similar": 2, "unequal": 3}


def group_compare(gh1: int, gh2: int) -> int:
    return _COMPARE[_group(gh1).compare(_group(gh2))]


def comm_remote_group(ch: int) -> int:
    return _new_group_handle(_comm(ch).remote_group)


# ---------------------------------------------------------------------------
# datatype extras
# ---------------------------------------------------------------------------

def type_dup(code: int) -> int:
    d = _dt(code)
    return _new_derived(d.dup() if hasattr(d, "dup") else d)


def type_indexed_block(blocklength: int, displacements, oldcode: int) -> int:
    return _new_derived(dt.create_indexed_block(
        blocklength, list(displacements), _dt(oldcode)))


def type_hindexed(blocklengths, disp_bytes, oldcode: int) -> int:
    d = dt.create_hindexed(list(blocklengths), list(disp_bytes),
                           _dt(oldcode))
    return _new_derived(d)


def type_create_subarray(sizes, subsizes, starts, order: int,
                         oldcode: int) -> int:
    return _new_derived(dt.create_subarray(
        list(sizes), list(subsizes), list(starts), _dt(oldcode),
        order="F" if order == 57 else "C"))   # MPI_ORDER_FORTRAN = 57


def type_create_darray(size: int, rank: int, gsizes, distribs, dargs,
                       psizes, order: int, oldcode: int) -> int:
    return _new_derived(dt.create_darray(
        size, rank, list(gsizes), list(distribs), list(dargs),
        list(psizes), _dt(oldcode),
        order="F" if order == 57 else "C"))


def type_hindexed_block(blocklength: int, disp_bytes, oldcode: int) -> int:
    disp_bytes = list(disp_bytes)
    code = type_hindexed([blocklength] * len(disp_bytes),
                         disp_bytes, oldcode)
    # the envelope must reflect HINDEXED_BLOCK with ints
    # [count, blocklength] (hindexed_block_contents.c checks ni == 2)
    d = _derived[code]
    d._envelope = ("hindexed_block", [len(disp_bytes), blocklength],
                   disp_bytes, [_dt(oldcode)])
    return code


_type_names: Dict[int, str] = {}


def type_set_name(code: int, name: str) -> int:
    _type_names[code] = name
    return 0


def type_get_name(code: int) -> str:
    got = _type_names.get(code)
    if got is not None:
        return got
    if code < _DERIVED_BASE:
        return _BUILTIN_TYPE_NAMES.get(code, "")
    return ""   # derived types are unnamed until set (MPI-3.1 §8.4)


_BUILTIN_TYPE_NAMES = {
    0: "MPI_BYTE", 1: "MPI_CHAR", 2: "MPI_INT", 3: "MPI_FLOAT",
    4: "MPI_DOUBLE", 5: "MPI_LONG_LONG", 6: "MPI_UNSIGNED_LONG",
    7: "MPI_SHORT", 8: "MPI_UNSIGNED_CHAR", 9: "MPI_AINT",
    10: "MPI_UNSIGNED", 11: "MPI_UNSIGNED_SHORT", 12: "MPI_LONG_DOUBLE",
    13: "MPI_C_BOOL", 14: "MPI_FLOAT_INT", 15: "MPI_DOUBLE_INT",
    16: "MPI_LONG_INT", 17: "MPI_2INT", 18: "MPI_SHORT_INT",
    19: "MPI_LONG_DOUBLE_INT",
    20: "MPI_LONG", 21: "MPI_SIGNED_CHAR", 22: "MPI_OFFSET",
    23: "MPI_COUNT", 24: "MPI_INT8_T", 25: "MPI_INT16_T",
    26: "MPI_INT32_T", 27: "MPI_INT64_T", 28: "MPI_UINT8_T",
    29: "MPI_UINT16_T", 30: "MPI_UINT32_T", 31: "MPI_UINT64_T",
    32: "MPI_WCHAR", 33: "MPI_C_FLOAT_COMPLEX",
    34: "MPI_C_DOUBLE_COMPLEX", 35: "MPI_C_LONG_DOUBLE_COMPLEX",
    36: "MPI_CXX_BOOL", 37: "MPI_CXX_FLOAT_COMPLEX",
    38: "MPI_CXX_DOUBLE_COMPLEX", 39: "MPI_CXX_LONG_DOUBLE_COMPLEX",
    40: "MPI_PACKED", 41: "MPI_LB", 42: "MPI_UB",
}


def type_true_extent(code: int):
    """(true_lb, true_extent): tightest byte span actually touched."""
    if code < _DERIVED_BASE:
        sz = _DTYPES[code].itemsize
        return (0, sz)
    d = _dt(code)
    if len(d.spans) == 0:
        return (0, 0)
    sp = np.asarray(d.spans, dtype=np.int64).reshape(-1, 2)
    lo = int(sp[:, 0].min())
    hi = int((sp[:, 0] + sp[:, 1]).max())
    return (lo, hi - lo)


def pack(inview, incount: int, dtcode: int, outview, position: int) -> int:
    """Returns the new position (bytes)."""
    d = _dt(dtcode)
    raw_out = np.frombuffer(outview, np.uint8)
    if _needs_abs(inview, incount, dtcode):
        data = _bottom_gather(incount, dtcode, _view_addr(inview))
    elif not inview and dtcode >= _DERIVED_BASE:
        data = _bottom_gather(incount, dtcode)      # MPI_BOTTOM input
    else:
        raw_in = np.frombuffer(inview, np.uint8)
        data = (np.asarray(d.pack(raw_in, incount)).view(np.uint8)
                .reshape(-1)
                if dtcode >= _DERIVED_BASE else
                raw_in[:incount * _DTYPES[dtcode].itemsize])
    raw_out[position:position + data.size] = data
    return position + data.size


def unpack(inview, position: int, outview, outcount: int,
           dtcode: int) -> int:
    d = _dt(dtcode)
    raw_in = np.frombuffer(inview, np.uint8)
    nbytes = _esz(dtcode) * outcount
    if _needs_abs(outview, outcount, dtcode):
        _bottom_scatter(
            np.ascontiguousarray(raw_in[position:position + nbytes]),
            outcount, dtcode, _view_addr(outview))
    elif not outview and dtcode >= _DERIVED_BASE:
        _bottom_scatter(
            np.ascontiguousarray(raw_in[position:position + nbytes]),
            outcount, dtcode)                       # MPI_BOTTOM output
    else:
        raw_out = np.frombuffer(outview, np.uint8)
        if dtcode >= _DERIVED_BASE:
            d.unpack(raw_in[position:position + nbytes], raw_out,
                     outcount)
        else:
            raw_out[:nbytes] = raw_in[position:position + nbytes]
    return position + nbytes


def pack_size(incount: int, dtcode: int) -> int:
    return incount * _esz(dtcode)


# ---------------------------------------------------------------------------
# nonblocking collectives (sched-based; request handles interop with
# wait/test/waitall like pt2pt requests)
# ---------------------------------------------------------------------------

def _new_req(r) -> int:
    global _next_req
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


class _CommWorker:
    """Per-communicator FIFO worker: nonblocking operations an INTERCOMM
    cannot yet express as an NBC-engine schedule (the v-collectives,
    comm_idup) execute serially in call order on one thread. Queue order
    equals call order — identical on every rank by MPI's
    collective-ordering rule. Collective TAGS are reserved on the
    calling thread (see ``_queued``): since the six core icolls now run
    on the DAG scheduler and allocate their tags at call time, a
    worker-side allocation at RUN time could interleave differently
    across ranks and mispair the bridge traffic."""

    def __init__(self):
        import queue
        self.q: "queue.Queue" = queue.Queue()
        self.t = threading.Thread(target=self._run, daemon=True)
        self.t.start()

    def _run(self):
        while True:
            item = self.q.get()
            if item is None:
                return
            fn, done, wake = item
            try:
                fn()
            except BaseException as e:   # noqa: BLE001 — raised at wait
                done[1] = e
            done[0].set()
            if wake is not None:
                wake()      # doorbell: the waiter sits in progress_wait

    def submit(self, fn, wake=None):
        done = [threading.Event(), None]
        self.q.put((fn, done, wake))
        return done


class _QueuedRequest:
    persistent = False

    def __init__(self, done, engine=None):
        self._done = done
        self._engine = engine

    def wait(self):
        if self._engine is not None and not self._done[0].is_set():
            # wait INSIDE the progress engine: the caller keeps pumping
            # packets for the worker (and everyone else) instead of
            # parking on a bare Event while the engine idles
            self._engine.progress_wait(self._done[0].is_set)
        else:
            self._done[0].wait()
        if self._done[1] is not None:
            raise self._done[1]
        return None

    def test(self) -> bool:
        if self._engine is not None and not self._done[0].is_set():
            self._engine.progress_poke()
        return self._done[0].is_set()


_workers: Dict[int, _CommWorker] = {}


def _queued(ch: int, fn) -> int:
    c = _comm(ch)
    # reserve the operation's collective tag NOW, in call order on the
    # caller's thread; the worker hands it back to the op's single
    # next_coll_tag() call so tag pairing across ranks is independent
    # of worker scheduling (DAG-scheduled icolls allocate at call time)
    tag = c.next_coll_tag()

    def run():
        c.push_reserved_coll_tag(tag)
        try:
            fn()
        finally:
            c.drop_reserved_coll_tag(tag)

    with _lock:
        w = _workers.get(ch)
        if w is None:
            w = _workers[ch] = _CommWorker()
    eng = c.u.engine
    return _new_req(_QueuedRequest(w.submit(run, wake=eng.wakeup), eng))


def _is_inter(c) -> bool:
    from .core.intercomm import Intercomm
    return isinstance(c, Intercomm)


class _ThreadRequest:
    """Request backed by a worker thread (nonblocking comm dup — the
    reference's MPIR_Comm_idup runs the context-id protocol from the
    progress engine; here the host progress engine is thread-driven, so
    a thread IS the idiomatic nonblocking engine)."""

    persistent = False

    def __init__(self, fn):
        self._result = None
        self._exc = None

        def run():
            try:
                self._result = fn()
            except BaseException as e:   # noqa: BLE001 — joined in wait
                self._exc = e

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def wait(self):
        self._t.join()
        if self._exc is not None:
            raise self._exc
        return None        # empty status

    def test(self) -> bool:
        return not self._t.is_alive()


def comm_idup(view, ch: int) -> int:
    """Nonblocking MPI_Comm_idup: the dup's context-agreement collective
    runs on a worker thread; completion writes the new handle into the
    caller's MPI_Comm storage (``view``). Must not block — the MPICH
    comm_idup tests overlap it with pt2pt traffic before MPI_Wait.

    Concurrency contract: the coll tag AND a fresh ctx base are reserved
    HERE (the caller's thread, where idup calls are issued in the same
    order on every rank), so any number of in-flight idups pair their
    internal messages by tag and agree on distinct context ids."""
    out = np.frombuffer(view, dtype=np.int32)
    parent = _comm(ch)
    if _is_inter(parent):
        # rides the per-intercomm worker queue: serialized in call order
        # with any queued icolls, so internal tag/ctx agreement pairs
        # across ranks without a reservation protocol
        def run():
            out[0] = _new_comm_handle(parent.dup())
        return _queued(ch, run)
    tag = parent.next_coll_tag()
    u = parent.u
    with _lock:
        base = u._next_ctx
        u._next_ctx = base + 2     # distinct base per in-flight idup

    def run():
        from .coll import algorithms as alg
        from .core.comm import Comm
        from .utils.config import get_config
        # the live-comm count rides the ctx agreement so exhaustion is
        # a symmetric verdict (errors/comm/too_many_icomms.c expects
        # idup to fail once the 2048-comm budget is spent)
        mine = np.array([base, len(u.comms_by_ctx)], dtype=np.int64)
        agreed = alg.allreduce_recursive_doubling(parent, mine,
                                                  opmod.MAX, tag)
        ctx = int(agreed[0])
        with _lock:
            u._next_ctx = max(u._next_ctx, ctx + 2)
        if int(agreed[1]) >= int(get_config()["MAX_CONTEXTS"]):
            from .core.errors import MPI_ERR_OTHER
            raise MPIException(MPI_ERR_OTHER,
                               "out of context ids (idup)")
        new = Comm(u, parent.group, ctx, parent.name + "_dup", parent)
        parent.attrs.copy_all(parent, new.attrs)
        new.errhandler = parent.errhandler
        new.topo = parent.topo
        out[0] = _new_comm_handle(new)

    return _new_req(_ThreadRequest(run))


def ibarrier(ch: int) -> int:
    # intercomms included: nb.ibarrier dispatches to the leader-bridge
    # DAG schedule (coll/nbc/inter.py) — true nonblocking progression,
    # no worker thread
    return _new_req(_comm(ch).ibarrier())


def ibcast(view, count: int, dtcode: int, root: int, ch: int) -> int:
    c = _comm(ch)
    buf = _arr(view, count, dtcode) if view is not None else None
    return _new_req(c.ibcast(buf, root, count=count))


def iallreduce(sview, rview, count: int, dtcode: int, opcode: int,
               ch: int) -> int:
    c = _comm(ch)
    recv = _arr(rview, count, dtcode)
    send = recv.copy() if sview is None else _arr(sview, count, dtcode)
    return _new_req(c.iallreduce(send, recv, op=_OPS[opcode]))


def ireduce(sview, rview, count: int, dtcode: int, opcode: int, root: int,
            ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        recv0 = _arr(rview, count, dtcode) if rview else None
        send0 = _arr(sview, count, dtcode) if sview is not None else None
        return _new_req(nb.ireduce(c, send0, recv0, count, _dt(dtcode),
                                   _OPS[opcode], root))
    if not rview:
        recv = np.empty(count, dtype=_DTYPES[dtcode])
    else:
        recv = _arr(rview, count, dtcode)
    send = recv.copy() if sview is None else _arr(sview, count, dtcode)
    return _new_req(nb.ireduce(c, send, recv, count, _dt(dtcode),
                               _OPS[opcode], root))


def iallgather(sview, rview, count: int, dtcode: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        recv = _arr(rview, count * c.remote_size, dtcode)
        send = _arr(sview, count, dtcode)
        return _new_req(nb.iallgather(c, send, recv, count, _dt(dtcode)))
    recv = _arr(rview, count * c.size, dtcode)
    send = recv[c.rank * count:(c.rank + 1) * count].copy() \
        if sview is None else _arr(sview, count, dtcode)
    return _new_req(nb.iallgather(c, send, recv, count, _dt(dtcode)))


def ialltoall(sview, rview, count: int, dtcode: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        recv = _arr(rview, count * c.remote_size, dtcode)
        send = _arr(sview, count * c.remote_size, dtcode)
        return _new_req(nb.ialltoall(c, send, recv, count, _dt(dtcode)))
    recv = _arr(rview, count * c.size, dtcode)
    send = recv.copy() if sview is None \
        else _arr(sview, count * c.size, dtcode)
    return _new_req(nb.ialltoall(c, send, recv, count, _dt(dtcode)))


def iscan(sview, rview, count: int, dtcode: int, opcode: int,
          ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        from .core.errors import MPI_ERR_COMM
        raise MPIException(MPI_ERR_COMM,
                           "scan is undefined on intercommunicators")
    recv = _arr(rview, count, dtcode)
    send = recv.copy() if sview is None else _arr(sview, count, dtcode)
    return _new_req(nb.iscan(c, send, recv, count, _dt(dtcode),
                             _OPS[opcode]))


def iexscan(sview, rview, count: int, dtcode: int, opcode: int,
            ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        from .core.errors import MPI_ERR_COMM
        raise MPIException(MPI_ERR_COMM,
                           "exscan is undefined on intercommunicators")
    recv = _arr(rview, count, dtcode)
    send = recv.copy() if sview is None else _arr(sview, count, dtcode)
    return _new_req(nb.iexscan(c, send, recv, count, _dt(dtcode),
                               _OPS[opcode]))


def igather(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
            root: int, ch: int) -> int:
    """recvcount/recvtype are significant only at the root (MPI-3.1
    §5.5); non-roots contribute sendcount elements of sendtype."""
    c = _comm(ch)
    if _is_inter(c):
        # same count/type/root logic as the blocking path, run on the
        # per-intercomm worker (issue-order serialized)
        return _queued(ch, lambda: gather(sview, rview, scount, sdt,
                                          rcount, rdt, root, ch))
    # byte-level v-path unconditionally — mirrors blocking gather():
    # per-rank branching on root-only datatypes diverges algorithms,
    # and derived types (nonblocking2.c's dup'd recvtype) need the
    # pack/unpack route anyway
    n = c.size
    return igatherv(sview, rview, scount, sdt, [rcount] * n,
                    [i * rcount for i in range(n)], rdt, root, ch)


def iscatter(sview, rview, scount: int, sdt: int, rcount: int,
             rdt: int, root: int, ch: int) -> int:
    """sendcount/sendtype are significant only at the root."""
    c = _comm(ch)
    if _is_inter(c):
        return _queued(ch, lambda: scatter(sview, rview, scount, sdt,
                                           rcount, rdt, root, ch))
    if rview is None:
        # IN_PLACE root: recvcount/recvtype ignored (§5.6)
        rcount, rdt = 0, sdt
    n = c.size
    return iscatterv(sview, rview, [scount] * n,
                     [i * scount for i in range(n)], sdt, rcount, rdt,
                     root, ch)


# ---------------------------------------------------------------------------
# cancel / request status / generalized requests
# ---------------------------------------------------------------------------

def cancel(rh: int) -> int:
    with _lock:
        r = _reqs.get(rh)
    if r is not None and hasattr(r, "cancel"):
        r.cancel()
    return 0


def request_get_status(rh: int):
    """(flag, src, tag, count, cancelled) WITHOUT freeing the request
    (MPI_Request_get_status semantics)."""
    with _lock:
        r = _reqs.get(rh)
    if r is None:
        return (1, -1, -2, 0, 0)
    done = bool(getattr(r, "complete_flag", False))
    if not done and hasattr(r, "test"):
        # poke progress nondestructively where the request supports it
        try:
            done = bool(r.test())
        except TypeError:
            done = False
    st = getattr(r, "status", None)
    if not done:
        return (0, -1, -2, 0, 0)
    if st is None:
        return (1, -1, -2, 0, 0)
    return (1, getattr(st, "source", -1), getattr(st, "tag", -2),
            getattr(st, "count", 0),
            1 if getattr(st, "cancelled", False) else 0)


def grequest_start() -> int:
    """Plain user-completed request (callbacks live on the C side —
    libmpi_ext.c invokes them around completion)."""
    r = mpi.Grequest_start(None, None, None)
    return _new_req(r)


def grequest_complete(rh: int) -> int:
    with _lock:
        r = _reqs.get(rh)
    if r is not None:
        r.complete()
    return 0


# ---------------------------------------------------------------------------
# process topologies (core/topo.py over the C ABI)
# ---------------------------------------------------------------------------

def dims_create(nnodes: int, ndims: int, dims):
    from .core import topo as tp
    return tp.dims_create(nnodes, ndims, list(dims))


def cart_create(ch: int, dims, periods, reorder: int) -> int:
    from .core import topo as tp
    c = tp.cart_create(_comm(ch), list(dims),
                       [bool(p) for p in periods], bool(reorder))
    if c is None:
        return -1
    return _new_comm_handle(c)


def cart_rank(ch: int, coords) -> int:
    return _comm(ch).topo.rank_of(list(coords))


def cart_coords(ch: int, rank: int):
    return _comm(ch).topo.coords_of(rank)


def cart_shift(ch: int, direction: int, disp: int):
    from .core import topo as tp
    return tp.cart_shift(_comm(ch), direction, disp)


def cart_sub(ch: int, remain_dims) -> int:
    from .core import topo as tp
    c = tp.cart_sub(_comm(ch), [bool(r) for r in remain_dims])
    if c is None:
        return -1
    return _new_comm_handle(c)


def cart_get(ch: int):
    t = _comm(ch).topo
    return (list(t.dims), [1 if p else 0 for p in t.periods],
            t.coords_of(_comm(ch).rank))


def cartdim_get(ch: int) -> int:
    return _comm(ch).topo.ndims


def cart_map(ch: int, dims, periods) -> int:
    from .core import topo as tp
    r = tp.cart_map(_comm(ch), list(dims), [bool(p) for p in periods])
    return -32766 if r in (None, -32766) else r


def graph_create(ch: int, index, edges, reorder: int) -> int:
    from .core import topo as tp
    c = tp.graph_create(_comm(ch), list(index), list(edges),
                        bool(reorder))
    if c is None:
        return -1
    return _new_comm_handle(c)


def graphdims_get(ch: int):
    t = _comm(ch).topo
    return (len(t.index), len(t.edges))


def graph_get(ch: int):
    t = _comm(ch).topo
    return (list(t.index), list(t.edges))


def graph_neighbors(ch: int, rank: int):
    return _comm(ch).topo.neighbors_of(rank)


def topo_test(ch: int) -> int:
    from .core import topo as tp
    kind = tp.topo_test(_comm(ch))
    return {"cart": 2, "graph": 1, "dist_graph": 3}.get(kind, -32766)


def dist_graph_create_adjacent(ch: int, sources, sweights, dests,
                               dweights, reorder: int,
                               weighted: int) -> int:
    from .core import topo as tp
    c = tp.dist_graph_create_adjacent(
        _comm(ch), list(sources), list(dests),
        list(sweights) if sweights is not None else None,
        list(dweights) if dweights is not None else None,
        weighted=bool(weighted))
    return _new_comm_handle(c)


def dist_graph_create(ch: int, sources, degrees, dests, weights,
                      reorder: int, weighted: int) -> int:
    from .core import topo as tp
    c = tp.dist_graph_create(
        _comm(ch), list(sources), list(degrees), list(dests),
        list(weights) if weights is not None else None,
        bool(reorder), weighted=bool(weighted))
    return _new_comm_handle(c)


def dist_graph_neighbors(ch: int):
    t = _comm(ch).topo
    weighted = 1 if getattr(t, "weighted", False) else 0
    sw = list(t.sweights) if getattr(t, "sweights", None) is not None \
        else [1] * len(t.sources)
    dw = list(t.dweights) if getattr(t, "dweights", None) is not None \
        else [1] * len(t.destinations)
    return (list(t.sources), sw, list(t.destinations), dw, weighted)


def finalized() -> int:
    return 1 if mpi.Finalized() else 0


def query_thread() -> int:
    return mpi._provided_level


def set_thread_level(level: int) -> int:
    """Record what MPI_Init_thread granted so MPI_Query_thread agrees
    (init/initstat.c checks the two answers match)."""
    mpi._provided_level = level
    return 0


# ---------------------------------------------------------------------------
# error translation
# ---------------------------------------------------------------------------

def errclass(exc) -> int:
    if isinstance(exc, MPIException):
        return exc.error_class
    return 16   # MPI_ERR_OTHER


def c_error_class(exc) -> int:
    """Error class for a Python exception escaping to the C boundary.
    MPI errors map through errclass; anything else is also logged (it
    is a framework bug, not an erroneous-program error)."""
    if not isinstance(exc, MPIException):
        import sys
        import traceback
        print("libmpi: unexpected exception at the C boundary:",
              file=sys.stderr)
        traceback.print_exception(type(exc), exc, exc.__traceback__)
    return errclass(exc)


def type_basic_size(code: int) -> int:
    """Bytes per basic element of a homogeneous derived type (0 when
    heterogeneous — MPI_Get_elements falls back to packed size)."""
    if code < _DERIVED_BASE:
        return _DTYPES[code].itemsize
    d = _derived[code]
    return d.basic.itemsize if d.basic is not None else 0


def error_string(klass: int) -> str:
    from .core.errors import error_string as _es
    return _es(klass)


# ---------------------------------------------------------------------------
# ULFM fault tolerance (MPIX_Comm_* — mirrors ft/ulfm.py over the C ABI;
# reference: mvapich2 src/mpi/comm/comm_revoke.c, comm_shrink.c,
# comm_agree.c)
# ---------------------------------------------------------------------------

def comm_revoke(ch: int) -> int:
    from .ft import ulfm
    ulfm.revoke(_comm(ch))
    return 0


def comm_is_revoked(ch: int) -> int:
    return 1 if _comm(ch).revoked else 0


def comm_shrink(ch: int) -> int:
    from .ft import ulfm
    return _new_comm_handle(ulfm.shrink(_comm(ch)))


def comm_agree(ch: int, flag: int):
    """Returns (errclass, agreed_flag): the agreed value is established
    even when unacked failures force MPIX_ERR_PROC_FAILED (comm_agree.c
    contract — survivors stay in lockstep)."""
    from .ft import ulfm
    try:
        return (0, ulfm.agree(_comm(ch), flag))
    except MPIException as e:
        agreed = getattr(e, "agreed_flag", flag)
        return (e.error_class, agreed)


def comm_failure_ack(ch: int) -> int:
    from .ft import ulfm
    ulfm.failure_ack(_comm(ch))
    return 0


def comm_failure_get_acked(ch: int) -> int:
    from .ft import ulfm
    return _new_group_handle(ulfm.failure_get_acked(_comm(ch)))


# ---------------------------------------------------------------------------
# MPI-IO (MPI_File_* — forwards to io/; reference: src/mpi/romio/mpi-io/
# open.c, read.c, write_all.c, set_view.c ... The C side passes raw byte
# views; pack/unpack placement runs through the datatype engine exactly
# like the pt2pt paths.)
# ---------------------------------------------------------------------------

_files: Dict[int, object] = {}
_next_file = 1

# ops whose first MPI argument is an explicit offset
_IO_AT_OPS = frozenset(
    {"read_at", "write_at", "read_at_all", "write_at_all"})

_DISPLACEMENT_CURRENT = -54278278


def _file(fh: int):
    f = _files.get(fh)
    if f is None:
        from .core.errors import MPI_ERR_FILE
        raise MPIException(MPI_ERR_FILE, f"invalid file handle {fh}")
    return f


def file_open(ch: int, filename: str, amode: int, ih: int) -> int:
    global _next_file
    from .io.file import File
    info = dict(_info(ih).items()) if ih >= 0 or ih == -2 else None
    f = File(_comm(ch), filename, amode, info)
    f._etype_code = 0            # current view's C datatype handles,
    f._ftype_code = 0            # reported back by MPI_File_get_view
    with _lock:
        h = _next_file
        _next_file += 1
        _files[h] = f
    return h


def file_close(fh: int) -> int:
    f = _file(fh)
    f.close()
    with _lock:
        _files.pop(fh, None)
    return 0


def file_delete(filename: str) -> int:
    from .io.file import file_delete as _fd
    _fd(filename)
    return 0


def file_rw(fh: int, op: str, offset: int, view, count: int,
            dtcode: int) -> int:
    """Blocking read/write dispatch; returns transferred bytes."""
    f = _file(fh)
    d = _dt(dtcode)
    buf = np.frombuffer(view, np.uint8) if view is not None \
        else np.empty(0, np.uint8)
    fn = getattr(f, op)
    st = fn(offset, buf, count, d) if op in _IO_AT_OPS \
        else fn(buf, count, d)
    return st.count


def file_irw(fh: int, op: str, offset: int, view, count: int,
             dtcode: int) -> int:
    """Nonblocking variant; returns a request handle for MPI_Wait/Test."""
    global _next_req
    f = _file(fh)
    d = _dt(dtcode)
    buf = np.frombuffer(view, np.uint8) if view is not None \
        else np.empty(0, np.uint8)
    fn = getattr(f, "i" + op)
    r = fn(offset, buf, count, d) if op in _IO_AT_OPS \
        else fn(buf, count, d)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def file_set_view(fh: int, disp: int, et_code: int, ft_code: int,
                  datarep: str) -> int:
    f = _file(fh)
    if disp == _DISPLACEMENT_CURRENT:
        # MODE_SEQUENTIAL: the new displacement is the current absolute
        # byte position (MPI-3.1 §13.3)
        disp = f.view.physical(f._pos)
    et = _dt(et_code)
    ft = _dt(ft_code) if ft_code >= 0 else None
    f.set_view(disp, et, ft, datarep)
    f._etype_code = et_code
    f._ftype_code = ft_code if ft_code >= 0 else et_code
    return 0


def file_get_view(fh: int):
    f = _file(fh)
    return (f.view.disp, f._etype_code, f._ftype_code)


def file_seek(fh: int, offset: int, whence: int) -> int:
    _file(fh).seek(offset, whence)
    return 0


def file_get_position(fh: int) -> int:
    return _file(fh).get_position()


def file_get_byte_offset(fh: int, offset: int) -> int:
    return _file(fh).get_byte_offset(offset)


def file_seek_shared(fh: int, offset: int, whence: int) -> int:
    _file(fh).seek_shared(offset, whence)
    return 0


def file_get_position_shared(fh: int) -> int:
    return _file(fh).get_position_shared()


def file_get_size(fh: int) -> int:
    return _file(fh).get_size()


def file_set_size(fh: int, size: int) -> int:
    _file(fh).set_size(size)
    return 0


def file_preallocate(fh: int, size: int) -> int:
    _file(fh).preallocate(size)
    return 0


def file_get_amode(fh: int) -> int:
    return _file(fh).get_amode()


def file_get_group(fh: int) -> int:
    return _new_group_handle(_file(fh).get_group())


def file_set_info(fh: int, ih: int) -> int:
    _file(fh).set_info(dict(_info(ih).items()) if ih >= 0 or ih == -2
                       else None)
    return 0


def file_get_info(fh: int) -> int:
    global _next_info
    from .core.info import Info
    with _lock:
        h = _next_info
        _next_info += 1
        _infos[h] = Info(dict(_file(fh).get_info()))
    return h


def file_set_atomicity(fh: int, flag: int) -> int:
    _file(fh).set_atomicity(bool(flag))
    return 0


def file_get_atomicity(fh: int) -> int:
    return 1 if _file(fh).get_atomicity() else 0


def file_sync(fh: int) -> int:
    _file(fh).sync()
    return 0


# ---------------------------------------------------------------------------
# MPI_T tools interface (MPI_T_* — forwards to mpit.py; reference:
# src/mpi_t/cvar_read.c, pvar_session_create.c et al.)
# ---------------------------------------------------------------------------

def _mpit_dtype_code(typ_name: str) -> int:
    """C datatype handle for a cvar's Python type (codes from mpi.h)."""
    return {"int": 2, "bool": 2, "str": 1, "float": 4}.get(typ_name, 2)


def mpit_cvar_num() -> int:
    from . import mpit
    return mpit.cvar_get_num()


def mpit_cvar_info(i: int):
    """(name, desc, dtype_code, scope, verbosity) or None for bad index."""
    from . import mpit
    if not 0 <= i < mpit.cvar_get_num():
        return None
    info = mpit.cvar_get_info(i)
    return (info["name"], info["desc"] or "",
            _mpit_dtype_code(info["type"]), int(info["scope"]),
            int(info["verbosity"]))


def mpit_cvar_index(name: str) -> int:
    from . import mpit
    try:
        return mpit.cvar_get_index(name)
    except KeyError:
        return -1


def mpit_cvar_read_int(i: int) -> int:
    from . import mpit
    return int(mpit.cvar_read(i))


def mpit_cvar_read_double(i: int) -> float:
    from . import mpit
    return float(mpit.cvar_read(i))


def mpit_cvar_read_str(i: int) -> str:
    from . import mpit
    v = mpit.cvar_read(i)
    return "" if v is None else str(v)


def mpit_cvar_write_int(i: int, v: int) -> int:
    from . import mpit
    # bool cvars store the raw int so MPI_T round-trips exactly
    # (cvarwrite.c writes 123 and expects to read 123 back; truthiness
    # is what the consuming code paths test anyway)
    mpit.cvar_write(i, int(v))
    return 0


def mpit_cvar_count(i: int) -> int:
    """MPI_T handle element count: 1 for numerics; string cvars report
    their buffer size (choice-restricted ones report 512 so generic
    write-garbage probes — cvarwrite.c gates on count < 512 — skip
    values the declarative registry would reject)."""
    from . import mpit
    cv = mpit._cvar_list()[i]
    if cv.typ.__name__ == "str":
        return 512 if cv.choices is not None else 256
    return 1


def mpit_cvar_write_double(i: int, v: float) -> int:
    from . import mpit
    mpit.cvar_write(i, float(v))
    return 0


def mpit_cvar_write_str(i: int, s: str) -> int:
    from . import mpit
    mpit.cvar_write(i, s)
    return 0


def mpit_pvar_num() -> int:
    from . import mpit
    return mpit.pvar_get_num()


def mpit_pvar_info(i: int):
    """(name, desc, class, continuous, readonly) or None."""
    from . import mpit
    if not 0 <= i < mpit.pvar_get_num():
        return None
    info = mpit.pvar_get_info(i)
    cont = 1 if info["continuous"] else 0
    return (info["name"], info["desc"] or "", int(info["class"]), cont, 1)


def mpit_pvar_index(name: str) -> int:
    from . import mpit
    try:
        return mpit.pvar_get_index(name)
    except ValueError:
        return -1


_mpit_sessions: Dict[int, object] = {}
_next_mpit_session = 1


def mpit_pvar_session_create() -> int:
    global _next_mpit_session
    from . import mpit
    with _lock:
        h = _next_mpit_session
        _next_mpit_session += 1
        _mpit_sessions[h] = mpit.pvar_session_create()
    return h


def mpit_pvar_session_free(sh: int) -> int:
    with _lock:
        _mpit_sessions.pop(sh, None)
    return 0


def mpit_pvar_handle_alloc(sh: int, index: int) -> int:
    return _mpit_sessions[sh].handle_alloc(index)


def mpit_pvar_handle_free(sh: int, h: int) -> int:
    _mpit_sessions[sh].handle_free(h)
    return 0


def mpit_pvar_start(sh: int, h: int) -> int:
    _mpit_sessions[sh].start(h)
    return 0


def mpit_pvar_reset(sh: int, h: int) -> int:
    _mpit_sessions[sh].reset(h)
    return 0


def mpit_pvar_read(sh: int, h: int) -> float:
    return float(_mpit_sessions[sh].read(h))


def mpit_cat_num() -> int:
    from . import mpit
    return mpit.category_get_num()


def mpit_cat_info(i: int):
    """(name, desc, num_cvars, num_pvars) or None."""
    from . import mpit
    if not 0 <= i < mpit.category_get_num():
        return None
    info = mpit.category_get_info(i)
    return (info["name"], f"cvars/pvars in group {info['name']}",
            info["num_cvars"], info["num_pvars"])


def mpit_cat_index(name: str) -> int:
    from . import mpit
    try:
        return mpit.category_names().index(name)
    except ValueError:
        return -1


def mpit_cat_cvars(i: int):
    from . import mpit
    info = mpit.category_get_info(i)
    return [mpit.cvar_get_index(n) for n in info["cvars"]]


def mpit_cat_pvars(i: int):
    from . import mpit
    info = mpit.category_get_info(i)
    return [mpit.pvar_get_index(n) for n in info["pvars"]]


# ---------------------------------------------------------------------------
# dynamic processes (MPI-3.1 §10): spawn, ports, name service
# C surface: MPI_Comm_spawn / MPI_Open_port / MPI_Comm_connect etc.
# (reference: src/mpi/spawn/ — spawn.c, open_port.c, comm_connect.c)
# ---------------------------------------------------------------------------

def _fill_errcodes(view, errcodes) -> None:
    """Write spawn errcodes into the caller's int32 buffer, clamped to
    its capacity — non-root ranks legally size it from root-only args
    they don't know (MPI-3.1 §10.3.2), so never trust the length."""
    if view is None:
        return
    arr = np.frombuffer(view, dtype=np.int32)
    n = min(arr.size, len(errcodes))
    arr[:n] = errcodes[:n]


def comm_spawn(ch: int, command: str, argv_us: str, maxprocs: int,
               root: int, errcodes_view=None, wd: str = "",
               path: str = "") -> int:
    """argv_us: argv strings joined with '\\x1f' ('' = no args).
    Returns the intercomm handle; fills errcodes (int32) if given."""
    args = argv_us.split("\x1f") if argv_us else []
    info = {}
    if wd:
        info["wd"] = wd
    if path:
        info["path"] = path
    ic, errcodes = mpi.Comm_spawn(command, args, maxprocs, root,
                                  comm=_comm(ch), info=info or None)
    _fill_errcodes(errcodes_view, errcodes)
    return _new_comm_handle(ic)


def comm_spawn_multiple(ch: int, cmds_us: str, root: int,
                        errcodes_view=None) -> int:
    """cmds_us: records joined with '\\x1e'; each record is
    command '\\x1f' maxprocs '\\x1f' wd '\\x1f' path
    ['\\x1f' arg0 ...] — wd/path are the per-command spawn hints
    (spawnminfo1.c gives each command its own wdir)."""
    cmds = []
    for rec in cmds_us.split("\x1e"):
        parts = rec.split("\x1f")
        if parts[0]:
            info = {}
            if len(parts) > 2 and parts[2]:
                info["wd"] = parts[2]
            if len(parts) > 3 and parts[3]:
                info["path"] = parts[3]
            cmds.append((parts[0], parts[4:], int(parts[1] or "0"),
                         info))
    ic, errcodes = mpi.Comm_spawn_multiple(cmds, root, comm=_comm(ch))
    _fill_errcodes(errcodes_view, errcodes)
    return _new_comm_handle(ic)


_parent_handle = None


def comm_get_parent() -> int:
    """The spawn parent intercomm — same handle every call (the
    reference's MPIR_Process.comm_parent singleton), -1 when none."""
    global _parent_handle
    if _parent_handle is None:
        p = mpi.Comm_get_parent()
        if p is None:
            return -1
        _parent_handle = _new_comm_handle(p)
        # expose the predefined name "MPI_COMM_PARENT" (MPI-3.1 §6.8)
        _named_comms.add(_parent_handle)
    return _parent_handle


def open_port() -> str:
    return mpi.Open_port()


def close_port(port_name: str) -> int:
    mpi.Close_port(port_name)
    return 0


def comm_accept(port_name: str, ch: int, root: int) -> int:
    return _new_comm_handle(mpi.Comm_accept(port_name, _comm(ch), root))


def comm_connect(port_name: str, ch: int, root: int) -> int:
    return _new_comm_handle(mpi.Comm_connect(port_name, _comm(ch), root))


def comm_disconnect(ch: int) -> int:
    """MPI_Comm_disconnect: collective free that waits for pending
    communication (our free() already fences the channel). After
    disconnecting (or freeing) the parent intercomm,
    MPI_Comm_get_parent returns MPI_COMM_NULL (MPI-3.1 §10.3.2) —
    handled in comm_free, which this shares."""
    return comm_free(ch)


def publish_name(service_name: str, port_name: str) -> int:
    mpi.Publish_name(service_name, port_name)
    return 0


def unpublish_name(service_name: str, port_name: str) -> int:
    mpi.Unpublish_name(service_name, port_name)
    return 0


def lookup_name(service_name: str):
    return mpi.Lookup_name(service_name)


def universe_size() -> int:
    """MPI_UNIVERSE_SIZE: spawn capacity. MV2T_UNIVERSE_SIZE overrides;
    default world+8 (process-mode spawn forks children freely, so the
    universe is genuinely larger than the initial world)."""
    override = int(get_config().get("UNIVERSE_SIZE", 0) or 0)
    if override:
        return override
    return _comm(0).size + 8


def get_appnum() -> int:
    a = mpi.Get_appnum()
    return -1 if a is None else int(a)


def win_set_name(wh: int, name: str) -> int:
    _wins[wh].set_name(name)
    return 0


def win_get_name(wh: int) -> str:
    return _wins[wh].get_name()


# ---------------------------------------------------------------------------
# nonblocking v-collectives (MPI-3.0 §5.12; sched-based, byte-level)
# ---------------------------------------------------------------------------

def igatherv(sview, rview, scount: int, sdt: int, rcounts, displs,
             rdt: int, root: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        args = (sview, rview, scount, sdt,
                list(rcounts) if rcounts is not None else None,
                list(displs) if displs is not None else None,
                rdt, root, ch)
        return _queued(ch, lambda: gatherv(*args))
    esz = _esz(rdt)
    if c.rank == root:
        rcounts = [max(n, 0) for n in rcounts] if rview else \
            [0] * c.size
        displs = list(displs) if displs is not None and rview else \
            [0] * c.size
        bcounts = [n * esz for n in rcounts]
        tmp = np.empty(sum(bcounts), np.uint8)
        if sview is not None:
            sb = _gather_in(sview, 0, scount, sdt)
        elif rview and rcounts[root] > 0:
            sb = np.array(_gather_in(rview, displs[root],
                                     rcounts[root], rdt))
        else:
            sb = np.empty(0, np.uint8)
        req = nb.igatherv(c, sb, sb.size, tmp, bcounts, None,
                          dt.BYTE, root)

        if rview:
            def finish(_r, rv=rview, rcs=rcounts, dps=displs, t=tmp):
                off = 0
                for i, n in enumerate(rcs):
                    _scatter_out(rv, dps[i], n, rdt,
                                 t[off: off + n * esz])
                    off += n * esz
            req.add_callback(finish)
        return _new_req(req)
    sb = _gather_in(sview, 0, scount, sdt) if sview is not None \
        else np.empty(0, np.uint8)
    return _new_req(nb.igatherv(c, sb, sb.size, None, None, None,
                                dt.BYTE, root))


def iscatterv(sview, rview, scounts, displs, sdt: int, rcount: int,
              rdt: int, root: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        args = (sview, rview,
                list(scounts) if scounts is not None else None,
                list(displs) if displs is not None else None,
                sdt, rcount, rdt, root, ch)
        return _queued(ch, lambda: scatterv(*args))
    esz = _esz(rdt) if rview else 0
    nrecv = max(rcount, 0) * esz if rview else 0
    rtmp = np.empty(nrecv, np.uint8)
    if c.rank == root:
        scounts, displs = list(scounts), list(displs)
        esz_s = _esz(sdt)
        segs = ([_gather_in(sview, displs[j], scounts[j], sdt)
                 for j in range(c.size)] if sview is not None else
                [np.empty(0, np.uint8)] * c.size)
        sb = np.concatenate(segs) if segs else np.empty(0, np.uint8)
        req = nb.iscatterv(c, sb, [n * esz_s for n in scounts], None,
                           rtmp, nrecv, dt.BYTE, root)
    else:
        req = nb.iscatterv(c, None, None, None, rtmp, nrecv,
                           dt.BYTE, root)
    if rview:
        req.add_callback(lambda _r: _scatter_out(rview, 0, rcount, rdt,
                                                 rtmp))
    return _new_req(req)


def iallgatherv(sview, rview, scount: int, sdt: int, rcounts, displs,
                rdt: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        args = (sview, rview, scount, sdt, list(rcounts), list(displs),
                rdt, ch)
        return _queued(ch, lambda: allgatherv(*args))
    rcounts, displs = list(rcounts), list(displs)
    esz = _esz(rdt)
    if sview is None:                     # MPI_IN_PLACE
        sb = np.array(_gather_in(rview, displs[c.rank],
                                 rcounts[c.rank], rdt))
    else:
        sb = _gather_in(sview, 0, scount, sdt)
    bcounts = [n * esz for n in rcounts]
    tmp = np.empty(sum(bcounts), np.uint8)
    req = nb.iallgatherv(c, sb, sb.size, tmp, bcounts, None, dt.BYTE)

    def finish(_r):
        off = 0
        for i, n in enumerate(rcounts):
            _scatter_out(rview, displs[i], n, rdt,
                         tmp[off: off + n * esz])
            off += n * esz
    req.add_callback(finish)
    return _new_req(req)


def ialltoallv(sview, rview, scounts, sdispls, rcounts, rdispls,
               sdt: int, rdt: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        args = (sview, rview,
                list(scounts) if scounts is not None else None,
                list(sdispls) if sdispls is not None else None,
                list(rcounts), list(rdispls), sdt, rdt, ch)
        return _queued(ch, lambda: alltoallv(*args))
    if sview is None:
        sview, scounts, sdispls, sdt = rview, rcounts, rdispls, rdt
        sview = bytes(np.frombuffer(sview, np.uint8))
    scounts, sdispls = list(scounts), list(sdispls)
    rcounts, rdispls = list(rcounts), list(rdispls)
    esz_s, esz_r = _esz(sdt), _esz(rdt)
    segs = [_gather_in(sview, sdispls[j], scounts[j], sdt)
            for j in range(c.size)]
    sb = np.concatenate(segs) if segs else np.empty(0, np.uint8)
    rtmp = np.empty(sum(rcounts) * esz_r, np.uint8)
    bs = [n * esz_s for n in scounts]
    br = [n * esz_r for n in rcounts]
    req = nb.ialltoallv(c, sb, bs, None, rtmp, br, None, dt.BYTE)

    def finish(_r):
        off = 0
        for i in range(c.size):
            _scatter_out(rview, rdispls[i], rcounts[i], rdt,
                         rtmp[off: off + rcounts[i] * esz_r])
            off += rcounts[i] * esz_r
    req.add_callback(finish)
    return _new_req(req)


def ireduce_scatter(sview, rview, rcounts, dtcode: int, opcode: int,
                    ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    rcounts = list(rcounts)
    if _is_inter(c):
        return _queued(ch, lambda: reduce_scatter(sview, rview, rcounts,
                                                  dtcode, opcode, ch))
    total = sum(rcounts)
    if sview is None:
        sview = bytes(np.frombuffer(rview, np.uint8))
    sb, _ = _red_view(sview, total, dtcode)
    rb, wb = _red_view(rview, rcounts[c.rank], dtcode)
    req = nb.ireduce_scatter(c, sb, rb, rcounts, _dt(dtcode),
                             _OPS[opcode])
    if wb is not None:
        req.add_callback(lambda _r: wb())
    return _new_req(req)


def ireduce_scatter_block(sview, rview, rcount: int, dtcode: int,
                          opcode: int, ch: int) -> int:
    from .coll import nonblocking as nb
    c = _comm(ch)
    if _is_inter(c):
        return _queued(ch, lambda: reduce_scatter_block(
            sview, rview, rcount, dtcode, opcode, ch))
    if sview is None:
        sview = bytes(np.frombuffer(rview, np.uint8))
    sb, _ = _red_view(sview, rcount * c.size, dtcode)
    rb, wb = _red_view(rview, rcount, dtcode)
    req = nb.ireduce_scatter_block(c, sb, rb, rcount, _dt(dtcode),
                                   _OPS[opcode])
    if wb is not None:
        req.add_callback(lambda _r: wb())
    return _new_req(req)


# ---------------------------------------------------------------------------
# RMA surface extensions: shared windows, PSCW introspection, flavors
# ---------------------------------------------------------------------------

def win_allocate_shared(size: int, disp_unit: int, ch: int):
    """Returns (win_handle, base_memoryview) — base lives in the
    cross-process shared segment (rma/win.py win_allocate_shared)."""
    global _next_win
    w = _comm(ch).win_allocate_shared(size, disp_unit=disp_unit)
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    base = w.base if w.base is not None and len(w.base) else \
        np.empty(0, np.uint8)
    return (h, memoryview(base))


def win_shared_query(wh: int, rank: int):
    """(size, disp_unit, segment_memoryview) of rank's shared segment."""
    seg, size, du = _wins[wh].shared_query(rank)
    return (size, du, memoryview(seg))


def win_get_group(wh: int) -> int:
    return _new_group_handle(_wins[wh].comm.group)


def win_test(wh: int) -> int:
    return 1 if _wins[wh].test() else 0


def win_flavor(wh: int) -> int:
    return int(_wins[wh].flavor)


def completed_request() -> int:
    """An already-complete request handle (R-variant RMA ops complete
    locally at call time but must still return a waitable request —
    rma/reqops.c asserts it is not MPI_REQUEST_NULL)."""
    from .core.request import CompletedRequest
    return _new_req(CompletedRequest())


def type_elements_in(code: int, nbytes: int) -> int:
    """MPI_Get_elements: complete basic items covered by `nbytes` of
    packed data, walking the type signature in typemap order
    (datatype/get-elements.c receives 1.5 pairs and expects 3).
    Returns -1 when the signature is too large to walk (callers fall
    back to uniform division)."""
    if nbytes == 0:
        return 0
    seq = dt.element_size_seq(_dt(code))
    if not seq:
        return -1
    per = sum(seq)
    if per <= 0:
        return 0
    full, rem = divmod(int(nbytes), per)
    count = full * len(seq)
    for it in seq:
        if rem >= it:
            rem -= it
            count += 1
        else:
            break
    return count


def _code_of_type(t) -> int:
    """Reverse map a Datatype object to its C handle (builtin enum or
    derived code) for MPI_Type_get_contents."""
    for c in range(0, 43):
        if c in (_MARKER_LB, _MARKER_UB):
            continue
        try:
            if _dt(c) is t:
                return c
        except Exception:
            continue
    with _lock:
        for c, d in _derived.items():
            if d is t:
                return c
    return -1


def type_get_contents(code: int):
    """(integers, addresses, datatype codes) — the constructor args
    recorded at creation (MPI-3.1 §4.1.13)."""
    env = _dt(code).get_envelope()
    return (list(int(x) for x in env[1]),
            list(int(x) for x in env[2]),
            [_code_of_type(t) for t in env[3]])


# ---------------------------------------------------------------------------
# external32 representation (MPI-3.1 §13.5.2): big-endian packed data
# ---------------------------------------------------------------------------

def _swap_items(data: np.ndarray, seq, count: int) -> np.ndarray:
    """Byteswap little-endian packed data item-by-item (the host is
    LE; external32 is BE). `seq` is one element's item-size sequence."""
    out = data.copy()
    if seq and all(s == seq[0] for s in seq):
        s = seq[0]
        if s > 1:
            out = out.reshape(-1, s)[:, ::-1].reshape(-1)
        return out
    pos = 0
    n = len(out)
    while pos < n:
        for s in seq:
            if pos + s > n:
                break
            out[pos:pos + s] = out[pos:pos + s][::-1]
            pos += s
    return out


def pack_external(iview, incount: int, dtcode: int, oview,
                  position: int) -> int:
    d = _dt(dtcode)
    raw = np.frombuffer(iview, np.uint8) if iview is not None else \
        np.empty(0, np.uint8)
    data = np.asarray(d.pack(raw, incount)).view(np.uint8)
    seq = dt.element_size_seq(d) or [1]
    swapped = _swap_items(data, seq, incount)
    out = np.frombuffer(oview, np.uint8)
    out[position:position + swapped.size] = swapped
    return position + int(swapped.size)


def unpack_external(iview, insize: int, position: int, oview,
                    outcount: int, dtcode: int) -> int:
    d = _dt(dtcode)
    src = np.frombuffer(iview, np.uint8)
    nbytes = d.size * outcount
    chunk = src[position:position + nbytes]
    seq = dt.element_size_seq(d) or [1]
    native = _swap_items(np.asarray(chunk), seq, outcount)
    d.unpack(native, np.frombuffer(oview, np.uint8), outcount)
    return position + nbytes


def pack_external_size(dtcode: int, incount: int) -> int:
    # our fixed-size representations match external32 widths
    return _dt(dtcode).size * incount

"""C-ABI shim: the Python side of native/mpi/libmpi.c.

The reference's hard boundary is the MPI C ABI (SURVEY §7 hard part (a):
"the OSU benchmarks are C programs"). libmpi.so embeds CPython and calls
the functions here; handles cross the boundary as small integers, buffers
as writable memoryviews over the caller's memory (zero-copy in/out via
numpy frombuffer).

Handle tables: comm 0 = MPI_COMM_WORLD, 1 = MPI_COMM_SELF, dynamic ids
from 2. Datatype/op codes are fixed enums mirrored in native/mpi/mpi.h.
"""

from __future__ import annotations

import threading
from typing import Dict

import numpy as np

from . import mpi
from .core import datatype as dt
from .core import op as opmod
from .core.errors import MPIException
from .core.status import ANY_SOURCE, ANY_TAG, PROC_NULL
from .runtime import universe as uni

# ---------------------------------------------------------------------------
# handle tables (mirror the enum values in native/mpi/mpi.h)
# ---------------------------------------------------------------------------

_DTYPES = {
    0: np.dtype(np.uint8),     # MPI_BYTE
    1: np.dtype(np.int8),      # MPI_CHAR
    2: np.dtype(np.int32),     # MPI_INT
    3: np.dtype(np.float32),   # MPI_FLOAT
    4: np.dtype(np.float64),   # MPI_DOUBLE
    5: np.dtype(np.int64),     # MPI_LONG / MPI_LONG_LONG
    6: np.dtype(np.uint64),    # MPI_UNSIGNED_LONG
    7: np.dtype(np.int16),     # MPI_SHORT
    8: np.dtype(np.uint8),     # MPI_UNSIGNED_CHAR
    9: np.dtype(np.int64),     # MPI_AINT
}

_OPS = {
    0: opmod.SUM, 1: opmod.PROD, 2: opmod.MAX, 3: opmod.MIN,
    4: opmod.LAND, 5: opmod.LOR, 6: opmod.BAND, 7: opmod.BOR,
}

_lock = threading.Lock()
_comms: Dict[int, object] = {}
_reqs: Dict[int, object] = {}
_wins: Dict[int, object] = {}
_next_comm = 2
_next_req = 1
_next_win = 1


def _comm(h: int):
    if h == 0:
        return uni.current_universe().comm_world
    if h == 1:
        return uni.current_universe().comm_self
    return _comms[h]


def _arr(view, count: int, dtcode: int) -> np.ndarray:
    """Zero-copy numpy array over the C caller's buffer."""
    d = _DTYPES[dtcode]
    return np.frombuffer(view, dtype=d, count=count)


# ---------------------------------------------------------------------------
# init / world
# ---------------------------------------------------------------------------

def init() -> int:
    mpi.Init()
    return 0


def finalize() -> int:
    mpi.Finalize()
    return 0


def initialized() -> int:
    return 1 if mpi.Initialized() else 0


def comm_rank(ch: int) -> int:
    return _comm(ch).rank


def comm_size(ch: int) -> int:
    return _comm(ch).size


def abort(ch: int, code: int) -> int:
    mpi.Abort(None, code)
    return 0


def comm_split(ch: int, color: int, key: int) -> int:
    global _next_comm
    c = _comm(ch).split(color if color >= 0 else None, key)
    if c is None:          # MPI_UNDEFINED color: no handle slot burned
        return -1
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


def comm_dup(ch: int) -> int:
    global _next_comm
    c = _comm(ch).dup()
    with _lock:
        h = _next_comm
        _next_comm += 1
        _comms[h] = c
    return h


def comm_free(ch: int) -> int:
    with _lock:
        c = _comms.pop(ch, None)
    if c is not None:
        c.free()
    return 0


def get_processor_name() -> str:
    return mpi.Get_processor_name()


# ---------------------------------------------------------------------------
# pt2pt
# ---------------------------------------------------------------------------

def send(view, count: int, dtcode: int, dest: int, tag: int,
         ch: int) -> int:
    buf = _arr(view, count, dtcode)
    _comm(ch).send(buf, dest, tag)
    return 0


def recv(view, count: int, dtcode: int, source: int, tag: int,
         ch: int):
    """Returns (source, tag, count_bytes)."""
    buf = _arr(view, count, dtcode)
    st = _comm(ch).recv(buf, source, tag)
    return (st.source, st.tag, st.count)


def isend(view, count: int, dtcode: int, dest: int, tag: int,
          ch: int) -> int:
    global _next_req
    buf = _arr(view, count, dtcode)
    r = _comm(ch).isend(buf, dest, tag)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def irecv(view, count: int, dtcode: int, source: int, tag: int,
          ch: int) -> int:
    global _next_req
    buf = _arr(view, count, dtcode)
    r = _comm(ch).irecv(buf, source, tag)
    with _lock:
        h = _next_req
        _next_req += 1
        _reqs[h] = r
    return h


def wait(rh: int):
    """Returns (source, tag, count_bytes)."""
    with _lock:
        r = _reqs.pop(rh, None)
    if r is None:
        return (-1, -1, 0)
    st = r.wait()
    return (st.source, st.tag, st.count)


def test(rh: int) -> int:
    with _lock:
        r = _reqs.get(rh)
    if r is None:
        return 1
    done = r.test()
    if done:
        with _lock:
            _reqs.pop(rh, None)
        r.wait()
    return 1 if done else 0


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def barrier(ch: int) -> int:
    _comm(ch).barrier()
    return 0


def bcast(view, count: int, dtcode: int, root: int, ch: int) -> int:
    buf = _arr(view, count, dtcode)
    _comm(ch).bcast(buf, root=root)
    return 0


def allreduce(sview, rview, count: int, dtcode: int, opcode: int,
              ch: int) -> int:
    rb = _arr(rview, count, dtcode)
    c = _comm(ch)
    if sview is None:                       # MPI_IN_PLACE
        sb = rb.copy()
    else:
        sb = _arr(sview, count, dtcode)
    c.allreduce(sb, rb, op=_OPS[opcode])
    return 0


def reduce(sview, rview, count: int, dtcode: int, opcode: int, root: int,
           ch: int) -> int:
    c = _comm(ch)
    sb = _arr(sview, count, dtcode)
    rb = _arr(rview, count, dtcode) if rview is not None else None
    c.reduce(sb, rb, op=_OPS[opcode], root=root)
    return 0


def allgather(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
              ch: int) -> int:
    c = _comm(ch)
    rb = _arr(rview, rcount * c.size, rdt)
    sb = _arr(sview, scount, sdt) if sview is not None \
        else rb[c.rank * rcount:(c.rank + 1) * rcount].copy()
    c.allgather(sb, rb, count=rcount)
    return 0


def alltoall(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
             ch: int) -> int:
    c = _comm(ch)
    rb = _arr(rview, rcount * c.size, rdt)
    sb = _arr(sview, scount * c.size, sdt) if sview is not None \
        else rb.copy()
    c.alltoall(sb, rb, count=rcount)
    return 0


def gather(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
           root: int, ch: int) -> int:
    c = _comm(ch)
    sb = _arr(sview, scount, sdt)
    rb = _arr(rview, rcount * c.size, rdt) if rview is not None else None
    c.gather(sb, rb, root=root, count=rcount)
    return 0


def scatter(sview, rview, scount: int, sdt: int, rcount: int, rdt: int,
            root: int, ch: int) -> int:
    c = _comm(ch)
    sb = _arr(sview, scount * c.size, sdt) if sview is not None else None
    rb = _arr(rview, rcount, rdt)
    c.scatter(sb, rb, root=root, count=rcount)
    return 0


def reduce_scatter_block(sview, rview, rcount: int, dtcode: int,
                         opcode: int, ch: int) -> int:
    c = _comm(ch)
    sb = _arr(sview, rcount * c.size, dtcode)
    rb = _arr(rview, rcount, dtcode)
    c.reduce_scatter_block(sb, rb, op=_OPS[opcode], count=rcount)
    return 0


# ---------------------------------------------------------------------------
# groups (PSCW sync in the OSU one-sided benchmarks)
# ---------------------------------------------------------------------------

_groups: Dict[int, object] = {}
_next_group = 1


def comm_group(ch: int) -> int:
    global _next_group
    with _lock:
        h = _next_group
        _next_group += 1
        _groups[h] = _comm(ch).group
    return h


def group_incl(gh: int, ranks) -> int:
    global _next_group
    g = _groups[gh].incl(list(ranks))
    with _lock:
        h = _next_group
        _next_group += 1
        _groups[h] = g
    return h


def group_free(gh: int) -> int:
    with _lock:
        _groups.pop(gh, None)
    return 0


# ---------------------------------------------------------------------------
# one-sided (the OSU one-sided benchmark surface)
# ---------------------------------------------------------------------------

def win_allocate(size: int, ch: int):
    """Returns (win_handle, base_memoryview)."""
    global _next_win
    w = _comm(ch).win_allocate(size)
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    base = w.base if w.base is not None else np.empty(0, np.uint8)
    return (h, memoryview(base))


def win_create(view, ch: int) -> int:
    """Window over the C caller's memory (zero-copy frombuffer)."""
    global _next_win
    base = np.frombuffer(view, dtype=np.uint8) if view is not None \
        else np.empty(0, np.uint8)
    w = _comm(ch).win_create(base)
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    return h


def win_create_dynamic(ch: int) -> int:
    global _next_win
    w = _comm(ch).win_create_dynamic()
    with _lock:
        h = _next_win
        _next_win += 1
        _wins[h] = w
    return h


def win_attach(wh: int, view, c_addr: int) -> int:
    """Dynamic-window attach. The C caller addresses targets by raw
    pointer (MPI_Get_address); our Win.attach assigns its own address, so
    record the C address alias too."""
    arr = np.frombuffer(view, dtype=np.uint8)
    w = _wins[wh]
    addr = w.attach(arr)
    alias = getattr(w, "_c_addr_alias", None)
    if alias is None:
        alias = {}
        w._c_addr_alias = alias
    alias[c_addr] = addr
    return 0


def win_detach(wh: int, c_addr: int) -> int:
    w = _wins[wh]
    alias = getattr(w, "_c_addr_alias", {})
    addr = alias.pop(c_addr, c_addr)
    try:
        w.detach(addr)
    except Exception:
        pass
    return 0


def win_lock_all(wh: int) -> int:
    _wins[wh].lock_all()
    return 0


def win_unlock_all(wh: int) -> int:
    _wins[wh].unlock_all()
    return 0


def win_flush_local(wh: int, rank: int) -> int:
    _wins[wh].flush_local(rank)
    return 0


def win_post(wh: int, gh: int) -> int:
    _wins[wh].post(_groups[gh])
    return 0


def win_start(wh: int, gh: int) -> int:
    _wins[wh].start(_groups[gh])
    return 0


def win_complete(wh: int) -> int:
    _wins[wh].complete()
    return 0


def win_wait(wh: int) -> int:
    _wins[wh].wait()
    return 0


def win_free(wh: int) -> int:
    with _lock:
        w = _wins.pop(wh, None)
    if w is not None:
        w.free()
    return 0


def win_lock(wh: int, lock_type: int, rank: int) -> int:
    from .rma.win import LOCK_EXCLUSIVE, LOCK_SHARED
    _wins[wh].lock(rank, LOCK_EXCLUSIVE if lock_type == 1 else LOCK_SHARED)
    return 0


def win_unlock(wh: int, rank: int) -> int:
    _wins[wh].unlock(rank)
    return 0


def win_fence(wh: int) -> int:
    _wins[wh].fence()
    return 0


def win_flush(wh: int, rank: int) -> int:
    _wins[wh].flush(rank)
    return 0


def put(wh: int, oview, count: int, dtcode: int, target: int,
        tdisp: int) -> int:
    buf = _arr(oview, count, dtcode)
    _wins[wh].put(buf, target, tdisp)
    return 0


def get(wh: int, oview, count: int, dtcode: int, target: int,
        tdisp: int) -> int:
    buf = _arr(oview, count, dtcode)
    _wins[wh].get(buf, target, tdisp)
    return 0


# ---------------------------------------------------------------------------
# error translation
# ---------------------------------------------------------------------------

def errclass(exc) -> int:
    if isinstance(exc, MPIException):
        return exc.error_class
    return 16   # MPI_ERR_OTHER

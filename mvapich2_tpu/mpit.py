"""MPI_T tools-information interface: cvars, pvars, categories.

Analog of the reference's src/mpi_t/ (SURVEY §5.5 — cvar_read.c,
pvar_session_create.c; 14.7k LoC) plus the MV2 channel counters in
src/mpi_t/mv2_mpit.c:17-39 and the per-algorithm collective timers
(allreduce_osu.c:35-50).

Redesign: the cvar surface is a thin indexed view over utils.config's
declarative registry (one declaration serves env parsing, enumeration and
MPI_T, collapsing the reference's three cooperating layers). Pvars live in
a process-global registry; counters are either owned (incremented by
instrumented code) or sourced (a callable sampled at read time, e.g. a
progress engine's poll count). Sessions follow MPI_T semantics: a handle
bound in a session accumulates from its start value, so concurrent tools
don't perturb each other.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .utils.config import CVar, cvar, get_config

# MPI_T verbosity / scope / binding constants (subset)
VERBOSITY_USER_BASIC = 221
VERBOSITY_TUNER_BASIC = 333
SCOPE_LOCAL = 0
SCOPE_ALL = 1
PVAR_CLASS_COUNTER = 0
PVAR_CLASS_TIMER = 1
PVAR_CLASS_LEVEL = 2
PVAR_CLASS_HIGHWATERMARK = 3
PVAR_CLASS_HISTOGRAM = 4


# ---------------------------------------------------------------------------
# cvar surface (indexed view of the config registry)
# ---------------------------------------------------------------------------

def _cvar_list() -> List[CVar]:
    return [get_config().cvars()[k] for k in sorted(get_config().cvars())]


def cvar_get_num() -> int:
    return len(_cvar_list())


def cvar_get_index(name: str) -> int:
    for i, cv in enumerate(_cvar_list()):
        if cv.name == name:
            return i
    raise KeyError(name)


def cvar_get_info(index: int) -> Dict[str, Any]:
    cv = _cvar_list()[index]
    return {"name": cv.name, "type": cv.typ.__name__, "default": cv.default,
            "category": cv.group, "desc": cv.desc,
            "env": cv.env_name, "scope": SCOPE_LOCAL,
            "verbosity": VERBOSITY_USER_BASIC}


def cvar_read(index: int) -> Any:
    return _cvar_list()[index].value


def cvar_write(index: int, value: Any) -> None:
    _cvar_list()[index].set_value(value)


# ---------------------------------------------------------------------------
# pvars
# ---------------------------------------------------------------------------

class PVar:
    """One performance variable. Owned pvars are incremented by the
    instrumented code path; sourced pvars sample ``source()`` at read."""

    def __init__(self, name: str, klass: int, group: str, desc: str,
                 source: Optional[Callable[[], float]] = None):
        self.name = name
        self.klass = klass
        self.group = group
        self.desc = desc
        self.source = source
        self._value = 0.0
        self._lock = threading.Lock()

    # -- instrumentation API ---------------------------------------------
    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def mark(self, v: float) -> None:
        """High-watermark update."""
        with self._lock:
            if v > self._value:
                self._value = v

    def add_time(self, dt: float) -> None:
        self.inc(dt)

    class _Timer:
        def __init__(self, pv: "PVar"):
            self.pv = pv

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pv.add_time(time.perf_counter() - self.t0)
            return False

    def timing(self) -> "PVar._Timer":
        return PVar._Timer(self)

    # -- read ------------------------------------------------------------
    def read(self) -> float:
        if self.source is not None:
            return float(self.source())
        with self._lock:
            return self._value

    def reset(self) -> None:
        if self.source is None:
            with self._lock:
                self._value = 0.0


HIST_BUCKETS = 32    # == MV2T_MET_HIST_BUCKETS (metrics shm mirror)


def hist_bucket_index(v: int) -> int:
    """Log2 bucket of a non-negative integer value: bucket 0 holds 0,
    bucket i >= 1 holds [2**(i-1), 2**i - 1] — every power of two is
    exactly a bucket's inclusive LOWER edge, so bucket boundaries are
    value-exact (tested). Values past the last edge saturate into the
    final bucket."""
    i = v.bit_length() if v > 0 else 0
    return i if i < HIST_BUCKETS else HIST_BUCKETS - 1


def hist_bucket_lo(i: int) -> int:
    """Inclusive lower edge of bucket ``i`` (0 for the zero bucket)."""
    return 0 if i <= 0 else 1 << (i - 1)


class HistPVar(PVar):
    """PVAR_CLASS_HISTOGRAM: a log2-bucketed value distribution —
    latency in integer microseconds by convention. ``rec`` is the
    hot-path entry point: no lock, no allocation — one bit_length and
    three integer bumps into preallocated storage. Concurrent
    recorders may lose an increment in the GIL's read-modify-write
    window; this is a stat surface with the same tolerance as the
    fpctr shm mirror. Quantiles/merges over the bucket lists live in
    metrics/hist.py (this module stays on the stdlib light-boot
    path)."""

    def __init__(self, name: str, klass: int, group: str, desc: str,
                 source: Optional[Callable[[], float]] = None):
        super().__init__(name, klass, group, desc, source)
        self.buckets = [0] * HIST_BUCKETS
        self.count = 0
        self.sum = 0

    def rec(self, v: int) -> None:
        if v > 0:
            i = v.bit_length()
            self.buckets[i if i < HIST_BUCKETS else HIST_BUCKETS - 1] += 1
            self.sum += v
        else:
            self.buckets[0] += 1
        self.count += 1

    def snapshot(self) -> tuple:
        """(count, sum, buckets-copy) — consistent enough for the stat
        surface (single GIL-held list copy)."""
        return self.count, self.sum, list(self.buckets)

    def read(self) -> float:
        if self.source is not None:
            return float(self.source())
        return float(self.count)

    def reset(self) -> None:
        b = self.buckets
        for i in range(HIST_BUCKETS):
            b[i] = 0
        self.count = 0
        self.sum = 0


class _PvarRegistry:
    def __init__(self):
        self._vars: Dict[str, PVar] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, klass: int, group: str, desc: str,
                source: Optional[Callable[[], float]] = None) -> PVar:
        with self._lock:
            pv = self._vars.get(name)
            if pv is None:
                cls = HistPVar if klass == PVAR_CLASS_HISTOGRAM else PVar
                pv = cls(name, klass, group, desc, source)
                self._vars[name] = pv
            elif source is not None:
                pv.source = source   # rebind live source (fresh universe)
            return pv

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._vars)

    def get(self, name: str) -> PVar:
        return self._vars[name]


_pvars = _PvarRegistry()


def pvar(name: str, klass: int = PVAR_CLASS_COUNTER, group: str = "general",
         desc: str = "", source: Optional[Callable[[], float]] = None) -> PVar:
    """Declare (or fetch) a pvar — instrumentation-side entry point."""
    return _pvars.declare(name, klass, group, desc, source)


def pvar_get_num() -> int:
    return len(_pvars.names())


def pvar_get_info(index: int) -> Dict[str, Any]:
    pv = _pvars.get(_pvars.names()[index])
    return {"name": pv.name, "class": pv.klass, "category": pv.group,
            "desc": pv.desc, "continuous": pv.source is not None}


def pvar_get_index(name: str) -> int:
    return _pvars.names().index(name)


class PvarSession:
    """MPI_T pvar session: handles accumulate relative to their start."""

    def __init__(self):
        self._handles: Dict[int, tuple] = {}   # handle -> (pvar, base)
        self._next = 1

    def handle_alloc(self, name_or_index) -> int:
        name = name_or_index if isinstance(name_or_index, str) \
            else _pvars.names()[name_or_index]
        pv = _pvars.get(name)
        h = self._next
        self._next += 1
        self._handles[h] = (pv, 0.0)
        return h

    def start(self, handle: int) -> None:
        pv, _ = self._handles[handle]
        self._handles[handle] = (pv, pv.read())

    def read(self, handle: int) -> float:
        """Counters/timers read relative to session start; watermark and
        level pvars are instantaneous — a delta would be meaningless."""
        pv, base = self._handles[handle]
        if pv.klass in (PVAR_CLASS_HIGHWATERMARK, PVAR_CLASS_LEVEL):
            return pv.read()
        return pv.read() - base

    def reset(self, handle: int) -> None:
        self.start(handle)

    def handle_free(self, handle: int) -> None:
        self._handles.pop(handle, None)


def pvar_session_create() -> PvarSession:
    return PvarSession()


# ---------------------------------------------------------------------------
# categories
# ---------------------------------------------------------------------------

def category_get_num() -> int:
    return len(category_names())


def category_names() -> List[str]:
    groups = {cv.group for cv in _cvar_list()}
    groups.update(pv_group for pv_group in
                  (_pvars.get(n).group for n in _pvars.names()))
    return sorted(groups)


def category_get_info(index: int) -> Dict[str, Any]:
    name = category_names()[index]
    cvars = [cv.name for cv in _cvar_list() if cv.group == name]
    pvars = [n for n in _pvars.names() if _pvars.get(n).group == name]
    return {"name": name, "num_cvars": len(cvars), "num_pvars": len(pvars),
            "cvars": cvars, "pvars": pvars}


def dump() -> str:
    """Tool-style dump of every pvar's current value."""
    lines = []
    for n in _pvars.names():
        pv = _pvars.get(n)
        lines.append(f"{pv.name:<44} = {pv.read():<14g} [{pv.group}]")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# analysis knobs (mv2t-analyze). Declared HERE — not next to their code —
# so the MPI_T surface carries the checker's observability even before
# mvapich2_tpu.analysis is imported (the lockorder module fetches the
# already-declared pvars on first use).
# ---------------------------------------------------------------------------

cvar("LOCKCHECK", False, bool, "analysis",
     "Enable the runtime lock-order detector (analysis/lockorder.py): "
     "instrumented locks record a per-process acquisition-order graph; "
     "cycles (potential deadlocks) and locks held across progress_wait "
     "are reported through the stall-watchdog dump path. Zero overhead "
     "when off (lock creation sites return the raw lock).")


def _lint_baseline_count() -> float:
    """Committed mv2tlint suppression count — the ratchet position."""
    try:
        from .analysis.core import load_baseline
        return float(len(load_baseline().entries))
    except Exception:   # tools must never break the registry
        return -1.0


pvar("lint_findings_baseline", PVAR_CLASS_LEVEL, "analysis",
     "mv2tlint findings suppressed by the committed baseline "
     "(analysis/baseline.json); --strict only lets this shrink",
     source=_lint_baseline_count)
pvar("lockcheck_edges", PVAR_CLASS_COUNTER, "analysis",
     "distinct lock-acquisition-order edges observed by the "
     "MV2T_LOCKCHECK monitor")
pvar("lockcheck_cycles", PVAR_CLASS_COUNTER, "analysis",
     "distinct lock-order cycles (potential deadlocks) reported by the "
     "MV2T_LOCKCHECK monitor")

# ---------------------------------------------------------------------------
# failure-containment observability (mvapich2_tpu/faults + ft/ulfm).
# Predeclared so tools enumerate them before the datapath imports; the
# owning modules fetch the same instances by name.
# ---------------------------------------------------------------------------
pvar("faults_injected", PVAR_CLASS_COUNTER, "ft",
     "faults fired by the MV2T_FAULTS deterministic injection engine "
     "(python-side sites; the native flat_fold site counts via "
     "fp_dead_peer-adjacent plane counters)")
pvar("dead_peer_detections", PVAR_CLASS_COUNTER, "ft",
     "peers declared dead by liveness-lease expiry (python probe + "
     "reconciled C-plane scans)")
pvar("wait_deadline_trips", PVAR_CLASS_COUNTER, "ft",
     "blocking waits unwound by a lease deadline instead of completing")
pvar("revokes_propagated", PVAR_CLASS_COUNTER, "ft",
     "REVOKE floods sent by this rank (initiations + re-floods on "
     "first receipt, ft/ulfm.py)")
pvar("arena_reclaimed_dead", PVAR_CLASS_COUNTER, "shm",
     "arena blocks/segments reclaimed from dead ranks (failure sweep, "
     "Finalize leak-check tolerance, stale-segment sweep)")

# ---------------------------------------------------------------------------
# device-collective engine knobs + fallback observability (ops/pallas_ici,
# ops/pallas_ring, coll/device). Declared HERE so the MPI_T surface
# enumerates the device lane before any jax/ops import happens — the same
# early-declaration contract as the analysis knobs above; the kernel
# modules fetch the already-declared entries by name.
# ---------------------------------------------------------------------------

cvar("ICI_CHUNK_BYTES", 256 * 1024, int, "device",
     "VMEM chunk size (bytes) of the HBM-streaming ICI ring kernels: "
     "each chunk is double-buffered through VMEM scratch while the "
     "remote DMA of the next chunk is in flight. A measured tuning "
     "profile (kernel_params.ici_chunk_bytes) overrides this default; "
     "bin/measure_crossover --device re-derives it.")
cvar("ICI_PIPELINE_DEPTH", 2, int, "device",
     "VMEM slots per ring direction in the HBM-streaming kernels "
     "(2 = classic double buffering). Each slot is one in-flight chunk; "
     "the credit handshake bounds a sender to this many chunks ahead.")
cvar("ICI_BIDIR", True, bool, "device",
     "Drive both ring directions of the mesh axis at once (half of "
     "every block clockwise, half counter-clockwise) when the axis has "
     "more than 2 shards — full bisection bandwidth on a physical ring.")
cvar("ICI_INTERPRET", False, bool, "device",
     "Force the pallas ICI kernels through the Mosaic interpreter so "
     "the device tiers run on a CPU mesh (correctness sweeps, CI). "
     "Off-TPU with this unset, device collectives take the XLA "
     "lowering and count dev_coll_fallback_platform.")
cvar("QUANT_COLL", "", str, "device",
     "Accuracy budget opening the block-scaled quantized device-"
     "allreduce tier (ops/pallas_quant): '' = off (exact kernels "
     "only); '<budget>' = int8 wire with that max relative-error "
     "budget (e.g. '1e-2'); '<wire>:<budget>' selects the wire format "
     "(q8 | fp8). Integer dtypes, non-sum ops, budget 0 and budgets "
     "below the declared per-ring bound all keep the exact hbm tier — "
     "the quantized path never runs outside its error contract.")
cvar("QUANT_BLOCK", 512, int, "device",
     "Quantization block size (bytes of the unquantized dtype) of the "
     "quantized wire format: each block travels as one f32 absmax "
     "scale word plus packed int8/fp8 codes, so larger blocks shrink "
     "the wire further but share one scale across more elements. A "
     "measured profile (kernel_params.quant_block_bytes) overrides "
     "this default.")

pvar("dev_coll_fallback_size", PVAR_CLASS_COUNTER, "device",
     "device collectives routed to the XLA lowering because the shard "
     "was past the measured XLA crossover (DEV_TIER_XLA_MIN) — the "
     "once-silent VMEM-cap cliff, now counted")
pvar("dev_coll_fallback_dtype", PVAR_CLASS_COUNTER, "device",
     "device collectives routed to the XLA lowering because the "
     "op/dtype does not lower to the ring kernels")
pvar("dev_coll_fallback_shape", PVAR_CLASS_COUNTER, "device",
     "device collectives routed to the XLA lowering because of a "
     "degenerate buffer extent")
pvar("dev_coll_fallback_platform", PVAR_CLASS_COUNTER, "device",
     "device collectives routed to the XLA lowering because the pallas "
     "kernels cannot run here (no pallas, or off-TPU without "
     "MV2T_ICI_INTERPRET)")
pvar("dev_coll_tier_vmem", PVAR_CLASS_COUNTER, "device",
     "device collective calls served by the VMEM-resident flat ring "
     "tier (ops/pallas_ring)")
pvar("dev_coll_tier_hbm", PVAR_CLASS_COUNTER, "device",
     "device collective calls served by the HBM-streaming chunked ring "
     "tier (ops/pallas_ici)")
pvar("dev_coll_tier_quant", PVAR_CLASS_COUNTER, "device",
     "device collective calls served by the block-scaled quantized "
     "wire tier (ops/pallas_quant, gated by MV2T_QUANT_COLL)")
pvar("dev_coll_quant_bytes_saved", PVAR_CLASS_COUNTER, "device",
     "bytes kept off the ICI wire by the quantized tier: exact-wire "
     "minus quantized-wire accounting (ops/pallas_quant.wire_stats) "
     "summed per dispatched call at the collective wrapper")
pvar("dev_coll_fallback_nbc", PVAR_CLASS_COUNTER, "device",
     "nonblocking collectives on a device-capable comm that could not "
     "route through the device tier (op/dtype/residency/size or the "
     "slot channel) and took the host schedule instead — the NBC "
     "analog of the dev_coll_fallback_* family (coll/device.py "
     "build_nonblocking_request)")
pvar("coll_level_chip", PVAR_CLASS_COUNTER, "device",
     "collective calls that exercised the chip level of the three-"
     "level hierarchy: an HBM slot fold among co-resident ranks (the "
     "slot channel, or the fold stage of the leaders-per-chip channel "
     "— coll/device.py _run LEVELS accounting)")
pvar("coll_level_ici", PVAR_CLASS_COUNTER, "device",
     "collective calls that exercised the ICI level: a mesh program "
     "over the device ring/torus phases (the 1:1 mesh channel, or the "
     "inter-chip stage of the fold channel)")
pvar("coll_level_net", PVAR_CLASS_COUNTER, "device",
     "collective calls that exercised the network level: the net2 "
     "node-leader bridge over the KVS/TCP lanes past np=64 "
     "(coll/netcoll.py)")
pvar("dev_persistent_starts", PVAR_CLASS_COUNTER, "device",
     "persistent-collective start() dispatches that rode the device "
     "nonblocking tier (MPI_*_init handles whose cached program was "
     "pre-warmed through the exec-cache seam at init time)")
pvar("dev_nbc_segments", PVAR_CLASS_COUNTER, "device",
     "device nonblocking-collective program segments launched by the "
     "NBC DAG's poll vertices (coll/device.py _nb_poll — each launch "
     "is one async jitted dispatch the engine then pumps to "
     "completion)")

# device-lane timing observability (ISSUE 10): per-tier effective-
# bandwidth watermarks measured at the dispatch wrapper
# (coll/device.py _run — wall time of the whole rendezvous+execute, so
# the number is end-to-end, not kernel-only), plus the optional
# hardware-profiler bracket.
cvar("JAX_PROFILE", "", str, "device",
     "Directory for a jax.profiler trace bracketing the device-"
     "collective region (started at the first device collective, "
     "stopped at process exit). Empty = off. The hardware-tuning "
     "workflow for ici_chunk_bytes/ICI_PIPELINE_DEPTH on a real TPU "
     "(ROADMAP item 1) reads this trace in TensorBoard/XProf.")
for _tier in ("vmem", "hbm", "quant", "xla", "slot"):
    pvar(f"dev_effbw_{_tier}", PVAR_CLASS_HIGHWATERMARK, "device",
         f"high watermark of end-to-end algorithmic bandwidth (GB/s, "
         f"payload bytes / wall seconds) observed on the '{_tier}' "
         "device tier at the collective dispatch wrapper")

# device one-sided RMA engine knobs + tier observability (ISSUE 16:
# ops/pallas_rma, rma/device). Same early-declaration contract; the
# dev_rma_rdma_min / dev_rma_quant_min tier-edge cvars live with the
# other DEV_* edges in coll/tuning.py.
cvar("RMA_CHUNK_BYTES", 0, int, "device",
     "VMEM chunk size (bytes) of the one-sided remote-DMA kernels "
     "(ops/pallas_rma): each put/get/accumulate chunk is one remote "
     "DMA through a depth-slotted landing buffer. 0 (default) inherits "
     "the ICI chunk edge (kernel_params.ici_chunk_bytes / "
     "MV2T_ICI_CHUNK_BYTES) so both device lanes tune together.")
pvar("dev_rma_tier_rdma", PVAR_CLASS_COUNTER, "device",
     "one-sided window ops served by the chunked remote-DMA tier "
     "(ops/pallas_rma put/get/accumulate kernels)")
pvar("dev_rma_tier_quant", PVAR_CLASS_COUNTER, "device",
     "one-sided accumulates served by the block-scaled quantized "
     "remote-DMA wire (ops/pallas_rma + the pallas_quant codec, gated "
     "by MV2T_QUANT_COLL and the dev_rma_quant_min edge)")
pvar("dev_rma_tier_epoch", PVAR_CLASS_COUNTER, "device",
     "one-sided window ops served by the ppermute epoch compiler "
     "(rma/device.py _build_epoch — the scheduled fallback tier)")
pvar("dev_rma_fallback_noncontig", PVAR_CLASS_COUNTER, "device",
     "one-sided ops routed to the epoch compiler because the element "
     "pattern is strided/derived (the epoch compiler's home turf; the "
     "remote-DMA tier carries contiguous runs only)")
pvar("dev_rma_fallback_platform", PVAR_CLASS_COUNTER, "device",
     "one-sided ops routed to the epoch compiler because the pallas "
     "kernels cannot run here (no pallas, or off-TPU without "
     "MV2T_ICI_INTERPRET)")
pvar("dev_rma_fallback_size", PVAR_CLASS_COUNTER, "device",
     "one-sided ops routed to the epoch compiler because the payload "
     "is below the dev_rma_rdma_min edge (or degenerate)")
pvar("dev_rma_fallback_dtype", PVAR_CLASS_COUNTER, "device",
     "one-sided ops routed to the epoch compiler because the window "
     "dtype does not lower to the remote-DMA kernels")
pvar("dev_rma_flush", PVAR_CLASS_COUNTER, "device",
     "passive-target completion waves (flush/flush_local/unlock) "
     "closed on a DeviceWin (rma/device.py)")
pvar("dev_rma_wire_bytes", PVAR_CLASS_COUNTER, "device",
     "payload bytes the remote-DMA one-sided tier put on the wire "
     "(quantized accumulates count their shrunken wire run)")


# ---------------------------------------------------------------------------
# multi-tenant node-service knobs + observability (runtime/daemon.py,
# coll/device.py executable cache). Declared HERE — daemon.claim runs
# inside MPI_Init's stdlib-only light boot and this module is already
# on that path (faults -> mpit), so the MPI_T surface enumerates the
# serving-fabric knobs before any heavy import; the owning modules
# fetch the already-declared entries by name.
# ---------------------------------------------------------------------------

cvar("DAEMON_NSETS", 4, int, "runtime",
     "Warm-attach daemon: maximum segment-set instances per geometry "
     "key. Overlapping jobs of ONE geometry claim distinct instances "
     "(<geokey>-i<k>) up to this bound; further claims queue under the "
     "admission quota.")
cvar("DAEMON_QUOTA", 8, int, "runtime",
     "Warm-attach daemon: node-wide admission quota — maximum busy "
     "segment sets across all geometries. Claims past the quota queue "
     "(bounded) instead of being refused; a timed-out waiter falls "
     "back to private per-job segments.")
cvar("DAEMON_EXEC_CACHE", 1, int, "runtime",
     "Device-executable cache in the daemon dir: coll/device.py "
     "program builds serialize the traced+compiled executable "
     "(jax.export) keyed on (kernel, shape, mesh, jax/profile "
     "fingerprint) so the first device collective of a new process "
     "deserializes instead of re-tracing. 0 = build per process as "
     "before. Requires MV2T_DAEMON=1; no-op on jax without the export "
     "API.")

pvar("daemon_claims_active", PVAR_CLASS_LEVEL, "runtime",
     "warm-attach segment-set claims this process currently holds "
     "(claim grants minus epoch-guarded releases)")
pvar("daemon_queue_waits", PVAR_CLASS_COUNTER, "runtime",
     "claims that entered the daemon's bounded admission queue "
     "(all instances busy or quota reached) before being granted or "
     "timing out")
pvar("exec_cache_hits", PVAR_CLASS_COUNTER, "runtime",
     "device-executable cache hits: program builds served by "
     "deserializing a cached executable instead of trace+compile")
pvar("exec_cache_misses", PVAR_CLASS_COUNTER, "runtime",
     "device-executable cache misses (no entry for the key at the "
     "current cache epoch, or a stale-epoch entry rejected)")
pvar("exec_cache_bytes", PVAR_CLASS_COUNTER, "runtime",
     "bytes of serialized executables written into the daemon's "
     "exec-cache by this process")


# ---------------------------------------------------------------------------
# continuous serving telemetry (mvapich2_tpu/metrics). Declared HERE —
# the daemon claim path records attach/queue histograms inside MPI_Init's
# stdlib-only light boot, and this module is already on that path; the
# owning modules (metrics/, coll/, rma/, transport/) fetch the
# already-declared entries by name.
# ---------------------------------------------------------------------------

cvar("METRICS", 1, int, "metrics",
     "Continuous serving telemetry: per-rank latency histograms "
     "(PVAR_CLASS_HISTOGRAM) at the collective/rendezvous/RMA/daemon "
     "sites plus the heartbeat-thread sampler that snapshots the fp_* "
     "shm mirror and selected pvars into the <ring>.metrics "
     "time-series segment for bin/mpistat --watch / bin/mpimetrics / "
     "the daemon's `metrics` verb. 1 (default) = on; 0 = off — sites "
     "then pay one attribute check, nothing else (the trace-off "
     "discipline, guarded by tests/progs/trace_overhead_prog.py).")
cvar("METRICS_INTERVAL_MS", 250, int, "metrics",
     "Sampling period (milliseconds) of the metrics ring sampler. The "
     "sampler rides the shm heartbeat thread (no thread of its own), "
     "so the effective period is max(interval, heartbeat wait) and "
     "never busier than ~20 ms.")

for _h, _d in (
    ("lat_coll_flat", "host flat-tier collective wave latency "
     "(coll/flatcoll.py try_* around the cp_flat_* call)"),
    ("lat_coll_flat2", "host hierarchical flat2-tier collective wave "
     "latency (coll/flatcoll.py try_* around the cp_flat2_* call)"),
    ("lat_coll_sched", "host scheduled-algorithm collective latency "
     "(coll/api.py dispatch around the pt2pt schedule)"),
    ("lat_coll_net2", "net2 node-leader-tier collective latency "
     "(coll/netcoll.py: group fold + leader bridge + fan-out, "
     "end-to-end)"),
    ("lat_dev_vmem", "device collective latency on the VMEM flat ring "
     "tier (coll/device.py _run end-to-end)"),
    ("lat_dev_hbm", "device collective latency on the HBM-streaming "
     "chunked ring tier (coll/device.py _run end-to-end)"),
    ("lat_dev_quant", "device collective latency on the block-scaled "
     "quantized wire tier (coll/device.py _run end-to-end)"),
    ("lat_dev_xla", "device collective latency on the XLA lowering "
     "(coll/device.py _run end-to-end)"),
    ("lat_dev_slot", "device collective latency on the slot tier "
     "(coll/device.py _run end-to-end)"),
    ("lat_dev_nbc", "device nonblocking-collective segment latency "
     "(coll/device.py _nb_poll: async launch to observed completion "
     "on the NBC DAG)"),
    ("lat_rndv_chunk", "rendezvous pipeline chunk-batch service time "
     "(transport/base.py account_rndv_chunk: one publish/drain batch "
     "from first copy to hand-off)"),
    ("lat_rma_flush", "one-sided completion-wave latency (rma/device.py "
     "fence/flush/unlock around the queued-op drain)"),
    ("lat_daemon_attach", "daemon claim attach latency (runtime/"
     "daemon.py claim entry to grant, queue wait included)"),
    ("lat_daemon_queue", "daemon admission-queue wait (queue entry to "
     "grant; only queued claims record)"),
):
    pvar(_h, PVAR_CLASS_HISTOGRAM, "metrics",
         f"log2-bucketed latency histogram (us): {_d}")


# ---------------------------------------------------------------------------
# the autotuner lives beside MPI_T (tools space): mpit.autotune —
# re-exported lazily (PEP 562): it imports numpy, and this module sits
# on the C-ABI light boot path (faults -> mpit), which must stay
# stdlib-only until the deferred world build
# ---------------------------------------------------------------------------
def __getattr__(name: str):
    if name == "autotune":
        from . import autotune
        return autotune
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""ADIO — the abstract IO device layer.

Analog of ROMIO's ADIO (reference: src/mpi/romio/adio/ — 18 per-filesystem
drivers behind one open/read/write/resize contract, e.g. adio/ad_ufs,
adio/ad_testfs). Here two drivers:

  * ``ufs``   — POSIX files via os.pread/os.pwrite (positional, so
    concurrent rank processes and IO threads never race a shared seek
    pointer; the ad_ufs analog).
  * ``memfs`` — an in-process shared store (the ad_testfs analog and the
    thread-mode harness backend; also the model for a future HBM-staged
    checkpoint target).

Driver selection mirrors ROMIO's prefix convention: "ufs:fname",
"memfs:fname", default ufs.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..core.datatype import as_bytes_view
from ..core.errors import (MPIException, MPI_ERR_AMODE, MPI_ERR_FILE,
                           MPI_ERR_IO, MPI_ERR_NO_SUCH_FILE)

# MPI_File amode bits (MPI-3.1 §13.2.1 values as in mpi.h)
MODE_RDONLY = 2
MODE_RDWR = 8
MODE_WRONLY = 4
MODE_CREATE = 1
MODE_EXCL = 64
MODE_DELETE_ON_CLOSE = 16
MODE_UNIQUE_OPEN = 32
MODE_SEQUENTIAL = 256
MODE_APPEND = 128


def parse_filename(filename: str) -> Tuple[str, str]:
    """'driver:path' -> (driver, path); bare paths mean ufs."""
    if ":" in filename:
        drv, _, path = filename.partition(":")
        if drv in _DRIVERS:
            return drv, path
    return "ufs", filename


class ADIOFile:
    """One opened file on one rank (the fd-level contract)."""

    def read_at(self, offset: int, nbytes: int) -> bytes:
        raise NotImplementedError

    def write_at(self, offset: int, data) -> int:
        raise NotImplementedError

    def read_into(self, offset: int, mv: memoryview) -> int:
        """Read directly into a writable byte view (zero extra copy when
        the driver supports it); returns bytes read."""
        b = self.read_at(offset, len(mv))
        mv[:len(b)] = b
        return len(b)

    def size(self) -> int:
        raise NotImplementedError

    def resize(self, size: int) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def lock_all(self) -> None:
        """Whole-file advisory lock (atomic-mode read-modify-write)."""

    def unlock_all(self) -> None:
        pass


class UfsFile(ADIOFile):
    def __init__(self, path: str, amode: int):
        flags = 0
        if amode & MODE_RDWR:
            flags |= os.O_RDWR
        elif amode & MODE_WRONLY:
            flags |= os.O_WRONLY
        else:
            flags |= os.O_RDONLY
        if amode & MODE_CREATE:
            flags |= os.O_CREAT
        if amode & MODE_EXCL:
            flags |= os.O_EXCL
        # note: MPI MODE_APPEND only positions file pointers at EOF
        # (io/file.py); O_APPEND must NOT be set — pwrite on an O_APPEND
        # fd ignores the offset and lands at EOF on Linux
        try:
            self.fd = os.open(path, flags, 0o644)
        except FileNotFoundError as e:
            raise MPIException(MPI_ERR_NO_SUCH_FILE, str(e)) from e
        except OSError as e:
            raise MPIException(MPI_ERR_IO, f"open {path!r}: {e}") from e
        self.path = path

    # Linux caps a single pread/pwrite at MAX_RW_COUNT (2 GiB - 4 KiB)
    # and either may be partial anyway — always loop (bigtype.c writes
    # 2^31 bytes in one MPI call and checks the last bytes)
    def read_at(self, offset: int, nbytes: int) -> bytes:
        chunks = []
        got = 0
        try:
            while got < nbytes:
                b = os.pread(self.fd, min(nbytes - got, 1 << 30),
                             offset + got)
                if not b:
                    break
                chunks.append(b)
                got += len(b)
        except OSError as e:
            raise MPIException(MPI_ERR_IO, f"pread: {e}") from e
        return chunks[0] if len(chunks) == 1 else b"".join(chunks)

    def write_at(self, offset: int, data) -> int:
        mv = as_bytes_view(data)
        total = 0
        try:
            while total < len(mv):
                n = os.pwrite(self.fd, mv[total:total + (1 << 30)],
                              offset + total)
                if n <= 0:
                    break
                total += n
        except OSError as e:
            raise MPIException(MPI_ERR_IO, f"pwrite: {e}") from e
        return total

    def read_into(self, offset: int, mv: memoryview) -> int:
        total = 0
        try:
            while total < len(mv):
                n = os.preadv(self.fd, [mv[total:total + (1 << 30)]],
                              offset + total)
                if n <= 0:
                    break
                total += n
        except OSError as e:
            raise MPIException(MPI_ERR_IO, f"preadv: {e}") from e
        return total

    def size(self) -> int:
        return os.fstat(self.fd).st_size

    def resize(self, size: int) -> None:
        os.ftruncate(self.fd, size)

    def sync(self) -> None:
        os.fsync(self.fd)

    def lock_all(self) -> None:
        import fcntl
        fcntl.lockf(self.fd, fcntl.LOCK_EX)

    def unlock_all(self) -> None:
        import fcntl
        fcntl.lockf(self.fd, fcntl.LOCK_UN)

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


# shared in-process store for memfs (thread-mode ranks see one namespace)
_MEMFS: Dict[str, bytearray] = {}
_MEMFS_LOCKS: Dict[str, threading.RLock] = {}
_MEMFS_GUARD = threading.Lock()


class MemFile(ADIOFile):
    def __init__(self, path: str, amode: int):
        with _MEMFS_GUARD:
            exists = path in _MEMFS
            if not exists:
                if not (amode & MODE_CREATE):
                    raise MPIException(MPI_ERR_NO_SUCH_FILE,
                                       f"memfs:{path} does not exist")
                _MEMFS[path] = bytearray()
                _MEMFS_LOCKS[path] = threading.RLock()
            elif amode & MODE_EXCL:
                raise MPIException(MPI_ERR_AMODE,
                                   f"memfs:{path} exists (MODE_EXCL)")
            self.buf = _MEMFS[path]
            self.lock = _MEMFS_LOCKS[path]
        self.path = path

    def read_at(self, offset: int, nbytes: int) -> bytes:
        with self.lock:
            return bytes(self.buf[offset:offset + nbytes])

    def write_at(self, offset: int, data) -> int:
        mv = as_bytes_view(data)
        n = len(mv)
        with self.lock:
            if offset + n > len(self.buf):
                self.buf.extend(b"\0" * (offset + n - len(self.buf)))
            self.buf[offset:offset + n] = mv
        return n

    def size(self) -> int:
        with self.lock:
            return len(self.buf)

    def resize(self, size: int) -> None:
        with self.lock:
            if size < len(self.buf):
                del self.buf[size:]
            else:
                self.buf.extend(b"\0" * (size - len(self.buf)))

    def sync(self) -> None:
        pass

    def lock_all(self) -> None:
        self.lock.acquire()

    def unlock_all(self) -> None:
        self.lock.release()

    def close(self) -> None:
        pass

    @staticmethod
    def delete(path: str) -> None:
        with _MEMFS_GUARD:
            if path not in _MEMFS:
                raise MPIException(MPI_ERR_NO_SUCH_FILE, f"memfs:{path}")
            del _MEMFS[path]
            _MEMFS_LOCKS.pop(path, None)


_DRIVERS = {"ufs": UfsFile, "memfs": MemFile}


def open_file(filename: str, amode: int) -> ADIOFile:
    n_access = sum(1 for bit in (MODE_RDONLY, MODE_WRONLY, MODE_RDWR)
                   if amode & bit)
    if n_access != 1:
        raise MPIException(MPI_ERR_AMODE,
                           "exactly one of RDONLY, WRONLY, RDWR required")
    if (amode & MODE_SEQUENTIAL) and (amode & MODE_RDWR):
        raise MPIException(MPI_ERR_AMODE, "SEQUENTIAL with RDWR")
    drv, path = parse_filename(filename)
    return _DRIVERS[drv](path, amode)


def delete_file(filename: str) -> None:
    drv, path = parse_filename(filename)
    if drv == "memfs":
        MemFile.delete(path)
        return
    try:
        os.unlink(path)
    except FileNotFoundError as e:
        raise MPIException(MPI_ERR_NO_SUCH_FILE, str(e)) from e
    except OSError as e:
        raise MPIException(MPI_ERR_IO, str(e)) from e

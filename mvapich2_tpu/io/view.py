"""File views: mapping the logical data stream onto physical file bytes.

Analog of ROMIO's flattened-datatype machinery (reference:
src/mpi/romio/adio/common/flatten.c + ad_read_str.c offset walking): a view
is (disp, etype, filetype); the filetype tiles the file from ``disp`` with
extent-sized tiles, and only its data bytes are visible. The logical
stream is the concatenation of every tile's data bytes.

``map_range`` flattens a logical [off, off+nbytes) window into physical
(offset, length) runs — the common currency of data sieving and two-phase
collective IO (io/file.py).
"""

from __future__ import annotations

from typing import List, Tuple

from ..core.datatype import BYTE, Datatype

Run = Tuple[int, int]          # (physical offset, nbytes)


class FileView:
    def __init__(self, disp: int = 0, etype: Datatype = BYTE,
                 filetype: Datatype = None):
        self.disp = disp
        self.etype = etype
        self.filetype = filetype or etype
        # flatten one filetype instance: [(off, len)] data runs in one tile
        self.spans: List[Run] = [(int(o), int(l))
                                 for o, l in self.filetype.flatten(1)]
        self.tile_data = sum(l for _, l in self.spans)   # data bytes/tile
        self.tile_extent = self.filetype.extent
        # prefix sums of span lengths for logical->span lookup
        self._prefix = []
        acc = 0
        for _, l in self.spans:
            self._prefix.append(acc)
            acc += l

    @property
    def contiguous(self) -> bool:
        return (len(self.spans) == 1 and self.spans[0][0] == 0
                and self.spans[0][1] == self.tile_extent)

    def physical(self, logical: int) -> int:
        """Physical byte offset of logical stream position ``logical``."""
        runs = self.map_range(logical, 1)
        return runs[0][0] if runs else self.disp

    def map_range(self, logical: int, nbytes: int) -> List[Run]:
        """Flatten logical [logical, logical+nbytes) into physical runs,
        in ascending file order, adjacent runs merged."""
        if nbytes <= 0:
            return []
        if self.contiguous:
            return [(self.disp + logical, nbytes)]
        out: List[Run] = []
        tile, rem = divmod(logical, self.tile_data)
        # find the span containing ``rem`` (linear scan; spans are few)
        si = 0
        while si < len(self.spans) and \
                rem >= self._prefix[si] + self.spans[si][1]:
            si += 1
        left = nbytes
        while left > 0:
            s_off, s_len = self.spans[si]
            within = rem - self._prefix[si]
            take = min(s_len - within, left)
            phys = self.disp + tile * self.tile_extent + s_off + within
            if out and out[-1][0] + out[-1][1] == phys:
                out[-1] = (out[-1][0], out[-1][1] + take)
            else:
                out.append((phys, take))
            left -= take
            rem += take
            si += 1
            if si >= len(self.spans):
                si = 0
                tile += 1
                rem = 0
        return out

    def stream_size_to(self, phys_end: int) -> int:
        """How many logical bytes precede physical offset ``phys_end``
        (used by get_position / seek with SEEK_END)."""
        if self.contiguous:
            return max(0, phys_end - self.disp)
        rel = phys_end - self.disp
        if rel <= 0:
            return 0
        tiles, within = divmod(rel, self.tile_extent)
        n = tiles * self.tile_data
        for (s_off, s_len), pre in zip(self.spans, self._prefix):
            if within <= s_off:
                break
            n += min(within - s_off, s_len)
        return n

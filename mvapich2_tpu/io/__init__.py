"""MPI-IO subsystem (the ROMIO analog — reference: src/mpi/romio/).

Layers: adio.py (per-driver file access: ufs/memfs), view.py (file-view
flattening), file.py (MPI_File semantics: independent/collective/shared/
ordered/nonblocking IO, data sieving, two-phase collective buffering).
"""

from .adio import (MODE_APPEND, MODE_CREATE, MODE_DELETE_ON_CLOSE,
                   MODE_EXCL, MODE_RDONLY, MODE_RDWR, MODE_SEQUENTIAL,
                   MODE_UNIQUE_OPEN, MODE_WRONLY, delete_file)
from .file import (SEEK_CUR, SEEK_END, SEEK_SET, File, file_delete,
                   file_open)

__all__ = [
    "File", "file_open", "file_delete", "delete_file",
    "MODE_RDONLY", "MODE_RDWR", "MODE_WRONLY", "MODE_CREATE", "MODE_EXCL",
    "MODE_DELETE_ON_CLOSE", "MODE_UNIQUE_OPEN", "MODE_SEQUENTIAL",
    "MODE_APPEND", "SEEK_SET", "SEEK_CUR", "SEEK_END",
]

"""MPI_File — MPI-IO semantics over the ADIO layer.

Analog of ROMIO's MPI-IO surface (reference: src/mpi/romio/mpi-io/ +
adio/common/): file views (set_view), independent IO at explicit offsets
and individual file pointers (with data sieving for noncontiguous views —
ad_read_str.c/ad_write_str.c), two-phase collective buffering for
read_at_all/write_at_all (adio/common/ad_aggregate.c + ad_write_coll.c:
file-domain partitioning among aggregators and an exchange phase), shared
file pointers (ROMIO keeps them in a hidden file; here an RMA window
fetch-add on rank 0 — the TPU-idiomatic shared counter), ordered-mode
collectives, nonblocking IO, sync/atomicity.

All offsets are internally byte-based; the MPI surface converts from etype
units at the boundary (§13.3: offsets are in etypes).
"""

from __future__ import annotations

import pickle
import queue
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..coll.algorithms import crecv, csend
from ..core import op as opmod
from ..core.datatype import BYTE, Datatype, from_numpy_dtype
from ..core.errors import (MPIException, MPI_ERR_AMODE, MPI_ERR_ARG,
                           MPI_ERR_FILE, MPI_ERR_IO)
from ..core.request import Request
from ..core.status import Status
from . import adio
from .adio import (MODE_APPEND, MODE_CREATE, MODE_DELETE_ON_CLOSE,
                   MODE_EXCL, MODE_RDONLY, MODE_RDWR, MODE_SEQUENTIAL,
                   MODE_UNIQUE_OPEN, MODE_WRONLY)
from .view import FileView

SEEK_SET, SEEK_CUR, SEEK_END = 600, 602, 604


def _resolve(buf, count: Optional[int], datatype: Optional[Datatype]):
    if datatype is None:
        if isinstance(buf, np.ndarray):
            datatype = from_numpy_dtype(buf.dtype)
        else:
            datatype = BYTE
    if count is None:
        count = buf.size if isinstance(buf, np.ndarray) \
            else len(buf) // max(datatype.size, 1)
    return count, datatype


class File:
    """An open MPI file (collective over the opening comm)."""

    def __init__(self, comm, filename: str, amode: int, info=None):
        self.comm = comm.dup()            # IO traffic on a private comm
        self.filename = filename
        self.amode = amode
        self.info = dict(info or {})
        self.atomicity = False
        self.closed = False
        self.fh = adio.open_file(filename, amode)
        self.view = FileView()
        self._pos = 0                     # individual pointer, bytes
        self._lock = threading.Lock()     # pointer + view updates
        self._worker: Optional[threading.Thread] = None   # i-op drain
        self._q: Optional[queue.Queue] = None
        # shared file pointer: an int64 on rank 0, fetch-add via RMA
        self._sp_win = self.comm.win_allocate(8 if self.comm.rank == 0
                                              else 0)
        if self.comm.rank == 0:
            self._sp_win.base[:8] = 0
        if amode & MODE_APPEND:
            # MPI §13.2.1: ALL file pointers start at end of file
            eof = self.view.stream_size_to(self.fh.size())
            self._pos = eof
            if self.comm.rank == 0:
                self._sp_win.base[:8] = np.frombuffer(
                    int(eof).to_bytes(8, "little", signed=True), np.uint8)
        self.comm.barrier()               # open is collective

    # ------------------------------------------------------------------
    def _check(self, writing: bool = False) -> None:
        if self.closed:
            raise MPIException(MPI_ERR_FILE, "file is closed")
        if writing and (self.amode & MODE_RDONLY):
            # ROMIO reports this as the access class, not a bad amode
            # (errors/io/openerr.c accepts READ_ONLY or ACCESS)
            from ..core.errors import MPI_ERR_READ_ONLY
            raise MPIException(MPI_ERR_READ_ONLY,
                               "write on MODE_RDONLY file")
        if not writing and (self.amode & MODE_WRONLY):
            raise MPIException(MPI_ERR_AMODE, "read on MODE_WRONLY file")

    # -- view ----------------------------------------------------------
    def set_view(self, disp: int = 0, etype: Datatype = BYTE,
                 filetype: Optional[Datatype] = None, datarep: str =
                 "native", info=None) -> None:
        self._check_closed()
        if datarep != "native":
            from ..core.errors import MPI_ERR_UNSUPPORTED_DATAREP
            raise MPIException(MPI_ERR_UNSUPPORTED_DATAREP,
                               f"datarep {datarep!r} unsupported")
        with self._lock:
            self.view = FileView(disp, etype, filetype)
            self._pos = 0

    def get_view(self):
        return (self.view.disp, self.view.etype, self.view.filetype,
                "native")

    def _check_closed(self):
        if self.closed:
            raise MPIException(MPI_ERR_FILE, "file is closed")

    # -- raw run IO (data sieving for noncontiguous views) -------------
    _SIEVE_MAX = 4 << 20

    def _read_runs(self, runs: List[Tuple[int, int]], out: bytearray) -> int:
        """Fill ``out`` from physical runs; data sieving: one big pread
        spanning the runs when the holes are small (ad_read_str.c)."""
        if not runs:
            return 0
        lo, hi = runs[0][0], runs[-1][0] + runs[-1][1]
        total = sum(l for _, l in runs)
        got = 0
        if len(runs) > 1 and hi - lo <= max(self._SIEVE_MAX, total * 2):
            blob = self.fh.read_at(lo, hi - lo)
            pos = 0
            for off, ln in runs:
                piece = blob[off - lo:off - lo + ln]
                out[pos:pos + len(piece)] = piece
                pos += ln           # short file: later runs read as holes
                got += len(piece)
        else:
            pos = 0
            for off, ln in runs:
                piece = self.fh.read_at(off, ln)
                out[pos:pos + len(piece)] = piece
                pos += ln
                got += len(piece)
        return got

    def _write_runs(self, runs: List[Tuple[int, int]], data) -> int:
        """Write ``data`` over physical runs; read-modify-write sieving
        under atomicity, plain per-run writes otherwise."""
        if not runs:
            return 0
        # zero-copy byte view — bigtype-scale payloads must not be
        # duplicated here (the pack already produced the one copy)
        from ..core.datatype import as_bytes_view
        data = as_bytes_view(data)
        if self.atomicity:
            self.fh.lock_all()
        try:
            pos = 0
            for off, ln in runs:
                self.fh.write_at(off, data[pos:pos + ln])
                pos += ln
            return pos
        finally:
            if self.atomicity:
                self.fh.unlock_all()

    # -- independent, explicit offset ----------------------------------
    def read_at(self, offset: int, buf, count: Optional[int] = None,
                datatype: Optional[Datatype] = None,
                view: Optional[FileView] = None) -> Status:
        """``offset`` in etype units (MPI semantics). ``view`` overrides
        the file's current view — nonblocking ops capture the view at
        post time (§13.4.2: a later set_view must not retarget them)."""
        self._check(writing=False)
        v = view if view is not None else self.view
        count, datatype = _resolve(buf, count, datatype)
        nbytes = count * datatype.size
        runs = v.map_range(offset * v.etype.size, nbytes)
        if len(runs) == 1 and datatype.is_contiguous:
            # zero-copy: one physical run straight into the user buffer
            from ..core.datatype import as_bytes_view
            mv = as_bytes_view(buf, writable=True)[:runs[0][1]]
            got = self.fh.read_into(runs[0][0], mv)
            return Status(count=min(got, nbytes))
        out = bytearray(nbytes)
        got = self._read_runs(runs, out)
        datatype.unpack(np.frombuffer(out, np.uint8, count=nbytes),
                        buf, count)
        st = Status(count=min(got, nbytes))
        return st

    def write_at(self, offset: int, buf, count: Optional[int] = None,
                 datatype: Optional[Datatype] = None,
                 view: Optional[FileView] = None) -> Status:
        self._check(writing=True)
        v = view if view is not None else self.view
        count, datatype = _resolve(buf, count, datatype)
        nbytes = count * datatype.size
        if datatype.is_contiguous:
            # zero-copy: the user buffer IS the payload
            from ..core.datatype import as_bytes_view
            payload = as_bytes_view(buf)[:nbytes]
        else:
            payload = np.asarray(datatype.pack(buf, count))
        runs = v.map_range(offset * v.etype.size, nbytes)
        n = self._write_runs(runs, payload)
        return Status(count=n)

    # -- individual file pointer ---------------------------------------
    def _advance(self, nbytes: int, reading: bool = False) -> int:
        """Atomically reserve [pos, pos+nbytes) and return the old pos.

        For reads the advance is clamped to the last whole-etype boundary
        of the view's stream so a short read at EOF leaves the pointer
        after the last etype actually read (MPI-3.1 §13.4.3), not past it
        into a hole — and a drain loop sees count 0 at EOF.
        """
        with self._lock:
            old = self._pos
            new = self._pos + nbytes
            if reading:
                es = max(self.view.etype.size, 1)
                end = self.view.stream_size_to(self.fh.size())
                end -= end % es
                new = min(new, max(end, old))
            self._pos = new
        return old

    def read(self, buf, count: Optional[int] = None,
             datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size, reading=True)
        return self.read_at(self._etypes(old), buf, count, datatype)

    def write(self, buf, count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size)
        return self.write_at(self._etypes(old), buf, count, datatype)

    def _etypes(self, nbytes: int) -> int:
        return nbytes // max(self.view.etype.size, 1)

    def seek(self, offset: int, whence: int = SEEK_SET) -> None:
        """``offset`` in etype units."""
        self._check_closed()
        nb = offset * self.view.etype.size
        with self._lock:
            if whence == SEEK_SET:
                new = nb
            elif whence == SEEK_CUR:
                new = self._pos + nb
            elif whence == SEEK_END:
                new = self.view.stream_size_to(self.fh.size()) + nb
            else:
                raise MPIException(MPI_ERR_ARG, f"bad whence {whence}")
            if new < 0:
                raise MPIException(MPI_ERR_ARG, "seek before file start")
            self._pos = new

    def get_position(self) -> int:
        return self._etypes(self._pos)

    def get_byte_offset(self, offset: int) -> int:
        return self.view.physical(offset * self.view.etype.size)

    # -- collective (two-phase) ----------------------------------------
    def read_at_all(self, offset: int, buf, count: Optional[int] = None,
                    datatype: Optional[Datatype] = None,
                    view: Optional[FileView] = None) -> Status:
        return self._coll_io(offset, buf, count, datatype, writing=False,
                             view=view)

    def write_at_all(self, offset: int, buf, count: Optional[int] = None,
                     datatype: Optional[Datatype] = None,
                     view: Optional[FileView] = None) -> Status:
        return self._coll_io(offset, buf, count, datatype, writing=True,
                             view=view)

    def read_all(self, buf, count: Optional[int] = None,
                 datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size, reading=True)
        return self.read_at_all(self._etypes(old), buf, count, datatype)

    def write_all(self, buf, count: Optional[int] = None,
                  datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size)
        return self.write_at_all(self._etypes(old), buf, count, datatype)

    def _coll_io(self, offset: int, buf, count, datatype,
                 writing: bool,
                 view: Optional[FileView] = None) -> Status:
        """Two-phase collective IO (ad_write_coll.c analog): partition the
        aggregate file range into per-rank file domains; each rank ships
        the run pieces that fall into domain d to aggregator d; aggregators
        do one contiguous (sieved) file access per domain."""
        self._check(writing=writing)
        comm = self.comm
        v = view if view is not None else self.view
        if comm.size == 1:
            # degenerate collective: skip the exchange entirely (matters
            # at bigtype scale — no 2 GiB pickle round-trip to self)
            return (self.write_at(offset, buf, count, datatype, view=v)
                    if writing
                    else self.read_at(offset, buf, count, datatype,
                                      view=v))
        count, datatype = _resolve(buf, count, datatype)
        nbytes = count * datatype.size
        runs = v.map_range(offset * v.etype.size, nbytes)
        data = memoryview(np.asarray(datatype.pack(buf, count)).tobytes()) \
            if writing else None
        # aggregate extent over all ranks (runs are ascending)
        lo = runs[0][0] if runs else (1 << 62)
        hi = runs[-1][0] + runs[-1][1] if runs else 0
        ext = np.zeros(2, np.int64)
        comm.allreduce(np.array([-lo, hi], np.int64), ext, op=opmod.MAX)
        glo, ghi = -int(ext[0]), int(ext[1])
        if ghi <= glo:                       # nobody moves data
            comm.barrier()
            return Status(count=0)
        P = comm.size
        dsz = -(-(ghi - glo) // P)           # file-domain size (ceil)

        # split my runs into per-domain pieces; record the production
        # order so a read can be reassembled into logical-stream order
        per_dest: List[List[Tuple[int, int, bytes]]] = [[] for _ in range(P)]
        emit: List[int] = []                 # domain of the k-th piece
        pos = 0
        for off, ln in runs:
            while ln > 0:
                d = min((off - glo) // dsz, P - 1)
                dom_end = ghi if d == P - 1 else glo + (d + 1) * dsz
                take = min(ln, dom_end - off)
                per_dest[d].append(
                    (off, take, bytes(data[pos:pos + take]) if writing
                     else b""))
                emit.append(d)
                off += take
                ln -= take
                pos += take
        got = self._exchange_and_apply(per_dest, emit, writing, glo, dsz,
                                       ghi)
        if not writing:
            actual = min(len(got), nbytes)
            if len(got) < nbytes:            # short read (EOF holes)
                got = got + b"\0" * (nbytes - len(got))
            datatype.unpack(np.frombuffer(got[:nbytes], np.uint8), buf,
                            count)
            return Status(count=actual)
        return Status(count=nbytes)

    def _exchange_and_apply(self, per_dest, emit, writing: bool, glo: int,
                            dsz: int, ghi: int) -> bytes:
        """The exchange phase: pickled piece lists pairwise; aggregators
        apply writes / serve reads from one sieved access per domain."""
        comm = self.comm
        P = comm.size
        tag = comm.next_coll_tag()

        def a2a_blobs(blobs: List[bytes], t: int) -> List[bytes]:
            lens = np.array([len(b) for b in blobs], np.int64)
            all_lens = np.empty(P, np.int64)
            comm.alltoall(lens, all_lens, count=1)
            rreqs = [(src, np.empty(int(all_lens[src]), np.uint8))
                     for src in range(P)]
            rqs = [crecv(comm, rb, src, t) for src, rb in rreqs]
            sqs = [csend(comm, np.frombuffer(blobs[d], np.uint8), d, t)
                   for d in range(P)]
            for q in rqs + sqs:
                q.wait()
            return [rb.tobytes() for _, rb in rreqs]

        incoming = [pickle.loads(b) for b in a2a_blobs(
            [pickle.dumps(per_dest[d], protocol=4) for d in range(P)], tag)]

        if writing:
            if self.atomicity:
                self.fh.lock_all()
            try:
                for pieces in incoming:
                    for off, ln, payload in pieces:
                        self.fh.write_at(off, payload)
            finally:
                if self.atomicity:
                    self.fh.unlock_all()
            comm.barrier()        # all domains durable before return
            return b""

        # read: one sieved access over my file domain, serve pieces back
        d_lo = glo + comm.rank * dsz
        d_hi = ghi if comm.rank == P - 1 else min(glo + (comm.rank + 1)
                                                  * dsz, ghi)
        dom = self.fh.read_at(d_lo, d_hi - d_lo) if d_hi > d_lo else b""
        replies = []
        for pieces in incoming:
            parts = [bytes(dom[off - d_lo:off - d_lo + ln])
                     for off, ln, _ in pieces]
            replies.append(pickle.dumps(parts, protocol=4))
        by_src = [pickle.loads(b) for b in a2a_blobs(replies, tag + 1)]
        # reassemble in production order: piece k came from domain emit[k]
        out = bytearray()
        next_idx = [0] * P
        for d in emit:
            out.extend(by_src[d][next_idx[d]])
            next_idx[d] += 1
        return bytes(out)

    # -- shared file pointer -------------------------------------------
    def _shared_fetch_add(self, nbytes: int) -> int:
        from ..rma.win import LOCK_EXCLUSIVE
        old = np.zeros(1, np.int64)
        add = np.array([nbytes], np.int64)
        self._sp_win.lock(0, LOCK_EXCLUSIVE)
        self._sp_win.fetch_and_op(add, old, 0, 0, op=opmod.SUM)
        self._sp_win.unlock(0)
        return int(old[0])

    def _shared_advance_read(self, nbytes: int) -> int:
        """Shared-pointer advance clamped to the last whole-etype boundary
        of the stream (EOF): a short read must leave the pointer after the
        last etype read, and a multi-rank drain loop must observe EOF."""
        from ..rma.win import LOCK_EXCLUSIVE
        es = max(self.view.etype.size, 1)
        end = self.view.stream_size_to(self.fh.size())
        end -= end % es
        cur = np.zeros(1, np.int64)
        self._sp_win.lock(0, LOCK_EXCLUSIVE)
        self._sp_win.get(cur, 0, 0)
        self._sp_win.flush(0)
        old = int(cur[0])
        new = min(old + nbytes, max(end, old))
        self._sp_win.put(np.array([new], np.int64), 0, 0)
        self._sp_win.unlock(0)
        return old

    def read_shared(self, buf, count: Optional[int] = None,
                    datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        old = self._shared_advance_read(count * datatype.size)
        return self.read_at(self._etypes(old), buf, count, datatype)

    def write_shared(self, buf, count: Optional[int] = None,
                     datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._shared_fetch_add(count * datatype.size)
        return self.write_at(self._etypes(old), buf, count, datatype)

    def seek_shared(self, offset: int, whence: int = SEEK_SET) -> None:
        """Collective; all ranks must give the same offset."""
        nb = offset * self.view.etype.size
        if whence == SEEK_CUR or whence == SEEK_END:
            base = self.view.stream_size_to(self.fh.size()) \
                if whence == SEEK_END else self._shared_fetch_add(0)
            nb += base
        if self.comm.rank == 0:
            from ..rma.win import LOCK_EXCLUSIVE
            self._sp_win.lock(0, LOCK_EXCLUSIVE)
            self._sp_win.base[:8] = np.frombuffer(
                int(nb).to_bytes(8, "little", signed=True), np.uint8)
            self._sp_win.unlock(0)
        self.comm.barrier()

    def get_position_shared(self) -> int:
        return self._etypes(self._shared_fetch_add(0))

    # -- ordered mode --------------------------------------------------
    def _ordered_base(self, nbytes: int) -> int:
        sizes = self.comm.allgather(np.array([nbytes], np.int64), count=1)
        total = int(sizes.sum())
        if self.comm.rank == 0:
            base = self._shared_fetch_add(total)
        else:
            base = 0
        b = np.array([base], np.int64)
        self.comm.bcast(b, root=0)
        return int(b[0]) + int(sizes[:self.comm.rank].sum())

    def read_ordered(self, buf, count: Optional[int] = None,
                     datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        my = self._ordered_base(count * datatype.size)
        return self.read_at(self._etypes(my), buf, count, datatype)

    def write_ordered(self, buf, count: Optional[int] = None,
                      datatype: Optional[Datatype] = None) -> Status:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        my = self._ordered_base(count * datatype.size)
        return self.write_at(self._etypes(my), buf, count, datatype)

    # -- nonblocking ---------------------------------------------------
    # One worker thread per file drains a FIFO of posted i-ops: every
    # rank posts collective i-ops in the same program order, so the
    # workers across ranks execute matching ops in matching order and
    # two outstanding collectives can never interleave their exchange
    # traffic on the file's dup comm (ROMIO serializes per-file the
    # same way via the ADIOI request queue).
    def _async(self, fn, *a) -> Request:
        req = Request(self.comm.u.engine, "io")
        with self._lock:
            if self._worker is None:
                self._q = queue.Queue()
                self._worker = threading.Thread(
                    target=self._drain, daemon=True, name="mpiio")
                self._worker.start()
        self._q.put((fn, a, req))
        return req

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, a, req = item
            try:
                st = fn(*a)
                req.status = st
                req.complete()
            except MPIException as e:
                req.complete(e)

    def iread_at(self, offset, buf, count=None, datatype=None) -> Request:
        return self._async(self.read_at, offset, buf, count, datatype,
                           self.view)

    def iwrite_at(self, offset, buf, count=None, datatype=None) -> Request:
        return self._async(self.write_at, offset, buf, count, datatype,
                           self.view)

    def iread(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        # no EOF clamp for nonblocking ops: the short-read amount is
        # unknowable at issue time, and an outstanding iwrite may extend
        # the file before this read executes — the pointer advances by
        # the full request (standard practice for i-ops)
        old = self._advance(count * datatype.size)
        return self._async(self.read_at, self._etypes(old), buf, count,
                           datatype, self.view)

    def iwrite(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size)
        return self._async(self.write_at, self._etypes(old), buf, count,
                           datatype, self.view)

    # nonblocking collectives (MPI-3.1 §13.4.5; one outstanding op per
    # file is the supported discipline — the op's collective exchange
    # runs on the file's private dup comm inside the worker thread)
    def iread_at_all(self, offset, buf, count=None,
                     datatype=None) -> Request:
        return self._async(self.read_at_all, offset, buf, count, datatype,
                           self.view)

    def iwrite_at_all(self, offset, buf, count=None,
                      datatype=None) -> Request:
        return self._async(self.write_at_all, offset, buf, count,
                           datatype, self.view)

    def iread_all(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size)
        return self._async(self.read_at_all, self._etypes(old), buf,
                           count, datatype, self.view)

    def iwrite_all(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._advance(count * datatype.size)
        return self._async(self.write_at_all, self._etypes(old), buf,
                           count, datatype, self.view)

    # ordered-mode split collectives: the rank-ordered base is computed
    # collectively at post time (begin IS collective), the IO overlaps
    def iread_ordered(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        my = self._ordered_base(count * datatype.size)
        return self._async(self.read_at, self._etypes(my), buf, count,
                           datatype, self.view)

    def iwrite_ordered(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        my = self._ordered_base(count * datatype.size)
        return self._async(self.write_at, self._etypes(my), buf, count,
                           datatype, self.view)

    def iread_shared(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=False)
        count, datatype = _resolve(buf, count, datatype)
        # full advance, no EOF clamp — see iread
        old = self._shared_fetch_add(count * datatype.size)
        return self._async(self.read_at, self._etypes(old), buf, count,
                           datatype, self.view)

    def iwrite_shared(self, buf, count=None, datatype=None) -> Request:
        self._check(writing=True)
        count, datatype = _resolve(buf, count, datatype)
        old = self._shared_fetch_add(count * datatype.size)
        return self._async(self.write_at, self._etypes(old), buf, count,
                           datatype, self.view)

    # -- management ----------------------------------------------------
    def get_size(self) -> int:
        self._check_closed()
        return self.fh.size()

    def set_size(self, size: int) -> None:
        """Collective."""
        self._check(writing=True)
        if self.comm.rank == 0:
            self.fh.resize(size)
        self.comm.barrier()

    def preallocate(self, size: int) -> None:
        self._check(writing=True)
        if self.comm.rank == 0 and self.fh.size() < size:
            self.fh.resize(size)
        self.comm.barrier()

    def get_amode(self) -> int:
        return self.amode

    def get_group(self):
        return self.comm.group

    def get_info(self):
        return dict(self.info)

    def set_info(self, info) -> None:
        self.info.update(info or {})

    def set_atomicity(self, flag: bool) -> None:
        self.atomicity = bool(flag)

    def get_atomicity(self) -> bool:
        return self.atomicity

    def sync(self) -> None:
        """Collective flush."""
        self._check_closed()
        self.fh.sync()
        self.comm.barrier()

    def close(self) -> None:
        if self.closed:
            return
        if self._worker is not None:      # drain pending i-ops first
            self._q.put(None)
            self._worker.join()
            self._worker = None
        self.comm.barrier()
        self.fh.sync()
        self.fh.close()
        if (self.amode & MODE_DELETE_ON_CLOSE) and self.comm.rank == 0:
            try:
                adio.delete_file(self.filename)
            except MPIException:
                pass
        self.comm.barrier()
        self._sp_win.free()
        self.comm.free()
        self.closed = True

    def __repr__(self):
        return f"File({self.filename!r}, amode={self.amode})"


def file_open(comm, filename: str, amode: int = MODE_RDONLY,
              info=None) -> File:
    return File(comm, filename, amode, info)


def file_delete(filename: str, info=None) -> None:
    adio.delete_file(filename)

"""Pipeline parallelism over a mesh axis (GPipe-style).

Stages are shards along the "pp" axis; activations move stage->stage with
ppermute ring shifts (the ICI neighbor transfer), microbatches streamed so
all stages fill. This is the pp building block the dryrun exercises; the
reference analog is the mpispawn tree's neighbor pattern re-purposed as a
compute pipeline (communication skeleton = MPI_Sendrecv chain).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size as _ops_axis_size
from ..ops import ring_shift


def pipeline_apply(stage_fn: Callable, stage_params, micro, axis: str):
    """Run ``stage_fn(params, x)`` as a pipeline over ``axis``.

    stage_params: this shard's stage parameters.
    micro: [n_micro, mb, ...] microbatches (same on every stage; only
    stage 0 injects them).
    Returns [n_micro, mb, ...] outputs (valid on the LAST stage; other
    stages return zeros — broadcast with a psum/bcast if needed)."""
    p = _ops_axis_size(axis)
    stage = lax.axis_index(axis)
    n_micro = micro.shape[0]
    mb_shape = micro.shape[1:]
    ticks = n_micro + p - 1

    outs0 = jnp.zeros((n_micro,) + mb_shape, micro.dtype)
    carry0 = jnp.zeros(mb_shape, micro.dtype)

    def tick(carry, t):
        act_in, outs = carry
        # stage 0 injects microbatch t (while available); others consume
        # what arrived from the left
        inject = jnp.where(t < n_micro, t, 0)
        act = jnp.where(stage == 0, micro[inject], act_in)
        out = stage_fn(stage_params, act)
        # last stage emits a result once the pipeline is full
        emit_idx = t - (p - 1)
        do_emit = jnp.logical_and(stage == p - 1, emit_idx >= 0)
        outs = lax.cond(
            do_emit,
            lambda o: o.at[jnp.maximum(emit_idx, 0)].set(out),
            lambda o: o, outs)
        nxt = ring_shift(out, axis, 1)   # stage i -> i+1 (wrap ignored)
        return (nxt, outs), None

    (_, outs), _ = lax.scan(tick, (carry0, outs0), jnp.arange(ticks))
    return outs

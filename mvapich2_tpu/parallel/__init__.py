from . import mesh
from .mesh import MeshComm, make_mesh, mesh_shape_for

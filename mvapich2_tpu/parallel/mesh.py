"""Device-mesh communicators — binding MPI-style semantics to jax Meshes.

The analog of the reference's rank<->VC binding (SURVEY §3.1: MPIDI_PG /
VC tables) re-imagined for SPMD: a MeshComm names a mesh axis; "ranks" are
shards along that axis; collectives are the XLA-native ops from
mvapich2_tpu.ops. Hierarchical (2-level) communicators map to factored mesh
axes — intra-host axis over ICI-local devices + inter-host axis over DCN —
mirroring create_2level_comm's shmem/leader split (create_2level_comm.c:
57-96) with XLA's per-axis collective lowering doing the topology routing.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import ops
from ..utils.detect import detect
from ..utils.mlog import get_logger

log = get_logger("mesh")

shard_map = jax.shard_map if hasattr(jax, "shard_map") else None
if shard_map is None:  # jax < 0.5: experimental shard_map, check_rep era
    import inspect

    from jax.experimental.shard_map import shard_map as _sm

    _SM_PARAMS = set(inspect.signature(_sm).parameters)

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kw):
        # callers use the modern keyword (check_vma); the experimental
        # signature spells it check_rep — translate, and drop anything
        # the installed version does not know rather than TypeError-ing
        # the whole device path (the r6 seed failure mode)
        if "check_vma" in kw and "check_vma" not in _SM_PARAMS:
            kw["check_rep"] = kw.pop("check_vma")
        kw = {k: v for k, v in kw.items() if k in _SM_PARAMS}
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)


def mesh_shape_for(n: int, naxes: int = 2) -> Tuple[int, ...]:
    """Near-square factorization of n devices into naxes axes (the arch
    detect -> topology-shape step, mv2_arch_detect.c analog)."""
    if naxes == 1:
        return (n,)
    best = (1, n)
    for a in range(1, int(math.isqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    if naxes == 2:
        return best
    rest = mesh_shape_for(best[1], naxes - 1)
    return (best[0],) + rest


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("x",),
              devices=None) -> Mesh:
    """Build a Mesh over the available devices (row-major assignment)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        shape = mesh_shape_for(n, len(axis_names))
    total = math.prod(shape)
    if total > n:
        raise ValueError(f"mesh shape {shape} needs {total} devices, "
                         f"have {n}")
    arr = np.asarray(devices[:total]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


class MeshComm:
    """A communicator over one mesh axis, several, or all axes.

    Inside a jitted/shard_mapped function, methods are the XLA collectives;
    outside, ``run`` wraps a function in shard_map over the mesh. The
    ``split``/``sub`` methods mirror MPI_Comm_split along orthogonal axes.

    ``axis`` may be a single axis name (the 1-D ring dispatch every PR
    before 20 had) or an ordered sequence of names — then the comm spans
    the product extent with ranks row-major over the named axes, and
    allreduce dispatches the multi-axis torus decomposition
    (ops/pallas_ici.ici_all_reduce_mesh: per-axis RS/AG ring phases
    above the dev_tier_axes_min edge). Movement collectives compose
    per-axis phases in the rank-order-preserving direction (gather
    innermost-first, scatter outermost-first, bcast from the root's
    per-axis coordinates innermost-first).
    """

    def __init__(self, mesh: Mesh, axis=None):
        self.mesh = mesh
        if axis is None:
            axis = mesh.axis_names[0]
        if isinstance(axis, (tuple, list)):
            self.axes: Tuple[str, ...] = tuple(str(a) for a in axis)
        else:
            self.axes = (str(axis),)
        for a in self.axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in {mesh.axis_names}")
        self.axis = self.axes[0]

    # -- introspection ---------------------------------------------------
    @property
    def multi_axis(self) -> bool:
        return len(self.axes) > 1

    @property
    def size(self) -> int:
        return math.prod(self.mesh.shape[a] for a in self.axes)

    def axis_sizes(self) -> Tuple[Tuple[str, int], ...]:
        """Ordered (axis, extent) pairs this comm spans — the ``axes``
        argument of the ops-level multi-axis dispatchers."""
        return tuple((a, self.mesh.shape[a]) for a in self.axes)

    def rank(self):
        """Traced rank (call inside shard_map): the row-major flattened
        index over this comm's axes."""
        idx = ops.axis_rank(self.axes[0])
        for a in self.axes[1:]:
            idx = idx * self.mesh.shape[a] + ops.axis_rank(a)
        return idx

    def _coords(self, rank: int) -> Tuple[int, ...]:
        """Static per-axis coordinates of a flattened rank (row-major)."""
        out = []
        for a in reversed(self.axes):
            out.append(rank % self.mesh.shape[a])
            rank //= self.mesh.shape[a]
        return tuple(reversed(out))

    def sub(self, axis) -> "MeshComm":
        """Communicator over different axis/axes of the same mesh — the
        2-level split (e.g. 'host' × 'dcn' axes)."""
        return MeshComm(self.mesh, axis)

    # -- collectives (inside shard_map) ----------------------------------
    def allreduce(self, x, op: str = "sum"):
        if self.multi_axis:
            from ..ops import pallas_ici
            return pallas_ici.ici_all_reduce_mesh(
                x, self.axis_sizes(), op)
        return ops.allreduce(x, self.axis, op)

    def bcast(self, x, root: int = 0):
        if self.multi_axis:
            # innermost axis first: after bcasting axis k from the
            # root's coordinate on k, the root's whole k-line carries
            # the payload, so each outer phase fans a true copy
            coords = self._coords(root)
            for a, c in reversed(tuple(zip(self.axes, coords))):
                x = ops.bcast(x, a, c)
            return x
        return ops.bcast(x, self.axis, root)

    def all_gather(self, x, tiled: bool = False, gather_axis: int = 0):
        if self.multi_axis:
            for a in reversed(self.axes):   # innermost first: rank order
                x = ops.all_gather(x, a, tiled=tiled,
                                   gather_axis=gather_axis)
            return x
        return ops.all_gather(x, self.axis, tiled=tiled,
                              gather_axis=gather_axis)

    def reduce_scatter(self, x, scatter_dimension: int = 0):
        if self.multi_axis:
            for a in self.axes:             # outermost first: rank order
                x = ops.reduce_scatter(x, a,
                                       scatter_dimension=scatter_dimension)
            return x
        return ops.reduce_scatter(x, self.axis,
                                  scatter_dimension=scatter_dimension)

    def all_to_all(self, x, split_axis: int = 0, concat_axis: int = 0):
        return ops.all_to_all(x, self.axis, split_axis=split_axis,
                              concat_axis=concat_axis)

    def ring_shift(self, x, shift: int = 1):
        return ops.ring_shift(x, self.axis, shift)

    def halo_exchange(self, x, halo: int, dim: int = 0,
                      periodic: bool = True):
        return ops.halo_exchange(x, self.axis, halo, dim, periodic)

    def scan(self, x):
        return ops.scan_axis(x, self.axis)

    def barrier(self, token=None):
        return ops.barrier(self.axis)

    # -- launching SPMD regions ------------------------------------------
    def run(self, fn: Callable, *args, in_specs=None, out_specs=None,
            check_vma: bool = False):
        """shard_map ``fn`` over the mesh. Default: shard arg dim 0 over
        this axis; replicate output."""
        if in_specs is None:
            in_specs = tuple(P(self.axis) for _ in args)
        if out_specs is None:
            out_specs = P(self.axis)
        wrapped = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=check_vma)
        return wrapped(*args)

    def device_put_sharded(self, x, spec: Optional[P] = None):
        spec = spec if spec is not None else P(self.axis)
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def __repr__(self):
        return (f"MeshComm(axis={self.axis!r}, size={self.size}, "
                f"mesh={dict(self.mesh.shape)})")


@functools.lru_cache(maxsize=None)
def default_mesh_comm(naxes: int = 1) -> MeshComm:
    names = ("x", "y", "z")[:naxes]
    return MeshComm(make_mesh(axis_names=names))

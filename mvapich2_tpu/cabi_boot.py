"""Light C-ABI entry: what libmpi.so imports at MPI_Init.

The embedded interpreter used to import the full shim (numpy + the
whole protocol stack, ~400 ms) before MPI_Init could even start the
KVS exchange. Now libmpi.c imports THIS module — stdlib-only — and
``init()`` runs only the light boot (runtime/boot.py): KVS connect,
one batched fence for node topology + init-time cards, leader segment
provisioning (or a daemon warm-attach). World construction is deferred
to the first MPI call that needs it.

Dispatch contract: libmpi.c resolves every shim function with
``PyObject_GetAttrString`` against this module. The calls a C program
can legally make against an unbuilt world (rank/size of the
predefined comms, Initialized/Finalized, Finalize, Abort, processor
name) are implemented here from the BootState; everything else falls
into ``__getattr__``, which builds the world (imports cshim — the one
deferred heavy import) and forwards. tests/test_cabi.py guards that
importing this module never pulls numpy/jax.
"""

from __future__ import annotations

import os
import threading

from .runtime import boot as _boot
from .utils.config import get_config

_lock = threading.RLock()
_initialized = False
_finalized = False
_cshim = None                # the real shim, once the world is built


def _ensure_world():
    """Deferred world build: import the full shim and construct the
    Universe from the BootState. Idempotent and thread-safe; every
    forwarded attribute funnels through here."""
    global _cshim
    if _cshim is not None:
        return _cshim
    with _lock:
        if _cshim is not None:
            return _cshim
        import sys
        if sys.flags.no_site:
            # libmpi.c embeds the interpreter with Py_NoSiteFlag (the
            # light boot is stdlib-only); the heavy stack below needs
            # site-packages (.pth processing), so run site now, once
            import site
            site.main()
        from . import cshim as shim
        if _initialized and not shim.initialized():
            shim.adopt_boot()
        _cshim = shim
        return shim


def __getattr__(name: str):
    if name.startswith("__"):
        raise AttributeError(name)
    return getattr(_ensure_world(), name)


# ---------------------------------------------------------------------------
# calls that must work against an unbuilt world
# ---------------------------------------------------------------------------

def init() -> int:
    global _initialized
    with _lock:
        if _initialized:
            return 0
        # debugging aid (MV2_DEBUG-style): SIGUSR1 dumps all Python
        # thread stacks of a rank — how a hung run is diagnosed
        try:
            import faulthandler
            import signal as _sig
            faulthandler.register(_sig.SIGUSR1, all_threads=True)
        except (ImportError, AttributeError, ValueError):
            pass
        b = _boot.light_boot_from_env(cabi=True)
        _initialized = True
        if b is None \
                or not int(get_config().get("LAZY_INIT", 1) or 0) \
                or not int(get_config().get("LAZY_WIRING", 1) or 0):
            # singletons/spawned children have no light path, and
            # MV2T_LAZY_INIT=0 / MV2T_LAZY_WIRING=0 restore the eager
            # build (bit-identical startup semantics)
            _ensure_world()
    return 0


def initialized() -> int:
    return 1 if _initialized else 0


def finalized() -> int:
    if _cshim is not None:
        return _cshim.finalized()
    return 1 if _finalized else 0


def finalize() -> int:
    global _finalized
    with _lock:
        if _finalized:
            return 0
        b = _boot.current_boot()
        if _cshim is None and b is not None and not b.ft \
                and not b.any_failed():
            # world never built here: meet everyone at the finalize
            # rendezvous; stay light when the whole job stayed light
            b.finalized = True
            if not _boot.finalize_rendezvous(b):
                _boot.close_light(b)
                _finalized = True
                return 0
            # a peer built: join the collective teardown
        rc = _ensure_world().finalize()
        _finalized = True
        return rc


def comm_rank(ch: int) -> int:
    if _cshim is None:
        b = _boot.current_boot()
        if ch == 1:                 # MPI_COMM_SELF
            return 0
        if ch == 0:                 # MPI_COMM_WORLD
            return b.rank if b is not None \
                else int(os.environ.get("MV2T_RANK", "0"))
    return _ensure_world().comm_rank(ch)


def comm_size(ch: int) -> int:
    if _cshim is None:
        b = _boot.current_boot()
        if ch == 1:
            return 1
        if ch == 0:
            return b.size if b is not None \
                else int(os.environ.get("MV2T_SIZE", "1"))
    return _ensure_world().comm_size(ch)


def get_processor_name() -> str:
    b = _boot.current_boot()
    if b is not None:
        return b.nodekey
    import socket
    return socket.gethostname()


def abort(ch: int, code: int) -> int:
    """Best-effort job kill, world or no world: broadcast the abort
    event through the KVS (the launcher watches it) and exit hard."""
    if _cshim is not None:
        return _cshim.abort(ch, code)
    b = _boot.current_boot()
    if b is not None:
        try:
            b.kvs.abort(f"rank {b.rank} called MPI_Abort({code})")
        except Exception:
            pass
    os._exit(code if 0 < code < 256 else 1)

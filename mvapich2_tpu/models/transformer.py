"""Flagship model: a transformer trained entirely through the framework's
collective layer, demonstrating every parallelism axis the reference's
communication patterns support (SURVEY §5.7):

  dp — data parallel:      gradient psum over the "dp" axis (allreduce —
                           the north-star collective)
  sp — sequence parallel:  ring attention (ppermute KV ring)
  tp — tensor parallel:    column/row-parallel matmuls with psum reduction
                           (the two-level shmem-reduce analog: tp should
                           map to the intra-host mesh axis)
  ep — expert parallel:    MoE FFN with all_to_all token dispatch over the
                           dp axis (the MoE-shuffle acceptance config)

Everything is shard_map'd over a Mesh("dp", "sp", "tp") — XLA inserts the
ICI collectives; no hand-rolled transport.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.collectives import axis_size as _ops_axis_size
from ..ops import all_to_all, allreduce
from ..parallel.mesh import make_mesh, mesh_shape_for, shard_map
from .ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class Config:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    seq_len: int = 128          # global sequence length
    batch: int = 8              # global batch
    n_experts: int = 4          # MoE experts (layer 1 only), sharded over dp
    moe_layer: int = 1          # which layer index uses the MoE FFN
    dtype: Any = jnp.float32
    lr: float = 1e-2


def param_specs(cfg: Config) -> Dict[str, Any]:
    """PartitionSpec per parameter: tp-sharded matmuls, ep-sharded experts,
    everything else replicated (grads psum'd over dp+sp)."""
    specs = {
        "emb": P(),
        "ln_f": P(),
    }
    for i in range(cfg.n_layers):
        L = f"layer_{i}"
        specs[f"{L}/ln1"] = P()
        specs[f"{L}/ln2"] = P()
        specs[f"{L}/wq"] = P(None, "tp")
        specs[f"{L}/wk"] = P(None, "tp")
        specs[f"{L}/wv"] = P(None, "tp")
        specs[f"{L}/wo"] = P("tp", None)
        if i == cfg.moe_layer:
            specs[f"{L}/gate"] = P()
            specs[f"{L}/w1"] = P("dp", None, None)   # experts over ep(=dp)
            specs[f"{L}/w2"] = P("dp", None, None)
        else:
            specs[f"{L}/w1"] = P(None, "tp")
            specs[f"{L}/w2"] = P("tp", None)
    return specs


def init_params(cfg: Config, key) -> Dict[str, jnp.ndarray]:
    """Global (unsharded) parameter pytree; shard with param_specs."""
    ks = jax.random.split(key, 4 + 8 * cfg.n_layers)
    ki = iter(ks)
    D, H, F = cfg.d_model, cfg.n_heads, cfg.d_ff
    Dh = D // H
    p = {
        "emb": jax.random.normal(next(ki), (cfg.vocab, D), cfg.dtype) * 0.02,
        "ln_f": jnp.ones((D,), cfg.dtype),
    }
    for i in range(cfg.n_layers):
        L = f"layer_{i}"
        p[f"{L}/ln1"] = jnp.ones((D,), cfg.dtype)
        p[f"{L}/ln2"] = jnp.ones((D,), cfg.dtype)
        p[f"{L}/wq"] = jax.random.normal(next(ki), (D, D), cfg.dtype) * 0.02
        p[f"{L}/wk"] = jax.random.normal(next(ki), (D, D), cfg.dtype) * 0.02
        p[f"{L}/wv"] = jax.random.normal(next(ki), (D, D), cfg.dtype) * 0.02
        p[f"{L}/wo"] = jax.random.normal(next(ki), (D, D), cfg.dtype) * 0.02
        if i == cfg.moe_layer:
            p[f"{L}/gate"] = jax.random.normal(next(ki),
                                               (D, cfg.n_experts),
                                               cfg.dtype) * 0.02
            p[f"{L}/w1"] = jax.random.normal(
                next(ki), (cfg.n_experts, D, F), cfg.dtype) * 0.02
            p[f"{L}/w2"] = jax.random.normal(
                next(ki), (cfg.n_experts, F, D), cfg.dtype) * 0.02
        else:
            p[f"{L}/w1"] = jax.random.normal(next(ki), (D, F),
                                             cfg.dtype) * 0.02
            p[f"{L}/w2"] = jax.random.normal(next(ki), (F, D),
                                             cfg.dtype) * 0.02
    return p


def _layernorm(x, g):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-6) * g


def _attention_block(p, L, x, cfg: Config):
    """Ring attention over sp with heads column-sharded over tp.
    x: [B_local, T_local, D]."""
    B, T, D = x.shape
    h = _layernorm(x, p[f"{L}/ln1"])
    # local head count = H / tp (wq is [D, D/tp] on this shard)
    Hl = p[f"{L}/wq"].shape[1] // (D // cfg.n_heads)
    Dh = D // cfg.n_heads
    q = jnp.einsum("btd,de->bte", h, p[f"{L}/wq"]).reshape(B, T, Hl, Dh)
    k = jnp.einsum("btd,de->bte", h, p[f"{L}/wk"]).reshape(B, T, Hl, Dh)
    v = jnp.einsum("btd,de->bte", h, p[f"{L}/wv"]).reshape(B, T, Hl, Dh)
    attn = jax.vmap(lambda qq, kk, vv: ring_attention(qq, kk, vv, "sp"))(
        q, k, v)
    attn = attn.reshape(B, T, Hl * Dh)
    out = jnp.einsum("bte,ed->btd", attn, p[f"{L}/wo"])
    # row-parallel output projection: partial sums reduced over tp — the
    # intra-host shmem-reduce of the 2-level scheme
    out = allreduce(out, "tp")
    return x + out


def _dense_ffn(p, L, x):
    h = _layernorm(x, p[f"{L}/ln2"])
    h = jax.nn.gelu(jnp.einsum("btd,df->btf", h, p[f"{L}/w1"]))
    out = jnp.einsum("btf,fd->btd", h, p[f"{L}/w2"])
    return x + allreduce(out, "tp")


def _moe_ffn(p, L, x, cfg: Config):
    """Top-1 MoE with expert parallelism over the dp axis: tokens are
    dispatched to their expert's shard via all_to_all (BASELINE config 3's
    MoE-style shuffle) and return the same way."""
    B, T, D = x.shape
    ep = _ops_axis_size("dp")
    E_local = p[f"{L}/w1"].shape[0]          # experts on this shard
    E = E_local * ep
    h = _layernorm(x, p[f"{L}/ln2"])
    tokens = h.reshape(-1, D)                # [N, D]
    N = tokens.shape[0]
    gate = jnp.einsum("nd,de->ne", tokens, p[f"{L}/gate"])  # [N, E]
    expert = jnp.argmax(gate, axis=-1)                       # [N]
    gate_w = jax.nn.softmax(gate, axis=-1)
    sel_w = jnp.take_along_axis(gate_w, expert[:, None], axis=1)[:, 0]

    # fixed-capacity dispatch: C slots per (dest shard, local expert)
    C = max(1, (2 * N) // E)
    dest_shard = expert // E_local
    # position of each token within its expert's capacity
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    pos_in_e = jnp.cumsum(onehot, axis=0) * onehot
    slot = jnp.sum(pos_in_e, axis=-1) - 1                    # [N]
    keep = slot < C
    # buffer layout: [ep, E_local, C, D] flattened over first dim for a2a
    buf = jnp.zeros((ep, E_local, C, D), tokens.dtype)
    w_buf = jnp.zeros((ep, E_local, C), tokens.dtype)
    le = expert % E_local
    buf = buf.at[dest_shard, le, jnp.minimum(slot, C - 1)].add(
        jnp.where(keep[:, None], tokens, 0.0))
    w_buf = w_buf.at[dest_shard, le, jnp.minimum(slot, C - 1)].add(
        jnp.where(keep, sel_w, 0.0))
    # dispatch: every shard sends its [dest] slab to dest — ICI all_to_all
    recv = all_to_all(buf.reshape(ep, -1), "dp", split_axis=0,
                      concat_axis=0, tiled=False)
    recv = recv.reshape(ep, E_local, C, D)
    # expert compute on local experts (batched over source shards)
    hexp = jax.nn.gelu(jnp.einsum("secd,edf->secf", recv, p[f"{L}/w1"]))
    yexp = jnp.einsum("secf,efd->secd", hexp, p[f"{L}/w2"])
    # return shuffle
    back = all_to_all(yexp.reshape(ep, -1), "dp", split_axis=0,
                      concat_axis=0, tiled=False)
    back = back.reshape(ep, E_local, C, D)
    # gather back into token order
    y = back[dest_shard, le, jnp.minimum(slot, C - 1)]
    y = jnp.where(keep[:, None], y, 0.0) * sel_w[:, None]
    return x + y.reshape(B, T, D)


def forward(params, tokens, cfg: Config):
    """tokens: [B_local, T_local] int32 (this shard's batch x seq block).
    Returns logits [B_local, T_local, vocab]."""
    x = params["emb"][tokens]
    for i in range(cfg.n_layers):
        L = f"layer_{i}"
        x = _attention_block(params, L, x, cfg)
        if i == cfg.moe_layer and f"{L}/gate" in params:
            x = _moe_ffn(params, L, x, cfg)
        else:
            x = _dense_ffn(params, L, x)
    x = _layernorm(x, params["ln_f"])
    return jnp.einsum("btd,vd->btv", x, params["emb"])


def loss_fn(params, tokens, cfg: Config):
    """Next-token loss on this shard; psum-averaged over dp+sp."""
    logits = forward(params, tokens, cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    local = jnp.mean(nll[:, :-1])
    return lax.pmean(local, ("dp", "sp"))


def make_train_step(cfg: Config, mesh: Mesh):
    """Returns (jitted step fn, sharded-init fn). The step runs fully
    inside shard_map: grads psum over dp+sp (the gradient allreduce — the
    north-star collective), SGD update, new params out."""
    specs = param_specs(cfg)

    def spec_of(name):
        return specs[name]

    in_param_specs = {k: specs[k] for k in specs}

    def sharded_step(params, tokens):
        def step(params, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
            # replicated params: sum contributions over dp and sp;
            # tp/ep-sharded params hold distinct slices — their grads are
            # reduced over the axes they're replicated on only.
            def sync(name, g):
                spec = specs[name]
                axes_used = {a for part in spec if part
                             for a in ((part,) if isinstance(part, str)
                                       else part)}
                reduce_over = tuple(a for a in ("dp", "sp", "tp")
                                    if a not in axes_used)
                return lax.psum(g, reduce_over) if reduce_over else g
            grads = {k: sync(k, g) for k, g in grads.items()}
            new_params = jax.tree.map(lambda p, g: p - cfg.lr * g,
                                      params, grads)
            return new_params, loss

        fn = shard_map(
            step, mesh=mesh,
            in_specs=(in_param_specs, P("dp", "sp")),
            out_specs=(in_param_specs, P()),
            check_vma=False)
        return fn(params, tokens)

    return jax.jit(sharded_step)


def shard_params(params, cfg: Config, mesh: Mesh):
    specs = param_specs(cfg)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def demo_setup(cfg: Optional[Config] = None,
               mesh_shape: Optional[Tuple[int, int, int]] = None,
               devices=None):
    """Build (cfg, mesh, params, tokens, step_fn) over available devices."""
    cfg = cfg or Config()
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_shape is None:
        # prefer sp over tp over dp for small device counts
        if n == 1:
            mesh_shape = (1, 1, 1)
        elif n == 2:
            mesh_shape = (1, 2, 1)
        elif n == 4:
            mesh_shape = (1, 2, 2)
        elif n == 8:
            mesh_shape = (2, 2, 2)
        else:
            a = mesh_shape_for(n, 2)
            mesh_shape = (1, a[0], a[1])
    mesh = make_mesh(mesh_shape, ("dp", "sp", "tp"), devices)
    key = jax.random.PRNGKey(0)
    params = init_params(cfg, key)
    params = shard_params(params, cfg, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (cfg.batch,
                                cfg.seq_len), 0, cfg.vocab, jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
    step = make_train_step(cfg, mesh)
    return cfg, mesh, params, tokens, step

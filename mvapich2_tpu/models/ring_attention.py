"""Ring attention: sequence-parallel attention over an ICI ring.

The long-context path (SURVEY §5.7): the reference's contribution to
sequence scaling is its ring primitive set (MPIR_Allreduce_pt2pt_ring_MV2 /
MPI_Sendrecv shifts); ring attention is exactly that communication skeleton
— KV blocks circulate the ring via ppermute while each shard accumulates
its queries' attention in streaming (flash) form, so sequence length scales
with the number of shards and communication overlaps compute.

Causal masking across ring steps: at step s this shard (index i) holds the
KV block that originated at shard j = (i - s) mod p; keys with global
positions beyond the query's are masked (blockwise for j > i, triangular
for j == i).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size as _ops_axis_size
from ..ops import ring_shift

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale, causal):
    """One KV block's contribution: returns (scores_max, exp_scores@v,
    exp_scores row-sum) in streaming-softmax form.

    q [T, H, Dh], k/v [Tk, H, Dh]; positions are global token indices."""
    s = jnp.einsum("thd,khd->htk", q, k) * scale          # [H, T, Tk]
    if causal:
        mask = q_pos[None, :, None] >= k_pos[None, None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                # [H, T]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: exp(NEG_INF - NEG_INF) = 1 per element; zero them
    valid = m > NEG_INF / 2
    p = jnp.where(valid[..., None], p, 0.0)
    m = jnp.where(valid, m, NEG_INF)
    num = jnp.einsum("htk,khd->thd", p, v)                 # [T, H, Dh]
    den = jnp.sum(p, axis=-1)                              # [H, T]
    return m, num, den


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Streaming attention with KV blocks rotating around ``axis_name``.

    q/k/v: [T_local, H, Dh] for this sequence shard. Returns [T_local, H,
    Dh]. Accumulators are f32 regardless of input dtype."""
    p = _ops_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    T, H, Dh = q.shape
    scale = scale if scale is not None else Dh ** -0.5
    q32 = q.astype(jnp.float32)

    q_pos = my * T + jnp.arange(T)

    def step(carry, s):
        kk, vv, m_acc, num_acc, den_acc = carry
        j = lax.rem(my - s + p, p)           # origin shard of current block
        k_pos = j * T + jnp.arange(kk.shape[0])
        m_blk, num_blk, den_blk = _block_attend(
            q32, kk.astype(jnp.float32), vv.astype(jnp.float32),
            q_pos, k_pos, scale, causal)
        new_m = jnp.maximum(m_acc, m_blk)
        # rescale previous accumulators and the new block to the new max
        alpha = jnp.exp(m_acc - new_m)                    # [H, T]
        beta = jnp.exp(m_blk - new_m)
        num_acc = (num_acc * alpha.T[..., None]
                   + num_blk * beta.T[..., None])
        den_acc = den_acc * alpha + den_blk * beta
        m_acc = new_m
        # rotate KV to the right neighbor; at step s+1 I hold block my-s-1
        kk = ring_shift(kk, axis_name, 1)
        vv = ring_shift(vv, axis_name, 1)
        return (kk, vv, m_acc, num_acc, den_acc), None

    m0 = jnp.full((H, T), NEG_INF, jnp.float32)
    num0 = jnp.zeros((T, H, Dh), jnp.float32)
    den0 = jnp.zeros((H, T), jnp.float32)
    (ck, cv, m_f, num_f, den_f), _ = lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(p))
    den_f = jnp.maximum(den_f, 1e-20)
    out = num_f / den_f.T[..., None]
    return out.astype(q.dtype)


def local_attention_reference(q, k, v, causal: bool = True,
                              scale: Optional[float] = None):
    """Dense single-device attention for correctness checks.
    q/k/v: [T, H, Dh] full sequence."""
    T, H, Dh = q.shape
    scale = scale if scale is not None else Dh ** -0.5
    s = jnp.einsum("thd,khd->htk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("htk,khd->thd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def ring_attention_flash(q, k, v, axis_name: str, causal: bool = True,
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool = False):
    """Ring attention with the pallas flash kernel as the per-step
    compute: KV movement stays lax.ppermute (XLA/ICI), each block's
    (max, numerator, denominator) parts come from models/flash.py, and
    the streaming merge is the same rescaling as ring_attention.

    The block's causal relationship is decided per ring step at block
    granularity — the circulating block originated at shard
    j = (i - s) mod p, so it is entirely in this shard's past (j < i:
    unmasked), the diagonal (j == i: block-local causal mask), or
    entirely in the future (j > i: skipped) — the blockwise-causal
    structure ring attention is built on. lax.switch executes exactly
    one kernel per step.
    """
    from .flash import flash_attention_parts

    p = _ops_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    T, H, Dh = q.shape

    def parts_causal(kk, vv):
        return flash_attention_parts(q, kk, vv, True, block_q, block_k,
                                     interpret=interpret)

    def parts_past(kk, vv):
        return flash_attention_parts(q, kk, vv, False, block_q, block_k,
                                     interpret=interpret)

    def parts_future(kk, vv):
        return (jnp.full((H, T), NEG_INF, jnp.float32),
                jnp.zeros((T, H, Dh), jnp.float32),
                jnp.zeros((H, T), jnp.float32))

    def step(carry, s):
        kk, vv, m_acc, num_acc, den_acc = carry
        j = lax.rem(my - s + p, p)        # origin shard of this block
        if causal:
            case = jnp.where(j == my, 1, jnp.where(j < my, 2, 0))
            m_blk, num_blk, den_blk = lax.switch(
                case, [parts_future, parts_causal, parts_past], kk, vv)
        else:
            m_blk, num_blk, den_blk = parts_past(kk, vv)
        new_m = jnp.maximum(m_acc, m_blk)
        safe = jnp.where(new_m > NEG_INF / 2, new_m, 0.0)
        alpha = jnp.where(m_acc > NEG_INF / 2,
                          jnp.exp(m_acc - safe), 0.0)
        beta = jnp.where(m_blk > NEG_INF / 2,
                         jnp.exp(m_blk - safe), 0.0)
        num_acc = (num_acc * alpha.T[..., None]
                   + num_blk * beta.T[..., None])
        den_acc = den_acc * alpha + den_blk * beta
        kk = ring_shift(kk, axis_name, 1)
        vv = ring_shift(vv, axis_name, 1)
        return (kk, vv, new_m, num_acc, den_acc), None

    m0 = jnp.full((H, T), NEG_INF, jnp.float32)
    num0 = jnp.zeros((T, H, Dh), jnp.float32)
    den0 = jnp.zeros((H, T), jnp.float32)
    (_, _, m_acc, num_acc, den_acc), _ = lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(p))
    den_acc = jnp.maximum(den_acc, 1e-20)
    return (num_acc / den_acc.T[..., None]).astype(q.dtype)

"""Ring attention: sequence-parallel attention over an ICI ring.

The long-context path (SURVEY §5.7): the reference's contribution to
sequence scaling is its ring primitive set (MPIR_Allreduce_pt2pt_ring_MV2 /
MPI_Sendrecv shifts); ring attention is exactly that communication skeleton
— KV blocks circulate the ring via ppermute while each shard accumulates
its queries' attention in streaming (flash) form, so sequence length scales
with the number of shards and communication overlaps compute.

Causal masking across ring steps: at step s this shard (index i) holds the
KV block that originated at shard j = (i - s) mod p; keys with global
positions beyond the query's are masked (blockwise for j > i, triangular
for j == i).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import ring_shift

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale, causal):
    """One KV block's contribution: returns (scores_max, exp_scores@v,
    exp_scores row-sum) in streaming-softmax form.

    q [T, H, Dh], k/v [Tk, H, Dh]; positions are global token indices."""
    s = jnp.einsum("thd,khd->htk", q, k) * scale          # [H, T, Tk]
    if causal:
        mask = q_pos[None, :, None] >= k_pos[None, None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                # [H, T]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: exp(NEG_INF - NEG_INF) = 1 per element; zero them
    valid = m > NEG_INF / 2
    p = jnp.where(valid[..., None], p, 0.0)
    m = jnp.where(valid, m, NEG_INF)
    num = jnp.einsum("htk,khd->thd", p, v)                 # [T, H, Dh]
    den = jnp.sum(p, axis=-1)                              # [H, T]
    return m, num, den


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Streaming attention with KV blocks rotating around ``axis_name``.

    q/k/v: [T_local, H, Dh] for this sequence shard. Returns [T_local, H,
    Dh]. Accumulators are f32 regardless of input dtype."""
    p = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    T, H, Dh = q.shape
    scale = scale if scale is not None else Dh ** -0.5
    q32 = q.astype(jnp.float32)

    q_pos = my * T + jnp.arange(T)

    def step(carry, s):
        kk, vv, m_acc, num_acc, den_acc = carry
        j = lax.rem(my - s + p, p)           # origin shard of current block
        k_pos = j * T + jnp.arange(kk.shape[0])
        m_blk, num_blk, den_blk = _block_attend(
            q32, kk.astype(jnp.float32), vv.astype(jnp.float32),
            q_pos, k_pos, scale, causal)
        new_m = jnp.maximum(m_acc, m_blk)
        # rescale previous accumulators and the new block to the new max
        alpha = jnp.exp(m_acc - new_m)                    # [H, T]
        beta = jnp.exp(m_blk - new_m)
        num_acc = (num_acc * alpha.T[..., None]
                   + num_blk * beta.T[..., None])
        den_acc = den_acc * alpha + den_blk * beta
        m_acc = new_m
        # rotate KV to the right neighbor; at step s+1 I hold block my-s-1
        kk = ring_shift(kk, axis_name, 1)
        vv = ring_shift(vv, axis_name, 1)
        return (kk, vv, m_acc, num_acc, den_acc), None

    m0 = jnp.full((H, T), NEG_INF, jnp.float32)
    num0 = jnp.zeros((T, H, Dh), jnp.float32)
    den0 = jnp.zeros((H, T), jnp.float32)
    (ck, cv, m_f, num_f, den_f), _ = lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(p))
    den_f = jnp.maximum(den_f, 1e-20)
    out = num_f / den_f.T[..., None]
    return out.astype(q.dtype)


def local_attention_reference(q, k, v, causal: bool = True,
                              scale: Optional[float] = None):
    """Dense single-device attention for correctness checks.
    q/k/v: [T, H, Dh] full sequence."""
    T, H, Dh = q.shape
    scale = scale if scale is not None else Dh ** -0.5
    s = jnp.einsum("thd,khd->htk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("htk,khd->thd", w,
                      v.astype(jnp.float32)).astype(q.dtype)

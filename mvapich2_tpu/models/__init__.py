from . import ring_attention, stencil, transformer, ulysses

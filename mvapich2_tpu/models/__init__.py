from . import flash, ring_attention, stencil, transformer, ulysses

"""3D 7-point stencil with halo exchange (BASELINE config 4).

The acceptance workload '3D 7-pt stencil halo exchange (Isend/Irecv ->
ppermute), 512^3 grid': the grid is sharded along z over a mesh axis; each
iteration exchanges one-plane halos with both neighbors via ppermute and
applies the 7-point update. This is the direct TPU translation of the
MPI_Cart + Isend/Irecv halo pattern (src/mpi/topo/)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import halo_exchange
from ..parallel.mesh import MeshComm


def stencil_step(u, axis: str, periodic: bool = True):
    """One Jacobi update of the 7-pt stencil on this shard's [Zl, Y, X]
    block (halo width 1 along the sharded z dim)."""
    up = halo_exchange(u, axis, halo=1, dim=0, periodic=periodic)
    center = up[1:-1]
    z0, z1 = up[:-2], up[2:]
    y0 = jnp.roll(center, 1, axis=1)
    y1 = jnp.roll(center, -1, axis=1)
    x0 = jnp.roll(center, 1, axis=2)
    x1 = jnp.roll(center, -1, axis=2)
    return (z0 + z1 + y0 + y1 + x0 + x1 - 6.0 * center) / 6.0 + center


def run_stencil(comm: MeshComm, grid: int = 64, iters: int = 4,
                periodic: bool = True):
    """Run `iters` stencil steps on a [grid]^3 cube sharded along z."""
    p = comm.size
    assert grid % p == 0
    u = jnp.arange(grid ** 3, dtype=jnp.float32).reshape(grid, grid, grid)
    u = (u % 97) / 97.0

    def body(ushard):
        for _ in range(iters):
            ushard = stencil_step(ushard, comm.axis, periodic)
        return ushard

    return comm.run(body, u, in_specs=(P(comm.axis),),
                    out_specs=P(comm.axis))


def reference_stencil(u, iters: int, periodic: bool = True):
    """Single-device reference for correctness checks."""
    for _ in range(iters):
        if periodic:
            z0 = jnp.roll(u, 1, axis=0)
            z1 = jnp.roll(u, -1, axis=0)
        else:
            zpad = jnp.pad(u, ((1, 1), (0, 0), (0, 0)))
            z0, z1 = zpad[:-2], zpad[2:]
        y0 = jnp.roll(u, 1, axis=1)
        y1 = jnp.roll(u, -1, axis=1)
        x0 = jnp.roll(u, 1, axis=2)
        x1 = jnp.roll(u, -1, axis=2)
        u = (z0 + z1 + y0 + y1 + x0 + x1 - 6.0 * u) / 6.0 + u
    return u

"""Ulysses sequence parallelism: attention via head<->sequence all-to-all.

The second long-context strategy of SURVEY §5.7 (next to ring
attention): where the ring circulates KV blocks over ppermute
(MPI_Sendrecv-shift skeleton), Ulysses re-shards with the reference's
alltoall family (alltoall_osu.c -> one fused ICI all-to-all here). Each
shard holds a sequence block of ALL heads; two all-to-alls convert that
to all tokens of a head block, dense attention runs locally per head,
and one more all-to-all restores sequence sharding:

    [T/p tokens, H heads]  --a2a-->  [T tokens, H/p heads]
        (attention, embarrassingly parallel over the head block)
    [T tokens, H/p heads]  --a2a-->  [T/p tokens, H heads]

Communication: 3-4 all-to-alls of the activations per attention call
(vs the ring's p-1 KV shifts) — the better trade when heads >= shards
and ICI all-to-all bandwidth is plentiful (v5p tori), while ring
attention wins at extreme sequence lengths; the tuning-layer crossover
discipline applies (models pick per mesh shape).

Call inside shard_map over the sequence axis; the head count must be
divisible by the axis size.
"""

from __future__ import annotations

from jax import lax

from ..ops.collectives import axis_size as _ops_axis_size
from ..ops import all_to_all
from .flash import flash_attention
from .ring_attention import local_attention_reference


def _seq_to_heads(x, axis: str):
    """[T/p, H, Dh] -> [T, H/p, Dh]: gather the sequence, scatter heads."""
    return all_to_all(x, axis, split_axis=1, concat_axis=0)


def _heads_to_seq(x, axis: str):
    """[T, H/p, Dh] -> [T/p, H, Dh]: the inverse reshard."""
    return all_to_all(x, axis, split_axis=0, concat_axis=1)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      use_flash: bool = False, interpret: bool = False,
                      block_q: int = 128, block_k: int = 128):
    """Sequence-parallel attention via head/sequence all-to-all reshard.

    q/k/v: [T/p, H, Dh] — this shard's sequence block of every head
    (p = size of ``axis_name``; H % p == 0). Returns the attention
    output in the same [T/p, H, Dh] sharding.

    Numerically identical to dense attention over the gathered
    sequence (tested against it); the all-to-alls are the only
    communication. The local attention runs in f32 regardless of input
    dtype (like ring_attention's accumulators).
    """
    H = q.shape[1]
    p = _ops_axis_size(axis_name)
    if H % p != 0:
        raise ValueError(f"heads {H} not divisible by axis size {p}")
    qh = _seq_to_heads(q, axis_name)     # [T, H/p, Dh]
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)
    if use_flash:
        # the pallas hot-op kernel (models/flash.py): blockwise fused
        # attention, never materializing [T, T] scores in HBM
        oh = flash_attention(qh, kh, vh, causal=causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    else:
        oh = local_attention_reference(qh, kh, vh, causal=causal)
    return _heads_to_seq(oh, axis_name).astype(q.dtype)  # [T/p, H, Dh]

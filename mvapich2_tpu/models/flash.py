"""Blockwise flash attention — the pallas kernel for the attention hot op.

Ring attention (models/ring_attention.py) streams KV blocks over the ICI
ring and accumulates each block's contribution in flash (streaming
softmax) form; THIS module is the on-chip half of that design done as a
hand-scheduled pallas kernel: Q tiles stay VMEM-resident while the
kernel walks K/V tiles, keeping the running (max, numerator, denominator)
in scratch — attention never materializes the [T, T] score matrix in HBM.
The kernel is the single-shard building block: ring/Ulysses provide the
cross-shard movement, flash provides the per-shard FLOPs on the MXU.

Positions are parametrized by global offsets (q0, k0) so the SAME kernel
computes a ring step's block: shard i's queries live at q0 = i*T, the
circulating KV block at k0 = j*T.

VMEM budget: per (head, q-tile) grid step the kernel holds one
[Bq, D] Q tile, the full [Tk, D] K and V for that head, and [Bq, D]+2
accumulators — fine for the per-shard sequence lengths ring attention
produces (the whole point of sequence parallelism is that Tk/shard is
modest). Interpret mode runs the identical kernel on the CPU mesh in CI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    HAVE_PALLAS = False

from .ring_attention import NEG_INF


def _stream_blocks(Bk, causal, q0, k0, qi, q_ref, k_ref, v_ref):
    """The shared streaming-softmax core: walk K/V tiles of this head,
    carrying (max, numerator, denominator). q0/k0 are static global
    position offsets; refs are [1, ., D] head blocks."""
    _, Bq, D = q_ref.shape
    Tk = k_ref.shape[1]
    scale = D ** -0.5
    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = q0 + qi * Bq + jax.lax.broadcasted_iota(
        jnp.int32, (Bq, Bk), 0)

    def step(kt, carry):
        m_acc, num_acc, den_acc = carry
        k = k_ref[0, pl.ds(kt * Bk, Bk), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kt * Bk, Bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            k_pos = k0 + kt * Bk + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, Bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_blk = jnp.max(s, axis=1)
        new_m = jnp.maximum(m_acc, m_blk)
        # guard fully-masked rows: keep them at NEG_INF with zero weight
        safe_m = jnp.where(new_m > NEG_INF / 2, new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(s > NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_acc > NEG_INF / 2,
                          jnp.exp(m_acc - safe_m), 0.0)
        num_acc = num_acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        den_acc = den_acc * alpha + jnp.sum(p, axis=1)
        return new_m, num_acc, den_acc

    m0 = jnp.full((Bq,), NEG_INF, jnp.float32)
    num0 = jnp.zeros((Bq, D), jnp.float32)
    den0 = jnp.zeros((Bq,), jnp.float32)
    nk = Tk // Bk
    if causal:
        # skip K tiles entirely above the diagonal: only tiles whose
        # first key position <= this q tile's last query position can
        # contribute (halves the MXU work of causal self-attention)
        last_q = q0 + (qi + 1) * Bq - 1
        nk_eff = jnp.clip((last_q - k0) // Bk + 1, 0, nk)
    else:
        nk_eff = nk
    return jax.lax.fori_loop(0, nk_eff, step, (m0, num0, den0))


def _flash_kernel(Bk, causal, q0, k0, q_ref, k_ref, v_ref, o_ref):
    """Grid step = (head, q-tile): normalized attention output."""
    m_f, num_f, den_f = _stream_blocks(Bk, causal, q0, k0,
                                       pl.program_id(1), q_ref, k_ref,
                                       v_ref)
    den_f = jnp.maximum(den_f, 1e-20)
    o_ref[0] = (num_f / den_f[:, None]).astype(o_ref.dtype)


def _flash_parts_kernel(Bk, causal, q_ref, k_ref, v_ref, m_ref, num_ref,
                        den_ref):
    """Grid step = (head, q-tile): unnormalized streaming parts (block-
    local positions) for the ring-step merge."""
    m_f, num_f, den_f = _stream_blocks(Bk, causal, 0, 0,
                                       pl.program_id(1), q_ref, k_ref,
                                       v_ref)
    m_ref[0] = m_f
    num_ref[0] = num_f
    den_ref[0] = den_f


def _block_sizes(T, Tk, block_q, block_k):
    """Largest divisors of T/Tk not exceeding the requested blocks —
    non-power-of-two lengths shrink the tile instead of erroring."""
    import math
    bq = math.gcd(T, block_q) if T % min(block_q, T) else min(block_q, T)
    bk = math.gcd(Tk, block_k) if Tk % min(block_k, Tk) \
        else min(block_k, Tk)
    return bq, bk


def flash_attention(q, k, v, causal: bool = True, q0: int = 0,
                    k0: int = 0, block_q: int = 128, block_k: int = 128,
                    *, interpret: bool = False):
    """Fused attention over one device's data. q [T, H, D],
    k/v [Tk, H, D] -> [T, H, D]; q0/k0 are the global position offsets
    (ring-step parametrization). Accumulates in f32.

    Block sizes shrink automatically (gcd) when T/Tk aren't multiples
    of the requested blocks, so any shape the jnp path accepts works.
    """
    if not HAVE_PALLAS:
        raise RuntimeError("pallas unavailable")
    T, H, D = q.shape
    Tk = k.shape[0]
    bq, bk = _block_sizes(T, Tk, block_q, block_k)
    # [T, H, D] -> [H, T, D] so the head is a grid dimension
    qh = jnp.swapaxes(q, 0, 1)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)

    kern = functools.partial(_flash_kernel, bk, causal, int(q0), int(k0))
    out = pl.pallas_call(
        kern,
        grid=(H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda h, i: (h, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda h, i: (h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qh.shape, q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return jnp.swapaxes(out, 0, 1)


def flash_attention_parts(q, k, v, causal: bool, block_q: int = 128,
                          block_k: int = 128, *,
                          interpret: bool = False):
    """Streaming-softmax parts of one KV block's attention:
    (m [H, T], num [T, H, D], den [H, T]) in the layout ring_attention's
    merge expects. causal=True masks block-locally (the diagonal ring
    step); past blocks use causal=False."""
    if not HAVE_PALLAS:
        raise RuntimeError("pallas unavailable")
    T, H, D = q.shape
    Tk = k.shape[0]
    bq, bk = _block_sizes(T, Tk, block_q, block_k)
    qh = jnp.swapaxes(q, 0, 1)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)
    kern = functools.partial(_flash_parts_kernel, bk, causal)
    m, num, den = pl.pallas_call(
        kern,
        grid=(H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda h, i: (h, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda h, i: (h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq), lambda h, i: (h, i)),
            pl.BlockSpec((1, bq, D), lambda h, i: (h, i, 0)),
            pl.BlockSpec((1, bq), lambda h, i: (h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, T), jnp.float32),
            jax.ShapeDtypeStruct((H, T, D), jnp.float32),
            jax.ShapeDtypeStruct((H, T), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return m, jnp.swapaxes(num, 0, 1), den

"""Request objects (ch3u_request.c analog).

A Request is a completion promise tied to a rank's progress engine. Blocking
waits funnel into the engine's ``progress_wait`` (SURVEY §3.5) — the engine
polls its channels and sleeps on a condition variable that any completing
thread signals. Completion callbacks chain protocol state machines
(rendezvous CTS -> data -> FIN) and the nonblocking-collective scheduler.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from .errors import MPIException, MPI_SUCCESS, MPI_ERR_REQUEST
from .status import Status

REQUEST_NULL = None


class Request:
    _ids = iter(range(1, 1 << 62))

    def __init__(self, engine=None, kind: str = "generic"):
        self.engine = engine          # progress engine that completes me
        self.kind = kind
        self.status = Status()
        self.complete_flag = False
        self.error: Optional[MPIException] = None
        self.cancelled = False
        self._callbacks: List[Callable] = []
        self.persistent = False
        self._start_fn: Optional[Callable] = None  # for persistent requests
        self.req_id = next(Request._ids)

    # -- completion (called with engine lock held or from engine.complete) --
    def add_callback(self, cb: Callable) -> None:
        if self.complete_flag:
            cb(self)
        else:
            self._callbacks.append(cb)

    def _fire(self) -> None:
        self.complete_flag = True
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def complete(self, error: Optional[MPIException] = None) -> None:
        """Thread-safe completion via the owning engine."""
        if error is not None:
            self.error = error
            self.status.error = error.error_class
        if self.engine is not None:
            self.engine.complete_request(self)
        else:
            self._fire()

    # -- user-facing ------------------------------------------------------
    def test(self) -> bool:
        if not self.complete_flag and self.engine is not None:
            self.engine.progress_poke()
        return self.complete_flag

    def wait(self) -> Status:
        # already-complete fast path (every eager send): complete_flag
        # only ever transitions False->True, so an unlocked read that
        # sees True is safe and skips the progress mutex+poll
        if not self.complete_flag:
            if self.engine is not None:
                self.engine.progress_wait(lambda: self.complete_flag)
            else:
                raise MPIException(MPI_ERR_REQUEST,
                                   "wait on engine-less incomplete request")
        if self.error is not None:
            raise self.error
        return self.status

    def cancel(self) -> None:
        # Recv cancel = matching-queue removal; send cancel resolves
        # asynchronously through the protocol (see pt2pt/protocol.py).
        # _cancel_override marks requests (persistent sends) whose
        # local completion does not preclude cancelling.
        if self.complete_flag and not getattr(self, "_cancel_override",
                                              False):
            return
        canceller = getattr(self, "_cancel_fn", None)
        if canceller is not None and canceller():
            self.cancelled = True
            self.status.cancelled = True
            self.complete()

    def free(self) -> None:
        pass

    # -- persistent requests (MPI_Send_init / MPI_Start) ------------------
    def start(self) -> None:
        if not self.persistent or self._start_fn is None:
            raise MPIException(MPI_ERR_REQUEST, "not a persistent request")
        self.complete_flag = False
        self.status = Status()
        self._start_fn(self)

    def __repr__(self):
        return (f"Request({self.kind}, id={self.req_id}, "
                f"{'done' if self.complete_flag else 'pending'})")


class CompletedRequest(Request):
    """Immediately-complete request (e.g. self-send fast path, 0-byte ops)."""

    def __init__(self, status: Optional[Status] = None):
        super().__init__(None, "completed")
        if status is not None:
            self.status = status
        self.complete_flag = True


def waitall(requests: List[Optional[Request]]) -> List[Status]:
    stats = []
    for r in requests:
        stats.append(r.wait() if r is not None else Status())
    return stats


def waitany(requests: List[Optional[Request]]) -> int:
    """Returns index of a completed request; progresses until one completes."""
    live = [(i, r) for i, r in enumerate(requests) if r is not None]
    if not live:
        return -1
    engine = next((r.engine for _, r in live if r.engine is not None), None)

    def any_done():
        return any(r.complete_flag for _, r in live)

    if engine is not None:
        engine.progress_wait(any_done)
    for i, r in live:
        if r.complete_flag:
            if r.error is not None:
                raise r.error
            return i
    raise MPIException(MPI_ERR_REQUEST, "waitany: nothing completed")


def testall(requests: List[Optional[Request]]) -> bool:
    return all(r is None or r.test() for r in requests)


def testany(requests: List[Optional[Request]]):
    """(index, flag): first completed request's index, or (-1, False)."""
    for i, r in enumerate(requests):
        if r is not None and r.test():
            if r.error is not None:
                raise r.error
            return i, True
    return -1, False


def waitsome(requests: List[Optional[Request]]) -> List[int]:
    """Indices of all completed requests after at least one completes."""
    first = waitany(requests)
    if first < 0:
        return []
    out = []
    for i, r in enumerate(requests):
        if r is not None and r.complete_flag:
            if r.error is not None:
                raise r.error
            out.append(i)
    return out


def testsome(requests: List[Optional[Request]]) -> List[int]:
    out = []
    for i, r in enumerate(requests):
        if r is not None and r.test():
            if r.error is not None:
                raise r.error
            out.append(i)
    return out


class Grequest(Request):
    """Generalized request (MPI-3.1 §12.2, MPI_Grequest_start analog).

    The application completes it via ``complete()``; ``query_fn(status)``
    fills the status when the request is inspected at completion;
    ``free_fn``/``cancel_fn`` hook teardown and cancellation."""

    def __init__(self, engine, query_fn=None, free_fn=None,
                 cancel_fn=None):
        super().__init__(engine, "grequest")
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._user_cancel_fn = cancel_fn
        if engine is not None:
            with engine.mutex:
                engine.track(self)

    def complete(self, error=None) -> None:  # MPI_Grequest_complete
        if self._query_fn is not None:
            self._query_fn(self.status)
        super().complete(error)

    def cancel(self) -> None:
        # MPI-3.1 §12.2: cancel_fn is invoked unconditionally, with
        # complete=true when the request has already completed (the
        # cancel then has no effect on the request's state)
        if self.complete_flag:
            if self._user_cancel_fn is not None:
                self._user_cancel_fn(True)
            return
        if self._user_cancel_fn is not None:
            self._user_cancel_fn(False)
        self.cancelled = True
        self.status.cancelled = True
        super().complete(None)

    def free(self) -> None:
        if self._free_fn is not None:
            self._free_fn()


def grequest_start(query_fn=None, free_fn=None, cancel_fn=None) -> Grequest:
    from ..runtime.universe import current_universe
    u = current_universe()
    return Grequest(u.engine if u is not None else None, query_fn,
                    free_fn, cancel_fn)
